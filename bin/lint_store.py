#!/usr/bin/env python
"""CI gate: artifact publish goes through the tiered store.

The store PR moved every on-disk artifact lifecycle — the ``DMLCCHK1``
chunk cache, ``DMLCBC01`` block cache, and ``DMLCSN01`` snapshot formats
— onto ``dmlc_tpu/store/`` (one manifest, atomic publish, pin/refcount,
byte budgets with cost-aware eviction; docs/store.md). Before that, each
format hand-rolled its own ``<path>.tmp`` + ``os.replace`` publish, which
is exactly how three lifecycles drifted apart and how a fleet filled its
volume: a publish the store never sees is a publish the budget can never
bound, the manifest can never journal, and a pin can never protect.
``make lint-store`` keeps that from creeping back. It FAILS on, anywhere
under ``dmlc_tpu/`` outside ``dmlc_tpu/store/``:

- ``os.replace(`` — the atomic-publish rename; store-managed artifacts
  must publish via ``ArtifactStore.publish_file`` (and non-artifact
  files should not imitate the store's protocol beside it).
- ``+ ".tmp"`` — hand-allocated staging names; staging paths come from
  ``ArtifactStore.stage_path`` (process-unique, so concurrent writers
  of one signature can never clobber each other, and orphan GC can
  find crashed writers' leftovers).

The gate equally covers the data-service dispatcher's assignment
journal (``dmlc_tpu/service/dispatcher.py``, docs/service.md
control-plane recovery): it persists through the shared
``dmlc_tpu.store.journal.AppendJournal`` — the same flock'd
append/torn-tail-skip/atomic-compaction substrate as the store manifest
— so a hand-rolled ``.tmp`` staging name or a direct ``os.replace``
compaction beside it fails here, exactly like a direct artifact
publish would.

Sanctioned exceptions (non-artifact files, listed in ``ALLOWED``):
``utils/telemetry.py`` (Chrome-trace export writes a trace JSON, not a
store-managed artifact).

Exit status: 0 clean, 1 with offenders listed as ``path:line``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import List, Tuple

# the store package is the one sanctioned home of the publish protocol
STORE_PACKAGE = Path("dmlc_tpu") / "store"

# non-artifact modules allowed to atomically publish their own files
ALLOWED = {
    Path("dmlc_tpu") / "utils" / "telemetry.py",  # Chrome-trace export
}

_PATTERNS = (
    (re.compile(r"\bos\.replace\s*\("),
     "direct os.replace publish — store-managed artifacts publish via "
     "dmlc_tpu/store (ArtifactStore.publish_file)"),
    (re.compile(r"\+\s*[\"']\.tmp[\"']"),
     "hand-allocated .tmp staging name — staging paths come from "
     "ArtifactStore.stage_path (process-unique, orphan-GC-able)"),
)


def scan_source(text: str) -> List[Tuple[int, str]]:
    """Return (1-based line, reason) for each direct-publish site."""
    offenders: List[Tuple[int, str]] = []
    for i, line in enumerate(text.splitlines()):
        stripped = line.lstrip()
        if stripped.startswith("#"):
            continue
        for pattern, reason in _PATTERNS:
            if pattern.search(line):
                offenders.append((i + 1, reason))
    return offenders


def main(argv: List[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else \
        Path(__file__).resolve().parent.parent
    bad = 0
    for path in sorted((root / "dmlc_tpu").rglob("*.py")):
        rel = path.relative_to(root)
        if rel in ALLOWED or STORE_PACKAGE in rel.parents:
            continue
        for lineno, reason in scan_source(path.read_text(encoding="utf-8")):
            print(f"{rel}:{lineno}: {reason}", file=sys.stderr)
            bad += 1
    if bad:
        print(f"lint-store: {bad} direct artifact-publish site(s) found",
              file=sys.stderr)
        return 1
    print("lint-store: OK (artifact publish goes through dmlc_tpu/store)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
