#!/usr/bin/env python
"""CI gate: no bookkeeping beside the telemetry layer.

The telemetry PR centralized every stage clock and event counter onto
``dmlc_tpu/utils/telemetry.py`` (the registry + span tracer) with
``dmlc_tpu/utils/timer.py`` as the sanctioned clock (``get_time`` /
``StageMeter``). Before that, stage timing and counters were scattered
point solutions — process-global counters that let concurrent pipelines
contaminate each other, and ``time.monotonic()`` stopwatches whose
numbers never reached ``stats()`` or a trace. ``make lint-metrics`` keeps
that from creeping back. It FAILS on, anywhere under ``dmlc_tpu/`` —
every package, including ``dmlc_tpu/service/`` (whose frame
encode/send/recv/decode timing must ride the span tracer, and whose
failover AND control-plane recovery events — ``dispatcher_restarts``,
``worker_reregistrations``, ``parts_reclaimed``,
``control_plane_retries``, recorded around the dispatcher's
AppendJournal-backed assignment journal — must go through
``record_event``; an ad-hoc counter beside the journal fails here the
way a hand-rolled journal publish fails ``make lint-store``),
``dmlc_tpu/data/epoch.py`` (the epoch planner is pure plan math: any
timing it ever grows must pair with the ``cache_read`` spans its
consumer records), and ``dmlc_tpu/io/snapshot.py`` (the device-native
snapshot store: its ``snapshot_read``/``snapshot_write`` timing rides
the span tracer and its invalidation/corruption events go through
``record_event``) — except the sanctioned modules:

- ``COUNTERS.bump(`` — direct resilience-counter mutation; new events
  must go through ``dmlc_tpu.io.resilience.record_event`` (which stamps
  the pipeline scope on) or a registry counter.
- ``time.monotonic(`` — ad-hoc stage timing; use
  ``dmlc_tpu.utils.timer.get_time`` (so the reading can be paired with a
  ``telemetry.record_span``) or ``telemetry.span``.
- ad-hoc TUNABLE env reads (``DMLC_TPU_*_WORKERS``,
  ``DMLC_TPU_PREFETCH``, ``DMLC_TPU_CONVERT_AHEAD``,
  ``DMLC_TPU_AUTOTUNE*``, ``DMLC_TPU_STORE*``,
  ``DMLC_TPU_HEDGE_FACTOR``, ``DMLC_TPU_DRAIN_DEADLINE``,
  ``DMLC_TPU_PARSE_ENGINE``, ``DMLC_TPU_FLEET*``,
  ``DMLC_TPU_SERVICE_PIPELINE_DEPTH``,
  ``DMLC_TPU_WIRE_COMPRESSION``, ``DMLC_TPU_QOS*``,
  ``DMLC_TPU_CLAIM_WAIT_DEADLINE``, ``DMLC_TPU_METRICS*``) — every
  pipeline tunable must be a row in the
  autotune knob table (``dmlc_tpu/utils/knobs.py``, read via
  ``knobs.resolve``) so the feedback controller knows its bounds and the
  value is validated loudly; a point-of-use ``os.environ.get`` parse is
  exactly the pre-autotuner drift this gate closes (the three historical
  per-site parses in parsers.py/snapshot.py/device.py were consolidated
  by the autotuner PR).

A second gate guards the warm snapshot serve path: the device-decode PR
moved every per-batch byte decode onto two sanctioned homes —
``dmlc_tpu/io/block_cache.py`` (the host mmap views) and
``dmlc_tpu/ops/device_decode.py`` (the HBM span decode + the
widen/dequant dtype path). ``dmlc_tpu/io/snapshot.py`` and
``dmlc_tpu/data/device.py`` sit ON the warm serve path but must not
decode bytes themselves, so any ``np.frombuffer(`` or ``.astype(``
appearing there FAILS — that is host per-batch decode creeping back
into the path whose whole point is that the span ships verbatim.

A third gate guards service control-RPC observability: every ``cmd ==
"..."`` handler arm in ``dmlc_tpu/service/dispatcher.py`` and
``dmlc_tpu/service/worker.py`` must be covered by a
``record_span("service_rpc", ...)`` site in the same module — control
traffic that never hits the span tracer is invisible in merged pod
timelines (docs/observability.md Distributed tracing).

Exit status: 0 clean, 1 with offenders listed as ``path:line``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import List, Tuple

ALLOWED = {
    Path("dmlc_tpu") / "utils" / "telemetry.py",
    Path("dmlc_tpu") / "utils" / "timer.py",
}

# the knob table is the ONE sanctioned reader of tunable env variables
KNOB_TABLE_MODULE = Path("dmlc_tpu") / "utils" / "knobs.py"

# the two sanctioned byte-decode homes (module docstring): host views in
# block_cache, HBM decode + the widen/dequant dtype path in device_decode
DECODE_MODULES = {
    Path("dmlc_tpu") / "io" / "block_cache.py",
    Path("dmlc_tpu") / "ops" / "device_decode.py",
}

# warm-snapshot serve path modules that must stay decode-free: they route
# spans, they do not decode them
DECODE_SCOPE = {
    Path("dmlc_tpu") / "io" / "snapshot.py",
    Path("dmlc_tpu") / "data" / "device.py",
}

# service control-plane modules whose RPC dispatch must be covered by the
# span tracer (docs/observability.md Distributed tracing): every
# ``cmd == "..."`` handler arm must sit under a ``service_rpc`` span so
# control traffic is visible in merged pod timelines — a handler added
# outside the span-wrapped dispatch is un-traceable control flow
RPC_MODULES = {
    Path("dmlc_tpu") / "service" / "dispatcher.py",
    Path("dmlc_tpu") / "service" / "worker.py",
}

_RPC_HANDLER = re.compile(r"\bcmd\s*==\s*['\"](\w+)['\"]")
_RPC_SPAN = re.compile(r"record_span\(\s*['\"]service_rpc['\"]")

_PATTERNS = (
    (re.compile(r"\bCOUNTERS\.bump\s*\("),
     "direct COUNTERS.bump — use resilience.record_event / a registry "
     "counter"),
    (re.compile(r"\btime\.monotonic\s*\("),
     "ad-hoc time.monotonic() stage timing — use utils.timer.get_time / "
     "telemetry.span"),
)

_KNOB_PATTERN = (
    re.compile(r"(?:environ(?:\.get)?\s*[\(\[]|\bgetenv\s*\()\s*['\"]"
               r"DMLC_TPU_(?:[A-Z0-9_]*_WORKERS|PREFETCH|CONVERT_AHEAD|"
               r"AUTOTUNE[A-Z0-9_]*|STORE[A-Z0-9_]*|HEDGE_FACTOR|"
               r"DRAIN_DEADLINE|PARSE_ENGINE|FLEET[A-Z0-9_]*|"
               r"SERVICE_PIPELINE_DEPTH|WIRE_COMPRESSION|"
               r"QOS[A-Z0-9_]*|CLAIM_WAIT_DEADLINE|"
               r"DEVICE_DECODE[A-Z0-9_]*|METRICS[A-Z0-9_]*)['\"]"),
    "ad-hoc tunable env read — register the knob in "
    "dmlc_tpu/utils/knobs.py (KNOB_TABLE / a validated accessor like "
    "store_budget_bytes) and read it through that module")

_DECODE_PATTERNS = (
    (re.compile(r"\bnp\.frombuffer\s*\("),
     "host np.frombuffer on the warm snapshot serve path — per-batch "
     "byte decode belongs in io/block_cache.py (host views) or "
     "ops/device_decode.py (HBM span decode)"),
    (re.compile(r"\.astype\s*\("),
     "host dtype convert on the warm snapshot serve path — widening/"
     "dequant belongs in ops/device_decode.py (the sanctioned device "
     "dtype path)"),
)


def scan_source(text: str,
                knob_gate: bool = True) -> List[Tuple[int, str]]:
    """Return (1-based line, reason) for each ad-hoc bookkeeping site.
    ``knob_gate=False`` skips the tunable-env pattern (the knob table
    module is its one sanctioned home)."""
    offenders: List[Tuple[int, str]] = []
    patterns = _PATTERNS + ((_KNOB_PATTERN,) if knob_gate else ())
    for i, line in enumerate(text.splitlines()):
        stripped = line.lstrip()
        if stripped.startswith("#"):
            continue
        for pattern, reason in patterns:
            if pattern.search(line):
                offenders.append((i + 1, reason))
    return offenders


def scan_decode(text: str) -> List[Tuple[int, str]]:
    """The warm-serve decode gate (module docstring): (line, reason) for
    each per-batch host decode site in a DECODE_SCOPE module."""
    offenders: List[Tuple[int, str]] = []
    for i, line in enumerate(text.splitlines()):
        stripped = line.lstrip()
        if stripped.startswith("#"):
            continue
        for pattern, reason in _DECODE_PATTERNS:
            if pattern.search(line):
                offenders.append((i + 1, reason))
    return offenders


def scan_rpc_spans(text: str) -> List[Tuple[int, str]]:
    """The RPC-coverage gate (module docstring): in an RPC_MODULES file,
    every ``cmd == "..."`` handler arm requires a ``service_rpc``
    span-recording site in the same module — without one, every handler
    line is an offender (the whole dispatch runs untraced)."""
    if _RPC_SPAN.search(text):
        return []
    offenders: List[Tuple[int, str]] = []
    for i, line in enumerate(text.splitlines()):
        stripped = line.lstrip()
        if stripped.startswith("#"):
            continue
        m = _RPC_HANDLER.search(line)
        if m:
            offenders.append(
                (i + 1, f"RPC handler {m.group(1)!r} without a "
                        "record_span('service_rpc', ...) site in this "
                        "module — control RPCs must be span-traced "
                        "(docs/observability.md)"))
    return offenders


def main(argv: List[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else \
        Path(__file__).resolve().parent.parent
    bad = 0
    for path in sorted((root / "dmlc_tpu").rglob("*.py")):
        rel = path.relative_to(root)
        if rel in ALLOWED:
            continue
        text = path.read_text(encoding="utf-8")
        for lineno, reason in scan_source(
                text, knob_gate=rel != KNOB_TABLE_MODULE):
            print(f"{rel}:{lineno}: {reason}", file=sys.stderr)
            bad += 1
        if rel in DECODE_SCOPE:
            for lineno, reason in scan_decode(text):
                print(f"{rel}:{lineno}: {reason}", file=sys.stderr)
                bad += 1
        if rel in RPC_MODULES:
            for lineno, reason in scan_rpc_spans(text):
                print(f"{rel}:{lineno}: {reason}", file=sys.stderr)
                bad += 1
    if bad:
        print(f"lint-metrics: {bad} ad-hoc bookkeeping site(s) found",
              file=sys.stderr)
        return 1
    print("lint-metrics: OK (stage timing and counters live on the "
          "telemetry layer)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
