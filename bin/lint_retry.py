#!/usr/bin/env python
"""CI gate: no ad-hoc retry loops outside dmlc_tpu/io/resilience.py.

Two fixed-retry/linear-sleep loops (s3_filesys, azure_filesys) drifted
apart before the unified fault-tolerance layer existed — one retried auth
failures, the other didn't, and three filesystems had no retry at all.
``make lint-retry`` keeps that from creeping back: it FAILS on any
``time.sleep(`` that sits inside a retry-shaped loop — a ``for``/``while``
whose header-to-sleep region mentions attempt/retry/retries/backoff/trial
— anywhere under ``dmlc_tpu/`` except ``io/resilience.py`` (the one
sanctioned backoff implementation). New retry logic must delegate to
``dmlc_tpu.io.resilience.RetryPolicy``.

Exit status: 0 clean, 1 with offenders listed as ``path:line``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import List, Tuple

ALLOWED = {Path("dmlc_tpu") / "io" / "resilience.py"}
_SLEEP = re.compile(r"\btime\.sleep\s*\(")
_LOOP = re.compile(r"^\s*(for|while)\b")
_RETRY_WORDS = re.compile(r"attempt|retry|retries|backoff|trial", re.I)
_LOOKBACK = 40  # lines searched upward for the enclosing loop header


def scan_source(text: str) -> List[Tuple[int, str]]:
    """Return (1-based line, reason) for each retry-shaped sleep."""
    lines = text.splitlines()
    offenders: List[Tuple[int, str]] = []
    for i, line in enumerate(lines):
        if not _SLEEP.search(line) or line.lstrip().startswith("#"):
            continue
        indent = len(line) - len(line.lstrip())
        for j in range(i - 1, max(-1, i - _LOOKBACK), -1):
            prev = lines[j]
            if not prev.strip() or prev.lstrip().startswith("#"):
                continue
            pindent = len(prev) - len(prev.lstrip())
            if pindent < indent and _LOOP.match(prev):
                region = "\n".join(lines[j:i + 1])
                if _RETRY_WORDS.search(region):
                    offenders.append((
                        i + 1,
                        f"time.sleep inside retry-shaped loop "
                        f"(header at line {j + 1}: {prev.strip()!r})"))
                break
            if pindent < indent and re.match(r"\s*(def|class)\b", prev):
                break  # left the loop scope without finding a loop
    return offenders


def main(argv: List[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else Path(__file__).resolve().parent.parent
    bad = 0
    for path in sorted((root / "dmlc_tpu").rglob("*.py")):
        rel = path.relative_to(root)
        if rel in ALLOWED:
            continue
        for lineno, reason in scan_source(path.read_text(encoding="utf-8")):
            print(f"{rel}:{lineno}: {reason} — delegate to "
                  f"dmlc_tpu.io.resilience.RetryPolicy", file=sys.stderr)
            bad += 1
    if bad:
        print(f"lint-retry: {bad} ad-hoc retry sleep(s) found", file=sys.stderr)
        return 1
    print("lint-retry: OK (no ad-hoc retry loops outside resilience.py)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
