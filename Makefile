# One-command CI gate — the analog of the reference's travis_script.sh
# (scripts/travis/travis_script.sh:39-66: gtest suite + TSAN task).
#
#   make check        pytest + sanitizers + native parse bench + bench
#                     smoke, logged to CHECK.log (dated) — the full
#                     pre-commit gate
#   make test         pytest only (fast inner loop)
#   make sanitize     ASan/UBSan + TSan native runs -> native/SANITIZE.log
#   make native-test  plain build + run of the C++ unit smoke (skips with
#                     a notice when no toolchain is present)
#   make parse-bench  native scanner throughput tool (no device needed)
#   make bench-smoke  bench.py on the CPU backend; fails unless the JSON
#                     summary line carries the per-stage ingest
#                     attribution (read/cache_read/parse/convert/dispatch/
#                     transfer), the block-cache epoch-pair fields
#                     (warm_epoch_mb_per_sec/warm_vs_cold_speedup/
#                     cold_epoch_mb_per_sec/cache_state), the chunk-batch
#                     cold-parse leg (native_batch_parse_mb_per_sec +
#                     batch_vs_stream_parse_speedup >= 1.0 when the native
#                     kernel engaged (batch_parse_simd_level >= 0) AND the
#                     host has cores to fan onto (os.cpu_count() > 1;
#                     single-core hosts gate field presence only) — the
#                     native-batch engine's cold cache build vs the
#                     stream+re-encode path), the shuffle-native plan leg
#                     (shuffled_warm_epoch_mb_per_sec/shuffle_overhead_pct
#                     — a plan-ordered warm epoch on the same cache), the
#                     device-native snapshot leg (snapshot_warm_mb_per_sec/
#                     snapshot_vs_cache_speedup/snapshot_wire_bytes_ratio
#                     — warm epochs stream stored post-convert batches
#                     with convert busy ~0; bf16 halves stored bytes), the
#                     data-service leg (service_workers/
#                     service_mb_per_sec/service_vs_local_speedup from a
#                     localhost 2-worker fleet, plus the control-plane
#                     resilience quartet dispatcher_restarts/
#                     worker_reregistrations/parts_reclaimed/
#                     control_plane_retries — present and ZERO on a
#                     clean run), the online-autotuner leg
#                     (autotune_enabled/autotune_steps/
#                     autotune_final_config — the feedback controller
#                     climbs a starved config and emits the chosen knobs
#                     as reusable env),
#                     the production-QoS leg (service_qos_* — two-class
#                     contention: the critical tenant's warm wait frac
#                     under its SLO, the batch tenant throttled >= 1
#                     with zero giveups), the tiered artifact store
#                     (store_bytes/store_evictions/
#                     store_rebuilds_after_eviction — every cache and
#                     snapshot the legs publish is store-managed), the
#                     pod-scale training leg (als_rows_per_sec/
#                     als_step_seconds/als_input_wait_frac/
#                     als_overlap_frac — ALX-style sharded ALS warm-fed
#                     by the pod-sharded cache; the als_input_wait_frac
#                     < 0.2 compute-bound bar is judged on accelerator,
#                     the CPU host gates structure only), and
#                     the telemetry contract (telemetry_schema_version +
#                     per-stage span counts)
#   make fuzz         mutation fuzz of every native parse C-ABI entry point
#                     (crash-safety; DMLC_FUZZ_ITERS to scale)
#   make lint-retry   grep gate: no time.sleep inside retry-shaped loops
#                     outside dmlc_tpu/io/resilience.py (ad-hoc retry
#                     loops must delegate to the shared RetryPolicy)
#   make lint-metrics grep gate: no direct COUNTERS.bump / ad-hoc
#                     time.monotonic() stage timing outside
#                     dmlc_tpu/utils/{telemetry,timer}.py (bookkeeping
#                     must live on the telemetry registry/span tracer)
#   make lint-store   grep gate: no direct os.replace / hand-allocated
#                     .tmp publish of store-managed artifact formats
#                     outside dmlc_tpu/store/ (publish must go through
#                     the tiered artifact store — docs/store.md)

PYTHON ?= python
# the native core's translation units — keep in sync with the other three
# lists: native/CMakeLists.txt, native/run_sanitizers.sh SRCS, and
# dmlc_tpu/native/__init__.py _SRCS (the on-demand .so build)
NATIVE_SRCS = native/src/parse.cc native/src/reader.cc \
	native/src/recordio.cc native/src/batch_parse.cc
# bash + pipefail so a failing stage is never masked by the tee into CHECK.log
SHELL := /bin/bash
.SHELLFLAGS := -o pipefail -c

.PHONY: check test test-all sanitize parse-bench bench-smoke fuzz \
	lint-retry lint-metrics lint-store native-test

# the tier-1 contract: slow-marked scale/soak tests are opt-in (test-all)
test:
	$(PYTHON) -m pytest tests/ -q -m 'not slow'

test-all:
	$(PYTHON) -m pytest tests/ -q

lint-retry:
	$(PYTHON) bin/lint_retry.py

lint-metrics:
	$(PYTHON) bin/lint_metrics.py

lint-store:
	$(PYTHON) bin/lint_store.py

fuzz:
	$(PYTHON) native/test/fuzz_parse.py

sanitize:
	sh native/run_sanitizers.sh

# plain (unsanitized) build + run of the C++ unit smoke — the fast native
# gate `make check` runs on any host with a toolchain; hosts without g++
# skip with a notice instead of failing (the Python suites still cover
# behavior through the prebuilt .so when one exists)
native-test:
	@if command -v g++ >/dev/null 2>&1; then \
	    mkdir -p native/build && \
	    g++ -O2 -std=c++17 -pthread -o native/build/native_smoke \
	        native/test/native_smoke.cc $(NATIVE_SRCS) && \
	    ./native/build/native_smoke; \
	else \
	    echo "native-test: g++ not found, skipping native unit tests"; \
	fi

# CPU-backend smoke of the driver benchmark: proves the pipeline runs end
# to end off-chip AND that the measurement contracts hold — the one JSON
# line must carry every named attribution stage plus wall, the parse
# fan-out width, and the workers scaling curve, or the gate fails.
# Small corpus + 1 rep: this checks the contract, not the throughput.
bench-smoke:
	DMLC_BENCH_PLATFORM=cpu DMLC_BENCH_MB=8 DMLC_BENCH_REPS=1 \
	DMLC_BENCH_ATTEMPTS=1 DMLC_BENCH_TIMEOUT=600 \
	    $(PYTHON) bench.py --service --autotune > .bench_smoke.json
	$(PYTHON) -c "import json, os; \
	    line = json.load(open('.bench_smoke.json')); \
	    a = line.get('attribution') or {}; \
	    missing = [k for k in ('read', 'parse', 'convert', 'dispatch', \
	        'transfer', 'wall') if k not in a]; \
	    assert not missing, f'attribution fields missing: {missing}'; \
	    assert line.get('value'), 'bench smoke produced no throughput'; \
	    assert line.get('parse_workers'), 'parse_workers missing'; \
	    curve = line.get('parse_scaling') or {}; \
	    missing_w = [w for w in ('1', '4') if w not in curve]; \
	    assert not missing_w, f'parse_scaling widths missing: {missing_w}'; \
	    assert line.get('parse_ceiling_workers_4'), \
	        'parse_ceiling_workers_4 missing'; \
	    assert line.get('warm_epoch_mb_per_sec'), \
	        'warm_epoch_mb_per_sec missing'; \
	    assert line.get('cold_epoch_mb_per_sec'), \
	        'cold_epoch_mb_per_sec missing'; \
	    assert line.get('native_batch_parse_mb_per_sec'), \
	        'native_batch_parse_mb_per_sec missing (batch-parse leg did not run)'; \
	    bvs = line.get('batch_vs_stream_parse_speedup'); \
	    simd = line.get('batch_parse_simd_level'); \
	    assert bvs is not None and simd is not None, \
	        'batch_vs_stream_parse_speedup/batch_parse_simd_level missing'; \
	    assert simd < 0 or (os.cpu_count() or 1) <= 1 or bvs >= 1.0, \
	        f'batch_vs_stream_parse_speedup {bvs} < 1.0 (simd {simd}); on a ' \
	        'toolchain-less host (simd -1) both legs run the Python engine ' \
	        'and the ratio is noise, and on a single-core host the batch ' \
	        'fan-out has no cores to fan onto — in both cases only presence ' \
	        'is gated (the >1.5x bar is judged on multi-core hardware)'; \
	    assert line.get('warm_vs_cold_speedup'), \
	        'warm_vs_cold_speedup missing'; \
	    assert line.get('cache_state') == 'warm', \
	        f\"cache_state {line.get('cache_state')!r} != 'warm'\"; \
	    assert line.get('shuffled_warm_epoch_mb_per_sec'), \
	        'shuffled_warm_epoch_mb_per_sec missing (plan leg did not run)'; \
	    assert line.get('shuffle_overhead_pct') is not None, \
	        'shuffle_overhead_pct missing'; \
	    assert line.get('snapshot_warm_mb_per_sec'), \
	        'snapshot_warm_mb_per_sec missing (snapshot leg did not run)'; \
	    assert line.get('snapshot_vs_cache_speedup'), \
	        'snapshot_vs_cache_speedup missing'; \
	    assert line.get('snapshot_state') == 'warm', \
	        f\"snapshot_state {line.get('snapshot_state')!r} != 'warm'\"; \
	    ratio = line.get('snapshot_wire_bytes_ratio'); \
	    assert ratio is not None and ratio <= 0.55, \
	        f'snapshot_wire_bytes_ratio {ratio} missing or > 0.55'; \
	    conv = line.get('snapshot_warm_convert_seconds'); \
	    assert conv is not None and conv <= 0.05, \
	        f'snapshot warm convert busy {conv}s != ~0 (convert not bypassed)'; \
	    dd = line.get('device_decode_mb_per_sec'); \
	    assert dd, 'device_decode_mb_per_sec missing (device-decode leg did not run)'; \
	    ddspd = line.get('device_decode_vs_snapshot_speedup'); \
	    ddbytes = line.get('device_decode_transfer_bytes'); \
	    ddconv = line.get('device_decode_convert_seconds'); \
	    ddbk = line.get('device_decode_backend'); \
	    assert ddspd and ddbytes and ddconv is not None and ddbk, \
	        'device_decode speedup/transfer_bytes/convert_seconds/backend missing'; \
	    assert ddconv <= 0.05, \
	        f'device-decode warm convert busy {ddconv}s != ~0 (host decode crept back)'; \
	    assert ddbk == 'cpu' or ddspd >= 1.0, \
	        f'device_decode_vs_snapshot_speedup {ddspd} < 1.0 on accelerator ' \
	        f'backend {ddbk}; on the CPU backend device decode runs on the ' \
	        'same silicon as host decode, so only presence is gated'; \
	    assert line.get('service_workers') == 2, \
	        'service_workers missing (service leg did not run)'; \
	    assert line.get('service_mb_per_sec'), \
	        'service_mb_per_sec missing'; \
	    assert line.get('service_vs_local_speedup'), \
	        'service_vs_local_speedup missing'; \
	    cp = [k for k in ('dispatcher_restarts', \
	        'worker_reregistrations', 'parts_reclaimed', \
	        'control_plane_retries', 'worker_drains', 'drain_handoffs', \
	        'preemption_notices', 'speculative_reissues', \
	        'speculative_wins', 'worker_joins') if line.get(k) is None]; \
	    assert not cp, f'control-plane counters missing: {cp}'; \
	    hot = {k: line[k] for k in ('dispatcher_restarts', \
	        'worker_reregistrations', 'parts_reclaimed', \
	        'control_plane_retries', 'worker_drains', 'drain_handoffs', \
	        'preemption_notices', 'speculative_reissues', \
	        'speculative_wins', 'worker_joins') if line[k]}; \
	    assert not hot, f'control-plane events on a clean run: {hot}'; \
	    assert line.get('service_jobs') == 2, \
	        'service_jobs missing (two-job multi-tenant leg did not run)'; \
	    spr = line.get('shared_parse_ratio'); \
	    assert spr is not None and spr >= 0.5, \
	        f'shared_parse_ratio {spr} < 0.5: the identical-corpus pair ' \
	        'did not share its published artifacts (cross-job ' \
	        'share-by-signature broken)'; \
	    fse = line.get('fleet_scale_events'); \
	    assert fse == 0, \
	        f'fleet_scale_events {fse} != 0: the autoscaler flapped on a ' \
	        'clean smoke run'; \
	    wblocks = line.get('service_wire_blocks'); \
	    assert wblocks, \
	        'service_wire_blocks missing (wire v2 leg did not run)'; \
	    assert line.get('service_pipeline_depth'), \
	        'service_pipeline_depth missing'; \
	    assert line.get('service_wire_gbps'), 'service_wire_gbps missing'; \
	    wratio = line.get('service_wire_compression_ratio'); \
	    assert wratio is not None and wratio <= 1.0, \
	        f'service_wire_compression_ratio {wratio} missing or > 1.0 ' \
	        '(the per-dtype break-even check shipped an inflating codec)'; \
	    wspd = line.get('service_wire_pipelined_speedup'); \
	    assert wspd is not None and wspd >= 0.85, \
	        f'service_wire_pipelined_speedup {wspd} < 0.85: the pipelined ' \
	        'schedule lost to one-request-per-frame beyond measurement ' \
	        'noise (loopback RTT is microseconds, so the smoke gate is a ' \
	        'no-regression floor; the window must never cost throughput)'; \
	    wfp = line.get('service_wire_fastpath'); \
	    assert wfp == wblocks, \
	        f'service_wire_fastpath {wfp} != {wblocks}: the co-located ' \
	        'client did not serve every block off the mmap fast path'; \
	    assert line.get('service_qos_jobs') == 2, \
	        'service_qos_jobs missing (production-QoS leg did not run)'; \
	    qthr = line.get('service_qos_throttles'); \
	    assert qthr is not None and qthr >= 1, \
	        f'service_qos_throttles {qthr}: admission control never shed ' \
	        'the saturating batch tenant (expected >= 1 retryable ' \
	        'throttled replies under the fleet ceiling)'; \
	    assert line.get('service_qos_admission_waits') is not None, \
	        'service_qos_admission_waits missing'; \
	    qgu = line.get('service_qos_giveups'); \
	    assert qgu == 0, \
	        f'service_qos_giveups {qgu} != 0: a throttled tenant burned ' \
	        'its failure budget — overload must degrade to bounded ' \
	        'queueing, never to give-up'; \
	    qwf = line.get('service_qos_critical_wait_frac'); \
	    qslo = line.get('service_qos_critical_slo'); \
	    assert qwf is not None and qslo and qwf < qslo, \
	        f'critical tenant wait frac {qwf} not under its SLO {qslo} ' \
	        'despite priority + admission budgets'; \
	    assert line.get('service_qos_batch_blocks'), \
	        'service_qos_batch_blocks missing/zero (the throttled batch ' \
	        'tenant never drained its epoch)'; \
	    assert line.get('autotune_enabled') is True, \
	        'autotune_enabled missing (autotune leg did not run)'; \
	    assert line.get('autotune_steps') is not None, \
	        'autotune_steps missing'; \
	    acfg = line.get('autotune_final_config') or {}; \
	    assert acfg.get('DMLC_TPU_PREFETCH') and \
	        acfg.get('DMLC_TPU_CONVERT_AHEAD'), \
	        f'autotune_final_config incomplete: {acfg}'; \
	    assert line.get('input_wait_seconds') is not None, \
	        'input_wait_seconds missing'; \
	    alsr = line.get('als_rows_per_sec'); \
	    assert alsr, 'als_rows_per_sec missing (als train leg did not run)'; \
	    assert line.get('als_step_seconds'), 'als_step_seconds missing'; \
	    alsw = line.get('als_input_wait_frac'); \
	    assert alsw is not None, 'als_input_wait_frac missing'; \
	    also = line.get('als_overlap_frac'); \
	    assert also is not None, 'als_overlap_frac missing'; \
	    assert line.get('als_cache_state') == 'warm', \
	        f\"als_cache_state {line.get('als_cache_state')!r} != 'warm' \" \
	        '(the training loop was not warm-fed)'; \
	    assert line.get('store_bytes'), \
	        'store_bytes missing/zero (artifacts not store-managed)'; \
	    assert line.get('store_evictions') is not None, \
	        'store_evictions missing'; \
	    assert line.get('store_rebuilds_after_eviction') is not None, \
	        'store_rebuilds_after_eviction missing'; \
	    assert line.get('telemetry_schema_version') == 2, \
	        'telemetry_schema_version missing/mismatched'; \
	    assert line.get('trace_spans'), 'trace_spans missing/zero'; \
	    sc = line.get('trace_span_counts') or {}; \
	    missing_s = [s for s in ('read', 'parse', 'convert', 'dispatch', \
	        'cache_read') if not sc.get(s)]; \
	    assert not missing_s, f'span counts missing stages: {missing_s}'; \
	    tov = line.get('trace_overhead_pct'); \
	    assert tov is not None and tov < 5.0, \
	        f'trace_overhead_pct {tov} missing or >= 5: trace propagation ' \
	        'must stay cheap enough to leave on'; \
	    xp = line.get('trace_spans_crossproc'); \
	    assert xp is not None and xp >= 1, \
	        f'trace_spans_crossproc {xp}: no (job, part) trace linked the ' \
	        'worker-side encode/send to the client-side recv/decode'; \
	    assert line.get('trace_timeline_events'), \
	        'trace_timeline_events missing/zero (merged pod timeline empty)'; \
	    pm = line.get('prometheus_metrics'); \
	    assert pm, \
	        f'prometheus_metrics {pm}: render_prometheus did not round-trip ' \
	        'through the text-format parser'; \
	    assert line.get('decisions_total') is not None, \
	        'decisions_total missing (decision ledger absent)'; \
	    print('bench-smoke: telemetry OK: schema', \
	          line['telemetry_schema_version'], 'spans', \
	          line['trace_spans'], sc); \
	    print('bench-smoke: observability OK: trace overhead', tov, \
	          'pct,', xp, 'cross-process trace(s),', \
	          line['trace_timeline_events'], 'timeline events,', pm, \
	          'prometheus metrics,', line['decisions_total'], \
	          'decisions'); \
	    print('bench-smoke: attribution OK:', \
	          {k: a[k] for k in sorted(a)}); \
	    print('bench-smoke: parse scaling OK:', curve, \
	          'workers =', line['parse_workers']); \
	    print('bench-smoke: block cache OK:', \
	          line['warm_epoch_mb_per_sec'], 'MB/s warm, speedup x', \
	          line['warm_vs_cold_speedup']); \
	    print('bench-smoke: batch parse OK:', \
	          line['native_batch_parse_mb_per_sec'], 'MB/s cold build,', \
	          'vs stream x', bvs, ', simd level', \
	          line.get('batch_parse_simd_level')); \
	    print('bench-smoke: shuffled warm OK:', \
	          line['shuffled_warm_epoch_mb_per_sec'], 'MB/s, overhead', \
	          line['shuffle_overhead_pct'], 'pct, seed', \
	          line.get('shuffle_seed')); \
	    print('bench-smoke: snapshot OK:', \
	          line['snapshot_warm_mb_per_sec'], 'MB/s warm, x', \
	          line['snapshot_vs_cache_speedup'], 'over cache warm,', \
	          'bf16 bytes ratio', line['snapshot_wire_bytes_ratio'], \
	          ', warm convert', conv, 's'); \
	    print('bench-smoke: device decode OK:', dd, 'MB/s warm, x', ddspd, \
	          'vs host-decode,', ddbytes, 'span bytes on', ddbk, \
	          'backend, convert', ddconv, 's'); \
	    print('bench-smoke: data service OK:', \
	          line['service_mb_per_sec'], 'MB/s with', \
	          line['service_workers'], 'workers, vs-local x', \
	          line['service_vs_local_speedup']); \
	    print('bench-smoke: multi-tenant OK:', line['service_jobs'], \
	          'jobs, shared_parse_ratio', spr, ',', fse, \
	          'fleet scale events'); \
	    print('bench-smoke: wire v2 OK:', line['service_wire_gbps'], \
	          'gbps at depth', line['service_pipeline_depth'], \
	          ', pipelined x', wspd, ', compression', wratio, \
	          ', fastpath', wfp, '/', wblocks, 'blocks'); \
	    print('bench-smoke: production QoS OK: critical wait frac', qwf, \
	          'under slo', qslo, ',', qthr, 'batch throttles,', \
	          line['service_qos_admission_waits'], 'admission waits,', \
	          qgu, 'giveups'); \
	    print('bench-smoke: autotune OK:', line['autotune_steps'], \
	          'steps,', line.get('autotune_adjustments'), \
	          'adjustments, converged', line.get('autotune_converged'), \
	          ', config', acfg); \
	    print('bench-smoke: artifact store OK:', line['store_bytes'], \
	          'managed bytes,', line['store_evictions'], 'evictions,', \
	          line['store_rebuilds_after_eviction'], \
	          'rebuilds after eviction'); \
	    print('bench-smoke: als training OK:', alsr, 'rows/s warm-fed,', \
	          'step', line['als_step_seconds'], 's, input wait frac', \
	          alsw, '(< 0.2 is the TPU-return bar), overlap', also)"

parse-bench:
	mkdir -p native/build
	g++ -O3 -std=c++17 -pthread -o native/build/parse_bench \
	    native/test/parse_bench.cc $(NATIVE_SRCS)
	@test -f native/build/bench_corpus.libsvm || $(PYTHON) -c "import random; \
	    r = random.Random(7); \
	    f = open('native/build/bench_corpus.libsvm', 'w'); \
	    [f.write(str(i % 2) + ' ' + ' '.join(f'{j}:{r.random():.6f}' \
	        for j in range(28)) + '\n') for i in range(40000)]"
	./native/build/parse_bench native/build/bench_corpus.libsvm 28 3

check:
	@echo "== make check $$(date -u +%Y-%m-%dT%H:%M:%SZ) ==" | tee CHECK.log
	@echo "-- lint-retry (ad-hoc retry loop gate) --" | tee -a CHECK.log
	$(MAKE) --no-print-directory lint-retry 2>&1 | tee -a CHECK.log
	@echo "-- lint-metrics (ad-hoc bookkeeping gate) --" | tee -a CHECK.log
	$(MAKE) --no-print-directory lint-metrics 2>&1 | tee -a CHECK.log
	@echo "-- lint-store (direct artifact-publish gate) --" | tee -a CHECK.log
	$(MAKE) --no-print-directory lint-store 2>&1 | tee -a CHECK.log
	@echo "-- pytest --" | tee -a CHECK.log
	$(PYTHON) -m pytest tests/ -q -m 'not slow' 2>&1 | tee -a CHECK.log
	@echo "-- native unit tests --" | tee -a CHECK.log
	$(MAKE) --no-print-directory native-test 2>&1 | tee -a CHECK.log
	@echo "-- sanitizers --" | tee -a CHECK.log
	sh native/run_sanitizers.sh 2>&1 | tee -a CHECK.log
	@echo "-- parse fuzz --" | tee -a CHECK.log
	$(PYTHON) native/test/fuzz_parse.py 2>&1 | tee -a CHECK.log
	@echo "-- parse bench --" | tee -a CHECK.log
	$(MAKE) --no-print-directory parse-bench 2>&1 | tee -a CHECK.log
	@echo "-- bench smoke (CPU backend + attribution contract) --" | tee -a CHECK.log
	$(MAKE) --no-print-directory bench-smoke 2>&1 | tee -a CHECK.log
	@echo "== make check: ALL GREEN ==" | tee -a CHECK.log
