"""Benchmark: RowBlockIter MB/s into HBM (the BASELINE.md north star).

Measures the full path on a HIGGS-like libsvm corpus:
  file -> InputSplit -> parser -> RowBlock -> fixed-shape dense batches ->
  jax.device_put -> HBM (consumer touches every batch on device).

Baseline (vs_baseline denominator): the same corpus through the
single-threaded host-only parse (no device), i.e. BASELINE.json config #1's
"single-host CPU reference". >1.0 means the async pipeline into HBM beats
host-only parsing.

Prints ONE JSON line on stdout; everything else goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
CORPUS = os.path.join(CACHE_DIR, "higgs_like.libsvm")
TARGET_MB = float(os.environ.get("DMLC_BENCH_MB", "64"))
NUM_COL = 28  # HIGGS has 28 features
BATCH = 8192


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_corpus() -> str:
    """Generate a HIGGS-like dense libsvm corpus once, cached on disk."""
    import numpy as np

    if os.path.exists(CORPUS) and os.path.getsize(CORPUS) >= TARGET_MB * 0.95 * 2**20:
        return CORPUS
    os.makedirs(CACHE_DIR, exist_ok=True)
    rng = np.random.default_rng(42)
    log(f"bench: generating ~{TARGET_MB:.0f} MB corpus at {CORPUS}")
    with open(CORPUS, "w") as f:
        written = 0
        target = TARGET_MB * 2**20
        while written < target:
            rows = []
            vals = rng.standard_normal((2000, NUM_COL)).astype(np.float32)
            labels = rng.integers(0, 2, 2000)
            for lbl, row in zip(labels, vals):
                feats = " ".join(f"{j}:{row[j]:.6f}" for j in range(NUM_COL))
                rows.append(f"{lbl} {feats}")
            chunk = "\n".join(rows) + "\n"
            f.write(chunk)
            written += len(chunk)
    return CORPUS


# 1MB chunks measured fastest for the async pipeline (fine-grained quanta
# interleave parse/convert/transfer best; larger chunks lump the stages and
# stall the device) and equal-or-better for the baseline
CHUNK_BYTES = 1 << 20
REPS = 3  # best-of, to tame shared-host + tunnel noise


def host_only_mb_per_sec(path: str, size_mb: float) -> float:
    """Single-threaded parse to RowBlocks on the host (the CPU reference)."""
    from dmlc_tpu.data import create_parser

    best = float("inf")
    for _ in range(REPS):
        parser = create_parser(path, 0, 1, "libsvm", threaded=False,
                               chunk_bytes=CHUNK_BYTES)
        t0 = time.monotonic()
        rows = 0
        for block in parser:
            rows += len(block)
        dt = time.monotonic() - t0
        parser.close()
        best = min(best, dt)
        log(f"bench: host-only parse {rows} rows in {dt:.2f}s = {size_mb/dt:.1f} MB/s")
    return size_mb / best


def into_hbm_mb_per_sec(path: str, size_mb: float, x_dtype: str = "float32"):
    """Full async pipeline into device HBM."""
    import jax

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter

    dev = jax.devices()[0]
    log(f"bench: device = {dev} (x_dtype={x_dtype})")
    # warm up the transfer path (backend init + first-DMA setup) so the timed
    # region measures the steady-state pipeline, matching the host-only
    # baseline which pays no device-init cost
    import numpy as np

    jax.block_until_ready(
        jax.device_put(np.zeros((BATCH, NUM_COL), np.float32), dev))
    best = 0.0
    stats = None
    for _ in range(REPS):
        t0 = time.monotonic()
        parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                               chunk_bytes=CHUNK_BYTES)
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", prefetch=4, convert_ahead=6,
                        x_dtype=x_dtype)
        # the FIRST pull carries pipeline spin-up (producer threads
        # starting, first chunk parsed) — a per-epoch constant. Its time
        # stays IN the throughput wall-clock (no free head start), but the
        # stall counters reset after it so the stall metric speaks to the
        # steady state, which is what "zero input-bound stalls" is about.
        nbatches = 1
        last = next(it)
        it.stall_seconds = 0.0
        it.host_stall_seconds = 0.0
        for batch in it:
            last = batch
            nbatches += 1
        # ensure all transfers have actually landed in HBM. device_put is
        # async, so stall_seconds (wait for a batch HANDLE) cannot see
        # transfers still in flight — this drain is that blind spot made
        # visible: the backlog of issued-but-unlanded transfers when the
        # consumer finishes pulling. Pipeline keeping up => ~one batch.
        t_drain = time.monotonic()
        if last is not None:
            jax.block_until_ready(last)
        drain = time.monotonic() - t_drain
        dt = time.monotonic() - t0
        mbps = size_mb / dt
        if mbps > best:
            best = mbps
            stats = it.stats()
        it.close()
        log(
            f"bench: into-HBM {nbatches} batches in {dt:.2f}s = "
            f"{mbps:.1f} MB/s, "
            f"device bytes {it.bytes_to_device/2**20:.1f} MB, "
            f"steady-state stall {it.stall_seconds:.3f}s = "
            f"{100*it.stall_seconds/dt:.1f}% of wall "
            f"(host {it.host_stall_seconds:.3f}s, "
            f"final transfer drain {drain:.3f}s)"
        )
    return best, stats


def main() -> None:
    path = make_corpus()
    size_mb = os.path.getsize(path) / 2**20
    log(f"bench: corpus {size_mb:.1f} MB")
    baseline = host_only_mb_per_sec(path, size_mb)
    value, _stats = into_hbm_mb_per_sec(path, size_mb)
    line = {
        "metric": "rowblockiter_mb_per_sec_into_hbm",
        "value": round(value, 2),
        "unit": "MB/s",
        "vs_baseline": round(value / baseline, 3),
    }
    # bf16 ingest: the C++ repack emits bfloat16 (the MXU's operand width),
    # halving host->HBM bytes — reported alongside, headline stays f32
    try:
        bf16_value, _ = into_hbm_mb_per_sec(path, size_mb, x_dtype="bfloat16")
        line["bf16_mb_per_sec"] = round(bf16_value, 2)
        line["bf16_vs_baseline"] = round(bf16_value / baseline, 3)
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: bf16 leg failed: {exc}")
    print(json.dumps(line))


if __name__ == "__main__":
    main()
