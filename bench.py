"""Benchmark: RowBlockIter MB/s into HBM (the BASELINE.md north star).

Measures the full path on a HIGGS-like libsvm corpus:
  file -> InputSplit -> parser -> RowBlock -> fixed-shape dense batches ->
  jax.device_put -> HBM (consumer touches every batch on device).

Baseline (vs_baseline denominator): the same corpus through the
single-threaded host-only parse (no device), i.e. BASELINE.json config #1's
"single-host CPU reference". >1.0 means the async pipeline into HBM beats
host-only parsing.

Prints ONE JSON line on stdout; everything else goes to stderr.

Infra resilience: the TPU tunnel on this host flakes transiently (r3's
driver run died on one unguarded backend init). The measurement therefore
runs in a CHILD process under a supervisor that (a) retries the whole run
in a fresh process when it fails on a backend/transport error, probing the
device between attempts until it recovers, and (b) on persistent
unavailability still prints a machine-readable JSON line
({"infra": "tpu_unavailable", ...}, exit code 3) instead of a traceback —
the reference's harness always yields a parseable record
(/root/reference/src/data/basic_row_iter.h:68-81 logs unconditionally;
/root/reference/tracker/dmlc_tracker/local.py:26-49 retries failed workers).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
CORPUS = os.path.join(CACHE_DIR, "higgs_like.libsvm")
TARGET_MB = float(os.environ.get("DMLC_BENCH_MB", "64"))
NUM_COL = 28  # HIGGS has 28 features
# per-put overhead on a tunneled device is material (~1.1 ms/batch): a
# larger batch amortizes it at the cost of coarser overlap — tunable for
# A/B without editing (the framework, not the workload, picks batch size).
# Default 16384 (1.8 MB dense puts): halves the dispatch count vs 8192;
# measured +3-4% at GB scale on the CPU backend (r5), and the dispatch
# share this amortizes is several-fold larger on the tunneled device
BATCH = int(os.environ.get("DMLC_BENCH_BATCH", "16384"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_corpus() -> str:
    """Generate a HIGGS-like dense libsvm corpus once, cached on disk."""
    import numpy as np

    if os.path.exists(CORPUS) and os.path.getsize(CORPUS) >= TARGET_MB * 0.95 * 2**20:
        return CORPUS
    os.makedirs(CACHE_DIR, exist_ok=True)
    rng = np.random.default_rng(42)
    log(f"bench: generating ~{TARGET_MB:.0f} MB corpus at {CORPUS}")
    with open(CORPUS, "w") as f:
        written = 0
        target = TARGET_MB * 2**20
        while written < target:
            rows = []
            vals = rng.standard_normal((2000, NUM_COL)).astype(np.float32)
            labels = rng.integers(0, 2, 2000)
            for lbl, row in zip(labels, vals):
                feats = " ".join(f"{j}:{row[j]:.6f}" for j in range(NUM_COL))
                rows.append(f"{lbl} {feats}")
            chunk = "\n".join(rows) + "\n"
            f.write(chunk)
            written += len(chunk)
    return CORPUS


# 1MB chunks measured fastest for the async pipeline (fine-grained quanta
# interleave parse/convert/transfer best; larger chunks lump the stages and
# stall the device) and equal-or-better for the baseline
CHUNK_BYTES = 1 << 20
# best-of/median-of rep count, to tame shared-host + tunnel noise. The
# tunnel's line rate swings 2-4x minute-to-minute, so a 3-rep median can
# sit entirely inside one bad window; 5 reps cost ~+20s at GB scale and
# make the median robust to two outliers. Overridable for quick smokes.
REPS = max(1, int(os.environ.get("DMLC_BENCH_REPS", "5") or 5))


from statistics import median as _median  # noqa: E402


def host_only_mb_per_sec(path: str, size_mb: float, threaded: bool = False,
                         emit_dense: bool = False):
    """Host-only parse (threaded=False: the single-thread CPU reference;
    threaded=True + emit_dense: the PIPELINE'S parse ceiling — the exact
    native dense-emit path the device leg runs, minus the device_put, so
    the binding-bound comparison is like-for-like; a CSR-emitting ceiling
    under-reads it and can even sit below the pipeline itself).

    Returns (best, median) MB/s over REPS runs — ambient host speed swings
    2-4x on this shared machine, so both statistics are recorded.
    """
    from dmlc_tpu.data import create_parser

    rates = []
    for _ in range(REPS):
        parser = create_parser(path, 0, 1, "libsvm", threaded=threaded,
                               chunk_bytes=CHUNK_BYTES)
        if emit_dense and hasattr(parser, "set_emit_dense"):
            # pack_aux matches the device leg's config so this ceiling
            # measures the exact same native repack work
            try:
                parser.set_emit_dense(NUM_COL, batch_rows=BATCH,
                                      pack_aux=True)
            except TypeError:
                parser.set_emit_dense(NUM_COL)
        t0 = time.monotonic()
        rows = 0
        for block in parser:
            rows += len(block)
        dt = time.monotonic() - t0
        parser.close()
        rates.append(size_mb / dt)
        log(f"bench: host-only parse ({'threaded' if threaded else '1-thread'}"
            f"{', dense-emit' if emit_dense else ''})"
            f" {rows} rows in {dt:.2f}s = {size_mb/dt:.1f} MB/s")
    return max(rates), _median(rates)


def parse_fanout_mb_per_sec(path: str, size_mb: float, workers: int) -> float:
    """One drain of the PYTHON-ENGINE parse path at a given fan-out width
    (``parse_workers=1`` is the single-producer parse-ahead thread — the
    pre-fan-out engine; >1 is the ParallelTextParser pool over the
    zero-copy mmap chunk source). ``engine=python`` pins the route so the
    curve measures the fan-out, not the native reader (which keeps its own
    C++ threading and ignores the knob)."""
    from dmlc_tpu.data import create_parser

    parser = create_parser(path + "?engine=python", 0, 1, "libsvm",
                           threaded=True, parse_workers=workers,
                           chunk_bytes=CHUNK_BYTES)
    try:
        t0 = time.monotonic()
        rows = 0
        while (block := parser.next_block()) is not None:
            rows += len(block)
        dt = time.monotonic() - t0
    finally:
        parser.close()  # a mid-drain error must not leak the worker pool
    log(f"bench: parse fan-out workers={workers} {rows} rows in {dt:.2f}s "
        f"= {size_mb/dt:.1f} MB/s")
    return size_mb / dt


def parse_scaling_curve(path: str, size_mb: float, workers=(1, 2, 4)):
    """Host-only parse ceiling at each fan-out width, INTERLEAVED across
    reps so this host's 2-4x ambient swings hit every width evenly —
    the scaling ratio is the stable quantity, not the absolutes. Returns
    {workers: (best, median)}."""
    rates = {w: [] for w in workers}
    for _ in range(REPS):
        for w in workers:
            rates[w].append(parse_fanout_mb_per_sec(path, size_mb, w))
    return {w: (max(v), _median(v)) for w, v in rates.items()}


def into_hbm_mb_per_sec(path: str, size_mb: float, x_dtype: str = "float32"):
    """Full async pipeline into device HBM."""
    import jax

    _bench_common().pin_platform()

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter

    dev = jax.devices()[0]
    log(f"bench: device = {dev} (x_dtype={x_dtype})")
    # warm up the transfer path (backend init + first-DMA setup) so the timed
    # region measures the steady-state pipeline, matching the host-only
    # baseline which pays no device-init cost
    import numpy as np

    jax.block_until_ready(
        jax.device_put(np.zeros((BATCH, NUM_COL), np.float32), dev))
    rates = []
    dev_rates = []  # device-side MB/s (bytes_to_device / wall) for the
    # line-rate join: comparable to the raw device_put floor, unlike the
    # corpus MB/s headline whose bytes differ from wire bytes
    best = 0.0
    attribution = None  # per-stage table of the best rep (steady state)
    resilience = None  # retry/resume/restart counters of the best rep
    parallel = None  # parse fan-out sideband of the best rep
    for _ in range(REPS):
        t0 = time.monotonic()
        parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                               chunk_bytes=CHUNK_BYTES)
        # pack_aux: label/weight ride as two trailing x columns — ONE
        # device_put per batch instead of three arrays (the 3-array put
        # measured ~2x slower per byte, bench_transfer_floor.py aux leg).
        # f32 packs automatically (lossless); the bf16 opt-in is sound
        # HERE because this corpus's labels (0/1) and weights (1.0) are
        # bf16-exact — general callers must make that call themselves.
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", prefetch=4, convert_ahead=6,
                        x_dtype=x_dtype, pack_aux=True)
        # the FIRST pull carries pipeline spin-up (producer threads
        # starting, first chunk parsed) — a per-epoch constant. Its time
        # stays IN the throughput wall-clock (no free head start), but the
        # stall counters reset after it so the stall metric speaks to the
        # steady state, which is what "zero input-bound stalls" is about.
        nbatches = 1
        last = next(it)
        it.stall_seconds = 0.0
        it.host_stall_seconds = 0.0
        for batch in it:
            last = batch
            nbatches += 1
        # ensure all transfers have actually landed in HBM. device_put is
        # async, so stall_seconds (wait for a batch HANDLE) cannot see
        # transfers still in flight — this drain is that blind spot made
        # visible: the backlog of issued-but-unlanded transfers when the
        # consumer finishes pulling. Pipeline keeping up => ~one batch.
        t_drain = time.monotonic()
        if last is not None:
            jax.block_until_ready(last)
        drain = time.monotonic() - t_drain
        dt = time.monotonic() - t0
        mbps = size_mb / dt
        rates.append(mbps)
        dev_rates.append(it.bytes_to_device / 2**20 / dt)
        if mbps > best:
            best = mbps
            # stage attribution of the winning rep, with the final drain
            # folded into the transfer stage (the sampled sideband only
            # sees every Nth batch; the drain is the end-of-epoch residue)
            stats = it.stats()
            attribution = _bench_common().attribution_line(
                stats, extra_transfer=drain)
            resilience = stats.get("resilience")
            parallel = {
                "parse_workers": stats.get("parse_workers"),
                "parse_parallelism_efficiency":
                    stats.get("parse_parallelism_efficiency"),
                # the trustworthy input-bound counter (ISSUE 10 satellite:
                # handle waits + sampled transfer landings — nonzero on a
                # transfer-bound epoch even when stall_seconds reads 0)
                "input_wait_seconds": round(
                    stats.get("input_wait_seconds") or 0.0, 4),
            }
        it.close()
        log(
            f"bench: into-HBM {nbatches} batches in {dt:.2f}s = "
            f"{mbps:.1f} MB/s, "
            f"device bytes {it.bytes_to_device/2**20:.1f} MB, "
            f"steady-state stall {it.stall_seconds:.3f}s = "
            f"{100*it.stall_seconds/dt:.1f}% of wall "
            f"(host {it.host_stall_seconds:.3f}s, "
            f"final transfer drain {drain:.3f}s)"
        )
    return (best, _median(rates), (min(rates), max(rates)), attribution,
            (max(dev_rates), _median(dev_rates)), resilience, parallel)


def block_cache_epoch_pair(path: str, size_mb: float):
    """Cold+warm epoch pair through the parse-once block cache (ISSUE 5).

    Epoch 1 (cold): parse + shadow-write the columnar block cache while
    feeding HBM. Epoch 2 (warm): the same DeviceIter, re-armed by reset(),
    now streams mmap'd parsed RowBlocks — the parser is bypassed, so warm
    MB/s above the measured parse ceiling is structural proof the cache
    works (the acceptance bar: warm_vs_cold_speedup >= 2 on a quiet host).
    A third leg (ISSUE 8) re-opens the published cache with the epoch
    planner armed (``shuffle_seed=``) and times one PLAN-ORDERED warm
    epoch — seeded block permutation + windowed row shuffle — so the JSON
    line carries ``shuffled_warm_epoch_mb_per_sec`` and
    ``shuffle_overhead_pct`` (the price of shuffling vs sequential warm;
    the acceptance bar: within 20% — make bench-smoke gates the fields).

    Returns (cold_mb_per_sec, warm_mb_per_sec, warm_cache_state,
    warm_cache_read_seconds, shuffled_mb_per_sec, shuffled_stats).
    """
    import jax

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter

    cache = CORPUS + ".blockcache"
    for stale in (cache, cache + ".tmp"):
        try:
            os.remove(stale)
        except OSError:
            pass

    def one_epoch(it):
        t0 = time.monotonic()
        last = None
        nb = 0
        for batch in it:
            last = batch
            nb += 1
        if last is not None:
            jax.block_until_ready(last)
        return nb, time.monotonic() - t0

    # the cold epoch runs the NEW chunk-batch engine (ISSUE 14): parse
    # emits block-cache segment spans natively, the tee appends them with
    # zero re-encode (falls back loudly to the Python engine on a
    # toolchain-less host — the pair still measures)
    parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                           chunk_bytes=CHUNK_BYTES, block_cache=cache,
                           engine="native-batch")
    it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                    layout="dense", prefetch=4, convert_ahead=6,
                    pack_aux=True)
    rates = {}
    warm_stats = None
    warm_cache_read = 0.0
    shuffled = None
    shuffled_stats = None
    it_shuf = None
    try:
        nb, dt = one_epoch(it)
        rates["cold"] = size_mb / dt
        stats = it.stats()
        cr_prev = stats["stages"].get("cache_read", 0.0)
        log(f"bench: block-cache cold epoch {nb} batches in {dt:.2f}s = "
            f"{size_mb/dt:.1f} MB/s (cache_state={stats['cache_state']})")
        it.reset()  # flips the source to the published warm cache
        # shuffled-warm pipeline on the SAME published cache: a
        # warm-at-construction pipeline serves its first pass in plan
        # order (docs/data.md). Sequential and shuffled warm epochs run
        # INTERLEAVED, best-of-2 each, so this host's 2-4x ambient swings
        # hit both legs evenly and the overhead ratio is the stable
        # quantity (same trick as the parse scaling curve).
        sparser = create_parser(path, 0, 1, "libsvm", threaded=True,
                                chunk_bytes=CHUNK_BYTES, block_cache=cache,
                                shuffle_seed=1234, shuffle_window=BATCH)
        it_shuf = DeviceIter(sparser, num_col=NUM_COL, batch_size=BATCH,
                             layout="dense", prefetch=4, convert_ahead=6,
                             pack_aux=True)
        scr_prev = 0.0
        pair_ratios = []
        for _round in range(3):
            nb, dt = one_epoch(it)
            seq_rate = size_mb / dt
            rates["warm"] = max(rates.get("warm", 0.0), seq_rate)
            warm_stats = it.stats()
            # stage counters are registry-backed and CUMULATIVE across
            # reset(): report each epoch's own cache_read delta, not the
            # running sum over both warm epochs
            cr_now = warm_stats["stages"].get("cache_read", 0.0)
            warm_cache_read, cr_prev = cr_now - cr_prev, cr_now
            log(f"bench: block-cache warm epoch {nb} batches in "
                f"{dt:.2f}s = {seq_rate:.1f} MB/s "
                f"(cache_state={warm_stats['cache_state']}, "
                f"cache_read={warm_cache_read:.3f}s)")
            it.reset()
            nb, dt = one_epoch(it_shuf)
            shuf_rate = size_mb / dt
            shuffled = max(shuffled or 0.0, shuf_rate)
            # the overhead estimate pairs ADJACENT epochs (they share the
            # ambient window): the best round's ratio is the structural
            # cost, not the noise floor
            pair_ratios.append(shuf_rate / seq_rate)
            shuffled_stats = it_shuf.stats()
            scr_now = shuffled_stats["stages"].get("cache_read", 0.0)
            scr_epoch, scr_prev = scr_now - scr_prev, scr_now
            log(f"bench: block-cache SHUFFLED warm epoch {nb} batches in "
                f"{dt:.2f}s = {shuf_rate:.1f} MB/s "
                f"(shuffle_seed={shuffled_stats['shuffle_seed']}, "
                f"epoch={shuffled_stats['epoch']}, "
                f"cache_read={scr_epoch:.3f}s, "
                f"round ratio {shuf_rate/seq_rate:.3f})")
            it_shuf.reset()
        shuffled_stats = dict(shuffled_stats,
                              pair_ratio=max(pair_ratios))
    finally:
        it.close()
        if it_shuf is not None:
            it_shuf.close()
        for leftover in (cache, cache + ".tmp"):
            try:
                os.remove(leftover)  # the pair must start cold every run
            except OSError:
                pass
    return (rates["cold"], rates["warm"], warm_stats["cache_state"],
            warm_cache_read, shuffled, shuffled_stats)


def batch_parse_leg(path: str, size_mb: float, rounds: int = 3):
    """Cold-path chunk-batch parse leg (ISSUE 14): the full cold
    cache-build — parse + DMLCBC01 tee + publish — through the new
    ``native-batch`` engine (SIMD chunk scan, segments materialized
    natively, zero Python re-encode) vs the pre-PR cold path (the
    streaming native reader's RowBlocks re-encoded per block by the
    Python writer). Both builds produce byte-identical caches (the
    parity suite pins that), so the ratio isolates the engine.

    The two builds run INTERLEAVED per round and the reported speedup is
    the best ROUND-PAIRED ratio — this host's 2-4x ambient swings hit
    both legs of a pair evenly, so the ratio is the stable quantity
    (same trick as the shuffle-overhead and parse-scaling legs).
    """
    from dmlc_tpu import native as _native
    from dmlc_tpu.data import create_parser

    # keyed by the measured corpus; the writer stages through the store's
    # process-unique tmp names, so a torn build never leaves `cache`
    cache = path + ".batchleg.blockcache"

    def cold_build(engine):
        try:
            os.remove(cache)
        except OSError:
            pass
        parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                               chunk_bytes=CHUNK_BYTES, engine=engine,
                               block_cache=cache)
        try:
            t0 = time.monotonic()
            while parser.next_block() is not None:
                pass
            dt = time.monotonic() - t0
        finally:
            parser.close()
            try:
                os.remove(cache)
            except OSError:
                pass
        return size_mb / dt

    best_batch = best_stream = 0.0
    ratios = []
    for _round in range(max(2, rounds)):
        stream = cold_build("auto")
        batch = cold_build("native-batch")
        best_stream = max(best_stream, stream)
        best_batch = max(best_batch, batch)
        ratios.append(batch / stream)
        log(f"bench: cold cache-build round {_round}: native-batch "
            f"{batch:.1f} MB/s vs stream {stream:.1f} MB/s "
            f"(ratio {batch/stream:.3f})")
    out = {
        "native_batch_parse_mb_per_sec": round(best_batch, 2),
        "stream_cold_build_mb_per_sec": round(best_stream, 2),
        "batch_vs_stream_parse_speedup": round(max(ratios), 3),
        "batch_parse_simd_level": _native.simd_level(),
    }
    log(f"bench: native-batch cold build {best_batch:.1f} MB/s, "
        f"best paired speedup x{max(ratios):.2f}, simd level "
        f"{out['batch_parse_simd_level']}")
    return out


def snapshot_epoch_leg(path: str, size_mb: float):
    """Device-native snapshot store leg (ISSUE 9 tentpole): epoch 1
    parses + converts while shadow-writing the post-convert packed
    batches (``DMLCSN01``); warm epochs then mmap those batches straight
    into ``device_put`` with ZERO host convert work. The structural
    claims the JSON line carries:

    - ``snapshot_warm_mb_per_sec`` above the parse ceiling
      (``snapshot_vs_parse_ceiling > 1``) proves the parser AND the
      convert stage are bypassed, not merely overlapped;
    - ``snapshot_warm_convert_seconds`` ~ 0 with a nonzero
      ``snapshot_read_seconds`` is the stats()-level proof;
    - ``snapshot_wire_bytes_ratio`` (bf16 snapshot file bytes / f32)
      <= 0.55 shows reduced precision halves stored AND wire bytes.

    Returns the field dict to merge into the JSON line.
    """
    import jax

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter

    snap = CORPUS + ".snapshot"
    snap16 = CORPUS + ".bf16.snapshot"
    for stale in (snap, snap + ".tmp", snap16, snap16 + ".tmp"):
        try:
            os.remove(stale)
        except OSError:
            pass

    def one_epoch(it):
        t0 = time.monotonic()
        last = None
        nb = 0
        for batch in it:
            last = batch
            nb += 1
        if last is not None:
            jax.block_until_ready(last)
        return nb, time.monotonic() - t0

    out = {}
    it = it16 = None
    try:
        parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                               chunk_bytes=CHUNK_BYTES, snapshot=snap)
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", prefetch=4, convert_ahead=6,
                        pack_aux=True)
        nb, dt = one_epoch(it)
        stats = it.stats()
        log(f"bench: snapshot cold epoch {nb} batches in {dt:.2f}s = "
            f"{size_mb/dt:.1f} MB/s "
            f"(snapshot_state={stats['snapshot_state']})")
        warm = 0.0
        conv_prev = stats["stage_busy"].get("convert", 0.0)
        sr_prev = stats["stage_busy"].get("snapshot_read", 0.0)
        for _round in range(2):
            it.reset()
            nb, dt = one_epoch(it)
            warm = max(warm, size_mb / dt)
            stats = it.stats()
            # registry counters are cumulative across reset(): report the
            # epoch's own deltas, not the running sum
            conv_now = stats["stage_busy"].get("convert", 0.0)
            sr_now = stats["stage_busy"].get("snapshot_read", 0.0)
            conv_epoch, conv_prev = conv_now - conv_prev, conv_now
            sr_epoch, sr_prev = sr_now - sr_prev, sr_now
            log(f"bench: snapshot WARM epoch {nb} batches in {dt:.2f}s = "
                f"{size_mb/dt:.1f} MB/s "
                f"(snapshot_state={stats['snapshot_state']}, "
                f"convert={conv_epoch:.4f}s, "
                f"snapshot_read={sr_epoch:.4f}s)")
        out["snapshot_warm_mb_per_sec"] = round(warm, 2)
        out["snapshot_state"] = stats["snapshot_state"]
        out["snapshot_warm_convert_seconds"] = round(max(0.0, conv_epoch), 4)
        out["snapshot_read_seconds"] = round(max(0.0, sr_epoch), 4)
        # bf16 snapshot: one cold epoch through the bf16 pipeline writes
        # the half-width store — the file-size ratio IS the stored/wire
        # byte claim (the service ships the same segment encoding)
        parser16 = create_parser(path, 0, 1, "libsvm", threaded=True,
                                 chunk_bytes=CHUNK_BYTES, snapshot=snap16)
        it16 = DeviceIter(parser16, num_col=NUM_COL, batch_size=BATCH,
                          layout="dense", prefetch=4, convert_ahead=6,
                          x_dtype="bfloat16", pack_aux=True)
        one_epoch(it16)
        if os.path.exists(snap) and os.path.exists(snap16):
            ratio = os.path.getsize(snap16) / os.path.getsize(snap)
            out["snapshot_wire_bytes_ratio"] = round(ratio, 3)
            log(f"bench: snapshot bytes f32 "
                f"{os.path.getsize(snap)/2**20:.1f} MB, bf16 "
                f"{os.path.getsize(snap16)/2**20:.1f} MB -> ratio "
                f"{ratio:.3f}")
    finally:
        if it is not None:
            it.close()
        if it16 is not None:
            it16.close()
        for leftover in (snap, snap + ".tmp", snap16, snap16 + ".tmp"):
            try:
                os.remove(leftover)  # the leg must start cold every run
            except OSError:
                pass
    return out


def device_decode_leg(path: str, size_mb: float):
    """Device-side decode leg (ISSUE 18 tentpole): warm snapshot epochs
    with ``device_decode=True`` ship each batch's verbatim container
    span as ONE contiguous u8 transfer and decode it in HBM
    (``ops/device_decode``) — vs the host-decode warm tier, which builds
    numpy views over the mmap before ``device_put``. The JSON claims:

    - ``device_decode_mb_per_sec``: best warm epoch in span mode;
    - ``device_decode_vs_snapshot_speedup``: best ROUND-PAIRED ratio vs
      the host-decode warm epoch (alternating order cancels drift). On a
      real accelerator this is the decode-offload win and bench-smoke
      gates it >= 1.0; on the CPU backend "device" decode runs on the
      same silicon as the host path, so only field presence is gated —
      ``device_decode_backend`` says which case this run was;
    - ``device_decode_transfer_bytes``: verbatim span bytes of one warm
      epoch (the single-transfer contract: > 0 proves spans shipped);
    - ``device_decode_convert_seconds``: host convert busy in span mode,
      ~0 by construction (the zero-host-decode claim at stats() level).
    """
    import jax

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter

    snap = CORPUS + ".dd.snapshot"
    for stale in (snap, snap + ".tmp"):
        try:
            os.remove(stale)
        except OSError:
            pass

    def one_epoch(it):
        t0 = time.monotonic()
        last = None
        nb = 0
        for batch in it:
            last = batch
            nb += 1
        if last is not None:
            jax.block_until_ready(last)
        return nb, time.monotonic() - t0

    def make(dd):
        parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                               chunk_bytes=CHUNK_BYTES, snapshot=snap)
        return DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                          layout="dense", prefetch=4, convert_ahead=6,
                          pack_aux=True, device_decode=dd)

    out = {}
    it_cold = it_h = it_d = None
    try:
        it_cold = make(False)
        nb, dt = one_epoch(it_cold)  # cold pass publishes the snapshot
        it_cold.close()
        it_cold = None
        log(f"bench: device-decode leg cold publish {nb} batches in "
            f"{dt:.2f}s")
        it_h, it_d = make(False), make(True)
        started = set()
        best_host = best_dev = best_ratio = 0.0
        conv_prev = dd_bytes_prev = 0.0
        for rnd in range(2):
            pairs = [("host", it_h), ("device", it_d)]
            if rnd % 2:
                pairs.reverse()  # rotate order so ambient drift cancels
            mbps = {}
            for name, it_ in pairs:
                if id(it_) in started:
                    it_.reset()
                started.add(id(it_))
                nb, dt = one_epoch(it_)
                mbps[name] = size_mb / dt
            best_host = max(best_host, mbps["host"])
            best_dev = max(best_dev, mbps["device"])
            best_ratio = max(best_ratio, mbps["device"] / mbps["host"])
            stats = it_d.stats()
            # cumulative across reset(): report per-epoch deltas
            conv_now = stats["stage_busy"].get("convert", 0.0)
            dd_now = float(stats["device_decode_bytes"])
            conv_epoch, conv_prev = conv_now - conv_prev, conv_now
            dd_bytes, dd_bytes_prev = dd_now - dd_bytes_prev, dd_now
            log(f"bench: device-decode warm round {rnd}: span "
                f"{mbps['device']:.1f} MB/s vs host-decode "
                f"{mbps['host']:.1f} MB/s (ratio "
                f"{mbps['device']/mbps['host']:.3f}, "
                f"span bytes {dd_bytes/2**20:.1f} MB, "
                f"convert {conv_epoch:.4f}s)")
        check_stats = it_d.stats()
        assert check_stats["snapshot_state"] == "warm", "leg never warmed"
        out["device_decode_mb_per_sec"] = round(best_dev, 2)
        out["device_decode_vs_snapshot_speedup"] = round(best_ratio, 3)
        out["device_decode_transfer_bytes"] = int(dd_bytes)
        out["device_decode_convert_seconds"] = round(max(0.0, conv_epoch), 4)
        out["device_decode_backend"] = jax.devices()[0].platform
        log(f"bench: device-decode warm {best_dev:.1f} MB/s = "
            f"x{best_ratio:.2f} over host-decode warm "
            f"({out['device_decode_backend']} backend)")
    finally:
        for it_ in (it_cold, it_h, it_d):
            if it_ is not None:
                it_.close()
        for leftover in (snap, snap + ".tmp"):
            try:
                os.remove(leftover)  # the leg must start cold every run
            except OSError:
                pass
    return out


def service_leg(path: str, size_mb: float, workers: int = 2):
    """Disaggregated data-service leg (``--service`` / ISSUE 7): a
    localhost 1-dispatcher + N-worker fleet parses the corpus's N
    partitions in parallel and streams the frames to one client, timed
    against the same partitions parsed serially on this host with the
    identical config. ``service_vs_local_speedup > 1`` means the fleet's
    parallel parse beats the single-host serial pass even after paying
    the frame encode + loopback TCP + decode tax — the disaggregation
    claim at smoke scale (arXiv:2210.14826). Also emits the
    control-plane resilience quartet (``dispatcher_restarts`` /
    ``worker_reregistrations`` / ``parts_reclaimed`` /
    ``control_plane_retries``, docs/service.md control-plane recovery)
    AND the elastic-membership sextet (``worker_drains`` /
    ``drain_handoffs`` / ``preemption_notices`` /
    ``speculative_reissues`` / ``speculative_wins`` / ``worker_joins``,
    docs/service.md elastic membership): all ten MUST read zero on a
    clean run — a nonzero value on healthy infrastructure means the
    control plane restarted, a worker was preempted/hedged, or the fleet
    churned mid-bench, any of which taints the throughput numbers.

    The **two-job multi-tenant leg** (ISSUE 15, docs/service.md
    multi-tenant service) then registers the SAME corpus twice on one
    fleet with share-by-signature armed and a knob-paced fleet
    autoscaler attached: job A parses and publishes the shared block
    caches, job B's parts all resolve to the published artifacts --
    ``shared_parse_ratio`` (parses avoided / parts supplied) is 0.5 by
    construction for the identical-corpus pair, gated ``>= 0.5`` by
    ``make bench-smoke``. ``service_jobs`` counts the tenants and
    ``fleet_scale_events`` the autoscaler's scale decisions -- which
    must be ZERO on a clean run (no flapping: a fast healthy smoke run
    gives the controller no sustained starvation to react to)."""
    import tempfile

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.io import resilience as _resilience
    from dmlc_tpu.service import DEFAULT_JOB, LocalFleet, ServiceParser
    from dmlc_tpu.utils import telemetry as _telemetry

    num_parts = workers
    cfg = {"format": "libsvm", "chunk_bytes": CHUNK_BYTES}
    t0 = time.monotonic()
    rows = 0
    for p in range(num_parts):
        parser = create_parser(path, p, num_parts, "libsvm",
                               chunk_bytes=CHUNK_BYTES)
        while parser.next_block() is not None:
            rows += 1
        parser.close()
    local_dt = time.monotonic() - t0
    res_base = _resilience.counters_snapshot()
    # fleet construction is inside the timed region: the workers' parallel
    # parse IS the work being measured, not a warm pre-parse
    t0 = time.monotonic()
    fleet = LocalFleet(path, num_parts, num_workers=workers, parser=cfg)
    client = None
    try:
        client = ServiceParser(fleet.address)
        sblocks = 0
        while client.next_block() is not None:
            sblocks += 1
        service_dt = time.monotonic() - t0
        # merged pod timeline + cross-process trace count (docs/
        # observability.md Distributed tracing): export ONE Chrome/
        # Perfetto JSON for the whole fleet (kept when
        # DMLC_BENCH_TRACE_PATH names a destination), and count the
        # (job, part) traces whose spans link the worker-side
        # encode/send to the client-side recv/decode — the one-trace-
        # per-part acceptance signal bench-smoke gates >= 1
        keep = os.environ.get("DMLC_BENCH_TRACE_PATH", "")
        trace_path = keep or os.path.join(
            tempfile.gettempdir(), f"dmlc-bench-trace-{os.getpid()}.json")
        timeline_events = fleet.dump_trace(trace_path)
        if not keep:
            try:
                os.remove(trace_path)
            except OSError:
                pass
        worker_side = {"service_parse", "service_encode", "service_send"}
        client_side = {"service_recv", "service_decode"}
        by_tid: dict = {}
        for s in _telemetry.spans_snapshot():
            t = s.get("trace_id")
            if t:
                by_tid.setdefault(t, set()).add(s["name"])
        crossproc = sum(1 for names in by_tid.values()
                        if names & worker_side and names & client_side)
    finally:
        if client is not None:
            client.close()
        fleet.close()
    res = _resilience.counters_delta(res_base)
    log(f"bench: service {workers}-worker fleet {sblocks} blocks in "
        f"{service_dt:.2f}s = {size_mb/service_dt:.1f} MB/s vs local "
        f"serial {size_mb/local_dt:.1f} MB/s -> speedup "
        f"x{local_dt/service_dt:.2f} (control plane: "
        f"{res['dispatcher_restarts']} restarts, "
        f"{res['control_plane_retries']} retries; {crossproc} cross-"
        f"process trace(s), {timeline_events} timeline events)")
    # ---- two-job multi-tenant leg (docstring): same corpus, two jobs,
    # share-by-signature, knob-paced autoscaler attached for the ride
    tenant = "tenant-b"
    res2_base = _resilience.counters_snapshot()
    with tempfile.TemporaryDirectory(prefix="dmlc-svc-share-") as share:
        fleet = LocalFleet(path, num_parts, num_workers=workers,
                           parser=cfg, share_dir=share)
        scaler = None
        client = None
        try:
            # the autoscaler rides along on the clients' job-labeled
            # wait counters; a clean smoke run must produce ZERO scale
            # decisions (the fleet_scale_events == 0 gate)
            scaler = fleet.autoscale(
                source=lambda: {
                    j: _telemetry.REGISTRY.sum(
                        _telemetry.SERVICE_JOB_WAIT_METRIC, job=j)
                    for j in (DEFAULT_JOB, tenant)},
                start=True)
            client = ServiceParser(fleet.address)
            jobs_blocks = 0
            while client.next_block() is not None:
                jobs_blocks += 1
            client.close()
            # register the tenant AFTER job A published: its parts must
            # all resolve to the shared artifacts (parse-once)
            fleet.register_job(tenant, path, num_parts, parser=cfg)
            client = ServiceParser(fleet.address, job=tenant)
            tenant_blocks = 0
            while client.next_block() is not None:
                tenant_blocks += 1
        finally:
            if client is not None:
                client.close()
            if scaler is not None:
                scaler.close()
            fleet.close()
    res2 = _resilience.counters_delta(res2_base)
    parsed = res2["service_parts_parsed"]
    shared = res2["service_parts_shared"]
    shared_ratio = shared / max(1, parsed + shared)
    scale_events = res2["fleet_scale_ups"] + res2["fleet_scale_downs"]
    log(f"bench: service two-job leg: {jobs_blocks}+{tenant_blocks} "
        f"blocks, {parsed} parts parsed / {shared} shared -> "
        f"shared_parse_ratio {shared_ratio:.3f}, "
        f"{scale_events} fleet scale events")
    return {
        "service_workers": workers,
        "service_mb_per_sec": round(size_mb / service_dt, 2),
        "service_vs_local_speedup": round(local_dt / service_dt, 3),
        "dispatcher_restarts": res["dispatcher_restarts"],
        "worker_reregistrations": res["worker_reregistrations"],
        "parts_reclaimed": res["parts_reclaimed"],
        "control_plane_retries": res["control_plane_retries"],
        "worker_drains": res["worker_drains"],
        "drain_handoffs": res["drain_handoffs"],
        "preemption_notices": res["preemption_notices"],
        "speculative_reissues": res["speculative_reissues"],
        "speculative_wins": res["speculative_wins"],
        "worker_joins": res["worker_joins"],
        "service_jobs": 2,
        "shared_parse_ratio": round(shared_ratio, 3),
        "fleet_scale_events": scale_events,
        "trace_spans_crossproc": crossproc,
        "trace_timeline_events": timeline_events,
    }


def service_wire_leg(path: str, size_mb: float, workers: int = 2):
    """Wire v2 transport leg (``--service`` / ISSUE 16, docs/service.md
    Wire v2): measures the three transport optimisations separately.

    **Pipelining.** A warm fleet (cold pass untimed) streams the corpus
    over TCP at pipeline depth 1 (strict request/response — the
    one-request-per-frame baseline) and at the configured
    ``service_pipeline_depth``, interleaved, median of 5 each.
    ``service_wire_pipelined_speedup`` carries the ratio; the ``make
    bench-smoke`` gate is ``>= 0.85`` — a no-regression guard with a
    measurement-noise floor, because loopback RTT is microseconds
    against a ~100us/block decode (the window's win is proportional to
    real network latency, which a single-host smoke cannot manufacture;
    keeping the window full must never LOSE to lock-step).

    **Compression.** The worker-side byte ledger
    (``service_wire_bytes_sent / service_wire_bytes_raw``) over the
    timed streams yields ``service_wire_compression_ratio`` — gated
    ``<= 1.0`` because the per-dtype break-even check refuses codecs
    that inflate (f32 value segments ship raw; int offset/index
    segments compress). ``service_wire_gbps`` is the decoded payload
    rate of the best pipelined epoch (raw bytes, i.e. what the client
    actually materialises).

    **Local fast path.** A second share-armed fleet publishes its block
    caches, then a co-located client re-reads the corpus:
    ``service_wire_fastpath`` counts blocks served straight off the
    mmapped artifact (no socket) and must equal ``service_wire_blocks``
    on this single-host bench."""
    import tempfile

    from dmlc_tpu.service import LocalFleet, ServiceParser
    from dmlc_tpu.utils import knobs as _knobs
    from dmlc_tpu.utils import telemetry as _telemetry

    num_parts = workers
    # transport microbench: 16x smaller blocks than the throughput legs
    # so the frame count (and with it the per-request round-trip cost a
    # depth-1 schedule pays) is large enough to measure — the wire is
    # the subject here, not the parser
    cfg = {"format": "libsvm", "chunk_bytes": max(64 * 1024,
                                                  CHUNK_BYTES // 16)}
    depth = _knobs.resolve("service_pipeline_depth")

    def _drain(sp):
        n = 0
        while sp.next_block() is not None:
            n += 1
        return n

    def _wire_bytes():
        return (_telemetry.REGISTRY.counter(
                    _telemetry.SERVICE_WIRE_RAW_METRIC, job="default").value,
                _telemetry.REGISTRY.counter(
                    _telemetry.SERVICE_WIRE_SENT_METRIC, job="default").value)

    # --- TCP timings: no share_dir, so no published cache artifact and
    # no local fast path — every block crosses the socket
    fleet = LocalFleet(path, num_parts, num_workers=workers, parser=cfg)
    try:
        sp = ServiceParser(fleet.address)
        blocks = _drain(sp)  # cold pass (untimed): workers parse once
        sp.close()
        raw0, sent0 = _wire_bytes()

        def _one(d):
            sp = ServiceParser(fleet.address)
            if sp.pipeline_depth != d:
                sp.resize_pipeline_depth(d)
            r0, _s0 = _wire_bytes()
            t0 = time.monotonic()
            n = _drain(sp)
            dt = time.monotonic() - t0
            sp.close()
            if n != blocks:
                raise RuntimeError(
                    f"wire leg streamed {n} blocks, expected {blocks}")
            return dt, _wire_bytes()[0] - r0

        # interleaved pairs + best-of: scheduler hiccups and page-cache
        # drift only ever ADD time, so the per-schedule floor is the
        # noise-robust estimate, and interleaving keeps slow windows
        # from landing on one schedule wholesale
        seq_runs, pipe_runs = [], []
        for i in range(6):
            # alternate which schedule goes first so monotone drift
            # (thermal, page cache) cannot systematically favor one
            if i % 2 == 0:
                seq_runs.append(_one(1))
                pipe_runs.append(_one(depth))
            else:
                pipe_runs.append(_one(depth))
                seq_runs.append(_one(1))
        seq_dt = min(dt for dt, _ in seq_runs)
        pipe_dt, pipe_raw = min(pipe_runs)
        raw1, sent1 = _wire_bytes()
    finally:
        fleet.close()
    raw, sent = raw1 - raw0, sent1 - sent0
    ratio = sent / max(1, raw)
    # --- local fast path: share-armed fleet publishes block caches on
    # the cold pass; the warm co-located client mmaps them (docs/
    # service.md local fast path) and the socket carries zero blocks
    with tempfile.TemporaryDirectory(prefix="dmlc-wire-share-") as share:
        fleet = LocalFleet(path, num_parts, num_workers=workers,
                           parser=cfg, share_dir=share)
        fp_blocks = 0
        try:
            sp = ServiceParser(fleet.address)
            _drain(sp)
            sp.close()
            sp = ServiceParser(fleet.address)
            n = _drain(sp)
            fp_blocks = sp.fastpath_blocks
            sp.close()
            if n != blocks:
                raise RuntimeError(
                    f"fastpath leg streamed {n} blocks, expected {blocks}")
        finally:
            fleet.close()
    log(f"bench: wire v2 {blocks} blocks: sequential {seq_dt:.3f}s vs "
        f"depth-{depth} pipelined {pipe_dt:.3f}s -> "
        f"x{seq_dt / pipe_dt:.2f}, compression {sent}/{raw} bytes = "
        f"{ratio:.3f}, fastpath {fp_blocks}/{blocks} blocks off-socket")
    return {
        "service_wire_blocks": blocks,
        "service_pipeline_depth": depth,
        "service_wire_gbps": round(pipe_raw * 8 / max(pipe_dt, 1e-9) / 1e9,
                                   3),
        "service_wire_sequential_mb_per_sec": round(size_mb / seq_dt, 2),
        "service_wire_pipelined_mb_per_sec": round(size_mb / pipe_dt, 2),
        "service_wire_pipelined_speedup": round(seq_dt / pipe_dt, 3),
        "service_wire_compression_ratio": round(ratio, 3),
        "service_wire_fastpath": fp_blocks,
    }


def service_qos_leg(path: str, size_mb: float, workers: int = 2):
    """Production-QoS leg (``--service`` / ISSUE 17, docs/service.md
    Production QoS): two-class contention on one fleet. A
    latency-critical tenant (priority 1, weight 2, ``slo_wait_frac``)
    and a batch tenant (priority 0, ``max_inflight=1``) read the same
    corpus while ``DMLC_TPU_QOS_MAX_INFLIGHT`` caps the fleet's
    concurrent parses at the worker count. The critical job's cold
    epoch saturates the admission ceiling, so the batch tenant's
    locates shed with retryable ``throttled`` replies
    (``service_qos_throttles`` — gated ``>= 1`` by ``make
    bench-smoke``) that the client backs off on
    (``service_qos_admission_waits``) WITHOUT ever burning toward a
    give-up (``service_qos_giveups`` gated ``== 0``). Both tenants
    drain their full epochs — overload degrades to bounded queueing,
    never to failure.

    ``service_qos_critical_wait_frac`` is the critical job's WARM-epoch
    input-wait fraction (client wait seconds / epoch wall) measured
    while the batch tenant is still cold-parsing beside it, with a
    small per-block consume pause modeling a trainer's step cadence —
    the same job-labeled signal the SLO-driven autoscaler steers on.
    Gated ``< service_qos_critical_slo`` by ``make bench-smoke``: the
    priority band + admission budget must keep the critical tenant
    under its declared SLO despite the saturating sibling."""
    import threading as _threading

    from dmlc_tpu.io import resilience as _resilience
    from dmlc_tpu.service import LocalFleet, ServiceParser
    from dmlc_tpu.utils import telemetry as _telemetry

    num_parts = max(4, workers * 2)
    cfg = {"format": "libsvm", "chunk_bytes": CHUNK_BYTES}
    slo = 0.5
    res_base = _resilience.counters_snapshot()
    # born-empty fleet: both tenants are explicit registrations, so the
    # default job cannot skew the grant rotation under test
    fleet = LocalFleet(None, 0, num_workers=workers, parser=cfg)
    os.environ["DMLC_TPU_QOS_MAX_INFLIGHT"] = str(workers)
    batch_blocks = [0]
    batch_errs: list = []

    def _drain_batch():
        sp = ServiceParser(fleet.address, job="qos-batch")
        try:
            while sp.next_block() is not None:
                batch_blocks[0] += 1
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            batch_errs.append(exc)
        finally:
            sp.close()

    try:
        fleet.register_job("qos-critical", path, num_parts, parser=cfg,
                           priority=1, weight=2, slo_wait_frac=slo)
        fleet.register_job("qos-batch", path, num_parts, parser=cfg,
                           max_inflight=1)
        # critical cold epoch first: its grants preempt and saturate the
        # ceiling, so the batch thread's locates shed deterministically
        crit = ServiceParser(fleet.address, job="qos-critical")
        batch_thread = _threading.Thread(target=_drain_batch, daemon=True)
        crit_blocks = 0
        try:
            batch_thread.start()
            while crit.next_block() is not None:
                crit_blocks += 1
        finally:
            crit.close()
        # warm critical epoch, timed: every part is parsed and served
        # off the workers' stores, so the wait frac is steady-state
        # input starvation, not the cold build
        wait_c = _telemetry.REGISTRY.counter(
            _telemetry.SERVICE_JOB_WAIT_METRIC, job="qos-critical")
        crit = ServiceParser(fleet.address, job="qos-critical")
        warm_blocks = 0
        try:
            wait0 = wait_c.value
            t0 = time.monotonic()
            while crit.next_block() is not None:
                warm_blocks += 1
                time.sleep(0.02)  # the trainer's consume cadence
            warm_dt = time.monotonic() - t0
            crit_wait = wait_c.value - wait0
        finally:
            crit.close()
        batch_thread.join(timeout=600.0)
        if batch_errs:
            raise batch_errs[0]
        if batch_thread.is_alive():
            raise RuntimeError("qos leg: batch tenant never drained")
    finally:
        os.environ.pop("DMLC_TPU_QOS_MAX_INFLIGHT", None)
        fleet.close()
    res = _resilience.counters_delta(res_base)
    wait_frac = crit_wait / max(warm_dt, 1e-9)
    log(f"bench: service qos leg: critical {crit_blocks} cold + "
        f"{warm_blocks} warm blocks (wait frac {wait_frac:.3f} vs slo "
        f"{slo}), batch {batch_blocks[0]} blocks through "
        f"{res['service_throttles']} throttles / "
        f"{res['service_admission_waits']} admission waits, "
        f"{res['service_giveups']} giveups")
    return {
        "service_qos_jobs": 2,
        "service_qos_critical_slo": slo,
        "service_qos_critical_wait_frac": round(wait_frac, 4),
        "service_qos_critical_blocks": warm_blocks,
        "service_qos_batch_blocks": batch_blocks[0],
        "service_qos_throttles": res["service_throttles"],
        "service_qos_admission_waits": res["service_admission_waits"],
        "service_qos_giveups": res["service_giveups"],
    }


def autotune_leg(path: str, size_mb: float, max_epochs: int = 5):
    """Offline controller convergence (``--autotune`` / ISSUE 10): run
    the ingest pipeline with the feedback controller armed at a
    deliberately starved config (prefetch 1, convert_ahead 1) and
    mid-epoch stepping, for repeated epochs until the controller reports
    convergence (two consecutive steady windows — gap_stage == transfer /
    the consumer never waits) or the epoch budget runs out. The JSON
    line then carries the decision count and the CHOSEN CONFIG keyed by
    env variable names, so a converged run is reusable verbatim::

        export DMLC_TPU_PREFETCH=4 DMLC_TPU_CONVERT_AHEAD=8 ...

    (docs/data.md autotune section; make bench-smoke gates the fields).
    """
    import jax

    from dmlc_tpu.data import autotune as _autotune
    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter

    parser = create_parser(path, 0, 1, "libsvm", threaded=True,
                           chunk_bytes=CHUNK_BYTES)
    it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                    layout="dense", prefetch=1, convert_ahead=1,
                    pack_aux=True, autotune=True, autotune_interval=16)
    rate = 0.0
    try:
        for ep in range(max_epochs):
            t0 = time.monotonic()
            last = None
            nb = 0
            for batch in it:
                last = batch
                nb += 1
            if last is not None:
                jax.block_until_ready(last)
            dt = time.monotonic() - t0
            rate = max(rate, size_mb / dt)
            snap = it.autotuner.snapshot(history=1)
            log(f"bench: autotune epoch {ep} {nb} batches in {dt:.2f}s = "
                f"{size_mb/dt:.1f} MB/s (steps {snap['steps']}, "
                f"adjustments {snap['adjustments']}, knobs "
                f"{snap['knobs']}, converged {snap['converged']})")
            if it.autotuner.converged and ep >= 1:
                break
            it.reset()
        snap = it.autotuner.snapshot(history=4)
        for d in snap["history"]:
            log(f"bench: autotune decision: {d}")
        return {
            "autotune_enabled": True,
            "autotune_steps": snap["steps"],
            "autotune_adjustments": snap["adjustments"],
            "autotune_converged": snap["converged"],
            "autotune_gap_stage": snap["gap_stage"],
            "autotune_final_config": _autotune.env_config(snap["knobs"]),
            "autotune_mb_per_sec": round(rate, 2),
        }
    finally:
        it.close()


def trace_overhead_leg(path: str, size_mb: float, reps: int = 3):
    """Trace-propagation tax (docs/observability.md Distributed
    tracing): a warm parse-epoch pair — trace context armed (a live
    trace installed, every span stamped) against propagation forced off
    — interleaved, best-of-``reps`` each. ``trace_overhead_pct`` is the
    relative cost of the armed leg; ``make bench-smoke`` gates it < 5%
    (the observability plane must be cheap enough to leave on). Best-of
    because scheduler noise and page-cache drift only ever ADD time;
    interleaved so drift lands on both legs equally."""
    from dmlc_tpu.data import create_parser
    from dmlc_tpu.utils import telemetry as _telemetry

    def _epoch() -> float:
        t0 = time.monotonic()
        parser = create_parser(path, 0, 1, "libsvm",
                               chunk_bytes=CHUNK_BYTES)
        while parser.next_block() is not None:
            pass
        parser.close()
        return time.monotonic() - t0

    _epoch()  # both legs must measure warm page-cache supply
    on = off = float("inf")
    try:
        for _ in range(max(1, int(reps))):
            _telemetry.set_trace_propagation(True)
            with _telemetry.trace(_telemetry.new_trace_id(),
                                  _telemetry.new_span_id()):
                on = min(on, _epoch())
            _telemetry.set_trace_propagation(False)
            off = min(off, _epoch())
    finally:
        _telemetry.set_trace_propagation(None)
    pct = (on - off) / off * 100.0 if off > 0 else 0.0
    log(f"bench: trace overhead: traced {size_mb/on:.1f} MB/s vs "
        f"untraced {size_mb/off:.1f} MB/s -> {pct:+.2f}%")
    return {"trace_overhead_pct": round(pct, 2)}


def als_train_leg(size_mb: float, epochs: int = 4):
    """Pod-scale sparse training (ISSUE 20): ALX-style sharded ALS
    (models/als.py) trained end-to-end off the warm pod-sharded block
    cache, measuring whether the ingest stack keeps the loop
    COMPUTE-bound — tf.data's (arXiv:2101.12127) input-starvation
    failure mode, quantified per epoch:

    - ``als_rows_per_sec``: user rows solved per second, best warm epoch;
    - ``als_step_seconds``: mean jitted-step wall on that epoch;
    - ``als_input_wait_frac``: input_wait_seconds delta / epoch wall —
      the PR 10 trustworthy input-bound counter as a fraction of the
      training wall. The compute-bound bar (< 0.2 on accelerator) is the
      TPU-return criterion; on the CPU host ``make bench-smoke`` gates
      field presence + a completed warm-fed loop only;
    - ``als_overlap_frac``: 1 - input_wait / ingest_busy — the fraction
      of producer busy time hidden under training compute.

    The leg builds its own small fixed-size ratings corpus (label = user
    id, features = item:rating — the models/als.py encoding): overlap
    fractions, not throughput scaling, are the judged signal, so corpus
    size does not track DMLC_BENCH_MB."""
    import shutil

    import jax
    import numpy as np

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.data.device import DeviceIter
    from dmlc_tpu.models import AlsLearner

    users, items, per_row, factors, batch = 2048, 512, 16, 8, 512
    corpus = os.path.join(CACHE_DIR, f"als_{users}x{items}x{per_row}.libsvm")
    if not os.path.exists(corpus):
        rng = np.random.default_rng(0)
        gt_u = rng.normal(size=(users, factors)).astype(np.float32)
        gt_v = rng.normal(size=(items, factors)).astype(np.float32)
        with open(corpus + ".tmp", "w") as f:
            for uid in range(users):
                cols = rng.choice(items, size=per_row, replace=False)
                ratings = gt_u[uid] @ gt_v[cols].T
                feats = " ".join(f"{j}:{r:.6f}"
                                 for j, r in zip(cols, ratings))
                f.write(f"{uid} {feats}\n")
        os.replace(corpus + ".tmp", corpus)
    cache = os.path.join(CACHE_DIR, "als_cache")
    shutil.rmtree(cache, ignore_errors=True)  # deterministic cold->warm

    model = AlsLearner(users, items, num_factors=factors, reg=0.05, seed=0)
    parser = create_parser(corpus, 0, 1, "libsvm", block_cache=cache,
                           shuffle_seed=0, pod_sharding=True,
                           chunk_bytes=32 << 10)
    it = DeviceIter(parser, num_col=model.device_num_col(),
                    batch_size=batch, layout="ell", max_nnz=per_row,
                    drop_remainder=True)
    best = None
    loss = 0.0
    try:
        for ep in range(max(2, int(epochs))):
            st0 = it.stats()
            wait0 = st0["input_wait_seconds"]
            busy0 = sum(st0["stage_busy"].values())
            t0 = time.monotonic()
            rows = steps = 0
            step_s = 0.0
            dloss = None
            for b in it:
                ts = time.monotonic()
                dloss = model.step(b)
                step_s += time.monotonic() - ts
                steps += 1
                rows += b.batch_size
            model.finalize_items()
            # training wall must include the epoch's full device work:
            # the async dispatches drain here, inside the timed window
            jax.block_until_ready((model.params.users, model.params.items))
            wall = time.monotonic() - t0
            loss = float(dloss) if dloss is not None else 0.0
            st1 = it.stats()
            wait = st1["input_wait_seconds"] - wait0
            busy = sum(st1["stage_busy"].values()) - busy0
            it.reset()
            if ep == 0 or steps == 0:
                continue  # cold epoch builds the cache; warm epochs judge
            rec = {
                "als_rows_per_sec": round(rows / max(wall, 1e-9), 1),
                "als_step_seconds": round(step_s / steps, 6),
                "als_input_wait_frac": round(wait / max(wall, 1e-9), 4),
                "als_overlap_frac": round(
                    min(1.0, max(0.0, 1.0 - wait / busy))
                    if busy > 1e-9 else 1.0, 4),
                "als_cache_state": st1.get("cache_state"),
            }
            if best is None or rec["als_rows_per_sec"] > \
                    best["als_rows_per_sec"]:
                best = rec
    finally:
        it.close()
    if best is None:
        raise RuntimeError("als leg: no warm epoch completed")
    best["als_train_loss"] = round(loss, 5)
    log(f"bench: als train: {best['als_rows_per_sec']} rows/s warm, step "
        f"{best['als_step_seconds']*1e3:.2f} ms, input wait frac "
        f"{best['als_input_wait_frac']}, overlap "
        f"{best['als_overlap_frac']}, cache {best['als_cache_state']}, "
        f"loss {best['als_train_loss']}")
    return best


def device_floor_mbps(x_dtype: str = "float32"):
    """Raw repeated-shape device_put floor for bench.py's exact batch
    geometry, measured in THIS process right after the pipeline reps (same
    backend, same tunnel weather) so the line-rate join compares rates
    captured minutes — not rounds — apart. Returns
    (best, median, trimmed_best) MB/s.

    This is the denominator of ``pct_of_line_rate``: the BASELINE claim is
    ">=90% of host->HBM line rate with zero input-bound stalls", and the
    line rate IS what device_put of the same bytes sustains with no
    parsing attached (benchmarks/bench_transfer_floor.py standalone form).

    Stability (BENCH_r05: the bf16 floor swung best 5159.7 vs median
    1858.2 MB/s): the first timed rounds used to eat lazy backend work —
    the bf16 view wrapper, dtype-specific transfer-plan setup — so the
    path is now WARMED with full untimed put rounds until the rate
    stabilizes (bounded), and ``trimmed_best`` (the best sample after
    dropping the single highest — one fluke window cannot own it) rides
    alongside best/median as the stable denominator snapshot gating
    divides by."""
    import jax
    import numpy as np

    if x_dtype == "bfloat16":
        from dmlc_tpu.native import bf16_dtype

        np_dtype = bf16_dtype()
    else:
        np_dtype = np.dtype(x_dtype)
    rng = np.random.default_rng(0)
    # the SAME put the pipeline issues per batch: since pack_aux, a dense
    # batch is ONE [B, D+2] array (label/weight as trailing columns) —
    # the floor must mirror that exact shape/array-count, or the
    # denominator pays per-array overhead the pipeline no longer pays
    # (the 3-array put measured ~2x slower per byte) and the judged
    # >=90% ratio reads too favorable
    batch = [
        rng.standard_normal((BATCH, NUM_COL + 2)).astype(np_dtype),
    ]
    n = 64
    mb = n * sum(a.nbytes for a in batch) / 2**20
    # warm up until two consecutive untimed rounds agree within 25% (or
    # the bounded budget runs out): first-touch costs — transfer-plan
    # build, dtype wrapper setup, allocator growth — must not land inside
    # a timed sample
    prev = None
    for _ in range(4):
        t0 = time.monotonic()
        jax.block_until_ready([jax.device_put(batch) for _ in range(n)])
        rate = mb / (time.monotonic() - t0)
        if prev is not None and abs(rate - prev) <= 0.25 * max(rate, prev):
            break
        prev = rate
    samples = []
    for _ in range(5):
        t0 = time.monotonic()
        handles = [jax.device_put(batch) for _ in range(n)]
        jax.block_until_ready(handles)
        samples.append(mb / (time.monotonic() - t0))
    trimmed = max(sorted(samples)[:-1])  # best-of after dropping the top
    log(f"bench: device_put floor ({x_dtype}) best {max(samples):.1f} "
        f"trimmed {trimmed:.1f} median {_median(samples):.1f} MB/s")
    return max(samples), _median(samples), trimmed


# child exit code for backend/transport failures — the supervisor retries
# these (after waiting out the flake) and treats any other nonzero rc as a
# deterministic bench bug, reported immediately without re-running
EX_INFRA = 75  # sysexits EX_TEMPFAIL

_INFRA_MARKERS = (
    "UNAVAILABLE", "Unable to initialize backend", "DEADLINE_EXCEEDED",
    "Socket closed", "failed to connect", "Connection reset",
    "backend setup/compile error",
)


def run_child() -> None:
    """The actual measurement (one process, one backend init)."""
    path = make_corpus()
    size_mb = os.path.getsize(path) / 2**20
    log(f"bench: corpus {size_mb:.1f} MB")
    base_best, base_med = host_only_mb_per_sec(path, size_mb)
    try:
        (value, med, spread, attribution, dev, resilience,
         parallel) = into_hbm_mb_per_sec(path, size_mb)
    except Exception as exc:  # noqa: BLE001 - classify for the supervisor
        msg = f"{type(exc).__name__}: {exc}"
        if any(m in msg for m in _INFRA_MARKERS):
            log(f"bench: backend/transport failure: {msg}")
            sys.exit(EX_INFRA)
        raise
    line = {
        "metric": "rowblockiter_mb_per_sec_into_hbm",
        "value": round(value, 2),
        "unit": "MB/s",
        "vs_baseline": round(value / base_best, 3),
        # median + spread alongside best-of: with 2-4x ambient swings on this
        # shared host a single lucky rep can overstate steady state
        "median": round(med, 2),
        "median_vs_baseline": round(med / base_med, 3),
        "spread": [round(spread[0], 2), round(spread[1], 2)],
        "reps": REPS,
    }
    if attribution is not None:
        # per-stage wall attribution of the best rep (VERDICT r5 weak #4:
        # the unaccounted share of pipeline bound, decomposed into named
        # costs) — same object in the JSON, human table on stderr
        line["attribution"] = attribution
        log("bench: ingest stage attribution (best rep):")
        log(_bench_common().attribution_table(attribution))
    if resilience is not None:
        # fault-tolerance counters of the best rep (docs/resilience.md):
        # a clean run emits zeros — nonzero retries/resumes on a healthy
        # loopback corpus would flag a regression in the I/O stack
        line["resilience"] = resilience
        hot = {k: v for k, v in resilience.items() if v}
        if hot:
            log(f"bench: resilience events: {hot}")
    if parallel is not None:
        # the pipeline's parse fan-out width + measured parallel efficiency
        # (docs/data.md parse_workers; the native reader reports its C++
        # thread count with no efficiency instrumentation)
        line["parse_workers"] = parallel.get("parse_workers")
        line["parse_parallelism_efficiency"] = parallel.get(
            "parse_parallelism_efficiency")
        line["input_wait_seconds"] = parallel.get("input_wait_seconds")
    # parse fan-out scaling curve (ISSUE 3): the host parse ceiling of the
    # PYTHON engine at 1/2/4 workers, interleaved so ambient drift cancels
    # in the ratio. parse_ceiling_workers_1 is the pre-fan-out engine;
    # parse_ceiling_workers_4 over it is the PR's raised ceiling.
    try:
        curve = parse_scaling_curve(path, size_mb)
        scaling = {}
        for w, (cbest, cmed) in sorted(curve.items()):
            line[f"parse_ceiling_workers_{w}"] = round(cbest, 2)
            scaling[str(w)] = {"best": round(cbest, 2),
                               "median": round(cmed, 2)}
        line["parse_scaling"] = scaling
        ws = sorted(curve)
        lo, hi = curve[ws[0]], curve[ws[-1]]
        line["parse_parallel_speedup"] = round(hi[0] / lo[0], 3)
        line["parse_parallel_speedup_median"] = round(hi[1] / lo[1], 3)
        log(f"bench: parse fan-out scaling (best): "
            + ", ".join(f"{w}w={curve[w][0]:.1f}" for w in ws)
            + f" MB/s -> speedup x{hi[0]/lo[0]:.2f}")
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: parse scaling leg failed: {exc}")
    # percent-of-line-rate (VERDICT r4 next #2): the BASELINE framing is
    # ">=90% of host->HBM line rate", which vs-parse-baseline does not
    # measure. Join the raw device_put floor for the same shapes/dtype,
    # captured in this same process, and report the pipeline's device-side
    # rate as a fraction of it.
    try:
        floor_best, floor_med, floor_trim = device_floor_mbps("float32")
        line["line_rate_trimmed_mb_per_sec"] = round(floor_trim, 2)
        line["pct_of_line_rate"] = round(dev[0] / floor_best, 3)
        line["pct_of_line_rate_median"] = round(dev[1] / floor_med, 3)
        line["device_mb_per_sec"] = round(dev[0], 2)
        line["line_rate_floor_mb_per_sec"] = round(floor_best, 2)
        # the BINDING bound: the pipeline can go no faster than
        # min(its parse ceiling, the link) — which resource binds flips
        # with tunnel weather on this host, so the ">=90%, zero stalls"
        # claim is judged against the minimum of both, in corpus MB/s.
        # (pct_of_line_rate alone under-reads a parse-bound pipeline and
        # says nothing about a link-bound one's parse headroom.)
        thr_best, thr_med = host_only_mb_per_sec(path, size_mb,
                                                 threaded=True,
                                                 emit_dense=True)
        # overlap check against the host-only parse ceiling measured in
        # THIS run: with convert/dispatch overlapped the pipeline should
        # reach >= 0.95x of it (the device leg runs the same parse plus an
        # async put) — when it does not, name the stage that owns the gap
        # so the shortfall is attributed, never unaccounted. Candidates:
        # every non-parse stage's full seconds, plus parse's EXCESS over
        # the seconds the standalone ceiling needs for the same bytes
        # (parse running over its own ceiling share = core contention /
        # ambient drift, and the honest owner is then parse itself).
        pct_ceiling = value / thr_best
        line["pct_of_parse_ceiling"] = round(pct_ceiling, 3)
        if pct_ceiling < 0.95 and attribution is not None:
            gap = {k: attribution.get(k, 0.0)
                   for k in ("read", "convert", "dispatch", "transfer")}
            gap["parse"] = max(
                0.0, attribution.get("parse", 0.0) - size_mb / thr_best)
            line["gap_stage"] = max(gap, key=gap.get)
            line["gap_stage_seconds"] = round(gap[line["gap_stage"]], 4)
        # floor in corpus units: floor_device * (corpus bytes / device
        # bytes); value/dev[0] is exactly corpus_mb/s per device_mb/s
        floor_corpus = floor_best * value / dev[0]
        bound = min(thr_best, floor_corpus)
        line["parse_ceiling_mb_per_sec"] = round(thr_best, 2)
        line["line_rate_corpus_equiv_mb_per_sec"] = round(floor_corpus, 2)
        line["binding_resource"] = ("link" if floor_corpus < thr_best
                                    else "parse")
        # the ceiling reps run minutes after the pipeline reps on a host
        # whose ambient speed swings 2-4x, so the measured ratio can land
        # above the physical 1.0 — report it CLAMPED (the claim the footer
        # decides is ">= 0.9 of bound", and being at-or-above bound
        # satisfies it) and flag the drift so readers know the ceiling
        # sample ran in a slower ambient window than the pipeline's
        pct = value / bound
        pct_med = med / min(thr_med, floor_med * med / dev[1])
        line["pct_of_pipeline_bound"] = round(min(pct, 1.0), 3)
        line["pct_of_pipeline_bound_median"] = round(min(pct_med, 1.0), 3)
        if pct > 1.0 or pct_med > 1.0:
            line["bound_drift"] = round(max(pct, pct_med), 3)
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: line-rate floor leg failed: {exc}")
    # parse-once block cache (ISSUE 5): cold epoch parses + shadow-writes,
    # warm epoch streams mmap'd parsed blocks into HBM — the epoch-pair
    # contract make bench-smoke gates (warm_epoch_mb_per_sec /
    # warm_vs_cold_speedup / cache_state). Warm above the parse ceiling
    # proves the parser is actually bypassed, not merely overlapped.
    try:
        (cold_mbps, warm_mbps, cache_state, cache_read_s, shuffled_mbps,
         shuffled_stats) = block_cache_epoch_pair(path, size_mb)
        line["cold_epoch_mb_per_sec"] = round(cold_mbps, 2)
        line["warm_epoch_mb_per_sec"] = round(warm_mbps, 2)
        line["warm_vs_cold_speedup"] = round(warm_mbps / cold_mbps, 3)
        line["cache_state"] = cache_state
        line["warm_cache_read_seconds"] = round(cache_read_s, 4)
        ceiling = line.get("parse_ceiling_mb_per_sec")
        if ceiling:
            line["warm_vs_parse_ceiling"] = round(warm_mbps / ceiling, 3)
        log(f"bench: block-cache warm {warm_mbps:.1f} MB/s vs cold "
            f"{cold_mbps:.1f} MB/s -> speedup x{warm_mbps/cold_mbps:.2f}"
            + (f", x{warm_mbps/ceiling:.2f} of parse ceiling"
               if ceiling else ""))
        if shuffled_mbps is not None:
            # shuffle-native warm epoch (ISSUE 8): plan-ordered serving
            # of the same cache — the overhead vs sequential warm is the
            # price of shuffled SGD epochs (acceptance bar: within 20%).
            # Estimated from the best ROUND-PAIRED ratio of the
            # interleaved epochs, so ambient drift between legs cancels.
            line["shuffled_warm_epoch_mb_per_sec"] = round(shuffled_mbps, 2)
            ratio = shuffled_stats.get("pair_ratio",
                                       shuffled_mbps / warm_mbps)
            line["shuffle_overhead_pct"] = round(
                max(0.0, 100.0 * (1.0 - ratio)), 2)
            line["shuffle_seed"] = shuffled_stats.get("shuffle_seed")
            log(f"bench: shuffled warm {shuffled_mbps:.1f} MB/s vs "
                f"sequential warm {warm_mbps:.1f} MB/s -> overhead "
                f"{line['shuffle_overhead_pct']:.1f}%")
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: block-cache epoch-pair leg failed: {exc}")
    # chunk-batch cold-parse leg (ISSUE 14): the full cold cache build
    # through the native-batch engine vs the pre-PR stream+re-encode
    # path — batch_vs_stream_parse_speedup >= 1.0 is the bench-smoke
    # gate when batch_parse_simd_level >= 0 (byte-identical caches, so
    # the ratio isolates the engine; on a toolchain-less host both legs
    # run the Python engine and only field presence is gated)
    try:
        line.update(batch_parse_leg(path, size_mb))
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: batch-parse leg failed: {exc}")
    # device-native snapshot store (ISSUE 9): warm epochs skip parse AND
    # convert — mmap'd post-convert batches stream straight into
    # device_put. snapshot_vs_cache_speedup positions the two warm tiers
    # (cache = parser output, snapshot = device layout); above the parse
    # ceiling proves the bypass is structural. make bench-smoke gates the
    # fields.
    try:
        snap_fields = snapshot_epoch_leg(path, size_mb)
        line.update(snap_fields)
        warm_snap = snap_fields.get("snapshot_warm_mb_per_sec")
        cache_warm = line.get("warm_epoch_mb_per_sec")
        if warm_snap and cache_warm:
            line["snapshot_vs_cache_speedup"] = round(
                warm_snap / cache_warm, 3)
        ceiling = line.get("parse_ceiling_mb_per_sec")
        if warm_snap and ceiling:
            line["snapshot_vs_parse_ceiling"] = round(warm_snap / ceiling, 3)
        if warm_snap:
            log(f"bench: snapshot warm {warm_snap:.1f} MB/s"
                + (f" = x{line['snapshot_vs_cache_speedup']:.2f} over the "
                   f"cache's warm epochs" if cache_warm else "")
                + (f", x{line['snapshot_vs_parse_ceiling']:.2f} of parse "
                   f"ceiling" if ceiling else ""))
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: snapshot epoch leg failed: {exc}")
    # device-side decode (ISSUE 18): warm snapshot epochs shipping the
    # raw container span verbatim and decoding in HBM vs the host-decode
    # warm tier above — the speedup claim only holds on a real
    # accelerator (device_decode_backend), bench-smoke gates accordingly
    try:
        line.update(device_decode_leg(path, size_mb))
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: device-decode leg failed: {exc}")
    # bf16 ingest: the C++ repack emits bfloat16 (the MXU's operand width),
    # halving host->HBM bytes — reported alongside, headline stays f32
    try:
        (bf16_value, bf16_med, _sp, _, bf16_dev, _res,
         _par) = into_hbm_mb_per_sec(path, size_mb, x_dtype="bfloat16")
        line["bf16_mb_per_sec"] = round(bf16_value, 2)
        line["bf16_vs_baseline"] = round(bf16_value / base_best, 3)
        line["bf16_median_vs_baseline"] = round(bf16_med / base_med, 3)
        bf_floor_best, bf_floor_med, bf_floor_trim = \
            device_floor_mbps("bfloat16")
        line["bf16_pct_of_line_rate"] = round(bf16_dev[0] / bf_floor_best, 3)
        line["bf16_pct_of_line_rate_median"] = round(
            bf16_dev[1] / bf_floor_med, 3)
        # the STABLE bf16 denominator (warmed + trimmed best-of): the
        # number snapshot gating divides by, immune to the one-fluke-
        # window swings BENCH_r05 recorded (best 5159.7 vs median 1858.2)
        line["bf16_line_rate_trimmed_mb_per_sec"] = round(bf_floor_trim, 2)
        line["bf16_pct_of_line_rate_trimmed"] = round(
            bf16_dev[0] / bf_floor_trim, 3)
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: bf16 leg failed: {exc}")
    # disaggregated data-service leg (docs/service.md): localhost fleet
    # throughput + speedup over the same partitions parsed serially —
    # emitted when --service / DMLC_BENCH_SERVICE=1 asked for it (make
    # bench-smoke gates the fields)
    if os.environ.get("DMLC_BENCH_SERVICE", "0") not in ("", "0"):
        try:
            line.update(service_leg(path, size_mb))
        except Exception as exc:  # noqa: BLE001 - the headline must still print
            log(f"bench: service leg failed: {exc}")
        # wire v2 transport leg (docs/service.md Wire v2): pipelined vs
        # lock-step TCP, compression byte ledger, local fast path
        try:
            line.update(service_wire_leg(path, size_mb))
        except Exception as exc:  # noqa: BLE001 - the headline must still print
            log(f"bench: service wire leg failed: {exc}")
        # production-QoS leg (docs/service.md Production QoS): two-class
        # contention — critical tenant under SLO, batch tenant throttled
        try:
            line.update(service_qos_leg(path, size_mb))
        except Exception as exc:  # noqa: BLE001 - the headline must still print
            log(f"bench: service qos leg failed: {exc}")
    # online-autotuner convergence leg (docs/data.md autotune): the
    # controller climbs a starved config until gap_stage == transfer and
    # the chosen knobs ride the JSON line as reusable env — emitted when
    # --autotune / DMLC_BENCH_AUTOTUNE=1 asked for it (make bench-smoke
    # gates the fields)
    if os.environ.get("DMLC_BENCH_AUTOTUNE", "0") not in ("", "0"):
        try:
            line.update(autotune_leg(path, size_mb))
        except Exception as exc:  # noqa: BLE001 - the headline must still print
            log(f"bench: autotune leg failed: {exc}")
    # tiered artifact store contract (docs/store.md): the cache/snapshot
    # legs above published their artifacts THROUGH the store, so the
    # registry gauge must show managed bytes; evictions/rebuilds are 0 on
    # an unbudgeted bench run and nonzero only under
    # DMLC_TPU_STORE_BUDGET_BYTES (make bench-smoke gates the fields)
    try:
        from dmlc_tpu.store import store_counters

        sc = store_counters()
        line["store_bytes"] = sc["store_bytes"]
        line["store_evictions"] = sc["store_evictions"]
        line["store_rebuilds_after_eviction"] = \
            sc["store_rebuilds_after_eviction"]
        log(f"bench: artifact store: {sc['store_bytes']} managed bytes, "
            f"{sc['store_evictions']} evictions, "
            f"{sc['store_rebuilds_after_eviction']} rebuilds after "
            f"eviction")
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: store counters failed: {exc}")
    # trace-propagation overhead guard (docs/observability.md): warm
    # epoch pair, context armed vs forced off — make bench-smoke gates
    # trace_overhead_pct < 5 so the plane stays cheap enough to leave on
    try:
        line.update(trace_overhead_leg(path, size_mb))
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: trace overhead leg failed: {exc}")
    # pod-scale sparse-training leg (docs/training.md): ALX-style sharded
    # ALS rides the warm pod-sharded cache end to end; make bench-smoke
    # gates presence of the four als_* fields (the als_input_wait_frac
    # < 0.2 compute-bound bar is the TPU-return criterion)
    try:
        line.update(als_train_leg(size_mb))
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: als train leg failed: {exc}")
    # always-on telemetry contract (docs/observability.md): the schema
    # version + per-stage span counts ride the JSON line, proving the span
    # tracer covered the whole measurement (make bench-smoke gates these)
    from dmlc_tpu.utils import telemetry as _telemetry

    line["telemetry_schema_version"] = _telemetry.SCHEMA_VERSION
    counts = _telemetry.span_counts()
    line["trace_spans"] = int(sum(counts.values()))
    line["trace_span_counts"] = {k: int(v) for k, v in sorted(counts.items())}
    # Prometheus exposition self-check: the render must round-trip
    # through the text-format parser (what a real scraper does), and the
    # decision ledger's lifetime count rides along — both gated
    try:
        prom = _telemetry.render_prometheus()
        line["prometheus_metrics"] = len(_telemetry.parse_prometheus_text(
            prom))
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        log(f"bench: prometheus render failed: {exc}")
        line["prometheus_metrics"] = None
    line["decisions_total"] = _telemetry.decisions_total()
    print(json.dumps(line))


# ---------------------------------------------------------------------------
# Supervisor: retry the child through TPU-tunnel flakes.

def _bench_common():
    """The shared benchmark helpers (probe, platform pin) — one module so
    the logic cannot diverge between bench.py and benchmarks/*."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "benchmarks"))
    import _common

    return _common


def _probe_device(timeout: float = 45.0) -> bool:
    return _bench_common().probe_device(timeout)


def wait_for_device(window_s: float) -> bool:
    """Probe every 60s for up to window_s; the tunnel demonstrably recovers
    within minutes (TPU_BATTERY.log r3)."""
    deadline = time.monotonic() + window_s
    while True:
        if _probe_device():
            return True
        if time.monotonic() >= deadline:
            return False
        log("bench: device unreachable, re-probing in 60s")
        time.sleep(60)


def _spawn_child(env: dict, timeout: float):
    """Run one measurement child. Returns the parsed JSON line (a dict
    with a 'metric' key) on success, the string ``"timeout"`` on a child
    timeout, or the child's int returncode otherwise — callers must
    isinstance-check for dict, not truthiness (rc=0 is falsy). Shared by
    the supervisor loop and the CPU-fallback leg so the extraction logic
    cannot diverge."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return "timeout"
    out_lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if proc.returncode == 0 and out_lines:
        try:
            parsed = json.loads(out_lines[-1])
        except ValueError:
            parsed = None
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed
    return proc.returncode


def main() -> int:
    if "--service" in sys.argv:
        # the measurement runs in a supervised child; the flag travels as
        # env so retries and the CPU fallback keep the leg
        os.environ["DMLC_BENCH_SERVICE"] = "1"
    if "--autotune" in sys.argv:
        os.environ["DMLC_BENCH_AUTOTUNE"] = "1"
    if os.environ.get("DMLC_BENCH_CHILD") == "1":
        run_child()
        return 0

    attempts = int(os.environ.get("DMLC_BENCH_ATTEMPTS", "3"))
    # GB-scale runs need hours-scale headroom; default scales with corpus
    timeout = float(os.environ.get("DMLC_BENCH_TIMEOUT",
                                   str(max(1800.0, TARGET_MB * 6.0))))
    probe_window = float(os.environ.get("DMLC_BENCH_PROBE_WINDOW", "600"))
    env = dict(os.environ, DMLC_BENCH_CHILD="1")
    last_err = ""
    infra = True
    attempt = 0
    # probe-gate the first attempt: when the device is down at start, wait
    # it out (bounded) instead of burning a full child timeout discovering
    # the same thing — the tunnel can hang a backend init for its entire
    # budget (observed: multi-hour outages). wait_for_device probes first,
    # so a healthy device costs one quick probe.
    if not wait_for_device(probe_window):
        last_err = "device unreachable before first attempt"
        attempts = 0
    for attempt in range(1, attempts + 1):
        log(f"bench: attempt {attempt}/{attempts}")
        result = _spawn_child(env, timeout)
        if isinstance(result, dict):
            if attempt > 1:
                result["infra_retries"] = attempt - 1
            print(json.dumps(result))
            return 0
        if result == "timeout":
            # the tunnel can hang a backend init indefinitely: a timeout is
            # an infra failure, not a bench bug
            last_err = f"timeout after {timeout:.0f}s"
            log(f"bench: child {last_err}")
        else:
            last_err = f"rc={result}"
            log(f"bench: child failed ({last_err})")
            if result != EX_INFRA:
                # deterministic bench bug: re-running cannot succeed
                infra = False
                break
        if attempt < attempts:
            # wait out the flake before burning another full run; if the
            # device never comes back inside the window, stop burning
            # child timeouts and report unavailability now
            if wait_for_device(probe_window):
                log("bench: device reachable again, retrying")
            else:
                log("bench: device still unreachable after probe window")
                break
    line = {
        "metric": "rowblockiter_mb_per_sec_into_hbm",
        "value": None,
        "unit": "MB/s",
        "vs_baseline": None,
        "infra": "tpu_unavailable" if infra else "bench_error",
        "attempts": attempt,  # attempts actually made, not the configured max
        "last_error": last_err,
    }
    if infra and os.environ.get("DMLC_BENCH_NO_CPU_FALLBACK", "0") == "0":
        # the device is gone but the round still deserves a number: run the
        # identical pipeline on the CPU backend and attach it under
        # clearly-labeled fallback keys. value stays null — a CPU-backend
        # device_put pays host-memory bandwidth, not tunnel bandwidth, so
        # it is structural evidence, never the judged TPU metric.
        log("bench: device unavailable — capturing labeled CPU-backend "
            "fallback")
        # fallback budget: bounded separately so it cannot stack a third
        # full child timeout onto an outer supervisor's budget (the
        # battery sizes its outer kill for the probe window + attempts;
        # it passes DMLC_BENCH_FALLBACK_TIMEOUT to keep the sum inside).
        # Default covers 64 MB comfortably and GB when the corpus exists;
        # GB-with-regeneration needs the explicit knob.
        fb_timeout = float(os.environ.get("DMLC_BENCH_FALLBACK_TIMEOUT",
                                          str(min(timeout, 1800.0))))
        try:
            parsed = _spawn_child(dict(env, DMLC_BENCH_PLATFORM="cpu"),
                                  fb_timeout)
            if isinstance(parsed, dict):
                for k in ("value", "vs_baseline", "median_vs_baseline",
                          "bf16_vs_baseline", "parse_ceiling_mb_per_sec",
                          "parse_workers", "parse_parallelism_efficiency",
                          "parse_ceiling_workers_1",
                          "parse_ceiling_workers_2",
                          "parse_ceiling_workers_4", "parse_scaling",
                          "parse_parallel_speedup",
                          "parse_parallel_speedup_median",
                          "cold_epoch_mb_per_sec", "warm_epoch_mb_per_sec",
                          "native_batch_parse_mb_per_sec",
                          "stream_cold_build_mb_per_sec",
                          "batch_vs_stream_parse_speedup",
                          "batch_parse_simd_level",
                          "warm_vs_cold_speedup", "cache_state",
                          "warm_vs_parse_ceiling",
                          "shuffled_warm_epoch_mb_per_sec",
                          "shuffle_overhead_pct", "shuffle_seed",
                          "snapshot_warm_mb_per_sec", "snapshot_state",
                          "snapshot_vs_cache_speedup",
                          "snapshot_vs_parse_ceiling",
                          "snapshot_wire_bytes_ratio",
                          "snapshot_warm_convert_seconds",
                          "snapshot_read_seconds",
                          "device_decode_mb_per_sec",
                          "device_decode_vs_snapshot_speedup",
                          "device_decode_transfer_bytes",
                          "device_decode_convert_seconds",
                          "device_decode_backend",
                          "bf16_line_rate_trimmed_mb_per_sec",
                          "service_workers", "service_mb_per_sec",
                          "service_vs_local_speedup",
                          "dispatcher_restarts", "worker_reregistrations",
                          "parts_reclaimed", "control_plane_retries",
                          "worker_drains", "drain_handoffs",
                          "preemption_notices", "speculative_reissues",
                          "speculative_wins", "worker_joins",
                          "service_jobs", "shared_parse_ratio",
                          "fleet_scale_events",
                          "service_wire_blocks", "service_pipeline_depth",
                          "service_wire_gbps",
                          "service_wire_sequential_mb_per_sec",
                          "service_wire_pipelined_mb_per_sec",
                          "service_wire_pipelined_speedup",
                          "service_wire_compression_ratio",
                          "service_wire_fastpath",
                          "service_qos_jobs", "service_qos_critical_slo",
                          "service_qos_critical_wait_frac",
                          "service_qos_critical_blocks",
                          "service_qos_batch_blocks",
                          "service_qos_throttles",
                          "service_qos_admission_waits",
                          "service_qos_giveups",
                          "autotune_enabled", "autotune_steps",
                          "autotune_adjustments", "autotune_converged",
                          "autotune_gap_stage", "autotune_final_config",
                          "autotune_mb_per_sec", "input_wait_seconds",
                          "als_rows_per_sec", "als_step_seconds",
                          "als_input_wait_frac", "als_overlap_frac",
                          "als_cache_state", "als_train_loss",
                          "telemetry_schema_version", "trace_spans",
                          "trace_span_counts", "trace_overhead_pct",
                          "trace_spans_crossproc", "trace_timeline_events",
                          "prometheus_metrics", "decisions_total"):
                    if parsed.get(k) is not None:
                        line[f"cpu_backend_{k}"] = parsed[k]
                line["cpu_backend_note"] = (
                    "identical pipeline, CPU backend: structural evidence "
                    "only — transfers cost host-memory bandwidth, not "
                    "tunnel bandwidth")
            else:
                # a failed fallback must say so — a silent no-keys line
                # reads as "fallback never attempted"
                log(f"bench: cpu fallback failed ({parsed})")
                line["cpu_backend_error"] = str(parsed)
        except Exception as exc:  # noqa: BLE001 - fallback must not mask infra
            log(f"bench: cpu fallback failed: {exc}")
    print(json.dumps(line))
    return 3 if infra else 1


if __name__ == "__main__":
    sys.exit(main())
