// C++ smoke test for the native core, runnable under TSan/ASan
// (the reference's CI runs its gtest binary under ThreadSanitizer,
// scripts/travis/travis_script.sh:53-60; this is the equivalent seam for
// the rebuilt core — the full behavioral suite lives in tests/ via pytest).

#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "../src/api.h"
#include "../src/buffer_pool.h"

static int failures = 0;
#define CHECK_TRUE(cond)                                        \
  do {                                                          \
    if (!(cond)) {                                              \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__,        \
                   __LINE__, #cond);                            \
      ++failures;                                               \
    }                                                           \
  } while (0)

int main() {
  // libsvm CSR parse across threads
  const char* text =
      "1 0:1.5 3:2.5\n0 1:0.5\n1 2:3.0 4:4.5 5:1e-2\n";
  CsrBlockResult* b =
      dmlc_parse_libsvm(text, static_cast<int64_t>(strlen(text)), 2, 0);
  CHECK_TRUE(b != nullptr);
  CHECK_TRUE(b->error == nullptr);
  CHECK_TRUE(b->n_rows == 3);
  CHECK_TRUE(b->nnz == 6);
  CHECK_TRUE(b->offset[3] == 6);
  dmlc_free_block(b);

  // dense scan + qid downgrade flag
  DenseResult* d = dmlc_parse_libsvm_dense(text,
                                           static_cast<int64_t>(strlen(text)),
                                           2, 6, 0);
  CHECK_TRUE(d != nullptr && d->error == nullptr && d->n_rows == 3);
  CHECK_TRUE(d->x[0] == 1.5f && d->x[3] == 2.5f);
  dmlc_free_dense(d);
  const char* qid_text = "1 qid:3 0:1\n";
  DenseResult* dq = dmlc_parse_libsvm_dense(
      qid_text, static_cast<int64_t>(strlen(qid_text)), 1, 4, 0);
  CHECK_TRUE(dq != nullptr && dq->needs_csr == 1);
  dmlc_free_dense(dq);

  // csv
  const char* csv = "1,2.5,3\n4,5.5,6\n";
  CsvResult* c = dmlc_parse_csv(csv, static_cast<int64_t>(strlen(csv)), 2, ',');
  CHECK_TRUE(c != nullptr && c->error == nullptr);
  CHECK_TRUE(c->n_rows == 2 && c->n_cols == 3 && c->cells[1] == 2.5f);
  dmlc_free_csv(c);

  // csv split: label mid-column, weight last — features are the two runs
  // around them; the sanitizers watch the run-wise memcpy bounds here
  const char* csv2 = "1,9,2.5,3,0.5\n4,8,5.5,6,0.25\n";
  CsvSplitResult* s = dmlc_parse_csv_split(
      csv2, static_cast<int64_t>(strlen(csv2)), 2, ',', /*label_col=*/1,
      /*weight_col=*/4);
  CHECK_TRUE(s != nullptr && s->error == nullptr);
  CHECK_TRUE(s->n_rows == 2 && s->n_feat_cols == 3);
  CHECK_TRUE(s->values[0] == 1.0f && s->values[1] == 2.5f &&
             s->values[2] == 3.0f && s->values[3] == 4.0f);
  CHECK_TRUE(s->label[0] == 9.0f && s->label[1] == 8.0f);
  CHECK_TRUE(s->weight[0] == 0.5f && s->weight[1] == 0.25f);
  dmlc_free_csv_split(s);
  // guard rails: equal columns and out-of-range columns must error, not
  // write out of bounds
  CsvSplitResult* s2 = dmlc_parse_csv_split(
      csv2, static_cast<int64_t>(strlen(csv2)), 1, ',', 2, 2);
  CHECK_TRUE(s2 != nullptr && s2->error != nullptr);
  dmlc_free_csv_split(s2);
  CsvSplitResult* s3 = dmlc_parse_csv_split(
      csv2, static_cast<int64_t>(strlen(csv2)), 1, ',', 9, -1);
  CHECK_TRUE(s3 != nullptr && s3->error != nullptr);
  dmlc_free_csv_split(s3);

  // streaming reader over a temp file, exercised twice (before_first)
  char path[] = "/tmp/dmlc_tpu_smoke_XXXXXX";
  int fd = mkstemp(path);
  CHECK_TRUE(fd >= 0);
  FILE* f = fdopen(fd, "w");
  for (int i = 0; i < 1000; ++i) std::fprintf(f, "%d 0:%d.5 1:2\n", i % 2, i);
  fclose(f);
  long size = 0;
  {
    FILE* g = fopen(path, "rb");
    fseek(g, 0, SEEK_END);
    size = ftell(g);
    fclose(g);
  }
  const char* paths[] = {path};
  int64_t sizes[] = {size};
  void* r = dmlc_reader_create(paths, sizes, 1, 0, 1, /*fmt=*/0, 0, 0, ',',
                               2, 4096, 2, /*batch_rows=*/0,
                               /*label_col=*/-1, /*weight_col=*/-1,
                               /*out_bf16=*/0, /*row_bucket=*/0,
                               /*nnz_bucket=*/0, /*elide_unit=*/0,
                               /*csr_wire=*/0, /*pack_aux=*/0);
  CHECK_TRUE(r != nullptr);
  for (int pass = 0; pass < 2; ++pass) {
    int64_t rows = 0;
    while (true) {
      int32_t fmt = 0;
      void* res = dmlc_reader_next(r, &fmt);
      if (!res) break;
      CsrBlockResult* blk = static_cast<CsrBlockResult*>(res);
      CHECK_TRUE(blk->error == nullptr);
      rows += blk->n_rows;
      dmlc_free_block(blk);
    }
    CHECK_TRUE(dmlc_reader_error(r) == nullptr);
    CHECK_TRUE(rows == 1000);
    dmlc_reader_before_first(r);
  }
  dmlc_reader_destroy(r);
  remove(path);

  // indexed recordio reader: sequential, shuffled epochs, native skip —
  // all under the sanitizer (producer thread + per-record seeks)
  {
    char rpath[] = "/tmp/dmlc_tpu_smoke_rec_XXXXXX";
    int rfd = mkstemp(rpath);
    CHECK_TRUE(rfd >= 0);
    FILE* rf = fdopen(rfd, "wb");
    const uint32_t magic = 0xced7230a;
    int64_t offsets[64];
    for (int i = 0; i < 64; ++i) {
      offsets[i] = static_cast<int64_t>(ftell(rf));
      uint32_t len = 8 + static_cast<uint32_t>(i % 4);
      uint32_t lrec = len;  // cflag 0
      fwrite(&magic, 4, 1, rf);
      fwrite(&lrec, 4, 1, rf);
      char payload[12] = {0};
      payload[0] = static_cast<char>(i);
      fwrite(payload, 1, len, rf);
      size_t pad = (4 - len % 4) % 4;
      char zeros[4] = {0, 0, 0, 0};
      fwrite(zeros, 1, pad, rf);
    }
    int64_t fsize = static_cast<int64_t>(ftell(rf));
    fclose(rf);
    const char* rpaths[1] = {rpath};
    for (int shuffle = 0; shuffle < 2; ++shuffle) {
      void* ir = dmlc_indexed_reader_create(
          rpaths, &fsize, 1, offsets, 64, /*part=*/0, /*nparts=*/1,
          /*batch_records=*/7, shuffle, /*seed=*/3, /*queue_depth=*/2);
      CHECK_TRUE(ir != nullptr);
      for (int pass = 0; pass < 2; ++pass) {
        int64_t recs = 0;
        while (true) {
          void* res = dmlc_indexed_reader_next(ir);
          if (!res) break;
          RecordBatchResult* rb = static_cast<RecordBatchResult*>(res);
          CHECK_TRUE(rb->error == nullptr);
          recs += rb->n_records;
          dmlc_free_records(rb);
        }
        CHECK_TRUE(dmlc_indexed_reader_error(ir) == nullptr);
        CHECK_TRUE(recs == 64);
        dmlc_indexed_reader_before_first(ir);
      }
      // native skip: land mid-epoch, count only the suffix
      dmlc_indexed_reader_skip(ir, /*epochs=*/2, /*records=*/50);
      CHECK_TRUE(dmlc_indexed_reader_error(ir) == nullptr);
      int64_t rest = 0;
      while (true) {
        void* res = dmlc_indexed_reader_next(ir);
        if (!res) break;
        RecordBatchResult* rb = static_cast<RecordBatchResult*>(res);
        rest += rb->n_records;
        dmlc_free_records(rb);
      }
      CHECK_TRUE(rest == 14);
      dmlc_indexed_reader_destroy(ir);
    }
    remove(rpath);
  }

  // text -> COO: one-shot parse with bucket padding + unit elision, and
  // the streaming reader in COO mode (format 7), all under the sanitizer
  {
    const char* fm = "1 0:10:1 1:20:1\n0 2:30:1\n";
    CooResult* co = dmlc_parse_coo(fm, static_cast<int64_t>(strlen(fm)),
                                   /*nthread=*/2, /*indexing_mode=*/0,
                                   /*fmt=*/3, /*num_col=*/100,
                                   /*row_bucket=*/4, /*nnz_bucket=*/8,
                                   /*elide_unit=*/1, /*csr_wire=*/0);
    CHECK_TRUE(co != nullptr && co->error == nullptr);
    CHECK_TRUE(co->n_rows == 2 && co->nnz == 3);
    CHECK_TRUE(co->rows_padded == 4 && co->nnz_padded == 8);
    CHECK_TRUE(co->values_elided == 1 && co->values == nullptr);
    CHECK_TRUE(co->csr_wire == 0 && co->row_ptr == nullptr);
    CHECK_TRUE(co->coords[0] == 0 && co->coords[1] == 10);
    CHECK_TRUE(co->coords[4] == 1 && co->coords[5] == 30);
    CHECK_TRUE(co->coords[6] == 4 && co->coords[7] == 100);  // OOB pad
    CHECK_TRUE(co->weight[1] == 1.0f && co->weight[2] == 0.0f);
    dmlc_free_coo(co);

    // CSR wire: cols-only coords + row_ptr with pad rows pinned at nnz
    CooResult* cw = dmlc_parse_coo(fm, static_cast<int64_t>(strlen(fm)),
                                   /*nthread=*/2, /*indexing_mode=*/0,
                                   /*fmt=*/3, /*num_col=*/100,
                                   /*row_bucket=*/4, /*nnz_bucket=*/8,
                                   /*elide_unit=*/1, /*csr_wire=*/1);
    CHECK_TRUE(cw != nullptr && cw->error == nullptr);
    CHECK_TRUE(cw->csr_wire == 1 && cw->row_ptr != nullptr);
    CHECK_TRUE(cw->coords[0] == 10 && cw->coords[1] == 20 &&
               cw->coords[2] == 30);
    CHECK_TRUE(cw->coords[3] == 100 && cw->coords[7] == 100);  // OOB pad
    CHECK_TRUE(cw->row_ptr[0] == 0 && cw->row_ptr[1] == 2 &&
               cw->row_ptr[2] == 3);
    CHECK_TRUE(cw->row_ptr[3] == 3 && cw->row_ptr[4] == 3);  // pad rows
    dmlc_free_coo(cw);

    char cpath[] = "/tmp/dmlc_tpu_smoke_coo_XXXXXX";
    int cfd = mkstemp(cpath);
    CHECK_TRUE(cfd >= 0);
    FILE* cf = fdopen(cfd, "w");
    for (int i = 0; i < 500; ++i)
      std::fprintf(cf, "%d 0:%d:1 1:%d:2.5\n", i % 2, i % 97, i % 89);
    long csize;
    fflush(cf);
    csize = ftell(cf);
    fclose(cf);
    const char* cpaths[] = {cpath};
    int64_t csizes[] = {csize};
    void* cr = dmlc_reader_create(cpaths, csizes, 1, 0, 1, /*fmt=*/7,
                                  /*num_col=*/128, 0, ',', 2, 4096, 2, 0,
                                  -1, -1, 0, /*row_bucket=*/64,
                                  /*nnz_bucket=*/256, /*elide_unit=*/1,
                                  /*csr_wire=*/0, /*pack_aux=*/0);
    CHECK_TRUE(cr != nullptr);
    for (int pass = 0; pass < 2; ++pass) {
      int64_t rows = 0, nnz = 0;
      while (true) {
        int32_t fmt = 7;
        void* res = dmlc_reader_next(cr, &fmt);
        if (!res) break;
        CHECK_TRUE(fmt == 7);
        CooResult* blk = static_cast<CooResult*>(res);
        CHECK_TRUE(blk->error == nullptr);
        CHECK_TRUE(blk->values_elided == 0);  // 2.5 values present
        CHECK_TRUE(blk->rows_padded % 64 == 0);
        CHECK_TRUE(blk->nnz_padded % 256 == 0);
        rows += blk->n_rows;
        nnz += blk->nnz;
        dmlc_free_coo(blk);
      }
      CHECK_TRUE(dmlc_reader_error(cr) == nullptr);
      CHECK_TRUE(rows == 500 && nnz == 1000);
      dmlc_reader_before_first(cr);
    }
    dmlc_reader_destroy(cr);
    remove(cpath);
  }

  // buffer pool (memory.h analog): same-size blocks recycle, depth and
  // byte caps hold, trim drains. Recycling checks only apply when the
  // pool is enabled — under DMLC_TPU_POOL=0 (the documented leak-triage
  // mode) every release goes straight to free() by design.
  {
    using dmlc_tpu::dmlc_pool_alloc;
    using dmlc_tpu::dmlc_pool_free;
    using dmlc_tpu::pool_detail::kMaxFreePerSize;
    using dmlc_tpu::pool_detail::kMinPooledBytes;
    const bool pooling = dmlc_tpu::pool_detail::pool().enabled;
    dmlc_tpu::dmlc_pool_trim();
    const size_t big = 1u << 20;
    void* a = dmlc_pool_alloc(big);
    CHECK_TRUE(a != nullptr);
    memset(a, 7, big);  // sanitizers watch the full payload
    dmlc_pool_free(a);
    if (pooling) {
      CHECK_TRUE(dmlc_tpu::dmlc_pool_cached_bytes() == big);
      void* b = dmlc_pool_alloc(big);
      CHECK_TRUE(b == a);  // recycled, not re-mmapped
      CHECK_TRUE(dmlc_tpu::dmlc_pool_cached_bytes() == 0);
      dmlc_pool_free(b);
      dmlc_tpu::dmlc_pool_trim();
    }
    void* small = dmlc_pool_alloc(kMinPooledBytes / 2);  // below threshold
    dmlc_pool_free(small);
    CHECK_TRUE(dmlc_tpu::dmlc_pool_cached_bytes() == 0);
    // per-size depth cap: free more than kMaxFreePerSize blocks of one
    // pooled size, cache stays capped at the configured depth
    const size_t sz = 2 * kMinPooledBytes;
    const size_t n_many = kMaxFreePerSize + 4;
    std::vector<void*> many;
    for (size_t i = 0; i < n_many; ++i) many.push_back(dmlc_pool_alloc(sz));
    for (void* p : many) dmlc_pool_free(p);
    CHECK_TRUE(dmlc_tpu::dmlc_pool_cached_bytes() <=
               kMaxFreePerSize * sz);
    dmlc_tpu::dmlc_pool_trim();
    CHECK_TRUE(dmlc_tpu::dmlc_pool_cached_bytes() == 0);
  }

  // chunk-batch segment parser (batch_parse.cc): the span layout, crc,
  // SIMD scan dispatch, and the boundary shapes the cold path must
  // survive — CRLF, CR-only, an unterminated final record, blank runs
  {
    CHECK_TRUE(dmlc_simd_level() >= 0 && dmlc_simd_level() <= 3);
    // crc kernel parity with the known IEEE test vector
    CHECK_TRUE(dmlc_crc32("123456789", 9) == 0xCBF43926u);
    const char* bt = "1 0:1.5 3:2.5\r\n0 1:0.5\r\n1 2:3.0 4:4.5";  // no EOL
    SegmentBlockResult* sb = dmlc_parse_batch(
        bt, static_cast<int64_t>(strlen(bt)), 2, /*fmt=*/0,
        /*indexing_mode=*/0, ',', -1, -1);
    CHECK_TRUE(sb != nullptr && sb->error == nullptr);
    CHECK_TRUE(sb->n_rows == 3 && sb->nnz == 5);
    CHECK_TRUE(sb->simd_level == dmlc_simd_level());
    // span structure: offset first at 0, every present segment 64-aligned
    CHECK_TRUE(sb->seg_off[DMLC_SEG_OFFSET] == 0);
    for (int sseg = 0; sseg < DMLC_SEG_COUNT; ++sseg) {
      if (sb->seg_off[sseg] >= 0) CHECK_TRUE(sb->seg_off[sseg] % 64 == 0);
    }
    CHECK_TRUE(sb->seg_off[DMLC_SEG_WEIGHT] < 0);  // unweighted corpus
    const int64_t* off =
        reinterpret_cast<const int64_t*>(sb->buf + sb->seg_off[DMLC_SEG_OFFSET]);
    CHECK_TRUE(off[0] == 0 && off[3] == 5);
    CHECK_TRUE(sb->num_col == 5);  // max index 4 + 1
    // the recorded crc is the crc of the span bytes
    CHECK_TRUE(dmlc_crc32(sb->buf, sb->buf_len) == sb->crc32);
    dmlc_free_segblock(sb);

    // weights + qid + blank runs, CR-only endings
    const char* wq = "1:0.5 qid:1 0:1\r\r0:0.25 qid:2 1:2\r";
    SegmentBlockResult* sw = dmlc_parse_batch(
        wq, static_cast<int64_t>(strlen(wq)), 1, 0, 0, ',', -1, -1);
    CHECK_TRUE(sw != nullptr && sw->error == nullptr);
    CHECK_TRUE(sw->n_rows == 2);
    CHECK_TRUE(sw->seg_off[DMLC_SEG_WEIGHT] >= 0 &&
               sw->seg_off[DMLC_SEG_QID] >= 0);
    dmlc_free_segblock(sw);

    // csv with label/weight split; trailing unterminated row
    const char* bc = "1,9,2.5\r\n4,8,5.5";
    SegmentBlockResult* sc = dmlc_parse_batch(
        bc, static_cast<int64_t>(strlen(bc)), 2, /*fmt=*/2, 0, ',',
        /*label_col=*/0, /*weight_col=*/1);
    CHECK_TRUE(sc != nullptr && sc->error == nullptr);
    CHECK_TRUE(sc->n_rows == 2 && sc->nnz == 2 && sc->num_col == 1);
    const float* vals =
        reinterpret_cast<const float*>(sc->buf + sc->seg_off[DMLC_SEG_VALUE]);
    CHECK_TRUE(vals[0] == 2.5f && vals[1] == 5.5f);
    dmlc_free_segblock(sc);

    // libfm triples + indexing heuristic (both mins > 0 -> convert)
    const char* bf = "1 1:10:0.5 2:20:1.5\n";
    SegmentBlockResult* sf = dmlc_parse_batch(
        bf, static_cast<int64_t>(strlen(bf)), 1, /*fmt=*/3,
        /*indexing_mode=*/-1, ',', -1, -1);
    CHECK_TRUE(sf != nullptr && sf->error == nullptr);
    const uint64_t* fld =
        reinterpret_cast<const uint64_t*>(sf->buf + sf->seg_off[DMLC_SEG_FIELD]);
    CHECK_TRUE(fld[0] == 0 && fld[1] == 1);  // 1-based -> 0-based
    dmlc_free_segblock(sf);

    // malformed input errors instead of crashing; empty chunk is clean
    const char* bad = "1 0:1.5 garbage$\n";
    SegmentBlockResult* se = dmlc_parse_batch(
        bad, static_cast<int64_t>(strlen(bad)), 1, 0, 0, ',', -1, -1);
    CHECK_TRUE(se != nullptr && se->error != nullptr);
    dmlc_free_segblock(se);
    SegmentBlockResult* sz = dmlc_parse_batch("\n\r\n", 3, 1, 0, 0, ',',
                                              -1, -1);
    CHECK_TRUE(sz != nullptr && sz->error == nullptr && sz->n_rows == 0);
    dmlc_free_segblock(sz);
  }

  CHECK_TRUE(dmlc_native_abi_version() == 16);
  if (failures == 0) std::printf("native_smoke: all checks passed\n");
  return failures == 0 ? 0 : 1;
}
