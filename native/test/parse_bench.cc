// Single-core parse throughput harness for the native scanners.
//
// Usage: parse_bench <corpus.libsvm> [num_col] [reps]
// Times dmlc_parse_libsvm_dense and dmlc_parse_libsvm (1 thread) over the
// whole file, printing MB/s per rep — the number that bounds into-HBM
// throughput on a 1-core bench host.

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "../src/api.h"

static double now() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s corpus.libsvm [num_col] [reps]\n", argv[0]);
    return 2;
  }
  int64_t num_col = argc > 2 ? atoll(argv[2]) : 28;
  int reps = argc > 3 ? atoi(argv[3]) : 3;
  FILE* f = fopen(argv[1], "rb");
  if (!f) { perror("fopen"); return 1; }
  fseek(f, 0, SEEK_END);
  long len = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data(static_cast<size_t>(len), '\0');
  if (fread(&data[0], 1, static_cast<size_t>(len), f) != static_cast<size_t>(len)) {
    perror("fread"); return 1;
  }
  fclose(f);
  double mb = static_cast<double>(len) / (1 << 20);
  printf("corpus: %.1f MB, num_col=%lld\n", mb, (long long)num_col);

  for (int r = 0; r < reps; ++r) {
    double t0 = now();
    DenseResult* res = dmlc_parse_libsvm_dense(data.data(), len, 1, num_col, -1);
    double dt = now() - t0;
    if (res->error) { fprintf(stderr, "dense error: %s\n", res->error); return 1; }
    printf("dense  1-thread: %lld rows in %.3fs = %.1f MB/s\n",
           (long long)res->n_rows, dt, mb / dt);
    dmlc_free_dense(res);
  }
  for (int r = 0; r < reps; ++r) {
    double t0 = now();
    CsrBlockResult* res = dmlc_parse_libsvm(data.data(), len, 1, -1);
    double dt = now() - t0;
    if (res->error) { fprintf(stderr, "csr error: %s\n", res->error); return 1; }
    printf("csr    1-thread: %lld rows in %.3fs = %.1f MB/s\n",
           (long long)res->n_rows, dt, mb / dt);
    dmlc_free_block(res);
  }
  return 0;
}
