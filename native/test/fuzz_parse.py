"""Crash-safety fuzz for the native parse C ABI.

Feeds mutated (byte-flip / delete / insert) variants of valid libsvm /
csv / libfm / RecordIO seeds into every native parse entry point and
asserts the process survives — parse errors are expected and fine; a
SIGSEGV/abort is the failure this hunts. The text scanners and the
RecordIO frame walker read length fields and delimiters straight from
untrusted bytes, which is exactly the surface a mutation fuzz stresses
(the reference's parsers carry the same risk class but no fuzz harness;
its sanitizer CI runs only fixed corpora, scripts/travis).

Runs in-process (a crash kills the run — run it via `make fuzz`, which
wraps it in a subprocess and checks the exit code). Iterations via
DMLC_FUZZ_ITERS (default 2000, ~15 s on the dev host; r5 validation ran
8000 per group clean).
"""

from __future__ import annotations


import os
import random
import struct
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from dmlc_tpu import native  # noqa: E402

ITERS = int(os.environ.get("DMLC_FUZZ_ITERS", "2000"))


def mutate(rng: random.Random, b: bytes) -> bytes:
    out = bytearray(b * rng.randint(1, 3))
    for _ in range(rng.randint(1, 16)):
        if not out:
            break
        op = rng.randint(0, 2)
        i = rng.randrange(len(out))
        if op == 0:
            out[i] = rng.randrange(256)
        elif op == 1:
            del out[i]
        else:
            out.insert(i, rng.randrange(256))
    return bytes(out)


def main() -> int:
    lib = native._load()  # signatures come from _declare (one ABI source)
    if lib is None:
        print("native core unavailable; nothing to fuzz")
        return 0
    rng = random.Random(int(os.environ.get("DMLC_FUZZ_SEED", "1234")))
    magic = struct.pack("<I", 0xCED7230A)
    seeds = [
        b"1 0:1.5 3:2.5\n0 1:0.5\n1 qid:3 2:3.0 4:4.5\n",
        b"1,2.5,3\n4,5.5,6\n",
        b"1 0:10:1 1:20:1\n0 2:30:0.5\n",
        b"# comment\n1:2 label\n",
        magic + struct.pack("<I", 8) + b"payload1",
        magic + struct.pack("<I", (1 << 29) | 12) + b"x" * 12,  # multipart
    ]
    for it in range(ITERS):
        data = mutate(rng, rng.choice(seeds))
        try:
            native.parse_libsvm(data, nthread=2)
        except Exception:  # noqa: BLE001 - parse errors are the happy path
            pass
        try:
            native.parse_csv(data)
        except Exception:  # noqa: BLE001
            pass
        try:
            native.parse_libfm(data, nthread=2)
        except Exception:  # noqa: BLE001
            pass
        try:
            native.parse_libsvm_dense(data, 8, nthread=2)
        except Exception:  # noqa: BLE001
            pass
        try:
            native.recordio_extract(data)
        except Exception:  # noqa: BLE001
            pass
        r = lib.dmlc_parse_csv_split(data, len(data), 2, b",",
                                     rng.randint(-1, 6), rng.randint(-1, 6))
        if r:
            lib.dmlc_free_csv_split(r)
        for fmt, nc in ((3, 1000), (0, 50)):
            r = lib.dmlc_parse_coo(data, len(data), 2, 0, fmt, nc,
                                   rng.choice([0, 4]), rng.choice([0, 8]),
                                   rng.randint(0, 1), rng.randint(0, 1))
            if r:
                lib.dmlc_free_coo(r)
        # chunk-batch segment parser (batch_parse.cc): every format,
        # random indexing mode and csv column config — the SIMD scan and
        # the span assembly walk untrusted boundary shapes here
        for fmt in (0, 2, 3):
            r = lib.dmlc_parse_batch(data, len(data), 2, fmt,
                                     rng.choice([-1, 0, 1]), b",",
                                     rng.randint(-1, 6), rng.randint(-1, 6))
            if r:
                lib.dmlc_free_segblock(r)
    print(f"fuzz_parse: {ITERS} iterations x 11 entry points, no crash")
    return 0


if __name__ == "__main__":
    sys.exit(main())
