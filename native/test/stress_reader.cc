// Threaded stress for the native core, built to run under TSan/ASan
// (native/run_sanitizers.sh): exercises exactly the code the sanitizers
// earn their keep on — the reader's producer/consumer handoff, epoch
// resets racing the producer, early destruction with results in flight,
// the push-mode feeder's pusher/producer/consumer triangle including
// abort, and the multi-threaded chunk parsers.

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "../src/api.h"

namespace {

int failures = 0;

#define CHECK_TRUE(cond, msg)                          \
  do {                                                 \
    if (!(cond)) {                                     \
      fprintf(stderr, "FAIL: %s (%s:%d)\n", msg,       \
              __FILE__, __LINE__);                     \
      ++failures;                                      \
    }                                                  \
  } while (0)

std::string write_libsvm(const char* path, int rows) {
  FILE* f = fopen(path, "wb");
  for (int i = 0; i < rows; ++i) {
    fprintf(f, "%d", i % 2);
    for (int j = 0; j < 16; ++j) fprintf(f, " %d:%d.%06d", j, i % 3, j * 7);
    fputc('\n', f);
  }
  fclose(f);
  return path;
}

std::string write_recordio(const char* path, int recs) {
  // complete records only (cflag 0): payload without aligned magic cells
  FILE* f = fopen(path, "wb");
  const uint32_t magic = 0xced7230a;
  for (int i = 0; i < recs; ++i) {
    uint32_t len = 64 + (i % 160);
    std::string payload(len, static_cast<char>('a' + i % 26));
    uint32_t lrec = len;  // cflag 0
    fwrite(&magic, 4, 1, f);
    fwrite(&lrec, 4, 1, f);
    fwrite(payload.data(), 1, len, f);
    static const char pad[4] = {0, 0, 0, 0};
    fwrite(pad, 1, (4 - len % 4) % 4, f);
  }
  fclose(f);
  return path;
}

int64_t fsize(const std::string& p) {
  FILE* f = fopen(p.c_str(), "rb");
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fclose(f);
  return n;
}

void drain_reader(void* h, int fmt_hint, int64_t* rows_out) {
  int64_t rows = 0;
  while (true) {
    int32_t fmt = fmt_hint;
    void* res = dmlc_reader_next(h, &fmt);
    if (!res) break;
    switch (fmt) {
      case 0:
      case 3: {
        auto* r = static_cast<CsrBlockResult*>(res);
        CHECK_TRUE(!r->error, "csr block error");
        rows += r->n_rows;
        dmlc_free_block(r);
        break;
      }
      case 1: {
        auto* r = static_cast<DenseResult*>(res);
        CHECK_TRUE(!r->error, "dense block error");
        rows += r->n_rows;
        dmlc_free_dense(r);
        break;
      }
      case 4: {
        auto* r = static_cast<RecordBatchResult*>(res);
        CHECK_TRUE(!r->error, "record batch error");
        rows += r->n_records;
        dmlc_free_records(r);
        break;
      }
      case 6:
      case 7: {
        auto* r = static_cast<CooResult*>(res);
        CHECK_TRUE(!r->error, "coo block error");
        rows += r->n_rows;
        dmlc_free_coo(r);
        break;
      }
      default: {
        auto* r = static_cast<CsvResult*>(res);
        rows += r->n_rows;
        dmlc_free_csv(r);
      }
    }
  }
  *rows_out = rows;
}

void stress_pull_reader(const std::string& p1, const std::string& p2) {
  const char* paths[2] = {p1.c_str(), p2.c_str()};
  int64_t sizes[2] = {fsize(p1), fsize(p2)};
  // multi-epoch with batch repack, consumer on another thread
  void* h = dmlc_reader_create(paths, sizes, 2, 0, 1, /*fmt dense*/ 1,
                               /*num_col*/ 16, -1, ',', 4, 1 << 16, 4,
                               /*batch_rows*/ 100, -1, -1, 0, 0, 0, 0, 0,
                               /*pack_aux=*/1);
  CHECK_TRUE(h != nullptr, "reader create");
  for (int epoch = 0; epoch < 3; ++epoch) {
    int64_t rows = 0;
    std::thread consumer(drain_reader, h, 1, &rows);
    consumer.join();
    CHECK_TRUE(rows == 4000, "dense rows per epoch");
    dmlc_reader_before_first(h);
  }
  dmlc_reader_destroy(h);

  // early destruction with the queue full (stop path racing the producer)
  for (int i = 0; i < 8; ++i) {
    void* h2 = dmlc_reader_create(paths, sizes, 2, 0, 1, 0, 0, -1, ',', 4,
                                  1 << 14, 2, 0, -1, -1, 0, 0, 0, 0, 0, 0);
    int32_t fmt = 0;
    void* res = dmlc_reader_next(h2, &fmt);
    if (res) dmlc_free_block(static_cast<CsrBlockResult*>(res));
    dmlc_reader_destroy(h2);  // producer mid-flight
  }

  // partitioned, concurrent readers
  std::vector<std::thread> ts;
  std::atomic<int64_t> total{0};
  for (int part = 0; part < 4; ++part) {
    ts.emplace_back([&, part] {
      void* hp = dmlc_reader_create(paths, sizes, 2, part, 4, 0, 0, -1, ',',
                                    2, 1 << 14, 2, 0, -1, -1, 0, 0, 0, 0, 0,
                                    0);
      int64_t rows = 0;
      drain_reader(hp, 0, &rows);
      total += rows;
      dmlc_reader_destroy(hp);
    });
  }
  for (auto& t : ts) t.join();
  CHECK_TRUE(total.load() == 4000, "partitioned row total");
}

void stress_feeder(const std::string& p1) {
  FILE* f = fopen(p1.c_str(), "rb");
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data(static_cast<size_t>(n), '\0');
  if (fread(&data[0], 1, static_cast<size_t>(n), f) != static_cast<size_t>(n))
    abort();
  fclose(f);

  for (int epoch = 0; epoch < 2; ++epoch) {
    void* h = dmlc_feeder_create(1, 16, -1, ',', 4, 1 << 14, 2, 128, -1, -1,
                                 /*out_bf16=*/0, 0, 0, 0, /*csr_wire=*/0,
                                 /*pack_aux=*/1);
    CHECK_TRUE(h != nullptr, "feeder create");
    std::thread pusher([&] {
      size_t at = 0;
      while (at < data.size()) {
        size_t take = std::min<size_t>(7919, data.size() - at);
        if (dmlc_feeder_push(h, data.data() + at, take) != 0) break;
        at += take;
      }
      dmlc_feeder_finish(h);
    });
    int64_t rows = 0;
    while (true) {
      int32_t fmt = 1;
      void* res = dmlc_feeder_next(h, &fmt);
      if (!res) break;
      auto* r = static_cast<DenseResult*>(res);
      rows += r->n_rows;
      dmlc_free_dense(r);
    }
    pusher.join();
    CHECK_TRUE(rows == 2000, "feeder rows");
    dmlc_feeder_destroy(h);
  }

  // abort racing an active pusher
  for (int i = 0; i < 8; ++i) {
    void* h = dmlc_feeder_create(0, 0, -1, ',', 2, 1 << 12, 1, 0, -1, -1, 0,
                                 0, 0, 0, /*csr_wire=*/0, /*pack_aux=*/0);
    std::thread pusher([&] {
      size_t at = 0;
      while (at < data.size()) {
        size_t take = std::min<size_t>(4096, data.size() - at);
        if (dmlc_feeder_push(h, data.data() + at, take) != 0) return;
        at += take;
      }
      dmlc_feeder_finish(h);
    });
    int32_t fmt = 0;
    void* res = dmlc_feeder_next(h, &fmt);
    if (res) dmlc_free_block(static_cast<CsrBlockResult*>(res));
    dmlc_feeder_abort(h);
    pusher.join();
    dmlc_feeder_destroy(h);
  }
}

void stress_coo(const std::string& p1, const std::string& p2) {
  // partitioned concurrent COO readers (libsvm -> fmt 6) with bucket
  // padding + elision enabled: the merge_parts_coo fill runs under TSan
  // against the chunk parse threads
  const char* paths[2] = {p1.c_str(), p2.c_str()};
  int64_t sizes[2] = {fsize(p1), fsize(p2)};
  std::vector<std::thread> ts;
  std::atomic<int64_t> total{0};
  for (int part = 0; part < 4; ++part) {
    ts.emplace_back([&, part] {
      void* hp = dmlc_reader_create(paths, sizes, 2, part, 4, /*fmt=*/6,
                                    /*num_col=*/64, -1, ',', 2, 1 << 14, 2,
                                    0, -1, -1, 0, /*row_bucket=*/32,
                                    /*nnz_bucket=*/128, /*elide_unit=*/1,
                                    /*csr_wire=*/1, /*pack_aux=*/0);
      int64_t rows = 0;
      drain_reader(hp, 6, &rows);
      total += rows;
      dmlc_reader_destroy(hp);
    });
  }
  for (auto& t : ts) t.join();
  CHECK_TRUE(total.load() == 4000, "coo partitioned row total");
}

void stress_recordio(const std::string& rec1, const std::string& rec2) {
  const char* paths[2] = {rec1.c_str(), rec2.c_str()};
  int64_t sizes[2] = {fsize(rec1), fsize(rec2)};
  std::vector<std::thread> ts;
  std::atomic<int64_t> total{0};
  for (int part = 0; part < 3; ++part) {
    ts.emplace_back([&, part] {
      void* h = dmlc_reader_create(paths, sizes, 2, part, 3, 4, 0, -1, ',',
                                   2, 1 << 14, 2, 0, -1, -1, 0, 0, 0, 0, 0,
                                   0);
      int64_t recs = 0;
      drain_reader(h, 4, &recs);
      total += recs;
      dmlc_reader_destroy(h);
    });
  }
  for (auto& t : ts) t.join();
  CHECK_TRUE(total.load() == 1200, "recordio record total");
}

void stress_parse_threads() {
  std::string blob;
  for (int i = 0; i < 20000; ++i) {
    char line[256];
    snprintf(line, sizeof(line), "%d 0:1.5 3:2.25 9:%d.125\n", i % 2, i % 17);
    blob += line;
  }
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; ++t) {
    ts.emplace_back([&] {
      CsrBlockResult* r = dmlc_parse_libsvm(
          blob.data(), static_cast<int64_t>(blob.size()), 4, -1);
      CHECK_TRUE(!r->error && r->n_rows == 20000, "parallel parse");
      dmlc_free_block(r);
    });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

int main() {
  char tmpl[] = "/tmp/dmlc_stress_XXXXXX";
  if (!mkdtemp(tmpl)) return 2;
  std::string dir(tmpl);
  auto p1 = write_libsvm((dir + "/a.libsvm").c_str(), 2000);
  auto p2 = write_libsvm((dir + "/b.libsvm").c_str(), 2000);
  auto r1 = write_recordio((dir + "/a.rec").c_str(), 600);
  auto r2 = write_recordio((dir + "/b.rec").c_str(), 600);

  stress_pull_reader(p1, p2);
  stress_feeder(p1);
  stress_coo(p1, p2);
  stress_recordio(r1, r2);
  stress_parse_threads();

  if (failures) {
    fprintf(stderr, "stress: %d failures\n", failures);
    return 1;
  }
  printf("stress: OK\n");
  return 0;
}
