// Native streaming reader: byte-range partitioned file reading + record
// boundary chunking + threaded parse, all off the Python thread.
//
// TPU-native rebuild of the reference read pipeline (src/io/
// input_split_base.cc + line_split.cc + threaded_input_split.h): one
// producer thread loads record-aligned chunks of this partition and parses
// each with the multi-threaded scanners in parse.cc, pushing parsed blocks
// into a bounded queue. The Python consumer pulls fully-parsed blocks with a
// single GIL-releasing ctypes call — so on a TPU-VM host the whole
// read+scan+parse path runs concurrently with JAX dispatch and host->HBM
// transfers.
//
// Partition invariants mirror the Python engine (dmlc_tpu/io/input_split.py)
// and therefore the reference:
//   - partition k of n owns bytes [k*step, (k+1)*step), step = ceil(total/n)
//     over the concatenation of all files (ResetPartition,
//     input_split_base.cc:30-64);
//   - both ends advance to the next record head unless they sit exactly on a
//     file boundary;
//   - '\n' is injected at text-file joins (input_split_base.cc:196-199,
//     PR#385) and when the final record lacks a newline
//     (input_split_base.cc:235-242, PR#452).

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <random>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "api.h"
#include "buffer_pool.h"

using dmlc_tpu::dmlc_pool_alloc;
using dmlc_tpu::dmlc_pool_free;
#include "parse_internal.h"

namespace {

constexpr int kFmtLibsvm = 0;
constexpr int kFmtLibsvmDense = 1;
constexpr int kFmtCsv = 2;
constexpr int kFmtLibfm = 3;
constexpr int kFmtRecordIO = 4;
constexpr int kFmtRecordIOChunk = 5;  // raw framed chunks, one per result
constexpr int kFmtLibsvmCoo = 6;      // device-ready COO (CooResult)
constexpr int kFmtLibfmCoo = 7;
constexpr int kFmtCsvSplit = 8;       // csv with label/weight split out
                                      // (CsvSplitResult) — auto-promoted
                                      // from kFmtCsv when label/weight
                                      // columns are configured and no
                                      // dense repack is requested

inline bool is_recordio_fmt(int format) {
  return format == kFmtRecordIO || format == kFmtRecordIOChunk;
}

void free_result(int format, void* res) {
  if (!res) return;
  switch (format) {
    case kFmtLibsvm:
    case kFmtLibfm:
      dmlc_free_block(static_cast<CsrBlockResult*>(res));
      break;
    case kFmtLibsvmDense:
      dmlc_free_dense(static_cast<DenseResult*>(res));
      break;
    case kFmtCsv:
      dmlc_free_csv(static_cast<CsvResult*>(res));
      break;
    case kFmtCsvSplit:
      dmlc_free_csv_split(static_cast<CsvSplitResult*>(res));
      break;
    case kFmtRecordIO:
    case kFmtRecordIOChunk:
      dmlc_free_records(static_cast<RecordBatchResult*>(res));
      break;
    case kFmtLibsvmCoo:
    case kFmtLibfmCoo:
      dmlc_free_coo(static_cast<CooResult*>(res));
      break;
  }
}

int64_t result_rows(int format, void* res) {
  switch (format) {
    case kFmtLibsvm:
    case kFmtLibfm:
      return static_cast<CsrBlockResult*>(res)->n_rows;
    case kFmtLibsvmDense:
      return static_cast<DenseResult*>(res)->n_rows;
    case kFmtCsv:
      return static_cast<CsvResult*>(res)->n_rows;
    case kFmtCsvSplit:
      return static_cast<CsvSplitResult*>(res)->n_rows;
    case kFmtRecordIO:
    case kFmtRecordIOChunk:
      return static_cast<RecordBatchResult*>(res)->n_records;
    case kFmtLibsvmCoo:
    case kFmtLibfmCoo:
      return static_cast<CooResult*>(res)->n_rows;
  }
  return 0;
}

const char* result_error(int format, void* res) {
  switch (format) {
    case kFmtLibsvm:
    case kFmtLibfm:
      return static_cast<CsrBlockResult*>(res)->error;
    case kFmtLibsvmDense:
      return static_cast<DenseResult*>(res)->error;
    case kFmtCsv:
      return static_cast<CsvResult*>(res)->error;
    case kFmtCsvSplit:
      return static_cast<CsvSplitResult*>(res)->error;
    case kFmtRecordIO:
    case kFmtRecordIOChunk:
      return static_cast<RecordBatchResult*>(res)->error;
    case kFmtLibsvmCoo:
    case kFmtLibfmCoo:
      return static_cast<CooResult*>(res)->error;
  }
  return nullptr;
}

inline bool is_eol(char c) { return c == '\n' || c == '\r'; }

// f32 -> bf16 with round-to-nearest-even (the TPU-native ingest format:
// half the host->HBM bytes; the MXU's preferred operand width)
inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, 4);
  if ((bits & 0x7fffffffu) > 0x7f800000u) {
    // NaN: the rounding add below could carry a low-mantissa-only payload
    // into the exponent and emit Inf — quiet it instead (sign + high
    // mantissa kept, quiet bit set), matching ml_dtypes' cast
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);
  }
  bits += 0x7fffu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>(bits >> 16);
}

inline void convert_row_bf16(uint16_t* dst, const float* src, size_t n) {
  for (size_t i = 0; i < n; ++i) dst[i] = f32_to_bf16(src[i]);
}

// ---------------- recordio framing helpers ----------------

constexpr uint32_t kRecMagic = 0xced7230a;

inline uint32_t load_u32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

// Offset of the LAST record head (aligned magic cell whose lrec has cflag
// 0|1) in [d, d+size), or 0 if none — find_last_record_begin semantics
// (recordio_split.cc FindLastRecordBegin / io/recordio.py find_record_heads).
int64_t find_last_record_head(const char* d, int64_t size) {
  for (int64_t i = ((size >> 2) << 2) - 8; i >= 0; i -= 4) {
    if (load_u32(d + i) == kRecMagic &&
        ((load_u32(d + i + 4) >> 29) & 7) <= 1) {
      return i;
    }
  }
  return 0;
}

class LineReader {
 public:
  LineReader(std::vector<std::string> paths, std::vector<int64_t> sizes,
             int64_t part_index, int64_t num_parts, int format,
             int64_t num_col, int indexing_mode, char delim, int nthread,
             int64_t chunk_bytes, int queue_depth, int64_t batch_rows,
             int32_t label_col, int32_t weight_col, bool out_bf16 = false,
             int64_t row_bucket = 0, int64_t nnz_bucket = 0,
             bool elide_unit = false, bool csr_wire = false,
             bool pack_aux = false)
      : paths_(std::move(paths)),
        format_(format),
        num_col_(num_col),
        indexing_mode_(indexing_mode),
        delim_(delim),
        nthread_(nthread < 1 ? 1 : nthread),
        chunk_bytes_(chunk_bytes < 4096 ? 4096 : chunk_bytes),
        queue_depth_(queue_depth < 1 ? 1 : queue_depth),
        batch_rows_(batch_rows > 0 ? batch_rows : 0),
        label_col_(label_col),
        weight_col_(weight_col),
        out_bf16_(out_bf16 && batch_rows > 0),
        row_bucket_(row_bucket > 0 ? row_bucket : 0),
        nnz_bucket_(nnz_bucket > 0 ? nnz_bucket : 0),
        elide_unit_(elide_unit),
        csr_wire_(csr_wire),
        pack_aux_(pack_aux && batch_rows > 0) {
    file_offset_.push_back(0);
    for (size_t i = 0; i < sizes.size(); ++i) {
      if (is_recordio_fmt(format_) && sizes[i] % 4 != 0) {
        error_ = "recordio: file " + paths_[i] + " does not align by 4 bytes";
      }
      file_offset_.push_back(file_offset_.back() + sizes[i]);
    }
    if (error_.empty()) reset_partition(part_index, num_parts);
    if (error_.empty()) try_mmap();
    if (error_.empty()) {
      start();
    } else {
      // never started: mark done so next() returns null (consumer then
      // surfaces error()) instead of waiting on a producer that isn't there
      produce_done_ = true;
    }
  }

  // Push-mode constructor: bytes arrive via push() instead of local files.
  LineReader(int format, int64_t num_col, int indexing_mode, char delim,
             int nthread, int64_t chunk_bytes, int queue_depth,
             int64_t batch_rows, int32_t label_col, int32_t weight_col,
             bool out_bf16 = false, int64_t row_bucket = 0,
             int64_t nnz_bucket = 0, bool elide_unit = false,
             bool csr_wire = false, bool pack_aux = false)
      : format_(format),
        num_col_(num_col),
        indexing_mode_(indexing_mode),
        delim_(delim),
        nthread_(nthread < 1 ? 1 : nthread),
        chunk_bytes_(chunk_bytes < 4096 ? 4096 : chunk_bytes),
        queue_depth_(queue_depth < 1 ? 1 : queue_depth),
        batch_rows_(batch_rows > 0 ? batch_rows : 0),
        label_col_(label_col),
        weight_col_(weight_col),
        out_bf16_(out_bf16 && batch_rows > 0),
        row_bucket_(row_bucket > 0 ? row_bucket : 0),
        nnz_bucket_(nnz_bucket > 0 ? nnz_bucket : 0),
        elide_unit_(elide_unit),
        csr_wire_(csr_wire),
        pack_aux_(pack_aux && batch_rows > 0),
        push_mode_(true) {
    file_offset_.push_back(0);
    start();
  }

  // Feed bytes into the pipeline; blocks while the byte queue is full
  // (backpressure against a fast remote stream). -1 = stopped/failed.
  int32_t push(const char* data, int64_t len) {
    if (len <= 0) return 0;
    std::unique_lock<std::mutex> lk(mu_);
    cv_feed_space_.wait(lk, [&] {
      return feed_bytes_ < kFeedCap || stop_ || produce_done_ || feed_abort_;
    });
    if (stop_ || produce_done_ || feed_done_ || feed_abort_) return -1;
    feed_q_.emplace_back(data, static_cast<size_t>(len));
    feed_bytes_ += static_cast<size_t>(len);
    cv_feed_data_.notify_all();
    return 0;
  }

  void finish() {
    std::lock_guard<std::mutex> lk(mu_);
    feed_done_ = true;
    cv_feed_data_.notify_all();
  }

  // Record a feed-side failure (e.g. a remote read error in the feeding
  // thread) and end the stream: already-parsed blocks still drain, then
  // next() returns NULL with the error set — never a silent truncation.
  void fail_feed(const char* msg) {
    set_error(msg && *msg ? msg : "feed failed");
    finish();
  }

  // Unblock and fail any pusher and let the producer drain to EOF — the
  // caller MUST abort + join its feed thread before before_first()/destroy
  // (a pusher blocked inside a freed reader would be use-after-free).
  void abort_feed() {
    std::lock_guard<std::mutex> lk(mu_);
    feed_abort_ = true;
    cv_feed_space_.notify_all();
    cv_feed_data_.notify_all();
  }

  ~LineReader() {
    stop_and_join();
    close_fp();
    if (map_base_) {
      munmap(const_cast<char*>(map_base_), map_len_);
      map_base_ = nullptr;
    }
    if (cur_) {
      dmlc_free_dense(cur_);
      cur_ = nullptr;
    }
  }

  void* next(int32_t* fmt_out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [&] { return !queue_.empty() || produce_done_; });
    if (queue_.empty()) return nullptr;
    auto item = queue_.front();
    queue_.pop_front();
    cv_push_.notify_one();
    if (fmt_out) *fmt_out = item.first;
    return item.second;
  }

  void before_first() {
    stop_and_join();
    offset_curr_ = offset_begin_;
    view_cur_ = view_begin_;
    overflow_.clear();
    close_fp();
    feed_q_.clear();
    feed_off_ = 0;
    feed_bytes_ = 0;
    feed_done_ = false;
    feed_abort_ = false;
    if (cur_) {
      dmlc_free_dense(cur_);
      cur_ = nullptr;
    }
    cur_rows_ = 0;
    cur_has_weight_ = false;
    if (error_.empty()) {
      start();
    } else {
      // sticky error: stay stopped but unblock any next() caller
      std::lock_guard<std::mutex> lk(mu_);
      produce_done_ = true;
      cv_pop_.notify_all();
    }
  }

  int64_t bytes_read() const { return bytes_read_.load(std::memory_order_relaxed); }

  const char* error() const {
    // set_error is set-once, so the pointer stays stable after return
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_.empty() ? nullptr : error_.c_str();
  }

 private:
  bool is_text() const { return !is_recordio_fmt(format_); }

  // ---------------- partitioning (create-time, mirrors ResetPartition) ----
  void reset_partition(int64_t part_index, int64_t num_parts) {
    int64_t ntotal = file_offset_.back();
    int64_t nstep = (ntotal + num_parts - 1) / num_parts;
    const int64_t align = is_text() ? 1 : 4;
    nstep = ((nstep + align - 1) / align) * align;
    offset_begin_ = std::min(nstep * part_index, ntotal);
    offset_end_ = std::min(nstep * (part_index + 1), ntotal);
    offset_curr_ = offset_begin_;
    if (offset_begin_ >= offset_end_) return;
    size_t fbegin = file_of(offset_begin_);
    size_t fend = file_of(offset_end_);
    if (offset_end_ != file_offset_[fend]) {
      offset_end_ += seek_record_begin(fend, offset_end_ - file_offset_[fend]);
      if (!error_.empty()) return;
    }
    if (offset_begin_ != file_offset_[fbegin]) {
      offset_begin_ +=
          seek_record_begin(fbegin, offset_begin_ - file_offset_[fbegin]);
      if (!error_.empty()) return;
    }
    offset_curr_ = offset_begin_;
  }

  // index of the file containing global offset `off` (last i with
  // file_offset_[i] <= off), like bisect_right(...) - 1
  size_t file_of(int64_t off) const {
    size_t lo = 0, hi = file_offset_.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (file_offset_[mid] <= off) lo = mid + 1; else hi = mid;
    }
    return lo - 1;
  }

  // Bytes from (file fidx, local offset) to the next record head. Text:
  // scan to the first EOL then past the EOL run (line_split.cc:9-26).
  // RecordIO: scan 4-byte cells for magic + cflag 0|1 (recordio_split.cc:
  // 9-25). Both mirror the Python engine exactly.
  int64_t seek_record_begin(size_t fidx, int64_t local_off) {
    FILE* f = fopen(paths_[fidx].c_str(), "rb");
    if (!f) {
      error_ = "cannot open " + paths_[fidx];
      return 0;
    }
    if (fseeko(f, static_cast<off_t>(local_off), SEEK_SET) != 0) {
      error_ = "seek failed in " + paths_[fidx];
      fclose(f);
      return 0;
    }
    int64_t nstep = 0;
    if (!is_text()) {
      char cell[4];
      while (fread(cell, 1, 4, f) == 4) {
        nstep += 4;
        if (load_u32(cell) == kRecMagic) {
          char lrec[4];
          if (fread(lrec, 1, 4, f) != 4) {
            error_ = "invalid recordio format in " + paths_[fidx];
            break;
          }
          nstep += 4;
          if (((load_u32(lrec) >> 29) & 7) <= 1) {
            fclose(f);
            return nstep - 8;
          }
        }
      }
      fclose(f);
      return nstep;  // EOF: no further head in this file
    }
    char buf[512];
    bool in_run = false;
    while (true) {
      size_t r = fread(buf, 1, sizeof(buf), f);
      if (r == 0) break;
      for (size_t i = 0; i < r; ++i) {
        if (!in_run) {
          ++nstep;
          if (is_eol(buf[i])) in_run = true;
        } else {
          if (is_eol(buf[i])) {
            ++nstep;
          } else {
            fclose(f);
            return nstep;
          }
        }
      }
    }
    fclose(f);
    return nstep;
  }

  // ---------------- reading (producer thread) ----------------

  void close_fp() {
    if (fp_) {
      fclose(fp_);
      fp_ = nullptr;
    }
  }

  bool open_file(size_t fidx, int64_t local_off) {
    close_fp();
    fp_ = fopen(paths_[fidx].c_str(), "rb");
    if (!fp_) {
      set_error("cannot open " + paths_[fidx]);
      return false;
    }
    if (local_off && fseeko(fp_, static_cast<off_t>(local_off), SEEK_SET) != 0) {
      set_error("seek failed in " + paths_[fidx]);
      return false;
    }
    file_ptr_ = fidx;
    return true;
  }

  // Read up to `size` payload bytes across file joins, injecting '\n' at
  // joins (Read, input_split_base.cc:177-219). Appends to `out`.
  bool read_bytes(int64_t size, std::string* out) {
    size = std::min(size, offset_end_ - offset_curr_);
    if (size <= 0) return true;
    if (!fp_) {
      size_t fidx = file_of(offset_curr_);
      if (!open_file(fidx, offset_curr_ - file_offset_[fidx])) return false;
    }
    int64_t nleft = size;
    size_t base = out->size();
    out->resize(base + static_cast<size_t>(size));
    char* dst = &(*out)[base];
    while (nleft > 0) {
      size_t got = fread(dst, 1, static_cast<size_t>(nleft), fp_);
      if (got > 0) {
        dst += got;
        nleft -= static_cast<int64_t>(got);
        offset_curr_ += static_cast<int64_t>(got);
        bytes_read_.fetch_add(static_cast<int64_t>(got),
                              std::memory_order_relaxed);
        continue;
      }
      if (ferror(fp_)) {
        set_error("read failed in " + paths_[file_ptr_]);
        return false;
      }
      // file exhausted: newline injection at text-file joins (PR#385);
      // binary formats concatenate files without synthetic bytes
      if (is_text()) {
        *dst++ = '\n';
        nleft -= 1;
        bytes_read_.fetch_add(1, std::memory_order_relaxed);
      }
      if (offset_curr_ != file_offset_[file_ptr_ + 1]) {
        set_error("file offset not calculated correctly");
        return false;
      }
      if (file_ptr_ + 1 >= paths_.size()) break;
      if (!open_file(file_ptr_ + 1, 0)) return false;
    }
    out->resize(static_cast<size_t>(dst - out->data()));
    return true;
  }

  // Pull up to `size` bytes from the push queue into `out`; blocks until
  // enough data, finish(), or stop. A short fill means end of feed.
  bool read_bytes_push(int64_t size, std::string* out) {
    int64_t got = 0;
    while (got < size) {
      std::unique_lock<std::mutex> lk(mu_);
      cv_feed_data_.wait(lk, [&] {
        return !feed_q_.empty() || feed_done_ || feed_abort_ || stop_;
      });
      if (stop_) return false;
      if (feed_q_.empty()) break;  // feed finished/aborted: EOF
      std::string& front = feed_q_.front();
      int64_t avail = static_cast<int64_t>(front.size() - feed_off_);
      int64_t take = std::min(size - got, avail);
      out->append(front, feed_off_, static_cast<size_t>(take));
      feed_off_ += static_cast<size_t>(take);
      feed_bytes_ -= static_cast<size_t>(take);
      got += take;
      if (feed_off_ == front.size()) {
        feed_q_.pop_front();
        feed_off_ = 0;
      }
      cv_feed_space_.notify_all();
    }
    bytes_read_.fetch_add(got, std::memory_order_relaxed);
    return true;
  }

  // One chunk of whole records into `chunk`; false at EOF/error
  // (ReadChunk + Chunk::Load grow loop, input_split_base.cc:221-277).
  bool load_chunk(std::string* chunk) {
    int64_t size = chunk_bytes_;
    while (true) {
      if (static_cast<int64_t>(overflow_.size()) >= size) {
        size *= 2;
        continue;
      }
      size_t olen = overflow_.size();
      chunk->assign(overflow_);
      overflow_.clear();
      bool ok = push_mode_
          ? read_bytes_push(size - static_cast<int64_t>(olen), chunk)
          : read_bytes(size - static_cast<int64_t>(olen), chunk);
      if (!ok) return false;
      if (chunk->empty()) return false;  // EOF
      if (!is_text()) {
        if (static_cast<int64_t>(chunk->size()) != size) {
          return true;  // EOF tail: binary records are exactly complete
        }
        // cut at the LAST record head so the chunk ends on whole records
        int64_t cut = find_last_record_head(
            chunk->data(), static_cast<int64_t>(chunk->size()));
        if (cut == 0) {
          overflow_.swap(*chunk);
          size *= 2;
          continue;
        }
        overflow_.assign(*chunk, static_cast<size_t>(cut), chunk->npos);
        chunk->resize(static_cast<size_t>(cut));
        return true;
      }
      if (chunk->size() == olen) {
        // final record of the partition lacked a newline (PR#452)
        chunk->push_back('\n');
      }
      // cut after the last EOL (find_last_record_begin, line_split.cc:27-34)
      size_t cut = chunk->size();
      while (cut > 0 && !is_eol((*chunk)[cut - 1])) --cut;
      if (cut == 0) {
        overflow_.swap(*chunk);
        size *= 2;
        continue;
      }
      overflow_.assign(*chunk, cut, chunk->npos);
      chunk->resize(cut);
      return true;
    }
  }

  void* parse_chunk(const std::string& chunk) {
    return parse_chunk(chunk.data(), static_cast<int64_t>(chunk.size()));
  }

  void* parse_chunk(const char* data, int64_t len) {
    switch (format_) {
      case kFmtLibsvm:
        return dmlc_parse_libsvm(data, len, nthread_, indexing_mode_);
      case kFmtLibsvmDense:
        return dmlc_parse_libsvm_dense(data, len, nthread_, num_col_,
                                       indexing_mode_);
      case kFmtCsv:
        return dmlc_parse_csv(data, len, nthread_, delim_);
      case kFmtCsvSplit:
        return dmlc_parse_csv_split(data, len, nthread_, delim_, label_col_,
                                    weight_col_);
      case kFmtLibfm:
        return dmlc_parse_libfm(data, len, nthread_, indexing_mode_);
      case kFmtLibsvmCoo:
      case kFmtLibfmCoo: {
        void* r = dmlc_parse_coo(data, len, nthread_, indexing_mode_,
                                 format_ == kFmtLibfmCoo ? 3 : 0, num_col_,
                                 row_bucket_, nnz_bucket_,
                                 elide_unit_ ? 1 : 0, csr_wire_ ? 1 : 0);
        if (!r) set_error("coo: out of memory");
        return r;
      }
      case kFmtRecordIO: {
        void* r = dmlc_recordio_extract(data, len);
        if (!r) set_error("recordio: out of memory");
        return r;
      }
      case kFmtRecordIOChunk: {
        // raw record-aligned chunk as a single-record batch (NextChunk
        // consumers re-frame it with RecordIOChunkReader themselves)
        auto* r = static_cast<RecordBatchResult*>(
            calloc(1, sizeof(RecordBatchResult)));
        char* d = r ? static_cast<char*>(malloc(len ? static_cast<size_t>(len) : 1))
                    : nullptr;
        auto* offs = r ? static_cast<int64_t*>(malloc(2 * sizeof(int64_t)))
                       : nullptr;
        if (!r || !d || !offs) {
          free(d);
          free(offs);
          free(r);
          set_error("recordio: out of memory");
          return nullptr;
        }
        memcpy(d, data, static_cast<size_t>(len));
        r->n_records = 1;
        r->data_len = len;
        r->data = d;
        r->offsets = offs;
        r->offsets[0] = 0;
        r->offsets[1] = r->data_len;
        return r;
      }
    }
    set_error("unknown format");
    return nullptr;
  }

  // ---------------- mmap fast path ----------------
  //
  // When the whole partition lies inside ONE local file (the common case:
  // a single big corpus, any partition not crossing a file join), chunking
  // reduces to pointer arithmetic over a read-only mapping: no fread copy,
  // no chunk-buffer assembly — the scanners read the page cache directly.
  // Chunk boundary rules are identical to the buffered path (cut after the
  // last EOL / at the last record head; EOF tail taken whole; the scanners
  // handle a final line without a trailing newline).

  void try_mmap() {
    if (push_mode_ || offset_begin_ >= offset_end_) return;
    const char* env = getenv("DMLC_TPU_NO_MMAP");
    if (env && *env && strcmp(env, "0") != 0) return;
    size_t f = file_of(offset_begin_);
    if (offset_end_ > file_offset_[f + 1]) return;  // crosses a file join
    int fd = ::open(paths_[f].c_str(), O_RDONLY);
    if (fd < 0) return;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size <= 0) {
      ::close(fd);
      return;
    }
    void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                      MAP_PRIVATE, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) return;
    map_base_ = static_cast<const char*>(base);
    map_len_ = static_cast<size_t>(st.st_size);
    madvise(base, map_len_, MADV_SEQUENTIAL);
    view_begin_ = offset_begin_ - file_offset_[f];
    view_end_ = std::min<int64_t>(offset_end_ - file_offset_[f],
                                  static_cast<int64_t>(map_len_));
    view_cur_ = view_begin_;
  }

  // Next record-aligned window of the mapping; false at partition end.
  bool next_view(const char** p, int64_t* n) {
    if (view_cur_ >= view_end_) return false;
    int64_t size = chunk_bytes_;
    const char* b = map_base_ + view_cur_;
    const int64_t remain = view_end_ - view_cur_;
    while (true) {
      if (size >= remain) {  // EOF tail: records are exactly complete
        *p = b;
        *n = remain;
        view_cur_ = view_end_;
        bytes_read_.fetch_add(remain, std::memory_order_relaxed);
        return true;
      }
      int64_t cut;
      if (is_text()) {
        cut = size;
        while (cut > 0 && !is_eol(b[cut - 1])) --cut;
      } else {
        cut = find_last_record_head(b, size);
      }
      if (cut == 0) {
        size *= 2;  // a single record larger than the window
        continue;
      }
      *p = b;
      *n = cut;
      view_cur_ += cut;
      bytes_read_.fetch_add(cut, std::memory_order_relaxed);
      return true;
    }
  }

  void produce_loop() {
    if (map_base_) {
      produce_loop_mmap();
      return;
    }
    std::string chunk;
    while (!stop_requested()) {
      chunk.clear();
      if (!load_chunk(&chunk)) break;  // EOF or IOerror
      if (format_ == kFmtLibsvmDense && batch_rows_ > 0) {
        // zero-merge path: per-thread part buffers are copied ONCE, straight
        // into exact [batch_rows_, num_col_] output blocks
        int r = process_dense_chunk(chunk);
        if (r == kChunkFatal) {
          mark_done();  // OOM (error set) or stop: never leave next() hanging
          return;
        }
        if (r == kChunkErrorPushed) break;
        continue;
      }
      void* res = parse_chunk(chunk);
      if (!res) break;
      if (format_ == kFmtLibsvmDense) {
        if (static_cast<DenseResult*>(res)->needs_csr) {
          // data the dense scanner can't express (qid rows): permanently
          // downgrade to the CSR path and re-parse this chunk
          free_result(format_, res);
          format_ = kFmtLibsvm;
          res = parse_chunk(chunk);
          if (!res) break;
        }
      }
      if (result_rows(format_, res) == 0 && !result_error(format_, res)) {
        free_result(format_, res);  // blank/comment-only chunk
        continue;
      }
      bool had_error = result_error(format_, res) != nullptr;
      if (!had_error && format_ == kFmtCsv && batch_rows_ > 0 &&
          num_col_ > 0) {
        // csv -> dense straight into the output batch
        DenseResult* cfg_err = nullptr;
        if (!accumulate_csv(static_cast<CsvResult*>(res), &cfg_err)) {
          mark_done();
          return;
        }
        if (cfg_err) {  // config error (label_col out of range)
          push_error_after_flush(kFmtLibsvmDense, cfg_err);
          break;
        }
        continue;
      }
      if (had_error && batch_rows_ > 0) {
        // deliver rows accumulated from earlier clean chunks BEFORE the
        // error block, preserving non-batch-mode ordering
        if (!push_error_after_flush(format_, res)) return;
        break;
      }
      if (!push_result(format_, res)) return;
      if (had_error) break;  // parse error rides the queued result
    }
    if (batch_rows_ > 0) flush_partial();
    mark_done();
  }

  // Same control flow as produce_loop, over zero-copy views of the mapping.
  void produce_loop_mmap() {
    const char* data;
    int64_t len;
    while (!stop_requested()) {
      if (!next_view(&data, &len)) break;  // partition exhausted
      if (format_ == kFmtLibsvmDense && batch_rows_ > 0) {
        int r = process_dense_chunk(data, len);
        if (r == kChunkFatal) {
          mark_done();
          return;
        }
        if (r == kChunkErrorPushed) break;
        continue;
      }
      void* res = parse_chunk(data, len);
      if (!res) break;
      if (format_ == kFmtLibsvmDense &&
          static_cast<DenseResult*>(res)->needs_csr) {
        free_result(format_, res);
        format_ = kFmtLibsvm;
        res = parse_chunk(data, len);
        if (!res) break;
      }
      if (result_rows(format_, res) == 0 && !result_error(format_, res)) {
        free_result(format_, res);
        continue;
      }
      bool had_error = result_error(format_, res) != nullptr;
      if (!had_error && format_ == kFmtCsv && batch_rows_ > 0 &&
          num_col_ > 0) {
        DenseResult* cfg_err = nullptr;
        if (!accumulate_csv(static_cast<CsvResult*>(res), &cfg_err)) {
          mark_done();
          return;
        }
        if (cfg_err) {
          push_error_after_flush(kFmtLibsvmDense, cfg_err);
          break;
        }
        continue;
      }
      if (had_error && batch_rows_ > 0) {
        if (!push_error_after_flush(format_, res)) return;
        break;
      }
      if (!push_result(format_, res)) return;
      if (had_error) break;
    }
    if (batch_rows_ > 0) flush_partial();
    mark_done();
  }

  enum { kChunkOk = 0, kChunkFatal = 1, kChunkErrorPushed = 2 };

  // Parse one chunk through the internal DensePart API and append the rows
  // directly to the in-progress output batch. Mirrors the merge semantics
  // of dmlc_parse_libsvm_dense (first erroring part wins, all-or-none
  // weights, per-chunk indexing heuristic) without materializing the merged
  // intermediate.
  int process_dense_chunk(const std::string& chunk) {
    return process_dense_chunk(chunk.data(), static_cast<int64_t>(chunk.size()));
  }

  int process_dense_chunk(const char* cdata, int64_t clen) {
    std::vector<dmlc_tpu::DensePart> parts;
    dmlc_tpu::parse_libsvm_dense_chunk(cdata, clen, nthread_, num_col_,
                                       &parts);
    for (auto& part : parts) {
      if (part.error.empty()) continue;
      if (part.needs_csr) {
        // qid rows: flush, permanently downgrade to CSR, re-parse the chunk
        if (!flush_partial()) return kChunkFatal;
        format_ = kFmtLibsvm;
        void* res = parse_chunk(cdata, clen);
        if (!res) return kChunkFatal;
        if (result_rows(format_, res) == 0 && !result_error(format_, res)) {
          free_result(format_, res);
          return kChunkOk;
        }
        bool had_error = result_error(format_, res) != nullptr;
        if (!push_result(format_, res)) return kChunkFatal;
        return had_error ? kChunkErrorPushed : kChunkOk;
      }
      DenseResult* err = make_error_dense(part.error);
      if (!err) {
        set_error("reader: out of memory reporting parse error");
        return kChunkFatal;
      }
      if (!push_error_after_flush(kFmtLibsvmDense, err)) return kChunkFatal;
      return kChunkErrorPushed;
    }
    int64_t n = 0;
    bool any_weight = false;
    uint64_t min_index = UINT64_MAX;
    for (auto& part : parts) {
      n += static_cast<int64_t>(part.label.size());
      any_weight |= !part.weight.empty();
      if (part.min_index < min_index) min_index = part.min_index;
    }
    if (n == 0) return kChunkOk;  // blank/comment-only chunk
    for (auto& part : parts) {
      if (any_weight && !part.label.empty() &&
          part.weight.size() != part.label.size()) {
        DenseResult* err = make_error_dense(
            "libsvm: label:weight must be set on every row or none");
        if (!err) {
          set_error("reader: out of memory reporting parse error");
          return kChunkFatal;
        }
        if (!push_error_after_flush(kFmtLibsvmDense, err)) return kChunkFatal;
        return kChunkErrorPushed;
      }
    }
    // per-chunk 1-based -> 0-based heuristic -> column offset into the
    // stride-(num_col_+1) part buffers (libsvm_parser.h:159-168)
    bool convert = indexing_mode_ > 0 ||
        (indexing_mode_ < 0 && min_index != UINT64_MAX && min_index > 0);
    const size_t off = convert ? 1 : 0;
    for (auto& part : parts) {
      if (part.label.empty()) continue;
      if (!append_rows(part.x.data(), off, part.label.data(),
                       part.weight.empty() ? nullptr : part.weight.data(),
                       part.label.size())) {
        return kChunkFatal;
      }
    }
    return kChunkOk;
  }

  // Mark the pipeline finished so a blocked next() always wakes — every
  // early exit from produce_loop must go through here (or push_result's
  // stop path, which does the same).
  void mark_done() {
    std::lock_guard<std::mutex> lk(mu_);
    produce_done_ = true;
    cv_pop_.notify_all();
    cv_feed_space_.notify_all();  // unblock a pusher: the stream is over
  }

  // Blocking push honoring queue depth; false = stop requested.
  bool push_result(int fmt, void* res) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_push_.wait(lk, [&] {
        return static_cast<int>(queue_.size()) < queue_depth_ || stop_;
      });
      if (stop_) {
        free_result(fmt, res);
        // a consumer may be blocked in next(): mark done so it wakes
        produce_done_ = true;
        cv_pop_.notify_all();
        return false;
      }
      queue_.emplace_back(fmt, res);
    }
    cv_pop_.notify_one();
    return true;
  }

  // Deliver rows accumulated from earlier clean chunks, THEN the error
  // result — the ordering contract shared by every error path in batch
  // mode. false = stop/OOM (err_res freed, pipeline marked done).
  bool push_error_after_flush(int fmt, void* err_res) {
    if (!flush_partial()) {
      free_result(fmt, err_res);
      mark_done();
      return false;
    }
    return push_result(fmt, err_res);
  }

  // A calloc'd DenseResult carrying only an error message; null on OOM.
  DenseResult* make_error_dense(const std::string& msg) {
    auto* out = static_cast<DenseResult*>(calloc(1, sizeof(DenseResult)));
    if (!out) return nullptr;
    out->n_cols = num_col_;
    out->error = strdup(msg.c_str());
    if (!out->error) {
      free(out);
      return nullptr;
    }
    return out;
  }

  // A fresh full-size output batch; null on OOM.
  DenseResult* alloc_batch() {
    auto* out = static_cast<DenseResult*>(calloc(1, sizeof(DenseResult)));
    if (!out) return nullptr;
    out->n_cols = num_col_;
    out->x_bf16 = out_bf16_ ? 1 : 0;
    out->packed_aux = pack_aux_ ? 1 : 0;
    // packed mode: label/weight live in two trailing x columns (ONE
    // device_put per batch downstream; see api.h DenseResult docs)
    const size_t xcols =
        static_cast<size_t>(num_col_) + (pack_aux_ ? 2 : 0);
    // pooled: every batch of an epoch has the same buffer sizes, so the
    // freed x of batch i becomes the x of batch i+k without touching
    // glibc's mmap path (buffer_pool.h)
    out->x = static_cast<float*>(
        dmlc_pool_alloc(static_cast<size_t>(batch_rows_) * xcols *
                        (out_bf16_ ? sizeof(uint16_t) : sizeof(float))));
    bool ok = out->x != nullptr;
    if (ok && !pack_aux_) {
      out->label = static_cast<float*>(
          dmlc_pool_alloc(static_cast<size_t>(batch_rows_) * sizeof(float)));
      ok = out->label != nullptr;
      if (ok && cur_has_weight_) {
        out->weight = static_cast<float*>(
            dmlc_pool_alloc(static_cast<size_t>(batch_rows_) * sizeof(float)));
        ok = out->weight != nullptr;
      }
    }
    if (!ok) {
      dmlc_free_dense(out);
      return nullptr;
    }
    return out;
  }

  // Lazily allocate + backfill the weight column of the in-progress batch
  // when the pipeline first sees weighted rows (earlier rows get 1.0,
  // matching the old accumulator's backfill). false on OOM.
  bool promote_weight() {
    cur_has_weight_ = true;
    if (pack_aux_) return true;  // weight column always exists when packed
    if (cur_ && !cur_->weight) {
      cur_->weight = static_cast<float*>(
          dmlc_pool_alloc(static_cast<size_t>(batch_rows_) * sizeof(float)));
      if (!cur_->weight) return false;
      for (int64_t i = 0; i < cur_rows_; ++i) cur_->weight[i] = 1.0f;
    }
    return true;
  }

  // Emit the in-progress batch as-is (short final block). false = stop/OOM.
  bool flush_partial() {
    if (!cur_) return true;
    if (cur_rows_ == 0) {
      dmlc_free_dense(cur_);
      cur_ = nullptr;
      return true;
    }
    cur_->n_rows = cur_rows_;
    DenseResult* out = cur_;
    cur_ = nullptr;
    cur_rows_ = 0;
    return push_result(kFmtLibsvmDense, out);
  }

  // Copy n rows from a stride-(num_col_+1) part buffer (column offset `off`
  // applying the indexing decision) straight into output batches, emitting
  // each one as it fills. weight may be null (rows weigh 1.0 if the batch
  // has a weight column). false = stop/OOM.
  bool append_rows(const float* x, size_t off, const float* label,
                   const float* weight, size_t n) {
    const size_t ncol = static_cast<size_t>(num_col_);
    const size_t stride = ncol + 1;
    size_t done = 0;
    while (done < n) {
      if (!cur_) {
        cur_ = alloc_batch();
        if (!cur_) {
          set_error("reader: out of memory repacking batch");
          return false;
        }
      }
      if (weight && !cur_has_weight_ && !promote_weight()) {
        set_error("reader: out of memory repacking batch");
        return false;
      }
      size_t space = static_cast<size_t>(batch_rows_ - cur_rows_);
      size_t take = n - done < space ? n - done : space;
      const float* src = x + done * stride + off;
      const size_t ocol = ncol + (pack_aux_ ? 2 : 0);
      if (out_bf16_) {
        // the single repack pass doubles as the f32->bf16 conversion
        uint16_t* dst16 = reinterpret_cast<uint16_t*>(cur_->x) +
                          static_cast<size_t>(cur_rows_) * ocol;
        for (size_t i = 0; i < take; ++i) {
          convert_row_bf16(dst16 + i * ocol, src + i * stride, ncol);
          if (pack_aux_) {
            dst16[i * ocol + ncol] = f32_to_bf16(label[done + i]);
            dst16[i * ocol + ncol + 1] =
                f32_to_bf16(weight ? weight[done + i] : 1.0f);
          }
        }
      } else {
        float* dst = cur_->x + static_cast<size_t>(cur_rows_) * ocol;
        for (size_t i = 0; i < take; ++i) {
          memcpy(dst + i * ocol, src + i * stride, ncol * sizeof(float));
          if (pack_aux_) {
            dst[i * ocol + ncol] = label[done + i];
            dst[i * ocol + ncol + 1] = weight ? weight[done + i] : 1.0f;
          }
        }
      }
      if (!pack_aux_) {
        memcpy(cur_->label + cur_rows_, label + done, take * sizeof(float));
        if (cur_has_weight_) {
          if (weight) {
            memcpy(cur_->weight + cur_rows_, weight + done,
                   take * sizeof(float));
          } else {
            for (size_t i = 0; i < take; ++i)
              cur_->weight[cur_rows_ + i] = 1.0f;
          }
        }
      }
      cur_rows_ += static_cast<int64_t>(take);
      done += take;
      if (cur_rows_ == batch_rows_) {
        cur_->n_rows = batch_rows_;
        DenseResult* out = cur_;
        cur_ = nullptr;
        cur_rows_ = 0;
        if (!push_result(kFmtLibsvmDense, out)) return false;  // stop
      }
    }
    return true;
  }

  // Append CSV cells straight into the output batch (one copy: cells ->
  // batch), splitting label/weight columns and padding/truncating features
  // to num_col_ (csv_cells_to_dense semantics). Consumes `res`. A config
  // error comes back via *err_out (a dense error result) with true
  // returned; false = stop/OOM.
  bool accumulate_csv(CsvResult* res, DenseResult** err_out) {
    *err_out = nullptr;
    const int64_t n = res->n_rows;
    const int64_t ncol = res->n_cols;
    if (label_col_ >= ncol || weight_col_ >= ncol) {
      DenseResult* out = make_error_dense("csv: label/weight column out of range");
      dmlc_free_csv(res);
      if (!out) {
        set_error("reader: out of memory converting csv");
        return false;
      }
      *err_out = out;
      return true;
    }
    const bool has_w = weight_col_ >= 0;
    int64_t done = 0;
    while (done < n) {
      if (!cur_) {
        cur_ = alloc_batch();
        if (!cur_) {
          dmlc_free_csv(res);
          set_error("reader: out of memory repacking batch");
          return false;
        }
      }
      if (has_w && !cur_has_weight_ && !promote_weight()) {
        dmlc_free_csv(res);
        set_error("reader: out of memory repacking batch");
        return false;
      }
      int64_t space = batch_rows_ - cur_rows_;
      int64_t take = n - done < space ? n - done : space;
      const int64_t ocol = num_col_ + (pack_aux_ ? 2 : 0);
      for (int64_t r = 0; r < take; ++r) {
        const float* row = res->cells + (done + r) * ncol;
        const float lab = label_col_ >= 0 ? row[label_col_] : 0.0f;
        const float wgt = has_w ? row[weight_col_] : 1.0f;
        if (!pack_aux_) {
          cur_->label[cur_rows_ + r] = lab;
          if (cur_has_weight_) cur_->weight[cur_rows_ + r] = wgt;
        }
        int64_t k = 0;
        if (out_bf16_) {
          uint16_t* dst16 = reinterpret_cast<uint16_t*>(cur_->x) +
                            static_cast<size_t>(cur_rows_ + r) * ocol;
          for (int64_t c = 0; c < ncol && k < num_col_; ++c) {
            if (c == label_col_ || c == weight_col_) continue;
            dst16[k++] = f32_to_bf16(row[c]);
          }
          while (k < num_col_) dst16[k++] = 0;  // bf16 zero is all-zero bits
          if (pack_aux_) {
            dst16[num_col_] = f32_to_bf16(lab);
            dst16[num_col_ + 1] = f32_to_bf16(wgt);
          }
        } else {
          float* dst = cur_->x + static_cast<size_t>(cur_rows_ + r) * ocol;
          for (int64_t c = 0; c < ncol && k < num_col_; ++c) {
            if (c == label_col_ || c == weight_col_) continue;
            dst[k++] = row[c];
          }
          while (k < num_col_) dst[k++] = 0.0f;  // x is malloc'd, not zeroed
          if (pack_aux_) {
            dst[num_col_] = lab;
            dst[num_col_ + 1] = wgt;
          }
        }
      }
      cur_rows_ += take;
      done += take;
      if (cur_rows_ == batch_rows_) {
        cur_->n_rows = batch_rows_;
        DenseResult* out = cur_;
        cur_ = nullptr;
        cur_rows_ = 0;
        if (!push_result(kFmtLibsvmDense, out)) {
          dmlc_free_csv(res);
          return false;
        }
      }
    }
    dmlc_free_csv(res);
    return true;
  }

  // ---------------- lifecycle ----------------

  void start() {
    stop_ = false;
    produce_done_ = false;
    // guard the whole producer: an escaping exception (e.g. bad_alloc while
    // regrowing chunk buffers for a pathological record) would
    // std::terminate the embedding Python process
    producer_ = std::thread([this] {
      try {
        produce_loop();
      } catch (const std::exception& ex) {
        set_error(std::string("reader failed: ") + ex.what());
        mark_done();
      } catch (...) {
        set_error("reader failed: unknown error");
        mark_done();
      }
    });
  }

  void stop_and_join() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_push_.notify_all();
      cv_feed_data_.notify_all();
      cv_feed_space_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
    for (auto& item : queue_) free_result(item.first, item.second);
    queue_.clear();
    stop_ = false;
    produce_done_ = false;
  }

  bool stop_requested() {
    std::lock_guard<std::mutex> lk(mu_);
    return stop_;
  }

  void set_error(std::string msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = std::move(msg);
  }

  std::vector<std::string> paths_;
  std::vector<int64_t> file_offset_;
  int format_;
  int64_t num_col_;
  int indexing_mode_;
  char delim_;
  int nthread_;
  int64_t chunk_bytes_;
  int queue_depth_;

  int64_t offset_begin_ = 0, offset_end_ = 0, offset_curr_ = 0;
  size_t file_ptr_ = 0;
  FILE* fp_ = nullptr;
  std::string overflow_;

  // dense batch repack (batch_rows_ > 0): rows fill `cur_` (a full-size
  // malloc'd output block) until it can be emitted — the single copy runs
  // off-GIL in this producer thread, replacing the consumer-side
  // np.concatenate per batch
  int64_t batch_rows_ = 0;
  int32_t label_col_ = -1;   // csv->dense: label/weight column extraction
  int32_t weight_col_ = -1;  // (csv_parser.h label_column/weight_column)
  bool out_bf16_ = false;    // emit x as bfloat16 (batch repack mode only)
  // COO formats: shape quantization buckets + unit-value elision
  int64_t row_bucket_ = 0;
  int64_t nnz_bucket_ = 0;
  bool elide_unit_ = false;
  bool csr_wire_ = false;
  bool pack_aux_ = false;
  DenseResult* cur_ = nullptr;  // in-progress output batch (producer-owned)
  int64_t cur_rows_ = 0;
  bool cur_has_weight_ = false;

  // mmap fast path (single-file partitions)
  const char* map_base_ = nullptr;
  size_t map_len_ = 0;
  int64_t view_begin_ = 0, view_cur_ = 0, view_end_ = 0;

  // push-mode feed queue (remote streams pushed from Python)
  bool push_mode_ = false;
  static constexpr size_t kFeedCap = 32 << 20;  // backpressure bound
  std::deque<std::string> feed_q_;
  size_t feed_off_ = 0;    // consumed prefix of feed_q_.front()
  size_t feed_bytes_ = 0;  // unconsumed bytes across the queue
  bool feed_done_ = false;
  bool feed_abort_ = false;

  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  std::condition_variable cv_feed_data_, cv_feed_space_;
  std::deque<std::pair<int, void*>> queue_;
  bool stop_ = false;
  bool produce_done_ = false;
  std::atomic<int64_t> bytes_read_{0};
  mutable std::mutex err_mu_;
  std::string error_;
};

// ---------------- indexed recordio reader ----------------
//
// Record-count partitioning over an external index, batched contiguous
// reads, and per-epoch shuffled per-record seeks — the native rebuild of
// the reference's IndexedRecordIOSplitter (indexed_recordio_split.cc:
// 12-41 ResetPartition by record count, 159-212 NextBatchEx batched /
// shuffled reads, 221-233 per-epoch reshuffle in BeforeFirst). Results are
// RecordBatchResult batches (payloads extracted + multi-part reassembled
// by dmlc_recordio_extract), matching the Python engine row-for-row for
// sequential access; shuffled order is produced by mt19937 and therefore
// deterministic per (seed, epoch) but intentionally NOT identical to the
// Python engine's random.Random order.

class IndexedReader {
 public:
  IndexedReader(std::vector<std::string> paths, std::vector<int64_t> sizes,
                std::vector<int64_t> index_offsets, int64_t part_index,
                int64_t num_parts, int64_t batch_records, bool shuffle,
                uint64_t seed, int queue_depth)
      : paths_(std::move(paths)),
        index_(std::move(index_offsets)),
        batch_records_(batch_records < 1 ? 256 : batch_records),
        shuffle_(shuffle),
        rng_(seed),
        queue_depth_(queue_depth < 1 ? 1 : queue_depth) {
    file_offset_.push_back(0);
    for (size_t i = 0; i < sizes.size(); ++i) {
      if (sizes[i] % 4 != 0) {
        error_ = "recordio: file " + paths_[i] + " does not align by 4 bytes";
      }
      file_offset_.push_back(file_offset_.back() + sizes[i]);
    }
    if (index_.empty()) error_ = "indexed recordio: empty index";
    std::sort(index_.begin(), index_.end());
    if (error_.empty()) reset_partition(part_index, num_parts);
    if (error_.empty()) {
      draw_epoch();
      start();
    } else {
      produce_done_ = true;
    }
  }

  ~IndexedReader() {
    stop_and_join();
    close_fp();
  }

  RecordBatchResult* next() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [&] { return !queue_.empty() || produce_done_; });
    if (queue_.empty()) return nullptr;
    RecordBatchResult* item = queue_.front();
    queue_.pop_front();
    cv_push_.notify_one();
    return item;
  }

  // Epoch reset: a NEW permutation is drawn each epoch (BeforeFirst,
  // indexed_recordio_split.cc:221-233) — rng_ keeps advancing, so the
  // epoch sequence is deterministic for a given seed.
  void before_first() {
    stop_and_join();
    close_fp();
    if (error_.empty()) {
      draw_epoch();
      start();
    } else {
      std::lock_guard<std::mutex> lk(mu_);
      produce_done_ = true;
      cv_pop_.notify_all();
    }
  }

  // Native resume: land in epoch `epochs` (counting before_first calls)
  // positioned at record `records` of the partition. The permutation is a
  // pure function of (seed, epoch), so replay = drawing the missing epoch
  // permutations (O(n) shuffles, no I/O) and starting the producer at the
  // record cursor — no bytes of the prefix are read.
  void skip(int64_t epochs, int64_t records) {
    stop_and_join();
    close_fp();
    if (!error_.empty()) {
      std::lock_guard<std::mutex> lk(mu_);
      produce_done_ = true;
      cv_pop_.notify_all();
      return;
    }
    if (epochs_drawn_ > epochs + 1) {
      // rng cannot rewind: resuming an earlier epoch needs a fresh reader
      set_error("indexed reader: cannot skip backwards");
      std::lock_guard<std::mutex> lk(mu_);
      produce_done_ = true;
      cv_pop_.notify_all();
      return;
    }
    while (epochs_drawn_ < epochs + 1) draw_epoch();
    start_record_ = std::max<int64_t>(0, records);
    start();
  }

  int64_t bytes_read() const {
    return bytes_read_.load(std::memory_order_relaxed);
  }

  const char* error() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_.empty() ? nullptr : error_.c_str();
  }

 private:
  int64_t ntotal() const { return static_cast<int64_t>(index_.size()); }

  int64_t record_size(int64_t i) const {
    int64_t end = (i + 1 < ntotal()) ? index_[i + 1] : file_offset_.back();
    return end - index_[i];
  }

  // Partition by record count (indexed_recordio_split.cc:12-41; identical
  // to the Python engine's IndexedRecordIOSplitter.reset_partition).
  void reset_partition(int64_t part_index, int64_t num_parts) {
    int64_t n = ntotal();
    int64_t nstep = (n + num_parts - 1) / num_parts;
    if (part_index * nstep >= n) {
      index_begin_ = index_end_ = 0;
      offset_end_ = 0;
      return;
    }
    index_begin_ = part_index * nstep;
    if ((part_index + 1) * nstep < n) {
      index_end_ = (part_index + 1) * nstep;
      offset_end_ = index_[index_end_];
    } else {
      index_end_ = n;
      offset_end_ = file_offset_.back();
    }
  }

  size_t file_of(int64_t off) const {
    size_t lo = 0, hi = file_offset_.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (file_offset_[mid] <= off) lo = mid + 1; else hi = mid;
    }
    return lo - 1;
  }

  void close_fp() {
    if (fp_) {
      fclose(fp_);
      fp_ = nullptr;
    }
  }

  // Append the absolute span [offset, offset+size) to `out`, crossing file
  // joins (binary: no synthetic bytes). Reuses the open FILE* when the
  // span continues where the last read ended — contiguous batches pay one
  // seek, shuffled access seeks per record as the reference does.
  bool read_span(int64_t offset, int64_t size, std::string* out) {
    while (size > 0) {
      size_t f = file_of(offset);
      int64_t local = offset - file_offset_[f];
      int64_t avail = file_offset_[f + 1] - offset;
      int64_t take = std::min(size, avail);
      if (!fp_ || fp_file_ != f) {
        close_fp();
        fp_ = fopen(paths_[f].c_str(), "rb");
        if (!fp_) {
          set_error("cannot open " + paths_[f]);
          return false;
        }
        fp_file_ = f;
        fp_pos_ = 0;
      }
      if (fp_pos_ != local) {
        if (fseeko(fp_, static_cast<off_t>(local), SEEK_SET) != 0) {
          set_error("seek failed in " + paths_[f]);
          return false;
        }
        fp_pos_ = local;
      }
      size_t base = out->size();
      out->resize(base + static_cast<size_t>(take));
      if (fread(&(*out)[base], 1, static_cast<size_t>(take), fp_) !=
          static_cast<size_t>(take)) {
        set_error("read failed in " + paths_[f]);
        return false;
      }
      fp_pos_ += take;
      offset += take;
      size -= take;
      bytes_read_.fetch_add(take, std::memory_order_relaxed);
    }
    return true;
  }

  // Draw the next epoch's permutation (shuffle only); rng_ advances once
  // per epoch so the sequence is deterministic per seed.
  void draw_epoch() {
    ++epochs_drawn_;
    if (!shuffle_) return;
    perm_.resize(static_cast<size_t>(index_end_ - index_begin_));
    for (size_t i = 0; i < perm_.size(); ++i) {
      perm_[i] = index_begin_ + static_cast<int64_t>(i);
    }
    std::shuffle(perm_.begin(), perm_.end(), rng_);
  }

  void produce_loop() {
    int64_t cur = index_begin_ + start_record_;
    size_t pcur = static_cast<size_t>(start_record_);
    start_record_ = 0;  // one-shot: consumed by this producer run
    std::string buf;
    while (!stop_requested()) {
      buf.clear();
      if (shuffle_) {
        if (pcur >= perm_.size()) break;
        size_t take = std::min<size_t>(
            static_cast<size_t>(batch_records_), perm_.size() - pcur);
        for (size_t i = 0; i < take; ++i) {
          int64_t rec = perm_[pcur + i];
          if (!read_span(index_[rec], record_size(rec), &buf)) {
            mark_done();
            return;
          }
        }
        pcur += take;
      } else {
        if (cur >= index_end_) break;
        int64_t last = std::min(cur + batch_records_, index_end_);
        int64_t begin_off = index_[cur];
        int64_t end_off =
            (last < ntotal()) ? index_[last] : file_offset_.back();
        if (last == index_end_) end_off = offset_end_;
        if (!read_span(begin_off, end_off - begin_off, &buf)) {
          mark_done();
          return;
        }
        cur = last;
      }
      if (buf.empty()) break;
      RecordBatchResult* res = dmlc_recordio_extract(
          buf.data(), static_cast<int64_t>(buf.size()));
      if (!res) {
        set_error("indexed recordio: out of memory");
        break;
      }
      bool had_error = res->error != nullptr;
      if (!push_result(res)) return;
      if (had_error) break;
    }
    mark_done();
  }

  void mark_done() {
    std::lock_guard<std::mutex> lk(mu_);
    produce_done_ = true;
    cv_pop_.notify_all();
  }

  bool push_result(RecordBatchResult* res) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_push_.wait(lk, [&] {
        return static_cast<int>(queue_.size()) < queue_depth_ || stop_;
      });
      if (stop_) {
        dmlc_free_records(res);
        produce_done_ = true;
        cv_pop_.notify_all();
        return false;
      }
      queue_.push_back(res);
    }
    cv_pop_.notify_one();
    return true;
  }

  void start() {
    stop_ = false;
    produce_done_ = false;
    producer_ = std::thread([this] {
      try {
        produce_loop();
      } catch (const std::exception& ex) {
        set_error(std::string("indexed reader failed: ") + ex.what());
        mark_done();
      } catch (...) {
        set_error("indexed reader failed: unknown error");
        mark_done();
      }
    });
  }

  void stop_and_join() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_push_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
    for (auto* item : queue_) dmlc_free_records(item);
    queue_.clear();
    stop_ = false;
    produce_done_ = false;
  }

  bool stop_requested() {
    std::lock_guard<std::mutex> lk(mu_);
    return stop_;
  }

  void set_error(std::string msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = std::move(msg);
  }

  std::vector<std::string> paths_;
  std::vector<int64_t> file_offset_;
  std::vector<int64_t> index_;  // sorted record start offsets (global)
  int64_t batch_records_;
  bool shuffle_;
  std::mt19937_64 rng_;
  int queue_depth_;

  std::vector<int64_t> perm_;   // current epoch's permutation (shuffle)
  int64_t epochs_drawn_ = 0;    // permutations drawn so far (epoch + 1)
  int64_t start_record_ = 0;    // resume cursor for the next producer run
  int64_t index_begin_ = 0, index_end_ = 0, offset_end_ = 0;
  FILE* fp_ = nullptr;
  size_t fp_file_ = 0;
  int64_t fp_pos_ = 0;

  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  std::deque<RecordBatchResult*> queue_;
  bool stop_ = false;
  bool produce_done_ = false;
  std::atomic<int64_t> bytes_read_{0};
  mutable std::mutex err_mu_;
  std::string error_;
};

}  // namespace

extern "C" {

void* dmlc_reader_create(const char** paths, const int64_t* sizes,
                         int32_t nfiles, int64_t part_index, int64_t num_parts,
                         int32_t format, int64_t num_col, int32_t indexing_mode,
                         char delim, int32_t nthread, int64_t chunk_bytes,
                         int32_t queue_depth, int64_t batch_rows,
                         int32_t label_col, int32_t weight_col,
                         int32_t out_bf16, int64_t row_bucket,
                         int64_t nnz_bucket, int32_t elide_unit,
                         int32_t csr_wire, int32_t pack_aux) {
  try {
    std::vector<std::string> p(paths, paths + nfiles);
    std::vector<int64_t> s(sizes, sizes + nfiles);
    return new LineReader(std::move(p), std::move(s), part_index, num_parts,
                          format, num_col, indexing_mode, delim, nthread,
                          chunk_bytes, queue_depth, batch_rows, label_col,
                          weight_col, out_bf16 != 0, row_bucket, nnz_bucket,
                          elide_unit != 0, csr_wire != 0, pack_aux != 0);
  } catch (...) {
    // alloc/thread-spawn failure must not cross the extern "C" boundary
    // (std::terminate); null tells the caller creation failed
    return nullptr;
  }
}

void* dmlc_reader_next(void* handle, int32_t* fmt_out) {
  return static_cast<LineReader*>(handle)->next(fmt_out);
}

void dmlc_reader_before_first(void* handle) {
  static_cast<LineReader*>(handle)->before_first();
}

int64_t dmlc_reader_bytes_read(void* handle) {
  return static_cast<LineReader*>(handle)->bytes_read();
}

const char* dmlc_reader_error(void* handle) {
  return static_cast<LineReader*>(handle)->error();
}

void dmlc_reader_destroy(void* handle) {
  delete static_cast<LineReader*>(handle);
}

void* dmlc_feeder_create(int32_t format, int64_t num_col,
                         int32_t indexing_mode, char delim, int32_t nthread,
                         int64_t chunk_bytes, int32_t queue_depth,
                         int64_t batch_rows, int32_t label_col,
                         int32_t weight_col, int32_t out_bf16,
                         int64_t row_bucket, int64_t nnz_bucket,
                         int32_t elide_unit, int32_t csr_wire,
                         int32_t pack_aux) {
  try {
    return new LineReader(format, num_col, indexing_mode, delim, nthread,
                          chunk_bytes, queue_depth, batch_rows, label_col,
                          weight_col, out_bf16 != 0, row_bucket, nnz_bucket,
                          elide_unit != 0, csr_wire != 0, pack_aux != 0);
  } catch (...) {
    return nullptr;
  }
}

int32_t dmlc_feeder_push(void* handle, const char* data, int64_t len) {
  return static_cast<LineReader*>(handle)->push(data, len);
}

void dmlc_feeder_abort(void* handle) {
  static_cast<LineReader*>(handle)->abort_feed();
}

void dmlc_feeder_fail(void* handle, const char* msg) {
  static_cast<LineReader*>(handle)->fail_feed(msg);
}

void dmlc_feeder_finish(void* handle) {
  static_cast<LineReader*>(handle)->finish();
}

void* dmlc_feeder_next(void* handle, int32_t* fmt_out) {
  return static_cast<LineReader*>(handle)->next(fmt_out);
}

void dmlc_feeder_before_first(void* handle) {
  static_cast<LineReader*>(handle)->before_first();
}

int64_t dmlc_feeder_bytes_read(void* handle) {
  return static_cast<LineReader*>(handle)->bytes_read();
}

const char* dmlc_feeder_error(void* handle) {
  return static_cast<LineReader*>(handle)->error();
}

void dmlc_feeder_destroy(void* handle) {
  delete static_cast<LineReader*>(handle);
}

void* dmlc_indexed_reader_create(const char** paths, const int64_t* sizes,
                                 int32_t nfiles, const int64_t* index_offsets,
                                 int64_t n_index, int64_t part_index,
                                 int64_t num_parts, int64_t batch_records,
                                 int32_t shuffle, uint64_t seed,
                                 int32_t queue_depth) {
  try {
    std::vector<std::string> p(paths, paths + nfiles);
    std::vector<int64_t> s(sizes, sizes + nfiles);
    std::vector<int64_t> idx(index_offsets, index_offsets + n_index);
    return new IndexedReader(std::move(p), std::move(s), std::move(idx),
                             part_index, num_parts, batch_records,
                             shuffle != 0, seed, queue_depth);
  } catch (...) {
    return nullptr;
  }
}

void* dmlc_indexed_reader_next(void* handle) {
  return static_cast<IndexedReader*>(handle)->next();
}

void dmlc_indexed_reader_before_first(void* handle) {
  static_cast<IndexedReader*>(handle)->before_first();
}

void dmlc_indexed_reader_skip(void* handle, int64_t epochs, int64_t records) {
  static_cast<IndexedReader*>(handle)->skip(epochs, records);
}

int64_t dmlc_indexed_reader_bytes_read(void* handle) {
  return static_cast<IndexedReader*>(handle)->bytes_read();
}

const char* dmlc_indexed_reader_error(void* handle) {
  return static_cast<IndexedReader*>(handle)->error();
}

void dmlc_indexed_reader_destroy(void* handle) {
  delete static_cast<IndexedReader*>(handle);
}

}  // extern "C"
