// Shared C ABI declarations for the native core (parse.cc + reader.cc).
//
// All result buffers are malloc'd and freed with the matching dmlc_free_*;
// Python loads these via ctypes (no pybind11 in this image).

#ifndef DMLC_TPU_NATIVE_API_H_
#define DMLC_TPU_NATIVE_API_H_

#include <cstdint>

// The wire formats this core reads (recordio frames, indexed .idx offsets)
// are little-endian, and the frame loads are memcpy-native by design (the
// hot path must not pay per-load byte swaps on the LE hosts we target).
// Refuse to BUILD on a big-endian target rather than corrupt data at
// runtime — the compile-time analog of the reference's s390x CI guard
// (scripts/travis/travis_script.sh:62-66, endian.h DMLC_IO_NO_ENDIAN_SWAP).
#if defined(__BYTE_ORDER__) && (__BYTE_ORDER__ != __ORDER_LITTLE_ENDIAN__)
#error "dmlc_tpu native core requires a little-endian host (LE wire format)"
#endif

extern "C" {

// One parsed CSR block (libsvm / libfm). Free with dmlc_free_block.
struct CsrBlockResult {
  int64_t n_rows;
  int64_t nnz;
  int64_t* offset;    // [n_rows + 1]
  float* label;       // [n_rows]
  float* weight;      // [n_rows] or null
  int64_t* qid;       // [n_rows] or null
  uint64_t* index;    // [nnz]
  uint64_t* field;    // [nnz] or null (libfm)
  float* value;       // [nnz] or null (all-binary)
  char* error;        // null on success
};

// Dense libsvm result: x laid out row-major [n_rows, n_cols].
struct DenseResult {
  int64_t n_rows;
  int64_t n_cols;
  float* x;       // [n_rows, n_cols]; bf16 (uint16) payload when x_bf16 = 1
  float* label;   // [n_rows]
  float* weight;  // [n_rows] or null
  char* error;    // null on success
  int32_t needs_csr;  // 1 = data needs the CSR path (e.g. qid rows); error is
                      // also set. Explicit flag so callers never route on
                      // error-message wording.
  int32_t x_bf16;     // 1 = x holds bfloat16 (the TPU-native ingest format:
                      // half the host->HBM bytes, MXU-preferred operand)
  // 1 = x is [n_rows, n_cols + 2] with label in column n_cols and weight
  // in column n_cols + 1 (label/weight pointers are then NULL): ONE
  // device_put per batch instead of three arrays — measured 2x on the
  // per-array put overhead (benchmarks/bench_transfer_floor.py aux leg).
  // Only emitted in batch-repack mode on request (pack_aux); in bf16 mode
  // the aux columns are bf16 too, so callers opt in only when their
  // labels/weights are bf16-exact.
  int32_t packed_aux;
};

// Dense CSV result: cells laid out row-major [n_rows, n_cols].
struct CsvResult {
  int64_t n_rows;
  int64_t n_cols;
  float* cells;
  char* error;
};

// CSV result with the label/weight columns split out during the single
// merge-copy pass: values holds ONLY the feature cells, row-major
// [n_rows, n_feat_cols], so the RowBlock wrapper needs zero further copies
// (the synthetic per-row 0..k-1 index/offset skeleton is format-implied
// and cached host-side). The reference's CSV path re-walks cells in its
// consumer (csv_parser.h:120-121); splitting here keeps the whole parse
// one pass over the bytes.
struct CsvSplitResult {
  int64_t n_rows;
  int64_t n_feat_cols;  // columns minus label/weight columns
  float* values;        // [n_rows, n_feat_cols]
  float* label;         // [n_rows], or NULL when label_col < 0
  float* weight;        // [n_rows], or NULL when weight_col < 0
  char* error;          // null on success
};

// Sparse batch in device-ready COO layout (the BCOO host half): coords are
// int32 (row, col) pairs — on KDD-shaped data the coordinate array
// dominates transfer bytes, so int32 halves host->HBM traffic vs int64 —
// padded out to rows_padded/nnz_padded with OUT-OF-BOUNDS entries
// (rows_padded, num_col), which every jax BCOO op masks. values may be
// NULL with values_elided=1 when every real value is 1.0f (binary-feature
// corpora): the consumer synthesizes ones on device, saving 4 B/nnz of
// transfer. qid/field are not carried (BCOO interop drops them, matching
// the Python convert path). Free with dmlc_free_coo.
struct CooResult {
  int64_t n_rows;       // real rows
  int64_t nnz;          // real entries
  int64_t rows_padded;  // label/weight length (>= n_rows)
  int64_t nnz_padded;   // coords rows / values length (>= nnz)
  int32_t* coords;      // [nnz_padded, 2] row-major (row, col), or
                        // [nnz_padded] cols-only when csr_wire
  float* values;        // [nnz_padded] or NULL when values_elided
  float* label;         // [rows_padded], zeros past n_rows
  float* weight;        // [rows_padded], zeros past n_rows
  char* error;          // null on success
  int32_t values_elided;
  // CSR wire format (csr_wire=1): coords carries ONLY the column ids and
  // row_ptr is [rows_padded + 1] with row i spanning entries
  // [row_ptr[i], row_ptr[i+1]); pad rows all point at nnz (real), so an
  // on-device prefix-sum rebuild maps every pad entry to the OOB row
  // rows_padded. Halves the coordinate transfer bytes (4 B/nnz instead of
  // 8) at the cost of one tiny [rows+1] array and a cheap device-side
  // scatter+cumsum — on a tunneled TPU the link bytes are the scarce
  // resource, the VPU cycles are free.
  int32_t csr_wire;
  int32_t* row_ptr;     // [rows_padded + 1] when csr_wire, else NULL
};

// Parse a text chunk (fmt: 0 = libsvm, 3 = libfm) straight to COO.
// row_bucket/nnz_bucket quantize the padded dims UP to bucket multiples so
// batch shapes REPEAT across chunks (a novel-shape device_put costs a fresh
// transfer plan, measured ~100x a repeated-shape one on a tunneled TPU);
// 0 disables. elide_unit enables the all-ones value elision. csr_wire
// emits the cols+row_ptr wire layout (see CooResult). Requires
// max(num_col, chunk rows) + 1 < 2^31 (int32 coords); callers guard.
CooResult* dmlc_parse_coo(const char* data, int64_t len, int nthread,
                          int indexing_mode, int fmt, int64_t num_col,
                          int64_t row_bucket, int64_t nnz_bucket,
                          int32_t elide_unit, int32_t csr_wire);
void dmlc_free_coo(CooResult* r);

// A batch of RecordIO record payloads: record i is
// data[offsets[i] : offsets[i+1]]. Free with dmlc_free_records.
struct RecordBatchResult {
  int64_t n_records;
  int64_t data_len;   // == offsets[n_records]
  char* data;         // concatenated payloads
  int64_t* offsets;   // [n_records + 1]
  char* error;        // null on success
};

// Extract every record from a span of RecordIO bytes that starts at a
// record head and contains only whole records (recordio.cc:53-82 framing:
// magic/lrecord cells, cflag 0|1|2|3 multi-part reassembly with the magic
// re-inserted between parts). Pure function — safe to feed spans read from
// any source (local chunk, cloud stream, indexed batch).
RecordBatchResult* dmlc_recordio_extract(const char* data, int64_t len);
void dmlc_free_records(RecordBatchResult* r);

// ---------------- chunk-batch segment parser (batch_parse.cc) ----------------
//
// Parse a whole text chunk and materialize it DIRECTLY as a block-cache v1
// (DMLCBC01) block span: the present arrays in canonical segment order
// (offset, label, weight, qid, field, index, value), every array start
// padded to 64-byte alignment relative to the span start, raw little-endian
// C-order payloads, zero bytes in the alignment gaps — byte-identical to
// what io/block_cache.write_segments emits at an aligned file position, with
// a zlib-compatible crc32 over the whole span. One materialization serves
// the parsed RowBlock (zero-copy views), the on-disk cache block (one
// file write), and the service wire frame (same encoding modulo framing).
// SIMD newline/delimiter scan with AVX2/SSE2/NEON runtime dispatch and a
// portable scalar fallback; line-count-balanced thread fan-out.

// canonical segment slots — io/block_cache.py SEGMENT_NAMES order
#define DMLC_SEG_OFFSET 0
#define DMLC_SEG_LABEL 1
#define DMLC_SEG_WEIGHT 2
#define DMLC_SEG_QID 3
#define DMLC_SEG_FIELD 4
#define DMLC_SEG_INDEX 5
#define DMLC_SEG_VALUE 6
#define DMLC_SEG_COUNT 7

struct SegmentBlockResult {
  int64_t n_rows;
  int64_t nnz;
  int64_t num_col;             // max converted index + 1 (0 when nnz == 0)
  char* buf;                   // the block span bytes; free with the result
  int64_t buf_len;             // exact span length (no trailing pad)
  int64_t seg_off[DMLC_SEG_COUNT];  // span-relative; -1 = segment absent
  int64_t seg_len[DMLC_SEG_COUNT];  // payload bytes (0-length is present!)
  uint32_t crc32;              // zlib-compatible crc over buf[0, buf_len)
  int32_t simd_level;          // scan ISA used: 0 scalar, 1 SSE2, 2 AVX2, 3 NEON
  char* error;                 // null on success
};

// fmt: 0 = libsvm (CSR, incl. weights/qids), 2 = csv (label/weight column
// split + synthetic skeleton), 3 = libfm. label_col/weight_col are csv-only
// (-1 = absent); delim is the csv delimiter.
SegmentBlockResult* dmlc_parse_batch(const char* data, int64_t len,
                                     int nthread, int fmt, int indexing_mode,
                                     char delim, int32_t label_col,
                                     int32_t weight_col);
void dmlc_free_segblock(SegmentBlockResult* r);
// The scan ISA the runtime dispatch picked on this host (same codes as
// SegmentBlockResult.simd_level).
int dmlc_simd_level();
// zlib-compatible crc32 (slice-by-8) — exposed so tests can pin equality
// against Python zlib.crc32 without a parse in the loop.
uint32_t dmlc_crc32(const void* data, int64_t len);

CsrBlockResult* dmlc_parse_libsvm(const char* data, int64_t len, int nthread,
                                  int indexing_mode);
CsrBlockResult* dmlc_parse_libfm(const char* data, int64_t len, int nthread,
                                 int indexing_mode);
DenseResult* dmlc_parse_libsvm_dense(const char* data, int64_t len, int nthread,
                                     int64_t num_col, int indexing_mode);
CsvResult* dmlc_parse_csv(const char* data, int64_t len, int nthread, char delim);
CsvSplitResult* dmlc_parse_csv_split(const char* data, int64_t len, int nthread,
                                     char delim, int32_t label_col,
                                     int32_t weight_col);

void dmlc_free_block(CsrBlockResult* r);
void dmlc_free_dense(DenseResult* r);
void dmlc_free_csv(CsvResult* r);
void dmlc_free_csv_split(CsvSplitResult* r);

int dmlc_native_abi_version();

// ---------------- streaming reader (reader.cc) ----------------
//
// A native read->chunk->parse pipeline over a byte-range partition of local
// text files: producer thread loads record-aligned chunks (the reference's
// InputSplitBase/LineSplitter invariants), parses each with worker threads,
// and queues parsed blocks for the consumer. Formats: 0=libsvm (CSR),
// 1=libsvm dense, 2=csv, 3=libfm, 4=recordio (binary records: 4-byte
// partition alignment, magic-head boundary seeks, no newline injection at
// file joins; results are RecordBatchResult).

// batch_rows > 0 (dense libsvm, or csv with num_col > 0): repack parsed
// rows into exact [batch_rows, num_col] dense blocks off the consumer
// thread (final block may be short). For csv, label_col/weight_col (-1 =
// absent) are split out and the remaining cells padded/truncated to
// num_col; results then carry format 1 (dense). out_bf16 = 1 converts x
// to bfloat16 (round-to-nearest-even) DURING the repack copy — the same
// single pass, half the output bytes.
// Formats 6 (libsvm -> COO) and 7 (libfm -> COO) emit CooResult blocks:
// one device-ready COO batch per chunk, with row_bucket/nnz_bucket shape
// quantization and optional unit-value elision (see dmlc_parse_coo).
void* dmlc_reader_create(const char** paths, const int64_t* sizes,
                         int32_t nfiles, int64_t part_index, int64_t num_parts,
                         int32_t format, int64_t num_col, int32_t indexing_mode,
                         char delim, int32_t nthread, int64_t chunk_bytes,
                         int32_t queue_depth, int64_t batch_rows,
                         int32_t label_col, int32_t weight_col,
                         int32_t out_bf16, int64_t row_bucket,
                         int64_t nnz_bucket, int32_t elide_unit,
                         int32_t csr_wire, int32_t pack_aux);
// Next parsed block; NULL at end-of-partition or on reader error (check
// dmlc_reader_error). Parse errors ride the result's own error field.
// Blocks with zero rows are never returned. `fmt_out` (may be NULL)
// receives the format of THIS result: a reader created with format 1
// (libsvm dense) downgrades permanently to format 0 (CSR) when it meets
// data the dense scanner cannot express (qid rows), so the tag can differ
// from the requested format.
void* dmlc_reader_next(void* handle, int32_t* fmt_out);
void dmlc_reader_before_first(void* handle);
int64_t dmlc_reader_bytes_read(void* handle);

// ---------------- indexed recordio reader (reader.cc) ----------------
//
// Record-count partitioned reader over an external index (sorted record
// start offsets, global over the concatenated files): batched contiguous
// reads when shuffle=0, per-epoch shuffled per-record seeks when
// shuffle=1 (mt19937_64 seeded with `seed`; each before_first draws the
// next epoch's permutation). Results are RecordBatchResult (payloads
// extracted, multi-part reassembled). Mirrors indexed_recordio_split.cc
// (ResetPartition :12-41, NextBatchEx :159-212, BeforeFirst :221-233).
void* dmlc_indexed_reader_create(const char** paths, const int64_t* sizes,
                                 int32_t nfiles, const int64_t* index_offsets,
                                 int64_t n_index, int64_t part_index,
                                 int64_t num_parts, int64_t batch_records,
                                 int32_t shuffle, uint64_t seed,
                                 int32_t queue_depth);
void* dmlc_indexed_reader_next(void* handle);  // RecordBatchResult*
void dmlc_indexed_reader_before_first(void* handle);
// Native resume: land in epoch `epochs` (counting before_first calls) at
// record `records` of the partition — missing epoch permutations are drawn
// (pure rng replay, no I/O) and the producer starts at the record cursor.
void dmlc_indexed_reader_skip(void* handle, int64_t epochs, int64_t records);
int64_t dmlc_indexed_reader_bytes_read(void* handle);
const char* dmlc_indexed_reader_error(void* handle);
void dmlc_indexed_reader_destroy(void* handle);
// Non-NULL when the reader itself failed (open/seek/IO); owned by the handle.
const char* dmlc_reader_error(void* handle);
void dmlc_reader_destroy(void* handle);

// ---------------- push-mode reader (chunk feeder) ----------------
//
// Same chunk->parse->queue pipeline, but bytes are PUSHED by the caller
// instead of read from local files — the path by which remote streams
// (S3/GCS/HTTP range reads in Python) reach the native parser. The caller
// owns partitioning (byte range + record-boundary adjustment + newline
// injection at text file joins, which the Python input-split engine
// already does for every filesystem); the feeder owns record-aligned
// chunking, threaded parsing, and batch repack. Push blocks (GIL released
// via ctypes) when the internal byte queue is full — natural backpressure.

void* dmlc_feeder_create(int32_t format, int64_t num_col,
                         int32_t indexing_mode, char delim, int32_t nthread,
                         int64_t chunk_bytes, int32_t queue_depth,
                         int64_t batch_rows, int32_t label_col,
                         int32_t weight_col, int32_t out_bf16,
                         int64_t row_bucket, int64_t nnz_bucket,
                         int32_t elide_unit, int32_t csr_wire,
                         int32_t pack_aux);
// 0 = accepted; -1 = reader stopped/failed (check dmlc_feeder_error).
int32_t dmlc_feeder_push(void* handle, const char* data, int64_t len);
// Signal end of input: the pipeline flushes its tail and then next()
// returns NULL at end of stream.
void dmlc_feeder_finish(void* handle);
// Unblock + fail any in-flight push and drain the pipeline to EOF. The
// caller MUST abort and join its feed thread before calling
// dmlc_feeder_before_first or dmlc_feeder_destroy.
void dmlc_feeder_abort(void* handle);
// Record a feed-side failure (remote read error in the feeding thread) and
// end the stream; queued results drain, then next() returns NULL with the
// error set.
void dmlc_feeder_fail(void* handle, const char* msg);
void* dmlc_feeder_next(void* handle, int32_t* fmt_out);
// Reset for a new epoch: the caller must re-feed from the start.
void dmlc_feeder_before_first(void* handle);
int64_t dmlc_feeder_bytes_read(void* handle);
const char* dmlc_feeder_error(void* handle);
void dmlc_feeder_destroy(void* handle);

}  // extern "C"

#endif  // DMLC_TPU_NATIVE_API_H_
