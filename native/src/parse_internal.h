// Internal interface between parse.cc and reader.cc (same .so) — lets the
// streaming reader consume per-thread DensePart buffers directly, skipping
// the merged DenseResult copy that the C ABI entry points produce for
// one-shot Python callers.
#ifndef DMLC_TPU_NATIVE_PARSE_INTERNAL_H_
#define DMLC_TPU_NATIVE_PARSE_INTERNAL_H_

#include <cstdint>
#include <string>
#include <vector>

namespace dmlc_tpu {

// One thread-range of the dense libsvm scanner. Rows are buffered with
// stride num_col + 1 so the 1-based -> 0-based indexing decision (which
// needs the chunk-global min index, libsvm_parser.h:159-168) reduces to a
// column offset chosen after all ranges finish.
struct DensePart {
  std::vector<float> x;       // [nrow, num_col + 1] row-major
  std::vector<float> label;
  std::vector<float> weight;  // empty or per-row
  uint64_t min_index = UINT64_MAX;
  std::string error;
  bool needs_csr = false;  // data the dense layout can't express (qid rows)
};

// Parse a chunk into per-thread parts (bulk/tail split so every scanner
// range is EOL-terminated in-buffer, thread fan-out, BOM skip). Fills
// `parts`; any per-range error is left in that part's `error`.
void parse_libsvm_dense_chunk(const char* data, int64_t len, int nthread,
                              int64_t num_col, std::vector<DensePart>* parts);

}  // namespace dmlc_tpu

#endif  // DMLC_TPU_NATIVE_PARSE_INTERNAL_H_
