// Multi-threaded chunk parsers for libsvm / csv / libfm -> CSR buffers.
//
// TPU-native rebuild of the reference parse hot path (src/data/
// text_parser.h:110-146 chunk-splitting across threads + libsvm_parser.h /
// csv_parser.h / libfm_parser.h ParseBlock scanners): a chunk of text is
// split at line boundaries into nthread ranges, each range parsed into
// per-thread CSR vectors, then the results are merged into one contiguous
// malloc'd block handed to Python over a C ABI (ctypes — no pybind11 in
// this image).
//
// Semantics intentionally identical to the Python engine in
// dmlc_tpu/data/parsers.py (which mirrors the reference):
//   libsvm: label[:weight] [qid:N] idx[:val]... , '#' comments, BOM skip,
//           indexing_mode {-1,0,1} with the sklearn heuristic per chunk.
//   csv:    single-char delimiter, dense cells; ragged rows -> error.
//   libfm:  label field:idx:val triples; heuristic needs BOTH mins > 0.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "api.h"
#include "buffer_pool.h"
#include "parse_internal.h"
#include "strtonum.h"

namespace dmlc_tpu {

struct CsrPart {
  std::vector<int64_t> row_nnz;
  std::vector<float> label;
  std::vector<float> weight;   // empty or per-row
  std::vector<int64_t> qid;    // empty or per-row
  std::vector<uint64_t> index;
  std::vector<uint64_t> field;  // libfm only
  std::vector<float> value;    // empty (all-binary) or per-entry
  uint64_t min_index = UINT64_MAX;
  uint64_t min_field = UINT64_MAX;
  std::string error;
};

// Clamp the thread count so small chunks don't pay thread spawn overhead:
// one thread per 512 KB, at least one.
static int clamp_threads(int nthread, size_t len) {
  int by_size = static_cast<int>(len / (512 * 1024)) + 1;
  return nthread < by_size ? nthread : by_size;
}

// Split [begin, end) into n ranges at line boundaries.
static std::vector<std::pair<const char*, const char*>> split_lines(
    const char* begin, const char* end, int n) {
  std::vector<std::pair<const char*, const char*>> out;
  size_t total = static_cast<size_t>(end - begin);
  size_t step = total / static_cast<size_t>(n) + 1;
  const char* cur = begin;
  for (int i = 0; i < n && cur < end; ++i) {
    const char* stop = cur + step;
    if (stop >= end) {
      stop = end;
    } else {
      while (stop < end && *stop != '\n' && *stop != '\r') ++stop;
      while (stop < end && (*stop == '\n' || *stop == '\r')) ++stop;
    }
    out.emplace_back(cur, stop);
    cur = stop;
  }
  if (cur < end && !out.empty()) out.back().second = end;
  return out;
}

static inline const char* line_end(const char* p, const char* end) {
  while (p != end && *p != '\n' && *p != '\r') ++p;
  return p;
}

// SIMD line scan: memchr for '\n' (and '\r' only when the range has any —
// one flag check instead of a scalar byte loop re-touching every line).
// The scalar pre-scan was ~1 cyc/byte, a full second pass over the chunk.
static inline const char* line_end_fast(const char* p, const char* end,
                                        bool has_cr) {
  const char* nl =
      static_cast<const char*>(memchr(p, '\n', static_cast<size_t>(end - p)));
  const char* stop = nl ? nl : end;
  if (has_cr) {
    const char* cr = static_cast<const char*>(
        memchr(p, '\r', static_cast<size_t>(stop - p)));
    if (cr) return cr;
  }
  return stop;
}

// ---------------- libsvm ----------------

// Count bytes equal to `c` in [p, end) via SIMD memchr hops — ~0.1 cyc/byte,
// repaid many times over by reserving the output vectors (push_back growth
// re-copies multi-MB index/value arrays several times otherwise).
static inline size_t count_byte(const char* p, const char* end, char c) {
  size_t n = 0;
  while ((p = static_cast<const char*>(memchr(p, c, end - p))) != nullptr) {
    ++n;
    ++p;
  }
  return n;
}

static void parse_libsvm_range(const char* begin, const char* end, CsrPart* out) {
  const bool has_cr =
      memchr(begin, '\r', static_cast<size_t>(end - begin)) != nullptr;
  const char* p = begin;
  {
    size_t rows = count_byte(begin, end, '\n') + 1;
    size_t entries = count_byte(begin, end, ':');  // upper bound (+weights/qids)
    out->row_nnz.reserve(rows);
    out->label.reserve(rows);
    out->index.reserve(entries);
    out->value.reserve(entries);
  }
  while (p < end) {
    const char* lend = line_end_fast(p, end, has_cr);
    const char* q = p;
    // strip comment
    const char* hash = static_cast<const char*>(memchr(q, '#', lend - q));
    const char* effective_end = hash ? hash : lend;
    double label;
    const char* after;
    if (!parse_value(q, effective_end, &after, &label)) {
      p = lend;
      while (p < end && (*p == '\n' || *p == '\r')) ++p;
      continue;  // blank/comment-only line
    }
    q = after;
    bool has_weight = false;
    double weight = 1.0;
    if (q != effective_end && *q == ':') {
      ++q;
      if (!parse_value(q, effective_end, &after, &weight)) {
        out->error = "libsvm: bad label:weight";
        return;
      }
      q = after;
      has_weight = true;
    }
    out->label.push_back(static_cast<float>(label));
    if (has_weight) {
      if (out->weight.size() != out->label.size() - 1) {
        out->error = "libsvm: label:weight must be set on every row or none";
        return;
      }
      out->weight.push_back(static_cast<float>(weight));
    } else if (!out->weight.empty()) {
      out->error = "libsvm: label:weight must be set on every row or none";
      return;
    }
    // qid
    while (q != effective_end && is_space(*q)) ++q;
    if (effective_end - q >= 4 && memcmp(q, "qid:", 4) == 0) {
      uint64_t qid;
      if (!parse_uint(q + 4, effective_end, &after, &qid)) {
        out->error = "libsvm: bad qid";
        return;
      }
      if (out->qid.size() != out->label.size() - 1) {
        out->error = "libsvm: qid must appear on every row or none";
        return;
      }
      out->qid.push_back(static_cast<int64_t>(qid));
      q = after;
    } else if (!out->qid.empty()) {
      out->error = "libsvm: qid must appear on every row or none";
      return;
    }
    // features
    int64_t nnz = 0;
    while (true) {
      uint64_t idx;
      if (!parse_uint(q, effective_end, &after, &idx)) break;
      q = after;
      out->index.push_back(idx);
      if (idx < out->min_index) out->min_index = idx;
      ++nnz;
      if (q != effective_end && *q == ':') {
        double v;
        ++q;
        if (!parse_value(q, effective_end, &after, &v)) {
          out->error = "libsvm: bad idx:value";
          return;
        }
        q = after;
        // lazily promote to valued mode: backfill 1.0 for prior binary entries
        if (out->value.size() + 1 < out->index.size()) {
          out->value.resize(out->index.size() - 1, 1.0f);
        }
        out->value.push_back(static_cast<float>(v));
      } else if (!out->value.empty()) {
        out->value.push_back(1.0f);
      }
    }
    // anything left that is not whitespace is malformed — error rather than
    // silently truncating the row (the fallback engine errors too)
    while (q != effective_end && is_space(*q)) ++q;
    if (q != effective_end) {
      out->error = "libsvm: malformed feature token";
      return;
    }
    out->row_nnz.push_back(nnz);
    p = lend;
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
  }
  // if any entry anywhere had a value, sizes must match
  if (!out->value.empty() && out->value.size() != out->index.size()) {
    out->value.resize(out->index.size(), 1.0f);
  }
}

// ---------------- libfm ----------------

static void parse_libfm_range(const char* begin, const char* end, CsrPart* out) {
  const bool has_cr =
      memchr(begin, '\r', static_cast<size_t>(end - begin)) != nullptr;
  const char* p = begin;
  {
    size_t rows = count_byte(begin, end, '\n') + 1;
    size_t entries = count_byte(begin, end, ':') / 2 + 1;  // two ':' per triple
    out->row_nnz.reserve(rows);
    out->label.reserve(rows);
    out->field.reserve(entries);
    out->index.reserve(entries);
    out->value.reserve(entries);
  }
  while (p < end) {
    const char* lend = line_end_fast(p, end, has_cr);
    const char* q = p;
    const char* hash = static_cast<const char*>(memchr(q, '#', lend - q));
    const char* effective_end = hash ? hash : lend;
    double label;
    const char* after;
    if (!parse_value(q, effective_end, &after, &label)) {
      p = lend;
      while (p < end && (*p == '\n' || *p == '\r')) ++p;
      continue;
    }
    q = after;
    out->label.push_back(static_cast<float>(label));
    int64_t nnz = 0;
    while (true) {
      uint64_t fld, idx;
      double v;
      if (!parse_uint(q, effective_end, &after, &fld)) break;
      q = after;
      if (q == effective_end || *q != ':' ||
          !parse_uint(q + 1, effective_end, &after, &idx)) {
        out->error = "libfm: features must be field:index:value triples";
        return;
      }
      q = after;
      if (q == effective_end || *q != ':' ||
          !parse_value(q + 1, effective_end, &after, &v)) {
        out->error = "libfm: features must be field:index:value triples";
        return;
      }
      q = after;
      out->field.push_back(fld);
      out->index.push_back(idx);
      out->value.push_back(static_cast<float>(v));
      if (idx < out->min_index) out->min_index = idx;
      if (fld < out->min_field) out->min_field = fld;
      ++nnz;
    }
    while (q != effective_end && is_space(*q)) ++q;
    if (q != effective_end) {
      out->error = "libfm: malformed feature token";
      return;
    }
    out->row_nnz.push_back(nnz);
    p = lend;
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
  }
}

// ---------------- libsvm -> dense ----------------
//
// TPU-first fast path: parse straight into the row-major [n, num_col] device
// layout, skipping CSR index/offset materialization (for HIGGS-shaped data
// the uint64 index array alone is 2x the bytes of the values). Rows are
// buffered with stride num_col+1 so the 1-based->0-based indexing decision
// (which needs the global min index, libsvm_parser.h:159-168) reduces to a
// column offset chosen at merge time. DensePart lives in parse_internal.h
// so the streaming reader can consume parts without the merge copy.

// Dense scanner. PRECONDITION: every line in [begin, end) is
// EOL-terminated IN-BUFFER (the last byte of the range is '\n' or '\r').
// That sentinel removes every per-iteration bounds check from the token
// loops and the per-line memchr line-end pre-scan — digit/space runs stop
// at the EOL byte naturally. Callers guarantee the invariant by splitting
// off a possibly-unterminated tail line (parse_libsvm_dense_chunk).
static void parse_libsvm_dense_range(const char* begin, const char* end,
                                            int64_t num_col, DensePart* out) {
  const char* p = begin;
  const size_t stride = static_cast<size_t>(num_col) + 1;
  {
    size_t rows = count_byte(begin, end, '\n') + 1;
    // cap the up-front reservation (64 MB of floats): mostly-blank input
    // with a huge num_col must not turn a hint into a multi-GB allocation
    size_t cap = (size_t(1) << 24) / stride + 1;
    out->x.reserve((rows < cap ? rows : cap) * stride);
    out->label.reserve(rows);
  }
  uint64_t min_index = out->min_index;
  while (p < end) {
    if (*p == '\n' || *p == '\r') { ++p; continue; }
    const char* q = p;
    double label;
    const char* after;
    if (!parse_value_hot(q, end, &after, &label)) {
      // blank, comment-only, or garbage line: skip to EOL (parity with the
      // CSR scanner's failed-label skip)
      while (*q != '\n' && *q != '\r') ++q;
      p = q;
      continue;
    }
    q = after;
    bool has_weight = false;
    double weight = 1.0;
    if (*q == ':') {
      ++q;
      if (!parse_value_hot(q, end, &after, &weight)) {
        out->error = "libsvm: bad label:weight";
        return;
      }
      q = after;
      has_weight = true;
    }
    out->label.push_back(static_cast<float>(label));
    if (has_weight) {
      if (out->weight.size() != out->label.size() - 1) {
        out->error = "libsvm: label:weight must be set on every row or none";
        return;
      }
      out->weight.push_back(static_cast<float>(weight));
    } else if (!out->weight.empty()) {
      out->error = "libsvm: label:weight must be set on every row or none";
      return;
    }
    while (is_space(*q)) ++q;
    if (end - q >= 4 && memcmp(q, "qid:", 4) == 0) {
      // qid has no dense analog; signal the caller to use the CSR path
      out->error = "libsvm-dense: qid not supported";
      out->needs_csr = true;
      return;
    }
    size_t base = out->x.size();
    out->x.resize(base + stride, 0.0f);
    float* xrow = out->x.data() + base;
    while (true) {
      // inline unsigned-int parse: digits only; the EOL sentinel stops
      // the run (SWAR digit counting measured slower here: 1-2 digit
      // indices are cheaper in the scalar loop than the classify+ctz chain)
      unsigned c = static_cast<unsigned char>(*q) - '0';
      if (c > 9) break;
      uint64_t idx = c;
      ++q;
      while ((c = static_cast<unsigned char>(*q) - '0') <= 9) {
        idx = idx * 10 + c;
        ++q;
      }
      if (idx < min_index) min_index = idx;
      double v = 1.0;
      if (*q == ':') {
        ++q;
        if (!parse_value_hot(q, end, &after, &v)) {
          out->error = "libsvm: bad idx:value";
          out->min_index = min_index;
          return;
        }
        q = after;
      }
      if (idx < stride) xrow[idx] = static_cast<float>(v);
      while (is_space(*q)) ++q;
    }
    while (is_space(*q)) ++q;
    if (*q != '\n' && *q != '\r') {
      if (*q == '#') {  // trailing comment is fine; garbage is not
        while (*q != '\n' && *q != '\r') ++q;
      } else {
        out->error = "libsvm: malformed feature token";
        out->min_index = min_index;
        return;
      }
    }
    p = q;
  }
  out->min_index = min_index;
}

// ---------------- csv ----------------

struct CsvPart {
  std::vector<float> cells;
  int64_t ncol = -1;
  int64_t nrow = 0;
  std::string error;
};

static void parse_csv_range(const char* begin, const char* end, char delim,
                            CsvPart* out) {
  const bool has_cr =
      memchr(begin, '\r', static_cast<size_t>(end - begin)) != nullptr;
  const char* p = begin;
  while (p < end) {
    const char* lend = line_end_fast(p, end, has_cr);
    if (lend == p) {
      ++p;
      continue;
    }
    int64_t cols = 0;
    const char* q = p;
    while (true) {
      // leading space that is not itself the delimiter (tab can be one)
      while (q != lend && is_space(*q) && *q != delim) ++q;
      double v = 0.0;
      const char* after;
      if (q == lend || *q == delim) {
        out->error = "csv: empty cell in row";
        return;
      }
      if (!parse_value(q, lend, &after, &v)) {
        out->error = "csv: unparseable cell in row";
        return;
      }
      q = after;
      out->cells.push_back(static_cast<float>(v));
      ++cols;
      while (q != lend && is_space(*q) && *q != delim) ++q;
      if (q == lend) break;
      if (*q == delim) { ++q; continue; }
      out->error = "csv: unexpected character in row";
      return;
    }
    if (out->ncol < 0) {
      out->ncol = cols;
    } else if (cols != out->ncol) {
      out->error = "csv: ragged rows in chunk";
      return;
    }
    ++out->nrow;
    p = lend;
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
  }
}

// Run a range-parser body capturing any exception (bad_alloc on degenerate
// input) into the part's error field — an exception escaping a worker thread
// or the extern "C" boundary would std::terminate the embedding Python
// process.
template <typename Body>
static void guard_into(std::string* err, Body body) {
  try {
    body();
  } catch (const std::exception& ex) {
    *err = std::string("parse failed: ") + ex.what();
  } catch (...) {
    *err = "parse failed: unknown error";
  }
}
static void parse_libsvm_range_guarded(const char* b, const char* e,
                                       CsrPart* out) {
  guard_into(&out->error, [&] { parse_libsvm_range(b, e, out); });
}
static void parse_libfm_range_guarded(const char* b, const char* e,
                                      CsrPart* out) {
  guard_into(&out->error, [&] { parse_libfm_range(b, e, out); });
}
static void parse_libsvm_dense_range_guarded(const char* b, const char* e,
                                             int64_t num_col, DensePart* out) {
  guard_into(&out->error, [&] { parse_libsvm_dense_range(b, e, num_col, out); });
}
static void parse_csv_range_guarded(const char* b, const char* e, char delim,
                                    CsvPart* out) {
  guard_into(&out->error, [&] { parse_csv_range(b, e, delim, out); });
}

static const char* skip_bom(const char* data, const char** end) {
  if (*end - data >= 3 && memcmp(data, "\xef\xbb\xbf", 3) == 0) return data + 3;
  return data;
}

void parse_libsvm_dense_chunk(const char* data, int64_t len, int nthread,
                              int64_t num_col, std::vector<DensePart>* parts) {
  const char* end = data + len;
  data = skip_bom(data, &end);
  // The dense scanner requires every line EOL-terminated in-buffer: split
  // off an unterminated final line and parse it from a '\n'-padded copy.
  const char* bulk_end = end;
  while (bulk_end > data && bulk_end[-1] != '\n' && bulk_end[-1] != '\r')
    --bulk_end;
  std::string tail_buf;
  if (bulk_end != end) {
    tail_buf.assign(bulk_end, end);
    tail_buf.push_back('\n');
  }
  if (nthread < 1) nthread = 1;
  nthread = clamp_threads(nthread, static_cast<size_t>(bulk_end - data));
  auto ranges = split_lines(data, bulk_end, nthread);
  parts->resize(ranges.size() + (tail_buf.empty() ? 0 : 1));
  std::vector<std::thread> threads;
  for (size_t i = 1; i < ranges.size(); ++i) {
    threads.emplace_back(parse_libsvm_dense_range_guarded, ranges[i].first,
                         ranges[i].second, num_col, &(*parts)[i]);
  }
  if (!tail_buf.empty()) {
    parse_libsvm_dense_range_guarded(tail_buf.data(),
                                     tail_buf.data() + tail_buf.size(),
                                     num_col, &parts->back());
  }
  if (!ranges.empty())
    parse_libsvm_dense_range_guarded(ranges[0].first, ranges[0].second,
                                     num_col, &(*parts)[0]);
  for (auto& t : threads) t.join();
}

}  // namespace dmlc_tpu

// ---------------- C ABI ----------------

using namespace dmlc_tpu;

extern "C" {

static char* dup_error(const std::string& s) {
  char* e = static_cast<char*>(malloc(s.size() + 1));
  if (e) memcpy(e, s.c_str(), s.size() + 1);
  return e;  // null only under OOM; callers treat a null error as set-failed
}

static CsrBlockResult* merge_parts(std::vector<CsrPart>& parts, int indexing_mode,
                                   bool heuristic_needs_field) {
  auto* res = static_cast<CsrBlockResult*>(calloc(1, sizeof(CsrBlockResult)));
  for (auto& part : parts) {
    if (!part.error.empty()) {
      res->error = dup_error(part.error);
      return res;
    }
  }
  int64_t n = 0, nnz = 0;
  bool any_weight = false, any_qid = false, any_value = false, any_field = false;
  uint64_t min_index = UINT64_MAX, min_field = UINT64_MAX;
  for (auto& part : parts) {
    n += static_cast<int64_t>(part.label.size());
    nnz += static_cast<int64_t>(part.index.size());
    any_weight |= !part.weight.empty();
    any_qid |= !part.qid.empty();
    any_value |= !part.value.empty();
    any_field |= !part.field.empty();
    if (part.min_index < min_index) min_index = part.min_index;
    if (part.min_field < min_field) min_field = part.min_field;
  }
  // all-or-none consistency across thread ranges. The format name follows
  // heuristic_needs_field (true == libfm; today the libfm scanner emits no
  // weights/qids, so these fire only for libsvm — the parameterization
  // keeps the message right if libfm weight syntax is ever wired up)
  const char* fmt = heuristic_needs_field ? "libfm" : "libsvm";
  for (auto& part : parts) {
    if (!part.label.empty()) {
      if (any_weight && part.weight.size() != part.label.size()) {
        res->error = dup_error(std::string(fmt) +
            ": label:weight must be set on every row or none");
        return res;
      }
      if (any_qid && part.qid.size() != part.label.size()) {
        res->error = dup_error(std::string(fmt) +
            ": qid must appear on every row or none");
        return res;
      }
    }
    if (any_value && !part.index.empty() && part.value.empty()) {
      part.value.resize(part.index.size(), 1.0f);
    }
  }
  res->n_rows = n;
  res->nnz = nnz;
  res->offset = static_cast<int64_t*>(malloc((n + 1) * sizeof(int64_t)));
  res->label = static_cast<float*>(malloc(n * sizeof(float)));
  if (any_weight) res->weight = static_cast<float*>(malloc(n * sizeof(float)));
  if (any_qid) res->qid = static_cast<int64_t*>(malloc(n * sizeof(int64_t)));
  res->index = static_cast<uint64_t*>(malloc(nnz * sizeof(uint64_t)));
  if (any_field) res->field = static_cast<uint64_t*>(malloc(nnz * sizeof(uint64_t)));
  if (any_value) res->value = static_cast<float*>(malloc(nnz * sizeof(float)));
  // a failed allocation must come back as an error result, not a segfault
  // in the embedding Python process
  if (!res->offset || !res->label || (any_weight && !res->weight) ||
      (any_qid && !res->qid) || !res->index || (any_field && !res->field) ||
      (any_value && !res->value)) {
    free(res->offset); free(res->label); free(res->weight); free(res->qid);
    free(res->index); free(res->field); free(res->value);
    memset(res, 0, sizeof(*res));
    res->error = dup_error("parse: out of memory merging chunk");
    return res;
  }
  int64_t row = 0, ent = 0;
  res->offset[0] = 0;
  for (auto& part : parts) {
    size_t pn = part.label.size();
    if (pn) {
      memcpy(res->label + row, part.label.data(), pn * sizeof(float));
      if (any_weight) memcpy(res->weight + row, part.weight.data(), pn * sizeof(float));
      if (any_qid) memcpy(res->qid + row, part.qid.data(), pn * sizeof(int64_t));
      for (size_t i = 0; i < pn; ++i) {
        res->offset[row + 1 + static_cast<int64_t>(i)] =
            res->offset[row + static_cast<int64_t>(i)] + part.row_nnz[i];
      }
      row += static_cast<int64_t>(pn);
    }
    size_t pe = part.index.size();
    if (pe) {
      memcpy(res->index + ent, part.index.data(), pe * sizeof(uint64_t));
      if (any_field) memcpy(res->field + ent, part.field.data(), pe * sizeof(uint64_t));
      if (any_value) memcpy(res->value + ent, part.value.data(), pe * sizeof(float));
      ent += static_cast<int64_t>(pe);
    }
  }
  // indexing mode conversion (libsvm_parser.h:159-168 / libfm_parser.h:130-143)
  bool convert = indexing_mode > 0;
  if (indexing_mode < 0 && nnz > 0 && min_index > 0) {
    convert = !heuristic_needs_field || min_field > 0;
  }
  if (convert) {
    for (int64_t i = 0; i < nnz; ++i) res->index[i] -= 1;
    if (res->field && heuristic_needs_field) {
      for (int64_t i = 0; i < nnz; ++i) res->field[i] -= 1;
    }
  }
  return res;
}

CsrBlockResult* dmlc_parse_libsvm(const char* data, int64_t len, int nthread,
                                  int indexing_mode) {
  const char* end = data + len;
  data = skip_bom(data, &end);
  if (nthread < 1) nthread = 1;
  nthread = clamp_threads(nthread, static_cast<size_t>(end - data));
  auto ranges = split_lines(data, end, nthread);
  std::vector<CsrPart> parts(ranges.size());
  std::vector<std::thread> threads;
  for (size_t i = 1; i < ranges.size(); ++i) {
    threads.emplace_back(parse_libsvm_range_guarded, ranges[i].first,
                         ranges[i].second, &parts[i]);
  }
  if (!ranges.empty())
    parse_libsvm_range_guarded(ranges[0].first, ranges[0].second, &parts[0]);
  for (auto& t : threads) t.join();
  return merge_parts(parts, indexing_mode, false);
}

CsrBlockResult* dmlc_parse_libfm(const char* data, int64_t len, int nthread,
                                 int indexing_mode) {
  const char* end = data + len;
  data = skip_bom(data, &end);
  if (nthread < 1) nthread = 1;
  nthread = clamp_threads(nthread, static_cast<size_t>(end - data));
  auto ranges = split_lines(data, end, nthread);
  std::vector<CsrPart> parts(ranges.size());
  std::vector<std::thread> threads;
  for (size_t i = 1; i < ranges.size(); ++i) {
    threads.emplace_back(parse_libfm_range_guarded, ranges[i].first,
                         ranges[i].second, &parts[i]);
  }
  if (!ranges.empty())
    parse_libfm_range_guarded(ranges[0].first, ranges[0].second, &parts[0]);
  for (auto& t : threads) t.join();
  return merge_parts(parts, indexing_mode, true);
}

// ---------------- text -> COO (device-ready sparse batch) ----------------
//
// TPU-first path for high-dim sparse corpora (KDD2012 libfm -> BCOO,
// BASELINE config #4): assemble the exact arrays jax.experimental.sparse
// wants — int32 (row, col) coordinate pairs, f32 values (or elided when all
// ones), f32 label/weight — in ONE fused pass over the per-thread parse
// parts, with bucketed shape padding. Replaces the numpy coordinate
// assembly (ops/sparse.py block_to_bcoo_host) that serialized with parsing
// on one-core hosts; here it runs at C++ speed with no temporaries.

static int64_t round_up_bucket(int64_t v, int64_t bucket) {
  if (bucket <= 0) return v;
  int64_t base = v > 1 ? v : 1;  // never a zero-size dim (matches Python)
  return (base + bucket - 1) / bucket * bucket;
}

static CooResult* merge_parts_coo(std::vector<CsrPart>& parts,
                                  int indexing_mode, bool heuristic_needs_field,
                                  int64_t num_col, int64_t row_bucket,
                                  int64_t nnz_bucket, bool elide_unit,
                                  bool csr_wire) {
  auto* res = static_cast<CooResult*>(calloc(1, sizeof(CooResult)));
  if (!res) return nullptr;
  for (auto& part : parts) {
    if (!part.error.empty()) {
      res->error = dup_error(part.error);
      return res;
    }
  }
  int64_t n = 0, nnz = 0;
  bool any_weight = false, any_value = false;
  uint64_t min_index = UINT64_MAX, min_field = UINT64_MAX;
  for (auto& part : parts) {
    n += static_cast<int64_t>(part.label.size());
    nnz += static_cast<int64_t>(part.index.size());
    any_weight |= !part.weight.empty();
    any_value |= !part.value.empty();
    if (part.min_index < min_index) min_index = part.min_index;
    if (part.min_field < min_field) min_field = part.min_field;
  }
  for (auto& part : parts) {
    if (any_weight && !part.label.empty() &&
        part.weight.size() != part.label.size()) {
      // format name follows heuristic_needs_field (true == libfm), same
      // rationale as merge_parts above
      res->error = dup_error(
          std::string(heuristic_needs_field ? "libfm" : "libsvm") +
          ": label:weight must be set on every row or none");
      return res;
    }
  }
  res->n_rows = n;
  res->nnz = nnz;
  if (n == 0) return res;  // blank chunk: dropped by the produce loop
  const int64_t rows_out = round_up_bucket(n, row_bucket);
  const int64_t nnz_out =
      nnz_bucket > 0 ? round_up_bucket(nnz, nnz_bucket) : nnz;
  res->rows_padded = rows_out;
  res->nnz_padded = nnz_out;
  // unit-value elision: all-binary input (no explicit values) or every
  // explicit value == 1.0f — the consumer synthesizes ones on device
  bool elide = elide_unit;
  if (elide && any_value) {
    for (auto& part : parts) {
      for (float v : part.value) {
        if (v != 1.0f) { elide = false; break; }
      }
      if (!elide) break;
    }
  }
  res->values_elided = elide ? 1 : 0;
  // malloc(0) may legally return NULL — label-only chunks (nnz == 0 with
  // buckets disabled) must not read as out-of-memory
  const size_t nnz_alloc = nnz_out > 0 ? static_cast<size_t>(nnz_out) : 1;
  res->csr_wire = csr_wire ? 1 : 0;
  // bucket-padded sizes repeat across chunks, so these buffers recycle
  // through the size-keyed pool (buffer_pool.h) instead of paying
  // glibc's mmap round trip per batch
  res->coords = static_cast<int32_t*>(
      dmlc_pool_alloc((csr_wire ? 1 : 2) * nnz_alloc * sizeof(int32_t)));
  if (csr_wire)
    res->row_ptr = static_cast<int32_t*>(
        dmlc_pool_alloc((rows_out + 1) * sizeof(int32_t)));
  if (!elide)
    res->values =
        static_cast<float*>(dmlc_pool_alloc(nnz_alloc * sizeof(float)));
  res->label = static_cast<float*>(dmlc_pool_alloc(rows_out * sizeof(float)));
  res->weight = static_cast<float*>(dmlc_pool_alloc(rows_out * sizeof(float)));
  if (!res->coords || (csr_wire && !res->row_ptr) ||
      (!elide && !res->values) || !res->label || !res->weight) {
    dmlc_pool_free(res->coords); dmlc_pool_free(res->row_ptr);
    dmlc_pool_free(res->values);
    dmlc_pool_free(res->label); dmlc_pool_free(res->weight);
    res->coords = nullptr; res->row_ptr = nullptr; res->values = nullptr;
    res->label = nullptr; res->weight = nullptr;
    res->error = dup_error("parse: out of memory building coo chunk");
    return res;
  }
  // indexing conversion heuristic, same decision as merge_parts
  // (libsvm_parser.h:159-168 / libfm_parser.h:130-143)
  bool convert = indexing_mode > 0;
  if (indexing_mode < 0 && nnz > 0 && min_index > 0) {
    convert = !heuristic_needs_field || min_field > 0;
  }
  const uint64_t off = convert ? 1 : 0;
  // column OOB sentinel: entries past the declared width clamp to num_col
  // (masked by every BCOO op) — also keeps int32 from overflowing on
  // out-of-spec indices
  const uint64_t col_max = static_cast<uint64_t>(num_col);
  int64_t row = 0, ent = 0;
  for (auto& part : parts) {
    const size_t pn = part.label.size();
    if (pn) {
      memcpy(res->label + row, part.label.data(), pn * sizeof(float));
      if (any_weight) {
        memcpy(res->weight + row, part.weight.data(), pn * sizeof(float));
      } else {
        for (size_t i = 0; i < pn; ++i) res->weight[row + i] = 1.0f;
      }
    }
    if (csr_wire) {
      // CSR wire: cumulative row_ptr instead of per-entry row ids —
      // O(rows) writes instead of O(nnz), and half the coordinate bytes
      // on the wire; the consumer rebuilds row ids on device
      for (size_t i = 0; i < pn; ++i) {
        res->row_ptr[row + static_cast<int64_t>(i)] =
            static_cast<int32_t>(ent);
        ent += part.row_nnz[i];
      }
    } else {
      for (size_t i = 0; i < pn; ++i) {
        const int64_t rn = part.row_nnz[i];
        const int32_t r32 =
            static_cast<int32_t>(row + static_cast<int64_t>(i));
        for (int64_t k = 0; k < rn; ++k) {
          res->coords[2 * ent] = r32;
          ++ent;
        }
      }
    }
    row += static_cast<int64_t>(pn);
  }
  if (csr_wire) {
    // rows [n, rows_out] (pad rows + the end sentinel) all start at nnz:
    // the device-side prefix-sum rebuild then maps every pad entry past
    // nnz to the OOB row rows_out, which every BCOO op masks
    for (int64_t i = n; i <= rows_out; ++i)
      res->row_ptr[i] = static_cast<int32_t>(nnz);
  }
  const int64_t cstride = csr_wire ? 1 : 2;
  const int64_t coff = csr_wire ? 0 : 1;
  // column pass: sequential over each part's index array (better locality
  // than interleaving with the row fill above)
  ent = 0;
  for (auto& part : parts) {
    const size_t pe = part.index.size();
    for (size_t i = 0; i < pe; ++i) {
      uint64_t c = part.index[i] - off;
      res->coords[cstride * ent + coff] =
          c > col_max ? static_cast<int32_t>(col_max)
                      : static_cast<int32_t>(c);
      ++ent;
    }
    if (!elide) {
      if (part.value.empty()) {  // all-binary part: implicit ones
        const size_t base = ent - pe;
        for (size_t i = 0; i < pe; ++i) res->values[base + i] = 1.0f;
      } else {
        memcpy(res->values + (ent - pe), part.value.data(),
               pe * sizeof(float));
      }
    }
  }
  // padding: OOB coords (rows_out, num_col), zero values/label/weight;
  // csr_wire pads cols only — the pad rows fall out of the row_ptr
  // sentinel fill above
  for (int64_t i = nnz; i < nnz_out; ++i) {
    if (csr_wire) {
      res->coords[i] = static_cast<int32_t>(col_max);
    } else {
      res->coords[2 * i] = static_cast<int32_t>(rows_out);
      res->coords[2 * i + 1] = static_cast<int32_t>(col_max);
    }
  }
  if (!elide && nnz_out > nnz) {
    memset(res->values + nnz, 0, (nnz_out - nnz) * sizeof(float));
  }
  if (rows_out > n) {
    memset(res->label + n, 0, (rows_out - n) * sizeof(float));
    memset(res->weight + n, 0, (rows_out - n) * sizeof(float));
  }
  return res;
}

CooResult* dmlc_parse_coo(const char* data, int64_t len, int nthread,
                          int indexing_mode, int fmt, int64_t num_col,
                          int64_t row_bucket, int64_t nnz_bucket,
                          int32_t elide_unit, int32_t csr_wire) {
  const char* end = data + len;
  data = skip_bom(data, &end);
  if (nthread < 1) nthread = 1;
  nthread = clamp_threads(nthread, static_cast<size_t>(end - data));
  auto ranges = split_lines(data, end, nthread);
  std::vector<CsrPart> parts(ranges.size());
  std::vector<std::thread> threads;
  const bool libfm = fmt == 3;
  auto range_fn =
      libfm ? parse_libfm_range_guarded : parse_libsvm_range_guarded;
  for (size_t i = 1; i < ranges.size(); ++i) {
    threads.emplace_back(range_fn, ranges[i].first, ranges[i].second,
                         &parts[i]);
  }
  if (!ranges.empty())
    range_fn(ranges[0].first, ranges[0].second, &parts[0]);
  for (auto& t : threads) t.join();
  return merge_parts_coo(parts, indexing_mode, libfm, num_col, row_bucket,
                         nnz_bucket, elide_unit != 0, csr_wire != 0);
}

void dmlc_free_coo(CooResult* r) {
  if (!r) return;
  dmlc_pool_free(r->coords); dmlc_pool_free(r->row_ptr);
  dmlc_pool_free(r->values);
  dmlc_pool_free(r->label); dmlc_pool_free(r->weight);
  free(r->error);
  free(r);
}

DenseResult* dmlc_parse_libsvm_dense(const char* data, int64_t len, int nthread,
                                     int64_t num_col, int indexing_mode) {
  std::vector<DensePart> parts;
  parse_libsvm_dense_chunk(data, len, nthread, num_col, &parts);

  auto* res = static_cast<DenseResult*>(calloc(1, sizeof(DenseResult)));
  if (!res) return nullptr;
  res->n_cols = num_col;
  int64_t n = 0;
  bool any_weight = false;
  uint64_t min_index = UINT64_MAX;
  for (auto& part : parts) {
    if (!part.error.empty()) {
      res->error = dup_error(part.error);
      res->needs_csr = part.needs_csr ? 1 : 0;
      return res;
    }
    n += static_cast<int64_t>(part.label.size());
    any_weight |= !part.weight.empty();
    if (part.min_index < min_index) min_index = part.min_index;
  }
  for (auto& part : parts) {
    if (any_weight && !part.label.empty() &&
        part.weight.size() != part.label.size()) {
      res->error = dup_error("libsvm: label:weight must be set on every row or none");
      return res;
    }
  }
  // 1-based -> 0-based conversion becomes a column offset into the
  // stride-(num_col+1) part buffers (libsvm_parser.h:159-168 heuristic)
  bool convert = indexing_mode > 0 ||
      (indexing_mode < 0 && min_index != UINT64_MAX && min_index > 0);
  const size_t off = convert ? 1 : 0;
  const size_t stride = static_cast<size_t>(num_col) + 1;
  res->n_rows = n;
  res->x = static_cast<float*>(
      dmlc_pool_alloc(static_cast<size_t>(n) * num_col * sizeof(float)));
  res->label = static_cast<float*>(dmlc_pool_alloc(n * sizeof(float)));
  if (any_weight)
    res->weight = static_cast<float*>(dmlc_pool_alloc(n * sizeof(float)));
  if (!res->x || !res->label || (any_weight && !res->weight)) {
    dmlc_pool_free(res->x); dmlc_pool_free(res->label);
    dmlc_pool_free(res->weight);
    memset(res, 0, sizeof(*res));
    res->n_cols = num_col;
    res->error = dup_error("parse: out of memory merging chunk");
    return res;
  }
  int64_t row = 0;
  for (auto& part : parts) {
    size_t pn = part.label.size();
    if (!pn) continue;
    memcpy(res->label + row, part.label.data(), pn * sizeof(float));
    if (any_weight) memcpy(res->weight + row, part.weight.data(), pn * sizeof(float));
    for (size_t i = 0; i < pn; ++i) {
      memcpy(res->x + (row + static_cast<int64_t>(i)) * num_col,
             part.x.data() + i * stride + off, num_col * sizeof(float));
    }
    row += static_cast<int64_t>(pn);
  }
  return res;
}

void dmlc_free_dense(DenseResult* r) {
  if (!r) return;
  dmlc_pool_free(r->x); dmlc_pool_free(r->label); dmlc_pool_free(r->weight);
  free(r->error);
  free(r);
}

CsvResult* dmlc_parse_csv(const char* data, int64_t len, int nthread, char delim) {
  const char* end = data + len;
  data = skip_bom(data, &end);
  if (nthread < 1) nthread = 1;
  nthread = clamp_threads(nthread, static_cast<size_t>(end - data));
  auto ranges = split_lines(data, end, nthread);
  std::vector<CsvPart> parts(ranges.size());
  std::vector<std::thread> threads;
  for (size_t i = 1; i < ranges.size(); ++i) {
    threads.emplace_back(parse_csv_range_guarded, ranges[i].first,
                         ranges[i].second, delim, &parts[i]);
  }
  if (!ranges.empty())
    parse_csv_range_guarded(ranges[0].first, ranges[0].second, delim,
                            &parts[0]);
  for (auto& t : threads) t.join();
  auto* res = static_cast<CsvResult*>(calloc(1, sizeof(CsvResult)));
  int64_t ncol = -1, nrow = 0, ncell = 0;
  for (auto& part : parts) {
    if (!part.error.empty()) {
      res->error = dup_error(part.error);
      return res;
    }
    if (part.nrow == 0) continue;
    if (ncol < 0) ncol = part.ncol;
    if (part.ncol != ncol) {
      res->error = dup_error("csv: ragged rows in chunk");
      return res;
    }
    nrow += part.nrow;
    ncell += static_cast<int64_t>(part.cells.size());
  }
  res->n_rows = nrow;
  res->n_cols = ncol < 0 ? 0 : ncol;
  res->cells = static_cast<float*>(malloc(ncell * sizeof(float)));
  if (!res->cells && ncell > 0) {
    memset(res, 0, sizeof(*res));
    res->error = dup_error("parse: out of memory merging chunk");
    return res;
  }
  int64_t at = 0;
  for (auto& part : parts) {
    if (part.cells.empty()) continue;
    memcpy(res->cells + at, part.cells.data(), part.cells.size() * sizeof(float));
    at += static_cast<int64_t>(part.cells.size());
  }
  return res;
}

void dmlc_free_block(CsrBlockResult* r) {
  if (!r) return;
  free(r->offset); free(r->label); free(r->weight); free(r->qid);
  free(r->index); free(r->field); free(r->value); free(r->error);
  free(r);
}

void dmlc_free_csv(CsvResult* r) {
  if (!r) return;
  free(r->cells); free(r->error);
  free(r);
}

static CsvSplitResult* csv_split_error(CsvSplitResult* res, const char* msg) {
  free(res->values); free(res->label); free(res->weight);
  res->values = res->label = res->weight = nullptr;
  res->n_rows = res->n_feat_cols = 0;
  res->error = dup_error(msg);
  return res;
}

CsvSplitResult* dmlc_parse_csv_split(const char* data, int64_t len, int nthread,
                                     char delim, int32_t label_col,
                                     int32_t weight_col) {
  // scan phase identical to dmlc_parse_csv (shared per-range scanner); the
  // split happens in the merge pass, which already touches every cell once
  const char* end = data + len;
  data = skip_bom(data, &end);
  if (nthread < 1) nthread = 1;
  nthread = clamp_threads(nthread, static_cast<size_t>(end - data));
  auto ranges = split_lines(data, end, nthread);
  std::vector<CsvPart> parts(ranges.size());
  std::vector<std::thread> threads;
  for (size_t i = 1; i < ranges.size(); ++i) {
    threads.emplace_back(parse_csv_range_guarded, ranges[i].first,
                         ranges[i].second, delim, &parts[i]);
  }
  if (!ranges.empty())
    parse_csv_range_guarded(ranges[0].first, ranges[0].second, delim,
                            &parts[0]);
  for (auto& t : threads) t.join();
  auto* res = static_cast<CsvSplitResult*>(calloc(1, sizeof(CsvSplitResult)));
  if (!res) return nullptr;
  int64_t ncol = -1, nrow = 0;
  for (auto& part : parts) {
    if (!part.error.empty()) return csv_split_error(res, part.error.c_str());
    if (part.nrow == 0) continue;
    if (ncol < 0) ncol = part.ncol;
    if (part.ncol != ncol)
      return csv_split_error(res, "csv: ragged rows in chunk");
    nrow += part.nrow;
  }
  if (nrow == 0 || ncol <= 0) return res;  // blank chunk
  if (label_col >= ncol || weight_col >= ncol)
    return csv_split_error(res, "csv: label/weight column out of range");
  if (label_col >= 0 && label_col == weight_col)
    // the Python layer validates this too, but the C ABI must be safe on
    // its own: equal columns would decrement k twice while the run
    // builder skips the column once — an out-of-bounds write per row
    return csv_split_error(res, "csv: label_column must differ from weight_column");
  const int lc = label_col, wc = weight_col;
  const int64_t k = ncol - (lc >= 0 ? 1 : 0) - (wc >= 0 ? 1 : 0);
  res->n_rows = nrow;
  res->n_feat_cols = k;
  res->values = static_cast<float*>(malloc(nrow * k * sizeof(float)));
  res->label = lc >= 0 ? static_cast<float*>(malloc(nrow * sizeof(float)))
                       : nullptr;
  res->weight = wc >= 0 ? static_cast<float*>(malloc(nrow * sizeof(float)))
                        : nullptr;
  if ((k > 0 && !res->values) || (lc >= 0 && !res->label) ||
      (wc >= 0 && !res->weight))
    return csv_split_error(res, "parse: out of memory merging chunk");
  // feature columns form <=3 contiguous runs around the label/weight
  // columns; copy run-wise per row (memcpy for all but one-or-two cells)
  int64_t runs[3][2];
  int nruns = 0;
  int64_t at = 0;
  while (at < ncol) {
    if (at == lc || at == wc) { ++at; continue; }
    int64_t hi = at;
    while (hi < ncol && hi != lc && hi != wc) ++hi;
    runs[nruns][0] = at;
    runs[nruns][1] = hi - at;
    ++nruns;
    at = hi;
  }
  int64_t row = 0;
  for (auto& part : parts) {
    const float* cells = part.cells.data();
    for (int64_t i = 0; i < part.nrow; ++i, ++row) {
      const float* src = cells + i * ncol;
      float* dst = res->values + row * k;
      for (int rix = 0; rix < nruns; ++rix) {
        memcpy(dst, src + runs[rix][0],
               static_cast<size_t>(runs[rix][1]) * sizeof(float));
        dst += runs[rix][1];
      }
      if (lc >= 0) res->label[row] = src[lc];
      if (wc >= 0) res->weight[row] = src[wc];
    }
  }
  return res;
}

void dmlc_free_csv_split(CsvSplitResult* r) {
  if (!r) return;
  free(r->values); free(r->label); free(r->weight); free(r->error);
  free(r);
}

int dmlc_native_abi_version() { return 16; }

}  // extern "C"
