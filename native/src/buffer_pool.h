// Pooled allocator for large, shape-repeating result buffers.
//
// TPU-native analog of the reference's memory-pool layer
// (include/dmlc/memory.h:24 MemoryPool fixed-size freelist,
// memory.h:87 ThreadlocalAllocator), redesigned for THIS pipeline's
// allocation profile rather than translated: the hot allocations here are
// a few LARGE, equal-size blocks per batch (a [B, D(+2)] x buffer, COO
// coordinate/value arrays padded to bucket multiples), one batch every
// few milliseconds, freed from a DIFFERENT thread (Python owner
// finalizers run wherever the GC runs). glibc serves >128 KB requests
// with mmap, so the naive malloc/free cycle pays mmap + munmap + a page
// fault per touched page EVERY batch — measurable on a single-core host.
//
// Design: a process-wide, mutex-guarded, size-keyed freelist of
// malloc'd blocks. dmlc_pool_alloc(n) prepends a 16-byte header (magic +
// usable size) so dmlc_pool_free can route any pointer — pooled blocks
// back to their size's freelist (bounded depth, oldest evicted to
// free()), non-pooled sizes straight to free(). Blocks repeat in a tiny
// set of sizes (shape bucketing upstream exists precisely to make
// transfer shapes repeat, which makes buffer sizes repeat too), so the
// freelist map stays small. Small requests (< kMinPooledBytes) bypass
// the pool entirely — they are not worth a mutex.
//
// Depth is capped per size (kMaxFreePerSize) and globally
// (kMaxPooledBytes) so a shape change cannot strand unbounded memory;
// DMLC_TPU_POOL=0 disables pooling (every alloc becomes plain malloc
// with a header) for A/B and leak triage. Thread-safe by construction:
// one mutex around the freelist map — the per-batch cadence (hundreds of
// Hz at most) makes contention unmeasurable next to the mmap churn it
// removes.

#ifndef DMLC_TPU_NATIVE_BUFFER_POOL_H_
#define DMLC_TPU_NATIVE_BUFFER_POOL_H_

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace dmlc_tpu {

namespace pool_detail {

constexpr uint64_t kMagic = 0x70cfb0f1d317a110ULL;
constexpr size_t kHeader = 16;                     // keeps payload 16-aligned
constexpr size_t kMinPooledBytes = 64 * 1024;      // below: plain malloc
constexpr size_t kMaxFreePerSize = 6;              // per-size freelist depth
constexpr size_t kMaxPooledBytes = 256u << 20;     // global cached-bytes cap

struct Header {
  uint64_t magic;
  uint64_t size;  // usable bytes (excludes the header)
};

struct Pool {
  std::mutex mu;
  std::unordered_map<uint64_t, std::vector<void*>> free_;  // size -> blocks
  size_t cached_bytes = 0;
  bool enabled;

  Pool() {
    const char* env = std::getenv("DMLC_TPU_POOL");
    enabled = !(env && env[0] == '0' && env[1] == '\0');
  }

  ~Pool() {
    for (auto& kv : free_)
      for (void* p : kv.second) std::free(p);
  }
};

inline Pool& pool() {
  static Pool* p = new Pool();  // leaked intentionally: owner finalizers in
  return *p;                    // Python may release after static dtors
}

}  // namespace pool_detail

// Allocate n usable bytes (16-aligned payload). Never returns a recycled
// block with stale-page semantics the callers don't already have: callers
// of malloc never assumed zeroed memory, and every result buffer is
// fully written before it crosses the ABI.
inline void* dmlc_pool_alloc(size_t n) {
  using namespace pool_detail;
  if (n == 0) n = 1;
  Pool& P = pool();
  if (P.enabled && n >= kMinPooledBytes) {
    std::lock_guard<std::mutex> lk(P.mu);
    auto it = P.free_.find(static_cast<uint64_t>(n));
    if (it != P.free_.end() && !it->second.empty()) {
      void* block = it->second.back();
      it->second.pop_back();
      P.cached_bytes -= n;
      return static_cast<char*>(block) + kHeader;
    }
  }
  void* raw = std::malloc(kHeader + n);
  if (!raw) return nullptr;
  auto* h = static_cast<Header*>(raw);
  h->magic = kMagic;
  h->size = static_cast<uint64_t>(n);
  return static_cast<char*>(raw) + kHeader;
}

// Release a pointer obtained from dmlc_pool_alloc (null-safe). Large
// blocks are cached for reuse up to the per-size and global caps.
inline void dmlc_pool_free(void* p) {
  using namespace pool_detail;
  if (!p) return;
  void* raw = static_cast<char*>(p) - kHeader;
  auto* h = static_cast<Header*>(raw);
  // a wrong-provenance pointer is a bug upstream; the magic check turns
  // silent corruption into an immediate, debuggable abort
  if (h->magic != kMagic) std::abort();
  const size_t n = static_cast<size_t>(h->size);
  Pool& P = pool();
  if (P.enabled && n >= kMinPooledBytes) {
    std::lock_guard<std::mutex> lk(P.mu);
    auto& list = P.free_[h->size];
    if (list.size() < kMaxFreePerSize &&
        P.cached_bytes + n <= kMaxPooledBytes) {
      list.push_back(raw);
      P.cached_bytes += n;
      return;
    }
  }
  std::free(raw);
}

// Test/diagnostic hooks.
inline size_t dmlc_pool_cached_bytes() {
  using namespace pool_detail;
  Pool& P = pool();
  std::lock_guard<std::mutex> lk(P.mu);
  return P.cached_bytes;
}

inline void dmlc_pool_trim() {
  using namespace pool_detail;
  Pool& P = pool();
  std::lock_guard<std::mutex> lk(P.mu);
  for (auto& kv : P.free_)
    for (void* p : kv.second) std::free(p);
  P.free_.clear();
  P.cached_bytes = 0;
}

}  // namespace dmlc_tpu

#endif  // DMLC_TPU_NATIVE_BUFFER_POOL_H_
