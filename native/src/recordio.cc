// RecordIO framing: record extraction with multi-part reassembly.
//
// TPU-native rebuild of the reference's recordio frame walk
// (src/recordio.cc:53-82 NextRecord, recordio_split.cc:44-82 in-place
// reassembly): wire format is [magic u32 LE][lrecord u32 LE][data][pad to
// 4B], magic = 0xced7230a, lrecord = (cflag << 29) | length. cflag 0 is a
// complete record; 1/2/3 are start/middle/end of a record whose payload
// contained the magic cell at an aligned offset — the writer split it there
// and dropped the cell, so the reader re-inserts the magic between parts.
//
// Semantics mirror dmlc_tpu/io/recordio.py extract_record exactly (both are
// exercised by the same parity tests).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

#include "api.h"

namespace {

constexpr uint32_t kMagic = 0xced7230a;

inline uint32_t load_u32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

RecordBatchResult* fail(RecordBatchResult* res, const char* msg) {
  free(res->data);
  free(res->offsets);
  memset(res, 0, sizeof(*res));
  res->error = strdup(msg);
  return res;  // strdup OOM leaves error null: caller sees an empty batch
}

}  // namespace

extern "C" {

RecordBatchResult* dmlc_recordio_extract(const char* data, int64_t len) {
  auto* res = static_cast<RecordBatchResult*>(calloc(1, sizeof(RecordBatchResult)));
  if (!res) return nullptr;
  // payload is strictly smaller than the framed bytes (every part drops an
  // 8-byte header and re-adds at most 4 magic bytes), so `len` bounds the
  // output; offsets are bounded by one record per 8 framed bytes
  res->data = static_cast<char*>(malloc(len > 0 ? static_cast<size_t>(len) : 1));
  int64_t max_records = len / 8 + 1;
  res->offsets = static_cast<int64_t*>(
      malloc(static_cast<size_t>(max_records + 1) * sizeof(int64_t)));
  if (!res->data || !res->offsets) return fail(res, "recordio: out of memory");
  int64_t pos = 0, w = 0, n = 0;
  res->offsets[0] = 0;
  while (pos < len) {
    if (pos + 8 > len || load_u32(data + pos) != kMagic) {
      return fail(res, "Invalid RecordIO Format");
    }
    uint32_t lrec = load_u32(data + pos + 4);
    uint32_t cflag = (lrec >> 29) & 7;
    uint32_t length = lrec & ((1u << 29) - 1);
    int64_t cursor = pos + 8 + ((static_cast<int64_t>(length) + 3) & ~int64_t(3));
    if (cursor > len) return fail(res, "Invalid RecordIO Format");
    memcpy(res->data + w, data + pos + 8, length);
    w += length;
    if (cflag != 0) {
      if (cflag != 1) return fail(res, "Invalid RecordIO Format");
      while (cflag != 3) {
        if (cursor + 8 > len || load_u32(data + cursor) != kMagic) {
          return fail(res, "Invalid RecordIO Format");
        }
        lrec = load_u32(data + cursor + 4);
        cflag = (lrec >> 29) & 7;
        length = lrec & ((1u << 29) - 1);
        int64_t next = cursor + 8 + ((static_cast<int64_t>(length) + 3) & ~int64_t(3));
        if (cursor + 8 + static_cast<int64_t>(length) > len || next > len) {
          return fail(res, "Invalid RecordIO Format");
        }
        // re-insert the magic the writer dropped between parts
        memcpy(res->data + w, &kMagic, 4);
        w += 4;
        memcpy(res->data + w, data + cursor + 8, length);
        w += length;
        cursor = next;
      }
    }
    res->offsets[++n] = w;
    pos = cursor;
  }
  res->n_records = n;
  res->data_len = w;
  return res;
}

void dmlc_free_records(RecordBatchResult* r) {
  if (!r) return;
  free(r->data);
  free(r->offsets);
  free(r->error);
  free(r);
}

}  // extern "C"
