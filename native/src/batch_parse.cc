// Chunk-at-a-time SIMD batch parser that materializes block-cache v1
// (DMLCBC01) segment spans directly — the cold-path promotion of ROADMAP
// item 3 (arXiv:2101.12127 input pipelines must saturate the host;
// arXiv:2501.10546 cold/first-epoch throughput dominates fleet cost).
//
// Where parse.cc's one-shot entry points hand Python separate malloc'd
// arrays that the block-cache writer then RE-ENCODES per block
// (ascontiguousarray + tobytes + per-array file writes + a Python-side
// crc pass), this path parses a whole chunk and writes the arrays
// STRAIGHT INTO one buffer laid out exactly as a DMLCBC01 block span:
// canonical segment order (offset, label, weight, qid, field, index,
// value), every present array start padded to 64-byte alignment, raw
// little-endian C-order payloads, with a zlib-compatible crc32 computed
// over the span while it is still cache-hot. Python mmap-views the
// arrays zero-copy for the RowBlock AND appends the identical bytes to
// the cache file / service frame with one write — a single
// materialization serves parse output, warm cache, and wire.
//
// Pipeline per chunk:
//   1. SIMD scan (AVX2 / SSE2 / NEON, runtime-dispatched, portable
//      scalar fallback) over the whole chunk: EOL positions ('\n' AND
//      '\r' — CRLF and CR-only corpora index cleanly, a CRLF pair
//      yields an empty span that the line loop skips) + delimiter
//      counts for exact output reservation.
//   2. Line spans fan out across nthread workers BY LINE COUNT (the
//      byte-based split of parse.cc skews when line lengths vary);
//      each worker runs the branch-light strtonum.h token loops.
//   3. Merge writes the per-thread results once, into their final
//      segment offsets, applying the indexing-mode conversion
//      (libsvm_parser.h:159-168 heuristic) during the copy.
//
// Semantics are byte-identical to parse.cc's scanners and the Python
// engine (pinned by the tests/test_native_batch.py A/B parity matrix):
//   libsvm: label[:weight] [qid:N] idx[:val]... , '#' comments, BOM
//           skip, all-or-none weight/qid, lazy binary->valued promotion.
//   csv:    single-char delimiter, uniform columns, label/weight column
//           split with synthetic 0..k-1 index / strided offset arrays
//           (the same skeleton csv_cells_to_block builds host-side).
//   libfm:  label field:idx:val triples; heuristic needs BOTH mins > 0.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#endif
#if defined(__aarch64__)
#include <arm_neon.h>
#endif

#include "api.h"
#include "strtonum.h"

namespace dmlc_tpu {
namespace batch {

// ---------------- zlib-compatible crc32 (slice-by-8) ----------------
//
// The block cache's per-block integrity word is Python zlib.crc32
// (IEEE 802.3 polynomial, init/xorout 0xFFFFFFFF). Computing it here —
// while the merged span is still in cache — removes the Python-side crc
// pass from the cold path; tests pin equality against zlib.crc32.

static uint32_t g_crc_tab[8][256];
static std::atomic<bool> g_crc_ready{false};

static void crc32_init() {
  if (g_crc_ready.load(std::memory_order_acquire)) return;
  static std::atomic<bool> building{false};
  bool expected = false;
  if (building.compare_exchange_strong(expected, true)) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      g_crc_tab[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = g_crc_tab[0][i];
      for (int t = 1; t < 8; ++t) {
        c = g_crc_tab[0][c & 0xFF] ^ (c >> 8);
        g_crc_tab[t][i] = c;
      }
    }
    g_crc_ready.store(true, std::memory_order_release);
  } else {
    while (!g_crc_ready.load(std::memory_order_acquire)) {
      std::this_thread::yield();
    }
  }
}

uint32_t crc32_span(const void* data, size_t len) {
  crc32_init();
  const unsigned char* p = static_cast<const unsigned char*>(data);
  uint32_t c = 0xFFFFFFFFu;
  while (len >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= c;
    c = g_crc_tab[7][lo & 0xFF] ^ g_crc_tab[6][(lo >> 8) & 0xFF] ^
        g_crc_tab[5][(lo >> 16) & 0xFF] ^ g_crc_tab[4][lo >> 24] ^
        g_crc_tab[3][hi & 0xFF] ^ g_crc_tab[2][(hi >> 8) & 0xFF] ^
        g_crc_tab[1][(hi >> 16) & 0xFF] ^ g_crc_tab[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) c = g_crc_tab[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------- SIMD chunk scan ----------------
//
// One pass over the chunk produces the EOL position index (both '\n'
// and '\r', so CRLF / CR-only corpora and unterminated final records
// all reduce to the same span arithmetic) and the delimiter count for
// exact output reservation. ISA picked once at runtime: AVX2 when the
// host has it, SSE2 on any x86-64, NEON on aarch64, scalar elsewhere.

struct ChunkScan {
  std::vector<int64_t> eols;  // ascending offsets of every EOL byte
  int64_t delims = 0;         // ':' (sparse formats) or the csv delimiter
};

static inline void scan_tail_scalar(const char* data, int64_t begin,
                                    int64_t end, char delim, ChunkScan* out) {
  for (int64_t i = begin; i < end; ++i) {
    const char c = data[i];
    if (c == '\n' || c == '\r') {
      out->eols.push_back(i);
    } else if (c == delim) {
      ++out->delims;
    }
  }
}

#if defined(__x86_64__) || defined(__i386__)

__attribute__((target("avx2"))) static void scan_avx2(const char* data,
                                                      int64_t len, char delim,
                                                      ChunkScan* out) {
  const __m256i vnl = _mm256_set1_epi8('\n');
  const __m256i vcr = _mm256_set1_epi8('\r');
  const __m256i vdl = _mm256_set1_epi8(delim);
  int64_t i = 0;
  int64_t delims = 0;
  for (; i + 32 <= len; i += 32) {
    const __m256i v =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(data + i));
    uint32_t eol = static_cast<uint32_t>(_mm256_movemask_epi8(
        _mm256_or_si256(_mm256_cmpeq_epi8(v, vnl), _mm256_cmpeq_epi8(v, vcr))));
    delims += __builtin_popcount(
        static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, vdl))));
    while (eol) {
      out->eols.push_back(i + __builtin_ctz(eol));
      eol &= eol - 1;
    }
  }
  out->delims += delims;
  scan_tail_scalar(data, i, len, delim, out);
}

static void scan_sse2(const char* data, int64_t len, char delim,
                      ChunkScan* out) {
  const __m128i vnl = _mm_set1_epi8('\n');
  const __m128i vcr = _mm_set1_epi8('\r');
  const __m128i vdl = _mm_set1_epi8(delim);
  int64_t i = 0;
  int64_t delims = 0;
  for (; i + 16 <= len; i += 16) {
    const __m128i v =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + i));
    uint32_t eol = static_cast<uint32_t>(_mm_movemask_epi8(
        _mm_or_si128(_mm_cmpeq_epi8(v, vnl), _mm_cmpeq_epi8(v, vcr))));
    delims += __builtin_popcount(
        static_cast<uint32_t>(_mm_movemask_epi8(_mm_cmpeq_epi8(v, vdl))));
    while (eol) {
      out->eols.push_back(i + __builtin_ctz(eol));
      eol &= eol - 1;
    }
  }
  out->delims += delims;
  scan_tail_scalar(data, i, len, delim, out);
}
#endif  // x86

#if defined(__aarch64__)

static void scan_neon(const char* data, int64_t len, char delim,
                      ChunkScan* out) {
  const uint8x16_t vnl = vdupq_n_u8('\n');
  const uint8x16_t vcr = vdupq_n_u8('\r');
  const uint8x16_t vdl = vdupq_n_u8(delim);
  int64_t i = 0;
  int64_t delims = 0;
  for (; i + 16 <= len; i += 16) {
    const uint8x16_t v = vld1q_u8(reinterpret_cast<const uint8_t*>(data + i));
    const uint8x16_t eolv = vorrq_u8(vceqq_u8(v, vnl), vceqq_u8(v, vcr));
    const uint8x16_t dlv = vceqq_u8(v, vdl);
    // 0xFF lanes -> 1s, horizontal add = matches in this block
    delims += vaddvq_u8(vshrq_n_u8(dlv, 7));
    // nibble-compress the match mask to one u64: 4 bits per byte lane
    uint64_t mask = vget_lane_u64(
        vreinterpret_u64_u8(vshrn_n_u16(vreinterpretq_u16_u8(eolv), 4)), 0);
    while (mask) {
      out->eols.push_back(i + (__builtin_ctzll(mask) >> 2));
      mask &= mask - 1;  // clears one bit of the low set nibble
      mask &= mask - 1;
      mask &= mask - 1;
      mask &= mask - 1;
    }
  }
  out->delims += delims;
  scan_tail_scalar(data, i, len, delim, out);
}
#endif  // aarch64

static int detect_simd_level() {
#if defined(__x86_64__) || defined(__i386__)
  return __builtin_cpu_supports("avx2") ? 2 : 1;
#elif defined(__aarch64__)
  return 3;
#else
  return 0;
#endif
}

static int simd_level() {
  static const int level = detect_simd_level();
  return level;
}

static void scan_chunk(const char* data, int64_t len, char delim,
                       ChunkScan* out) {
  // EOLs are ~1/30 of bytes in ML text corpora: reserve on that ratio so
  // the push_back loop never reallocs more than once
  out->eols.reserve(static_cast<size_t>(len / 24) + 8);
#if defined(__x86_64__) || defined(__i386__)
  if (simd_level() >= 2) {
    scan_avx2(data, len, delim, out);
  } else {
    scan_sse2(data, len, delim, out);
  }
#elif defined(__aarch64__)
  scan_neon(data, len, delim, out);
#else
  scan_tail_scalar(data, 0, len, delim, out);
#endif
}

// Non-empty line spans from the EOL index: a CRLF pair yields an empty
// span between '\r' and '\n' (dropped), the unterminated final record —
// bytes past the last EOL — becomes the last span. (first, last) are
// byte offsets into the chunk.
struct LineSpan {
  int32_t begin;
  int32_t end;
};

static void build_spans(int64_t len, const std::vector<int64_t>& eols,
                        std::vector<LineSpan>* spans) {
  spans->reserve(eols.size() + 1);
  int64_t cur = 0;
  for (int64_t e : eols) {
    if (e > cur) {
      spans->push_back({static_cast<int32_t>(cur), static_cast<int32_t>(e)});
    }
    cur = e + 1;
  }
  if (len > cur) {
    spans->push_back({static_cast<int32_t>(cur), static_cast<int32_t>(len)});
  }
}

// ---------------- per-thread sparse parts ----------------

struct Part {
  std::vector<int64_t> row_nnz;
  std::vector<float> label;
  std::vector<float> weight;   // empty or per-row
  std::vector<int64_t> qid;    // empty or per-row (libsvm only)
  std::vector<uint64_t> index;
  std::vector<uint64_t> field;  // libfm only
  std::vector<float> value;    // empty (all-binary) or per-entry
  std::vector<float> cells;    // csv only: row-major uniform cells
  int64_t ncol = -1;           // csv only
  uint64_t min_index = UINT64_MAX;
  uint64_t min_field = UINT64_MAX;
  std::string error;
};

// One libsvm line — the exact token semantics of parse.cc's
// parse_libsvm_range body (comment strip, label[:weight], qid:N,
// idx[:val] with lazy binary->valued promotion, loud trailing garbage).
static inline bool parse_libsvm_line(const char* q, const char* lend,
                                     Part* out) {
  const char* hash = static_cast<const char*>(memchr(q, '#', lend - q));
  const char* effective_end = hash ? hash : lend;
  double label;
  const char* after;
  if (!parse_value(q, effective_end, &after, &label)) {
    return true;  // blank / comment-only / unparsable-label line: skipped
  }
  q = after;
  bool has_weight = false;
  double weight = 1.0;
  if (q != effective_end && *q == ':') {
    ++q;
    if (!parse_value(q, effective_end, &after, &weight)) {
      out->error = "libsvm: bad label:weight";
      return false;
    }
    q = after;
    has_weight = true;
  }
  out->label.push_back(static_cast<float>(label));
  if (has_weight) {
    if (out->weight.size() != out->label.size() - 1) {
      out->error = "libsvm: label:weight must be set on every row or none";
      return false;
    }
    out->weight.push_back(static_cast<float>(weight));
  } else if (!out->weight.empty()) {
    out->error = "libsvm: label:weight must be set on every row or none";
    return false;
  }
  while (q != effective_end && is_space(*q)) ++q;
  if (effective_end - q >= 4 && memcmp(q, "qid:", 4) == 0) {
    uint64_t qid;
    if (!parse_uint(q + 4, effective_end, &after, &qid)) {
      out->error = "libsvm: bad qid";
      return false;
    }
    if (out->qid.size() != out->label.size() - 1) {
      out->error = "libsvm: qid must appear on every row or none";
      return false;
    }
    out->qid.push_back(static_cast<int64_t>(qid));
    q = after;
  } else if (!out->qid.empty()) {
    out->error = "libsvm: qid must appear on every row or none";
    return false;
  }
  int64_t nnz = 0;
  while (true) {
    uint64_t idx;
    if (!parse_uint(q, effective_end, &after, &idx)) break;
    q = after;
    out->index.push_back(idx);
    if (idx < out->min_index) out->min_index = idx;
    ++nnz;
    if (q != effective_end && *q == ':') {
      double v;
      ++q;
      if (!parse_value(q, effective_end, &after, &v)) {
        out->error = "libsvm: bad idx:value";
        return false;
      }
      q = after;
      if (out->value.size() + 1 < out->index.size()) {
        out->value.resize(out->index.size() - 1, 1.0f);
      }
      out->value.push_back(static_cast<float>(v));
    } else if (!out->value.empty()) {
      out->value.push_back(1.0f);
    }
  }
  while (q != effective_end && is_space(*q)) ++q;
  if (q != effective_end) {
    out->error = "libsvm: malformed feature token";
    return false;
  }
  out->row_nnz.push_back(nnz);
  return true;
}

static inline bool parse_libfm_line(const char* q, const char* lend,
                                    Part* out) {
  const char* hash = static_cast<const char*>(memchr(q, '#', lend - q));
  const char* effective_end = hash ? hash : lend;
  double label;
  const char* after;
  if (!parse_value(q, effective_end, &after, &label)) return true;
  q = after;
  out->label.push_back(static_cast<float>(label));
  int64_t nnz = 0;
  while (true) {
    uint64_t fld;
    uint64_t idx;
    double v;
    if (!parse_uint(q, effective_end, &after, &fld)) break;
    q = after;
    if (q == effective_end || *q != ':' ||
        !parse_uint(q + 1, effective_end, &after, &idx)) {
      out->error = "libfm: features must be field:index:value triples";
      return false;
    }
    q = after;
    if (q == effective_end || *q != ':' ||
        !parse_value(q + 1, effective_end, &after, &v)) {
      out->error = "libfm: features must be field:index:value triples";
      return false;
    }
    q = after;
    out->field.push_back(fld);
    out->index.push_back(idx);
    out->value.push_back(static_cast<float>(v));
    if (idx < out->min_index) out->min_index = idx;
    if (fld < out->min_field) out->min_field = fld;
    ++nnz;
  }
  while (q != effective_end && is_space(*q)) ++q;
  if (q != effective_end) {
    out->error = "libfm: malformed feature token";
    return false;
  }
  out->row_nnz.push_back(nnz);
  return true;
}

static inline bool parse_csv_line(const char* q, const char* lend, char delim,
                                  Part* out) {
  int64_t cols = 0;
  while (true) {
    while (q != lend && is_space(*q) && *q != delim) ++q;
    double v = 0.0;
    const char* after;
    if (q == lend || *q == delim) {
      out->error = "csv: empty cell in row";
      return false;
    }
    if (!parse_value(q, lend, &after, &v)) {
      out->error = "csv: unparseable cell in row";
      return false;
    }
    q = after;
    out->cells.push_back(static_cast<float>(v));
    ++cols;
    while (q != lend && is_space(*q) && *q != delim) ++q;
    if (q == lend) break;
    if (*q == delim) {
      ++q;
      continue;
    }
    out->error = "csv: unexpected character in row";
    return false;
  }
  if (out->ncol < 0) {
    out->ncol = cols;
  } else if (cols != out->ncol) {
    out->error = "csv: ragged rows in chunk";
    return false;
  }
  out->row_nnz.push_back(cols);
  return true;
}

static void parse_span_range(const char* data, const LineSpan* spans,
                             size_t nspans, int fmt, char delim,
                             size_t reserve_rows, size_t reserve_entries,
                             Part* out) {
  try {
    out->row_nnz.reserve(reserve_rows);
    out->label.reserve(reserve_rows);
    if (fmt == 2) {
      out->cells.reserve(reserve_entries);
    } else {
      out->index.reserve(reserve_entries);
      out->value.reserve(reserve_entries);
      if (fmt == 3) out->field.reserve(reserve_entries);
    }
    for (size_t i = 0; i < nspans; ++i) {
      const char* q = data + spans[i].begin;
      const char* lend = data + spans[i].end;
      bool ok;
      if (fmt == 3) {
        ok = parse_libfm_line(q, lend, out);
      } else if (fmt == 2) {
        ok = parse_csv_line(q, lend, delim, out);
      } else {
        ok = parse_libsvm_line(q, lend, out);
      }
      if (!ok) return;
    }
    // lazy valued-promotion backfill at range end (parse.cc parity)
    if (!out->value.empty() && out->value.size() != out->index.size()) {
      out->value.resize(out->index.size(), 1.0f);
    }
  } catch (const std::exception& ex) {
    out->error = std::string("parse failed: ") + ex.what();
  } catch (...) {
    out->error = "parse failed: unknown error";
  }
}

// ---------------- segment-span assembly ----------------

static const int64_t kAlign = 64;  // io/block_cache.py _ALIGN

static inline int64_t align_up(int64_t v) {
  return (v + kAlign - 1) / kAlign * kAlign;
}

static char* dup_err(const std::string& s) {
  char* e = static_cast<char*>(malloc(s.size() + 1));
  if (e) memcpy(e, s.c_str(), s.size() + 1);
  return e;
}

static SegmentBlockResult* seg_error(SegmentBlockResult* res,
                                     const std::string& msg) {
  free(res->buf);
  res->buf = nullptr;
  res->buf_len = 0;
  res->error = dup_err(msg);
  return res;
}

// Lay out the present segments exactly as io/block_cache.write_segments
// does at an aligned block start: pad-to-64 before every present array
// (even a zero-length one — the Python writer records those too), raw
// bytes, no trailing pad. Returns false on OOM.
static bool layout_segments(SegmentBlockResult* res, const int64_t* sizes,
                            const bool* present) {
  int64_t pos = 0;
  for (int s = 0; s < DMLC_SEG_COUNT; ++s) {
    if (!present[s]) {
      res->seg_off[s] = -1;
      res->seg_len[s] = 0;
      continue;
    }
    pos = align_up(pos);
    res->seg_off[s] = pos;
    res->seg_len[s] = sizes[s];
    pos += sizes[s];
  }
  res->buf_len = pos;
  res->buf = static_cast<char*>(malloc(pos > 0 ? pos : 1));
  if (!res->buf) return false;
  // zero the alignment gaps (they are crc'd and written to disk verbatim)
  int64_t end = 0;
  for (int s = 0; s < DMLC_SEG_COUNT; ++s) {
    if (res->seg_off[s] < 0) continue;
    if (res->seg_off[s] > end) {
      memset(res->buf + end, 0, res->seg_off[s] - end);
    }
    end = res->seg_off[s] + res->seg_len[s];
  }
  return true;
}

static SegmentBlockResult* merge_sparse(std::vector<Part>& parts, int fmt,
                                        int indexing_mode,
                                        SegmentBlockResult* res) {
  const bool libfm = fmt == 3;
  for (auto& part : parts) {
    if (!part.error.empty()) return seg_error(res, part.error);
  }
  int64_t n = 0;
  int64_t nnz = 0;
  bool any_weight = false;
  bool any_qid = false;
  bool any_value = false;
  uint64_t min_index = UINT64_MAX;
  uint64_t min_field = UINT64_MAX;
  for (auto& part : parts) {
    n += static_cast<int64_t>(part.label.size());
    nnz += static_cast<int64_t>(part.index.size());
    any_weight |= !part.weight.empty();
    any_qid |= !part.qid.empty();
    any_value |= !part.value.empty();
    if (part.min_index < min_index) min_index = part.min_index;
    if (part.min_field < min_field) min_field = part.min_field;
  }
  const char* fmtname = libfm ? "libfm" : "libsvm";
  for (auto& part : parts) {
    if (!part.label.empty()) {
      if (any_weight && part.weight.size() != part.label.size()) {
        return seg_error(res, std::string(fmtname) +
                                  ": label:weight must be set on every row "
                                  "or none");
      }
      if (any_qid && part.qid.size() != part.label.size()) {
        return seg_error(res, std::string(fmtname) +
                                  ": qid must appear on every row or none");
      }
    }
    if (any_value && !part.index.empty() && part.value.empty()) {
      part.value.resize(part.index.size(), 1.0f);
    }
  }
  res->n_rows = n;
  res->nnz = nnz;
  if (n == 0) return res;  // empty chunk: no segments, caller drops it
  int64_t sizes[DMLC_SEG_COUNT] = {0};
  bool present[DMLC_SEG_COUNT] = {false};
  sizes[DMLC_SEG_OFFSET] = (n + 1) * 8;
  present[DMLC_SEG_OFFSET] = true;
  sizes[DMLC_SEG_LABEL] = n * 4;
  present[DMLC_SEG_LABEL] = true;
  sizes[DMLC_SEG_WEIGHT] = n * 4;
  present[DMLC_SEG_WEIGHT] = any_weight;
  sizes[DMLC_SEG_QID] = n * 8;
  present[DMLC_SEG_QID] = any_qid;
  sizes[DMLC_SEG_FIELD] = nnz * 8;
  // libfm blocks always carry a field array (possibly empty), matching
  // the Python engine's field=np.empty(0) emit for feature-less chunks
  present[DMLC_SEG_FIELD] = libfm;
  sizes[DMLC_SEG_INDEX] = nnz * 8;
  present[DMLC_SEG_INDEX] = true;  // possibly zero-length, still recorded
  sizes[DMLC_SEG_VALUE] = nnz * 4;
  present[DMLC_SEG_VALUE] = any_value;
  if (!layout_segments(res, sizes, present)) {
    return seg_error(res, "parse: out of memory merging batch chunk");
  }
  // indexing-mode conversion (libsvm_parser.h:159-168 / libfm heuristic
  // needs both mins, libfm_parser.h:130-143), applied during the copy
  bool convert = indexing_mode > 0;
  if (indexing_mode < 0 && nnz > 0 && min_index > 0) {
    convert = !libfm || min_field > 0;
  }
  const uint64_t off = convert ? 1 : 0;
  int64_t* offset = reinterpret_cast<int64_t*>(res->buf +
                                               res->seg_off[DMLC_SEG_OFFSET]);
  float* label =
      reinterpret_cast<float*>(res->buf + res->seg_off[DMLC_SEG_LABEL]);
  float* weight =
      any_weight
          ? reinterpret_cast<float*>(res->buf + res->seg_off[DMLC_SEG_WEIGHT])
          : nullptr;
  int64_t* qid =
      any_qid
          ? reinterpret_cast<int64_t*>(res->buf + res->seg_off[DMLC_SEG_QID])
          : nullptr;
  uint64_t* field =
      libfm
          ? reinterpret_cast<uint64_t*>(res->buf + res->seg_off[DMLC_SEG_FIELD])
          : nullptr;
  uint64_t* index =
      reinterpret_cast<uint64_t*>(res->buf + res->seg_off[DMLC_SEG_INDEX]);
  float* value =
      any_value
          ? reinterpret_cast<float*>(res->buf + res->seg_off[DMLC_SEG_VALUE])
          : nullptr;
  int64_t row = 0;
  int64_t ent = 0;
  uint64_t max_index = 0;
  offset[0] = 0;
  for (auto& part : parts) {
    const size_t pn = part.label.size();
    if (pn) {
      memcpy(label + row, part.label.data(), pn * sizeof(float));
      if (weight) memcpy(weight + row, part.weight.data(), pn * sizeof(float));
      if (qid) memcpy(qid + row, part.qid.data(), pn * sizeof(int64_t));
      for (size_t i = 0; i < pn; ++i) {
        offset[row + 1 + static_cast<int64_t>(i)] =
            offset[row + static_cast<int64_t>(i)] + part.row_nnz[i];
      }
      row += static_cast<int64_t>(pn);
    }
    const size_t pe = part.index.size();
    if (pe) {
      for (size_t i = 0; i < pe; ++i) {
        const uint64_t c = part.index[i] - off;
        index[ent + static_cast<int64_t>(i)] = c;
        if (c > max_index) max_index = c;
      }
      if (field) {
        for (size_t i = 0; i < pe; ++i) {
          field[ent + static_cast<int64_t>(i)] = part.field[i] - off;
        }
      }
      if (value) {
        // the pre-merge backfill resized every entry-bearing part's
        // value to 1.0f defaults, so the copy is unconditional
        memcpy(value + ent, part.value.data(), pe * sizeof(float));
      }
      ent += static_cast<int64_t>(pe);
    }
  }
  res->num_col = nnz > 0 ? static_cast<int64_t>(max_index) + 1 : 0;
  return res;
}

static SegmentBlockResult* merge_csv(std::vector<Part>& parts,
                                     int32_t label_col, int32_t weight_col,
                                     SegmentBlockResult* res) {
  for (auto& part : parts) {
    if (!part.error.empty()) return seg_error(res, part.error);
  }
  int64_t ncol = -1;
  int64_t n = 0;
  for (auto& part : parts) {
    if (part.row_nnz.empty()) continue;
    if (ncol < 0) ncol = part.ncol;
    if (part.ncol != ncol) return seg_error(res, "csv: ragged rows in chunk");
    n += static_cast<int64_t>(part.row_nnz.size());
  }
  res->n_rows = n;
  if (n == 0) return res;
  if (label_col >= ncol || weight_col >= ncol) {
    return seg_error(res, "csv: label/weight column out of range");
  }
  if (label_col >= 0 && label_col == weight_col) {
    return seg_error(res, "csv: label_column must differ from weight_column");
  }
  const int64_t lc = label_col;
  const int64_t wc = weight_col;
  const int64_t k = ncol - (lc >= 0 ? 1 : 0) - (wc >= 0 ? 1 : 0);
  res->nnz = n * k;
  int64_t sizes[DMLC_SEG_COUNT] = {0};
  bool present[DMLC_SEG_COUNT] = {false};
  sizes[DMLC_SEG_OFFSET] = (n + 1) * 8;
  present[DMLC_SEG_OFFSET] = true;
  sizes[DMLC_SEG_LABEL] = n * 4;  // zeros when label_col < 0 (engine parity)
  present[DMLC_SEG_LABEL] = true;
  sizes[DMLC_SEG_WEIGHT] = n * 4;
  present[DMLC_SEG_WEIGHT] = wc >= 0;
  sizes[DMLC_SEG_INDEX] = n * k * 8;
  present[DMLC_SEG_INDEX] = true;
  sizes[DMLC_SEG_VALUE] = n * k * 4;
  present[DMLC_SEG_VALUE] = true;
  if (!layout_segments(res, sizes, present)) {
    return seg_error(res, "parse: out of memory merging batch chunk");
  }
  int64_t* offset = reinterpret_cast<int64_t*>(res->buf +
                                               res->seg_off[DMLC_SEG_OFFSET]);
  float* label =
      reinterpret_cast<float*>(res->buf + res->seg_off[DMLC_SEG_LABEL]);
  float* weight =
      wc >= 0
          ? reinterpret_cast<float*>(res->buf + res->seg_off[DMLC_SEG_WEIGHT])
          : nullptr;
  uint64_t* index =
      reinterpret_cast<uint64_t*>(res->buf + res->seg_off[DMLC_SEG_INDEX]);
  float* value =
      reinterpret_cast<float*>(res->buf + res->seg_off[DMLC_SEG_VALUE]);
  // synthetic skeleton: offset strided by k, index tiled 0..k-1 — the
  // exact arrays csv_cells_to_block builds host-side
  for (int64_t i = 0; i <= n; ++i) offset[i] = i * k;
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t j = 0; j < k; ++j) index[i * k + j] = j;
  }
  // feature columns form <= 3 contiguous runs around label/weight
  int64_t runs[3][2];
  int nruns = 0;
  int64_t at = 0;
  while (at < ncol) {
    if (at == lc || at == wc) {
      ++at;
      continue;
    }
    int64_t hi = at;
    while (hi < ncol && hi != lc && hi != wc) ++hi;
    runs[nruns][0] = at;
    runs[nruns][1] = hi - at;
    ++nruns;
    at = hi;
  }
  int64_t row = 0;
  for (auto& part : parts) {
    const float* cells = part.cells.data();
    const int64_t pn = static_cast<int64_t>(part.row_nnz.size());
    for (int64_t i = 0; i < pn; ++i, ++row) {
      const float* src = cells + i * ncol;
      float* dst = value + row * k;
      for (int r = 0; r < nruns; ++r) {
        memcpy(dst, src + runs[r][0],
               static_cast<size_t>(runs[r][1]) * sizeof(float));
        dst += runs[r][1];
      }
      label[row] = lc >= 0 ? src[lc] : 0.0f;
      if (weight) weight[row] = src[wc];
    }
  }
  res->num_col = k > 0 ? k : 0;
  return res;
}

}  // namespace batch
}  // namespace dmlc_tpu

// ---------------- C ABI ----------------

using namespace dmlc_tpu;
using namespace dmlc_tpu::batch;

extern "C" {

int dmlc_simd_level() { return simd_level(); }

uint32_t dmlc_crc32(const void* data, int64_t len) {
  return crc32_span(data, static_cast<size_t>(len));
}

SegmentBlockResult* dmlc_parse_batch(const char* data, int64_t len,
                                     int nthread, int fmt, int indexing_mode,
                                     char delim, int32_t label_col,
                                     int32_t weight_col) {
  auto* res =
      static_cast<SegmentBlockResult*>(calloc(1, sizeof(SegmentBlockResult)));
  if (!res) return nullptr;
  for (int s = 0; s < DMLC_SEG_COUNT; ++s) res->seg_off[s] = -1;
  res->simd_level = simd_level();
  if (len < 0 || (len > 0 && !data)) {
    return seg_error(res, "batch parse: bad buffer");
  }
  if (len > INT32_MAX) {
    // line spans are int32-packed; chunk sizes are MBs in practice
    return seg_error(res, "batch parse: chunk exceeds 2 GB");
  }
  const char* end = data + len;
  if (end - data >= 3 && memcmp(data, "\xef\xbb\xbf", 3) == 0) data += 3;
  len = end - data;
  try {
    ChunkScan scan;
    const char scan_delim = fmt == 2 ? delim : ':';
    scan_chunk(data, len, scan_delim, &scan);
    std::vector<LineSpan> spans;
    build_spans(len, scan.eols, &spans);
    if (nthread < 1) nthread = 1;
    // small chunks don't repay thread spawns (parse.cc clamp)
    const int by_size = static_cast<int>(len / (512 * 1024)) + 1;
    if (nthread > by_size) nthread = by_size;
    if (nthread > static_cast<int>(spans.size()) && !spans.empty()) {
      nthread = static_cast<int>(spans.size());
    }
    if (spans.empty()) nthread = 1;
    std::vector<Part> parts(static_cast<size_t>(nthread));
    const size_t per = spans.size() / static_cast<size_t>(nthread);
    const size_t extra = spans.size() % static_cast<size_t>(nthread);
    // reservation hints from the SIMD scan: rows from the span split,
    // entries from the chunk-global delimiter count, proportionally
    const size_t entries_hint =
        static_cast<size_t>(scan.delims) / static_cast<size_t>(nthread) + 16;
    std::vector<std::thread> threads;
    size_t at = 0;
    try {
      for (int t = 0; t < nthread; ++t) {
        const size_t cnt = per + (static_cast<size_t>(t) < extra ? 1 : 0);
        const LineSpan* base = spans.data() + at;
        Part* out = &parts[static_cast<size_t>(t)];
        at += cnt;
        if (t == nthread - 1) {
          parse_span_range(data, base, cnt, fmt, delim, cnt + 1, entries_hint,
                           out);
        } else {
          threads.emplace_back(parse_span_range, data, base, cnt, fmt, delim,
                               cnt + 1, entries_hint, out);
        }
      }
    } catch (...) {
      // a std::thread ctor can throw (EAGAIN under a pids cgroup limit)
      // after earlier workers spawned: join them before unwinding, or
      // ~thread() on a joinable element calls std::terminate and the
      // whole process aborts instead of surfacing res->error
      for (auto& t : threads) t.join();
      throw;
    }
    for (auto& t : threads) t.join();
    if (fmt == 2) {
      merge_csv(parts, label_col, weight_col, res);
    } else {
      merge_sparse(parts, fmt, indexing_mode, res);
    }
    if (!res->error && res->buf) {
      res->crc32 = crc32_span(res->buf, static_cast<size_t>(res->buf_len));
    }
    return res;
  } catch (const std::exception& ex) {
    return seg_error(res, std::string("batch parse failed: ") + ex.what());
  } catch (...) {
    return seg_error(res, "batch parse failed: unknown error");
  }
}

void dmlc_free_segblock(SegmentBlockResult* r) {
  if (!r) return;
  free(r->buf);
  free(r->error);
  free(r);
}

}  // extern "C"
