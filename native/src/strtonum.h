// Fast branch-light number parsing for the parser hot loops.
//
// TPU-native rebuild of the role of reference include/dmlc/strtonum.h
// (strtof/strtod/ParsePair/ParseTriple, strtonum.h:99-304): written from
// scratch — parse sign/digits/fraction/exponent with integer accumulation
// and a power table, falling back to libc strtod for long mantissas where
// float error could accumulate.
#ifndef DMLC_TPU_NATIVE_STRTONUM_H_
#define DMLC_TPU_NATIVE_STRTONUM_H_

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace dmlc_tpu {

inline bool is_space(char c) { return c == ' ' || c == '\t'; }
inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// 10^k for k in [0, 22] exactly representable in double
static const double kPow10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10,
    1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// 10^-k as nearest double: one fp multiply instead of a ~25-cycle divide in
// the fraction hot path. The <=2-ulp double error vanishes in the cast to
// float everywhere these values land (RowBlock/dense x are float32).
static const double kPow10Inv[] = {
    1e-0,  1e-1,  1e-2,  1e-3,  1e-4,  1e-5,  1e-6,  1e-7,
    1e-8,  1e-9,  1e-10, 1e-11, 1e-12, 1e-13, 1e-14, 1e-15,
    1e-16, 1e-17, 1e-18, 1e-19, 1e-20, 1e-21, 1e-22};

// Parse a double from [p, end); advances *out to one past the number.
// Returns false if no number present.
inline bool parse_double(const char* p, const char* end, const char** out,
                         double* value) {
  while (p != end && is_space(*p)) ++p;
  if (p == end) return false;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  const char* digits_begin = p;
  uint64_t mant = 0;
  int ndig = 0;
  while (p != end && is_digit(*p)) {
    if (ndig < 19) { mant = mant * 10 + (*p - '0'); ++ndig; }
    ++p;
  }
  int int_digits_dropped = static_cast<int>(p - digits_begin) - ndig;
  int frac = 0;
  if (p != end && *p == '.') {
    ++p;
    while (p != end && is_digit(*p)) {
      if (ndig < 19) { mant = mant * 10 + (*p - '0'); ++ndig; ++frac; }
      ++p;
    }
  }
  if (p == digits_begin || (frac == 0 && p == digits_begin + 1 && *digits_begin == '.')) {
    // no digits at all (handles inf/nan via fallback below)
    char* e = nullptr;
    double v = strtod(digits_begin - (neg ? 1 : 0), &e);
    if (e == digits_begin - (neg ? 1 : 0)) return false;
    *value = v;
    *out = e;
    return true;
  }
  int exp10 = int_digits_dropped - frac;
  if (p != end && (*p == 'e' || *p == 'E')) {
    const char* before_exp = p;
    ++p;
    bool eneg = false;
    if (p != end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ev = 0;
    int edig = 0;
    while (p != end && is_digit(*p)) { ev = ev * 10 + (*p - '0'); ++p; ++edig; }
    if (edig == 0) {
      // '3e' / '2e+': the marker is not part of the number — leave it for the
      // caller's trailing-garbage check (parity with the fallback engine)
      p = before_exp;
    } else {
      exp10 += eneg ? -ev : ev;
    }
  }
  double v;
  if (exp10 >= 0 && exp10 <= 22) {
    v = static_cast<double>(mant) * kPow10[exp10];
  } else if (exp10 < 0 && exp10 >= -22) {
    v = static_cast<double>(mant) * kPow10Inv[-exp10];
  } else {
    // rare: huge/tiny exponent — libc handles subnormals correctly
    char buf[64];
    size_t n = static_cast<size_t>(p - (digits_begin - (neg ? 1 : 0)));
    if (n >= sizeof(buf)) n = sizeof(buf) - 1;
    memcpy(buf, digits_begin - (neg ? 1 : 0), n);
    buf[n] = '\0';
    v = strtod(buf, nullptr);
    *value = v;
    *out = p;
    return true;
  }
  *value = neg ? -v : v;
  *out = p;
  return true;
}

// Lean fast path for the label/value hot loops: [sign] digits [. digits]
// with no exponent and <=19 total digits — one pass, no per-digit cap
// checks, fraction scaled by one multiply. Anything else (leading space,
// exponent, inf/nan, huge mantissa) falls back to parse_double, so the
// accepted grammar is identical.
inline bool parse_value(const char* p, const char* end, const char** out,
                        double* value) {
  const char* p0 = p;
  if (p == end || is_space(*p)) return parse_double(p0, end, out, value);
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  uint64_t mant = 0;
  const char* d0 = p;
  while (p != end && is_digit(*p))
    mant = mant * 10 + static_cast<uint64_t>(*p++ - '0');
  long idig = p - d0;
  long frac = 0;
  if (p != end && *p == '.') {
    ++p;
    const char* f0 = p;
    while (p != end && is_digit(*p))
      mant = mant * 10 + static_cast<uint64_t>(*p++ - '0');
    frac = p - f0;
  }
  if (idig + frac == 0 || idig + frac > 19 ||
      (p != end && (*p == 'e' || *p == 'E'))) {
    return parse_double(p0, end, out, value);
  }
  double v = static_cast<double>(mant) * kPow10Inv[frac];
  *value = neg ? -v : v;
  *out = p;
  return true;
}

// Parse an unsigned integer; returns false if no digits.
inline bool parse_uint(const char* p, const char* end, const char** out,
                       uint64_t* value) {
  while (p != end && is_space(*p)) ++p;
  if (p == end || !is_digit(*p)) return false;
  uint64_t v = 0;
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *value = v;
  *out = p;
  return true;
}

}  // namespace dmlc_tpu
#endif  // DMLC_TPU_NATIVE_STRTONUM_H_
