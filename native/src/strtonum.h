// Fast branch-light number parsing for the parser hot loops.
//
// TPU-native rebuild of the role of reference include/dmlc/strtonum.h
// (strtof/strtod/ParsePair/ParseTriple, strtonum.h:99-304): written from
// scratch — parse sign/digits/fraction/exponent with integer accumulation
// and a power table, falling back to libc strtod for long mantissas where
// float error could accumulate.
#ifndef DMLC_TPU_NATIVE_STRTONUM_H_
#define DMLC_TPU_NATIVE_STRTONUM_H_

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace dmlc_tpu {

inline bool is_space(char c) { return c == ' ' || c == '\t'; }
inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// 10^k for k in [0, 22] exactly representable in double
static const double kPow10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10,
    1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// 10^-k as nearest double: one fp multiply instead of a ~25-cycle divide in
// the fraction hot path. The <=2-ulp double error vanishes in the cast to
// float everywhere these values land (RowBlock/dense x are float32).
static const double kPow10Inv[] = {
    1e-0,  1e-1,  1e-2,  1e-3,  1e-4,  1e-5,  1e-6,  1e-7,
    1e-8,  1e-9,  1e-10, 1e-11, 1e-12, 1e-13, 1e-14, 1e-15,
    1e-16, 1e-17, 1e-18, 1e-19, 1e-20, 1e-21, 1e-22};

// Parse a double from [p, end); advances *out to one past the number.
// Returns false if no number present.
inline bool parse_double(const char* p, const char* end, const char** out,
                         double* value) {
  while (p != end && is_space(*p)) ++p;
  if (p == end) return false;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  const char* digits_begin = p;
  uint64_t mant = 0;
  int ndig = 0;
  while (p != end && is_digit(*p)) {
    if (ndig < 19) { mant = mant * 10 + (*p - '0'); ++ndig; }
    ++p;
  }
  int int_digits_dropped = static_cast<int>(p - digits_begin) - ndig;
  int frac = 0;
  if (p != end && *p == '.') {
    ++p;
    while (p != end && is_digit(*p)) {
      if (ndig < 19) { mant = mant * 10 + (*p - '0'); ++ndig; ++frac; }
      ++p;
    }
  }
  if (p == digits_begin || (frac == 0 && p == digits_begin + 1 && *digits_begin == '.')) {
    // no digits at all (handles inf/nan via fallback below)
    char* e = nullptr;
    double v = strtod(digits_begin - (neg ? 1 : 0), &e);
    if (e == digits_begin - (neg ? 1 : 0)) return false;
    *value = v;
    *out = e;
    return true;
  }
  int exp10 = int_digits_dropped - frac;
  if (p != end && (*p == 'e' || *p == 'E')) {
    const char* before_exp = p;
    ++p;
    bool eneg = false;
    if (p != end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ev = 0;
    int edig = 0;
    while (p != end && is_digit(*p)) { ev = ev * 10 + (*p - '0'); ++p; ++edig; }
    if (edig == 0) {
      // '3e' / '2e+': the marker is not part of the number — leave it for the
      // caller's trailing-garbage check (parity with the fallback engine)
      p = before_exp;
    } else {
      exp10 += eneg ? -ev : ev;
    }
  }
  double v;
  if (exp10 >= 0 && exp10 <= 22) {
    v = static_cast<double>(mant) * kPow10[exp10];
  } else if (exp10 < 0 && exp10 >= -22) {
    v = static_cast<double>(mant) * kPow10Inv[-exp10];
  } else {
    // rare: huge/tiny exponent — libc handles subnormals correctly
    char buf[64];
    size_t n = static_cast<size_t>(p - (digits_begin - (neg ? 1 : 0)));
    if (n >= sizeof(buf)) n = sizeof(buf) - 1;
    memcpy(buf, digits_begin - (neg ? 1 : 0), n);
    buf[n] = '\0';
    v = strtod(buf, nullptr);
    *value = v;
    *out = p;
    return true;
  }
  *value = neg ? -v : v;
  *out = p;
  return true;
}

// ---------------- SWAR digit-run primitives ----------------
//
// The scalar per-digit loops (mant = mant*10 + d) cost a dependent multiply
// chain plus an unpredictable loop-exit branch per number — the dominant
// cycles in the parser hot loops on real data (digit counts vary line to
// line, so the exit mispredicts constantly). These read 8 bytes at once and
// convert branch-free: one load, a byte-wise digit classification, a count
// via ctz, and a fixed 2-multiply reduction.

// 10^k as exact integers, k in [0, 8]
static const uint64_t kPow10U[] = {1ull,      10ull,      100ull,
                                   1000ull,   10000ull,   100000ull,
                                   1000000ull, 10000000ull, 100000000ull};

inline uint64_t load8(const char* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

// Number of leading ASCII-digit bytes (0..8) in the little-endian load.
// Marker construction: t = val ^ 0x30.. maps digits to 0x00..0x09; adding
// 0x76 sets the high bit for 0x0A..0x7F, and |t catches >=0x80. Byte-adds
// can carry upward, but a carry out of byte k-1 implies byte k-1 is itself
// a marker, so the LOWEST marker (the one ctz finds) is always genuine.
inline int swar_digit_count(uint64_t val) {
  uint64_t t = val ^ 0x3030303030303030ull;
  uint64_t m = ((t + 0x7676767676767676ull) | t) & 0x8080808080808080ull;
  return m ? static_cast<int>(__builtin_ctzll(m) >> 3) : 8;
}

// Value of the 8 ASCII digits in `val` (first char = low byte = most
// significant digit): pairwise SWAR reduction, 2 multiplies total.
inline uint32_t swar_parse8(uint64_t val) {
  const uint64_t mask = 0x000000FF000000FFull;
  const uint64_t mul1 = 0x000F424000000064ull;  // 100 + (1000000 << 32)
  const uint64_t mul2 = 0x0000271000000001ull;  // 1 + (10000 << 32)
  val -= 0x3030303030303030ull;
  val = (val * 10) + (val >> 8);
  val = (((val & mask) * mul1) + (((val >> 16) & mask) * mul2)) >> 32;
  return static_cast<uint32_t>(val);
}

// Value of the first n (1..8) digits: shift them to the high (least
// significant for swar_parse8) bytes and pad the front with ASCII zeros.
inline uint32_t swar_value_full(uint64_t val, int n) {
  uint64_t pad = (n < 8) ? (0x3030303030303030ull >> (n * 8)) : 0;
  return swar_parse8((val << (((8 - n) * 8) & 63)) | pad);
}

// Scalar fallback for buffer tails (< 18 bytes headroom): [sign] digits
// [. digits], no exponent, <=19 total digits; anything else falls through
// to parse_double, so the accepted grammar is identical.
inline bool parse_value_small(const char* p, const char* end, const char** out,
                              double* value) {
  const char* p0 = p;
  if (p == end || is_space(*p)) return parse_double(p0, end, out, value);
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  uint64_t mant = 0;
  const char* d0 = p;
  while (p != end && is_digit(*p))
    mant = mant * 10 + static_cast<uint64_t>(*p++ - '0');
  long idig = p - d0;
  long frac = 0;
  if (p != end && *p == '.') {
    ++p;
    const char* f0 = p;
    while (p != end && is_digit(*p))
      mant = mant * 10 + static_cast<uint64_t>(*p++ - '0');
    frac = p - f0;
  }
  if (idig + frac == 0 || idig + frac > 19 ||
      (p != end && (*p == 'e' || *p == 'E'))) {
    return parse_double(p0, end, out, value);
  }
  double v = static_cast<double>(mant) * kPow10Inv[frac];
  *value = neg ? -v : v;
  *out = p;
  return true;
}

// Hottest-path value parse for in-line tokens. The dominant shape in ML
// text corpora is "[-]d.ffffff" (one integer digit, short fraction): both
// 8-byte loads are issued together up front, so classifying the fraction
// does not wait on the integer part's digit count — that dependency chain
// is what bounds a 1-core scan. Leading whitespace falls through to
// parse_double (digit_count sees no digits), exponents / >8-digit runs /
// inf / nan fall back likewise, keeping the accepted grammar identical.
inline bool parse_value_hot(const char* p, const char* end, const char** out,
                            double* value) {
  // 19 bytes of headroom: sign + 8 digits + '.' + 8 digits consumed, plus
  // one lookahead byte dereferenced after the run
  if (end - p < 19) return parse_value_small(p, end, out, value);
  const char* p0 = p;
  unsigned neg = (*p == '-') ? 1u : 0u;
  p += (neg | ((*p == '+') ? 1u : 0u));
  uint64_t c1 = load8(p);
  uint64_t cs = load8(p + 2);  // speculative fraction load for "d.ffffff"
  unsigned d0 = static_cast<unsigned>(static_cast<unsigned char>(p[0])) - '0';
  if (d0 <= 9 && p[1] == '.') {
    int n2 = swar_digit_count(cs);
    if (n2 == 8 && is_digit(p[10])) return parse_double(p0, end, out, value);
    const char* q = p + 2 + n2;
    if (*q == 'e' || *q == 'E') return parse_double(p0, end, out, value);
    uint64_t mant =
        static_cast<uint64_t>(d0) * kPow10U[n2] + (n2 ? swar_value_full(cs, n2) : 0);
    int64_t sm = static_cast<int64_t>(
        (mant ^ (0ull - static_cast<uint64_t>(neg))) + neg);
    *value = static_cast<double>(sm) * kPow10Inv[n2];
    *out = q;
    return true;
  }
  int n1 = swar_digit_count(c1);
  if (n1 == 0) return parse_double(p0, end, out, value);  // also ".5", inf, nan
  if (n1 == 8 && is_digit(p[8])) return parse_double(p0, end, out, value);
  uint64_t mant = swar_value_full(c1, n1);
  p += n1;
  int frac = 0;
  if (*p == '.') {
    ++p;
    uint64_t c2 = load8(p);
    int n2 = swar_digit_count(c2);
    if (n2 == 8 && is_digit(p[8])) return parse_double(p0, end, out, value);
    mant = mant * kPow10U[n2] + (n2 ? swar_value_full(c2, n2) : 0);
    frac = n2;
    p += n2;
  }
  if (*p == 'e' || *p == 'E') return parse_double(p0, end, out, value);
  int64_t sm = static_cast<int64_t>(
      (mant ^ (0ull - static_cast<uint64_t>(neg))) + neg);
  *value = static_cast<double>(sm) * kPow10Inv[frac];
  *out = p;
  return true;
}

// Fast path for the label/value hot loops: SWAR digit runs, branch-free
// sign application. Falls back to parse_value_small near the buffer end and
// to parse_double for leading space / exponents / >8-digit runs, keeping
// the accepted grammar identical to the scalar version.
inline bool parse_value(const char* p, const char* end, const char** out,
                        double* value) {
  if (end - p < 19) return parse_value_small(p, end, out, value);
  const char* p0 = p;
  if (is_space(*p)) return parse_double(p0, end, out, value);
  unsigned neg = (*p == '-') ? 1u : 0u;
  p += (neg | ((*p == '+') ? 1u : 0u));
  // int part scalar: labels/values have 1-2 int digits, where the SWAR
  // machinery costs more than the loop. Capped at 9 so the scan stays
  // within the 18-byte headroom; 9+ digit int parts take the slow path.
  uint64_t mant = 0;
  const char* d0 = p;
  const char* ilim = p + 9;
  while (p != ilim && is_digit(*p))
    mant = mant * 10 + static_cast<uint64_t>(*p++ - '0');
  int n1 = static_cast<int>(p - d0);
  if (n1 > 8) return parse_double(p0, end, out, value);
  int frac = 0;
  if (*p == '.') {
    ++p;
    int n2 = swar_digit_count(load8(p));
    if (n2) {
      mant = mant * kPow10U[n2] + swar_value_full(load8(p), n2);
      frac = n2;
      p += n2;
      if (n2 == 8 && is_digit(*p)) return parse_double(p0, end, out, value);
    }
  }
  if (n1 + frac == 0 || (*p == 'e' || *p == 'E')) {
    return parse_double(p0, end, out, value);
  }
  // branch-free sign: negate the (<= 10^16 < 2^62) mantissa as int64
  int64_t sm = static_cast<int64_t>((mant ^ (0ull - neg)) + neg);
  *value = static_cast<double>(sm) * kPow10Inv[frac];
  *out = p;
  return true;
}

// Parse an unsigned integer; returns false if no digits.
inline bool parse_uint(const char* p, const char* end, const char** out,
                       uint64_t* value) {
  while (p != end && is_space(*p)) ++p;
  if (p == end || !is_digit(*p)) return false;
  uint64_t v = 0;
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *value = v;
  *out = p;
  return true;
}

}  // namespace dmlc_tpu
#endif  // DMLC_TPU_NATIVE_STRTONUM_H_
