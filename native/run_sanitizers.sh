#!/bin/sh
# Sanitizer CI for the native core — the discipline the reference keeps in
# scripts/travis/travis_script.sh:53-60 (TSAN Debug run of the unit suite).
# Builds native_smoke + the threaded stress driver under ASan+UBSan and
# TSan and runs both; output is recorded to native/SANITIZE.log (committed,
# so every round's sanitizer status is auditable in-repo).
#
# Usage: sh native/run_sanitizers.sh
set -eu
cd "$(dirname "$0")"
# keep in sync with Makefile NATIVE_SRCS, CMakeLists.txt, and
# dmlc_tpu/native/__init__.py _SRCS — a .cc missing here is a silent
# sanitizer coverage gap
SRCS="src/parse.cc src/reader.cc src/recordio.cc src/batch_parse.cc"
LOG=SANITIZE.log
: > "$LOG"

run() {
  name="$1"; flags="$2"
  echo "== $name ==" | tee -a "$LOG"
  g++ -O1 -g -std=c++17 -pthread -fno-omit-frame-pointer $flags \
      -o "build/smoke_$name" test/native_smoke.cc $SRCS 2>>"$LOG"
  g++ -O1 -g -std=c++17 -pthread -fno-omit-frame-pointer $flags \
      -o "build/stress_$name" test/stress_reader.cc $SRCS 2>>"$LOG"
  for bin in "build/smoke_$name" "build/stress_$name"; do
    echo "-- $bin" | tee -a "$LOG"
    if "./$bin" >>"$LOG" 2>&1; then
      echo "   PASS" | tee -a "$LOG"
    else
      echo "   FAIL (rc=$?)" | tee -a "$LOG"
      exit 1
    fi
  done
}

mkdir -p build
run asan "-fsanitize=address,undefined"
run tsan "-fsanitize=thread"
echo "sanitizers: ALL CLEAN" | tee -a "$LOG"
