"""Data layer: sparse row blocks, ML-text parsers, row iterators, device feed.

TPU-native equivalent of reference layer 5 (include/dmlc/data.h, src/data/):
parsers emit numpy-CSR RowBlocks on the host; :mod:`dmlc_tpu.data.device`
turns them into HBM-resident jax.Array / BCOO batches.
"""

from dmlc_tpu.data.row_block import Row, RowBlock, RowBlockContainer
from dmlc_tpu.data.autotune import AutoTuner, Knob, ParseTierTuner
from dmlc_tpu.data.epoch import (
    EpochPlan, block_permutation, permute_block_rows, row_permutation,
)
from dmlc_tpu.data.parsers import (
    Parser, LibSVMParser, CSVParser, LibFMParser, ThreadedParser,
    ParallelTextParser, BlockCacheIter, create_parser,
)
from dmlc_tpu.data.iterators import (
    RowBlockIter, BasicRowIter, DiskRowIter, create_row_block_iter,
)

__all__ = [
    "Row", "RowBlock", "RowBlockContainer",
    "AutoTuner", "Knob", "ParseTierTuner",
    "EpochPlan", "block_permutation", "permute_block_rows",
    "row_permutation",
    "Parser", "LibSVMParser", "CSVParser", "LibFMParser", "ThreadedParser",
    "ParallelTextParser", "BlockCacheIter", "create_parser",
    "RowBlockIter", "BasicRowIter", "DiskRowIter", "create_row_block_iter",
]
