"""Fully-native streaming parser: the whole read->chunk->parse pipeline runs
in C++ (native/src/reader.cc) with one GIL-releasing pull per parsed block.

This is the TPU-first hot path for local text corpora: where the reference
stacks ThreadedInputSplit (prefetch thread) + ThreadedParser (parse-ahead
thread) + per-chunk parse threads in C++ (src/io/threaded_input_split.h,
src/data/parser.h:70-126), this class delegates the identical pipeline to
the native core, so on a TPU-VM host parsing overlaps JAX dispatch and
host->HBM DMA without touching the GIL.

``create_parser`` (dmlc_tpu.data.parsers) routes eligible URIs here: local
filesystem, text formats (libsvm / csv / libfm), no cache or shuffle
decorators. Everything else takes the Python engine, which shares chunk
semantics with this path (both mirror input_split_base.cc).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from dmlc_tpu.data.parsers import (
    CSVParserParam,
    LibFMParserParam,
    LibSVMParserParam,
    Parser,
    _csv_skeleton,
    csv_cells_to_block,
    csv_cells_to_dense,
)
from dmlc_tpu.data.row_block import CooBlock, DenseBlock, RowBlock
from dmlc_tpu.io.filesystem import LocalFileSystem, get_filesystem
from dmlc_tpu.io.input_split import DEFAULT_CHUNK_BYTES, LineSplitter
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError, check
from dmlc_tpu.utils.timer import get_time


def list_partition_files(uri: str) -> Tuple[List[str], List[int]]:
    """Expand a local URI (';' lists, dirs, regex basenames) to (paths, sizes)
    using the same matching rules as the input-split engine."""
    fs = get_filesystem(uri)
    check(isinstance(fs, LocalFileSystem), "native reader requires local files")
    lister = LineSplitter(fs, uri)
    paths = [info.path.name for info in lister.files]
    sizes = [info.size for info in lister.files]
    return paths, sizes


class NativeStreamParser(Parser):
    """Parser facade over :class:`dmlc_tpu.native.Reader`.

    The native reader owns partitioning (byte-range + record-boundary
    adjustment), chunking, and multi-threaded parsing; this class wraps the
    returned buffers zero-copy into RowBlock / DenseBlock.
    """

    def __init__(
        self,
        uri: str,
        args: Optional[Dict[str, str]],
        part_index: int,
        num_parts: int,
        fmt_name: str,
        index_dtype=np.uint64,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    ):
        check(fmt_name in ("libsvm", "csv", "libfm"),
              f"native reader does not support format {fmt_name!r}")
        # same partition validation as the Python engine (create_input_split):
        # num_parts=0 would SIGFPE in the native byte-range divide, and an
        # out-of-range part would silently yield an empty stream
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.fmt_name = fmt_name
        self.index_dtype = index_dtype
        self.chunk_bytes = chunk_bytes
        self.part_index = part_index
        self.num_parts = num_parts
        args = dict(args or {})
        if fmt_name == "libsvm":
            self.param = LibSVMParserParam()
        elif fmt_name == "csv":
            self.param = CSVParserParam()
        else:
            self.param = LibFMParserParam()
        self.param.init(args, allow_unknown=True)
        if fmt_name == "csv":
            # the native csv scanner emits float32 cells only; a DMLCError
            # here routes the caller to the Python engine, which supports
            # int32/int64 and raises proper config errors
            check(self.param.dtype == "float32",
                  "native reader: csv dtype must be float32")
            # mirror CSVParser.__init__'s config validation (parsers.py) so
            # bad configs fail loudly instead of silently mis-parsing
            check(len(self.param.delimiter) == 1,
                  "CSVParser: delimiter must be one char")
            check(
                self.param.label_column != self.param.weight_column
                or self.param.label_column < 0,
                "CSVParser: label_column must differ from weight_column",
            )
        self._init_source(uri)
        self._reader = None
        self._emit_dense: Optional[int] = None
        self._emit_bf16 = False
        self._pack_aux = False
        self._emit_coo: Optional[int] = None
        self._coo_row_bucket = 0
        self._coo_nnz_bucket = 0
        self._coo_elide = False
        self._coo_csr_wire = False
        self._stall = 0.0
        self._blocks_out = 0  # delivered blocks, for count-based resume
        self._batch_rows = 0

    def _init_source(self, uri: str) -> None:
        """Resolve the byte source. Base class: local files, listed with the
        engine's matching rules (the native reader reads them itself)."""
        self.paths, self.sizes = list_partition_files(uri)

    # ---------------- configuration ----------------

    def set_emit_dense(self, num_col: int, batch_rows: int = 0,
                       dtype: str = "float32",
                       pack_aux: bool = False) -> bool:
        """Emit DenseBlock batches straight from the native dense scanner.
        With ``batch_rows``, the native reader additionally repacks rows
        into exact [batch_rows, num_col] blocks off-GIL (the consumer can
        then slice views instead of concatenating); ``dtype='bfloat16'``
        makes that repack pass emit bf16 x — half the host->HBM bytes in
        the MXU's preferred operand width. Must be called before the first
        pull (the reader pipeline starts lazily). libfm has no dense
        analog. ``pack_aux`` (batch mode only) packs label/weight into two
        trailing x columns — one [B, D+2] array per batch, ONE device_put
        instead of three (api.h DenseResult packed_aux docs); in bf16 mode
        the aux columns are bf16 too, so callers opt in only when their
        labels/weights are bf16-exact."""
        if self._reader is not None or self.fmt_name == "libfm":
            return False
        self._emit_dense = int(num_col)
        self._batch_rows = int(batch_rows)
        self._emit_bf16 = dtype == "bfloat16"
        self._pack_aux = bool(pack_aux) and batch_rows > 0
        return True

    def set_emit_coo(self, num_col: int, row_bucket: int = 0,
                     nnz_bucket: int = 0, elide_unit: bool = False,
                     csr_wire: bool = False) -> bool:
        """Emit CooBlock batches straight from the native parse: int32
        (row, col) coordinate pairs with OOB bucket padding, optional
        all-ones value elision — the whole convert stage of the BCOO
        pipeline moves off-GIL into the C++ parse threads. One CooBlock per
        chunk (natural-block mode). ``csr_wire`` ships cols + row_ptr
        instead of (row, col) pairs — half the coordinate bytes over the
        host->device link; the DeviceIter consumer rebuilds row ids on
        device (native/src/api.h CooResult docs). Must be called before the
        first pull. csv has no sparse analog; int32 coords require
        num_col + 1 < 2^31."""
        if (self._reader is not None or self.fmt_name == "csv"
                or int(num_col) + 1 >= (1 << 31)):
            return False
        self._emit_coo = int(num_col)
        self._coo_row_bucket = int(row_bucket)
        self._coo_nnz_bucket = int(nnz_bucket)
        self._coo_elide = bool(elide_unit)
        self._coo_csr_wire = bool(csr_wire)
        return True

    # ---------------- pipeline ----------------

    def _stream_config(self):
        """(fmt, kwargs) shared by the pull-mode Reader and the push-mode
        Feeder — one place for format selection and repack policy."""
        from dmlc_tpu import native

        if self._emit_coo is not None and self.fmt_name in ("libsvm", "libfm"):
            fmt = (native.FMT_LIBFM_COO if self.fmt_name == "libfm"
                   else native.FMT_LIBSVM_COO)
        elif self.fmt_name == "libsvm":
            fmt = (native.FMT_LIBSVM_DENSE if self._emit_dense is not None
                   else native.FMT_LIBSVM)
        elif self.fmt_name == "csv":
            # label/weight columns configured and no dense repack: the
            # native merge pass splits them out (FMT_CSV_SPLIT), so the
            # RowBlock wrap below is zero-copy — the reference re-walks
            # the cell matrix in its consumer instead (csv_parser.h:120)
            lc = getattr(self.param, "label_column", -1)
            wc = getattr(self.param, "weight_column", -1)
            fmt = (native.FMT_CSV_SPLIT
                   if self._emit_dense is None and (lc >= 0 or wc >= 0)
                   else native.FMT_CSV)
        else:
            fmt = native.FMT_LIBFM
        repack = (fmt == native.FMT_LIBSVM_DENSE
                  or (fmt == native.FMT_CSV and self._emit_dense is not None))
        coo = fmt in (native.FMT_LIBSVM_COO, native.FMT_LIBFM_COO)
        kwargs = dict(
            num_col=(self._emit_coo if coo else self._emit_dense) or 0,
            indexing_mode=getattr(self.param, "indexing_mode", 0),
            delimiter=getattr(self.param, "delimiter", ","),
            chunk_bytes=self.chunk_bytes,
            batch_rows=self._batch_rows if repack else 0,
            label_col=getattr(self.param, "label_column", -1),
            weight_col=getattr(self.param, "weight_column", -1),
            out_bf16=bool(repack and self._batch_rows and self._emit_bf16),
            row_bucket=self._coo_row_bucket if coo else 0,
            nnz_bucket=self._coo_nnz_bucket if coo else 0,
            elide_unit=self._coo_elide if coo else False,
            csr_wire=self._coo_csr_wire if coo else False,
            pack_aux=bool(repack and self._pack_aux),
        )
        return fmt, kwargs

    def _ensure_reader(self):
        if self._reader is None:
            from dmlc_tpu import native

            fmt, kwargs = self._stream_config()
            self._reader = native.Reader(
                self.paths, self.sizes, self.part_index, self.num_parts,
                fmt, **kwargs)
        return self._reader

    def next_block(self):
        from dmlc_tpu import native

        reader = self._ensure_reader()
        t0 = get_time()
        out = reader.next()
        self._stall += get_time() - t0
        if out is None:
            return None
        self._blocks_out += 1
        fmt, data = out
        if fmt == native.FMT_LIBSVM_DENSE:
            x, label, weight, owner, packed = data
            return DenseBlock(x, label, weight, hold=owner, packed=packed)
        if fmt in (native.FMT_LIBSVM_COO, native.FMT_LIBFM_COO):
            return CooBlock(
                data["coords"], data["values"], data["label"],
                data["weight"], data["n_rows"], data["nnz"],
                int(self._emit_coo), hold=data["_owner"],
                row_ptr=data.get("row_ptr"))
        if fmt in (native.FMT_LIBSVM, native.FMT_LIBFM):
            return RowBlock(
                offset=data["offset"], label=data["label"],
                index=data["index"], value=data["value"],
                weight=data["weight"], qid=data["qid"],
                field=data["field"], hold=data["_owner"],
            )
        if fmt == native.FMT_CSV_SPLIT:
            values, label, weight, n, owner = data
            k = values.shape[1]
            index, offset = _csv_skeleton(n, k, self.index_dtype)
            if label is None:
                label = np.zeros(n, np.float32)
            return RowBlock(
                offset=offset, label=label, index=index,
                value=values.reshape(-1), weight=weight, hold=owner)
        cells, owner = data
        n, ncol = cells.shape
        if self._emit_dense is not None:
            return csv_cells_to_dense(
                cells, n, ncol, int(self._emit_dense),
                self.param.label_column, self.param.weight_column, owner)
        return csv_cells_to_block(
            cells, n, ncol, self.param.label_column,
            self.param.weight_column, self.index_dtype)

    def before_first(self) -> None:
        if self._reader is not None:
            self._reader.before_first()
        self._blocks_out = 0

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Re-point at another partition; the file listing (paths/sizes) is
        reused — only the native reader is rebuilt, lazily."""
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        # keep bytes_read cumulative across partitions, matching the Python
        # engine's accumulating counter
        self._bytes_base = self.bytes_read
        self.close()
        self.part_index = part_index
        self.num_parts = num_parts
        self._blocks_out = 0

    # -------- checkpoint / resume (SURVEY.md §5.4 addition) --------

    def state_dict(self) -> dict:
        """Resume point at a block boundary. Chunking in the native reader is
        deterministic, so a block count replays exactly. Partition identity
        rides along so restore onto a differently-pointed parser re-applies
        the recorded shard first."""
        return {"kind": "blocks", "blocks": self._blocks_out,
                "part_index": self.part_index, "num_parts": self.num_parts}

    def load_state(self, state: dict) -> None:
        check(state.get("kind") == "blocks",
              f"native parser: incompatible resume state {state.get('kind')!r}")
        part, nparts = state.get("part_index"), state.get("num_parts")
        if (nparts is not None and part is not None
                and (part, nparts) != (self.part_index, self.num_parts)):
            self.reset_partition(int(part), int(nparts))
        n = int(state["blocks"])
        self.before_first()
        reader = self._ensure_reader()
        for _ in range(n):
            if reader.next() is None:
                break
        self._blocks_out = n

    @property
    def bytes_read(self) -> int:
        live = self._reader.bytes_read if self._reader is not None else 0
        return getattr(self, "_bytes_base", 0) + live

    @property
    def stall_seconds(self) -> float:
        """Consumer-side wait on the native pipeline."""
        return self._stall

    @property
    def parse_workers(self) -> int:
        """The native reader's own C++ parse-thread count — it keeps its
        own threading and ignores the Python engine's ``parse_workers``
        knob (docs/data.md)."""
        from dmlc_tpu import native

        return native.default_nthread()

    def parallel_stats(self) -> dict:
        """Scaling sideband in the same shape ParallelTextParser reports
        (DeviceIter.stats() consumes either): the C++ core does not expose
        per-thread busy seconds, so efficiency is unmeasured here."""
        return {
            "parse_workers": self.parse_workers,
            "parse_busy_seconds": None,
            "parse_span_seconds": None,
            "parse_parallelism_efficiency": None,
            "engine": "native",
        }

    def close(self) -> None:
        if self._reader is not None:
            self._reader.close()
            self._reader = None


def _native_eligible(uri: str, type_: str, threaded: bool, split_kw: Dict,
                     want_local: bool) -> bool:
    """Shared native-routing predicate; want_local picks pull-mode (local
    files, the Reader) vs push-mode (remote streams, the Feeder)."""
    from dmlc_tpu import native

    if not threaded or type_ not in ("libsvm", "csv", "libfm"):
        return False
    if "#" in uri or "engine=python" in uri:
        return False  # cachefile decorator / explicit engine opt-out
    for key in ("shuffle", "num_shuffle_parts", "index_uri"):
        if split_kw.get(key):
            return False
    if split_kw.get("recurse_directories"):
        return False
    base = uri.split("?", 1)[0]
    if base in ("stdin",):
        return False
    try:
        fs = get_filesystem(base)
    except DMLCError:
        return False
    if isinstance(fs, LocalFileSystem) != want_local:
        return False
    return native.available()


def native_reader_eligible(uri: str, type_: str, threaded: bool,
                           split_kw: Dict) -> bool:
    """True when create_parser can route to the native stream parser."""
    return _native_eligible(uri, type_, threaded, split_kw, want_local=True)


class NativeFeedParser(NativeStreamParser):
    """Remote corpora through the native pipeline (BASELINE config #2-style
    cloud streams): a Python feed thread range-reads this partition through
    the FileSystem layer (S3 / GCS / HTTP / anything registered) and pushes
    raw bytes into the C++ chunk feeder (reader.cc push mode), which owns
    record-aligned chunking, threaded parsing, and batch repack — so remote
    corpora get the same off-GIL parse path as local files instead of the
    single-threaded Python engine.

    Partitioning (byte ranges, record-boundary adjustment, newline
    injection at text file joins) stays with the Python input-split engine,
    which already speaks every filesystem; the feed thread streams exactly
    this partition's bytes (InputSplitBase._read).
    """

    FEED_CHUNK = 1 << 20

    def _init_source(self, uri: str) -> None:
        self.uri = uri
        self.paths = self.sizes = None
        self._feed_thread = None
        self._feed_exc = None  # original feed-thread exception (cause chain)

    def _make_split(self):
        from dmlc_tpu.io.input_split import LineSplitter

        split = LineSplitter(get_filesystem(self.uri), self.uri)
        split.reset_partition(self.part_index, self.num_parts)
        return split

    def _start_feed(self) -> None:
        import threading

        feeder = self._reader
        split = self._make_split()

        def run() -> None:
            try:
                while True:
                    data = split._read(self.FEED_CHUNK)
                    if not data or not feeder.push(data):
                        break
                feeder.finish()
            except Exception as exc:  # noqa: BLE001
                # a mid-stream remote failure must NOT look like EOF: record
                # it so the consumer's next() raises after the queue drains.
                # The C ABI carries only the message string; keep the
                # exception OBJECT here so next_block can restore the cause
                # chain (the resilience classifier walks __cause__ — a
                # retryable stream fault must stay retryable-class for the
                # DeviceIter pipeline-restart path).
                self._feed_exc = exc
                feeder.fail(f"feed failed: {exc}")
            finally:
                try:
                    split.close()
                except Exception:  # noqa: BLE001
                    pass

        # the feed thread inherits the creator's pipeline scope so its
        # retries/resumes land under the owning pipeline's label
        self._feed_thread = threading.Thread(
            target=_telemetry.scoped_target(run), name="dmlc-feed",
            daemon=True)
        self._feed_thread.start()

    def _stop_feed(self) -> None:
        if self._feed_thread is not None:
            if self._reader is not None:
                self._reader.abort()
            self._feed_thread.join()
            self._feed_thread = None

    def _ensure_reader(self):
        if self._reader is None:
            from dmlc_tpu import native

            fmt, kwargs = self._stream_config()
            self._reader = native.Feeder(fmt, **kwargs)
            self._start_feed()
        return self._reader

    def next_block(self):
        try:
            return super().next_block()
        except DMLCError as exc:
            cause = self._feed_exc
            if cause is not None and exc.__cause__ is None:
                # restore the original exception behind the ABI's string:
                # classification (retryable vs fatal) needs the real class
                self._feed_exc = None
                raise exc from cause
            raise

    def before_first(self) -> None:
        self._feed_exc = None  # cleared BEFORE the new feed thread starts
        if self._reader is not None:
            self._stop_feed()
            if self._reader.error() is not None:
                # errors are STICKY in the native pipeline (before_first
                # stays stopped) — a failed feeder cannot restart. Rebuild
                # it so an epoch reset after a fault (e.g. DeviceIter's
                # bounded pipeline restart) gets a clean stream instead of
                # replaying the stale error.
                self._reader.close()
                self._reader = None
                self._ensure_reader()  # fresh feeder + feed thread
            else:
                self._reader.before_first()
                self._start_feed()
        self._blocks_out = 0

    def close(self) -> None:
        self._stop_feed()
        super().close()


def native_feed_eligible(uri: str, type_: str, threaded: bool,
                         split_kw: Dict) -> bool:
    """True when create_parser can route a REMOTE uri to the chunk feeder."""
    return _native_eligible(uri, type_, threaded, split_kw, want_local=False)
