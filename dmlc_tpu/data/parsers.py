"""ML text-format parsers: libsvm, csv, libfm.

Equivalent of reference src/data/{parser.h,text_parser.h,libsvm_parser.h,
csv_parser.h,libfm_parser.h} + the factory/registry in src/data.cc.

Parsing strategy: the reference splits each chunk across OS threads and runs
a char-by-char scanner (text_parser.h:110-146). The Python engine instead
parses a whole chunk with vectorized numpy string conversion (one C-level
``split`` + one ``astype`` per chunk); the C++ native core
(:mod:`dmlc_tpu.native`) supplies the multi-threaded scanner for the hot
path. Both emit identical RowBlocks (tested against each other).

Semantics matched to the reference:
- libsvm: ``label[:weight] [qid:N] idx[:val]...``; ``#`` comments
  (libsvm_parser.h:67-84); missing values mean binary features; 1-based
  index heuristic à la sklearn when indexing_mode=-1 (libsvm_parser.h:159-168).
- csv: dense rows, synthetic indices 0..k (csv_parser.h:120-121);
  ``label_column``/``weight_column``/single-char ``delimiter`` params.
- libfm: ``label field:idx:val...``; indexing_mode applies to both field and
  index (libfm_parser.h:130-143).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
from typing import Dict, Iterator, Optional

import numpy as np

from dmlc_tpu.data.row_block import DenseBlock, RowBlock
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.io.input_split import (
    DEFAULT_CHUNK_BYTES,
    InputSplit,
    create_input_split,
    create_mmap_text_split,
)
from dmlc_tpu.io.threaded_iter import OrderedWorkerPool, ThreadedIter
from dmlc_tpu.io.uri import URISpec
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import (CacheCorruptionError, DMLCError, check,
                                  get_logger)
from dmlc_tpu.utils.params import Parameter, field
from dmlc_tpu.utils.registry import Registry
from dmlc_tpu.utils.timer import get_time

PARSER_REGISTRY: Registry = Registry.get("parser")


class Parser:
    """Single-pass RowBlock iterator — analog of dmlc::Parser (data.h:293-320)."""

    def next_block(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def before_first(self) -> None:
        raise NotImplementedError

    @property
    def bytes_read(self) -> int:
        return 0

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            blk = self.next_block()
            if blk is None:
                return
            yield blk

    def close(self) -> None:
        pass


# ---------------- param structs ----------------

class LibSVMParserParam(Parameter):
    """libsvm_parser.h:24-39."""
    format = field(str, default="libsvm")
    indexing_mode = field(
        int, default=0, enum=[-1, 0, 1],
        help=">0: 1-based indices; 0: 0-based; <0: sklearn-style auto-detect.",
    )


class CSVParserParam(Parameter):
    """csv_parser.h:23-40."""
    format = field(str, default="csv")
    label_column = field(int, default=-1, help="0-based column index of the label.")
    delimiter = field(str, default=",", help="Single-character field delimiter.")
    weight_column = field(int, default=-1, help="0-based column of instance weights.")
    dtype = field(str, default="float32", enum=["float32", "int32", "int64"],
                  help="Value dtype (data.cc instantiates real_t/int32/int64).")


class LibFMParserParam(Parameter):
    """libfm_parser.h:24-39."""
    format = field(str, default="libfm")
    indexing_mode = field(int, default=0, enum=[-1, 0, 1])


# ---------------- chunk parsers ----------------

class TextParserBase(Parser):
    """Pulls chunks from an InputSplit and parses each into a RowBlock
    (analog of TextParserBase::FillData, text_parser.h:110-146).

    Each chunk goes through the C++ native core when available (threaded
    scanner, dmlc_tpu/native) and falls back to the vectorized numpy engine
    otherwise; both produce identical blocks.
    """

    # class-level defaults so partially-constructed instances (tests drive
    # parse_chunk_* directly via __new__) behave
    _emit_dense: Optional[int] = None
    _native = None
    # per-chunk native scanner threads: 0 = the native default
    # (cores/2-ish). The data-parallel fan-out pins this to 1 — chunk-level
    # parallelism across pool workers replaces intra-chunk threading, whose
    # per-chunk thread spawn measured slower than a single lane anyway.
    _parse_nthread: int = 0
    # fast-path probing state: a corpus whose first chunks ALL reject the
    # _token_table signature (label:weight everywhere, all-binary
    # features) stops paying the qualification scan; one qualifying chunk
    # pins probing on for good. Both fields are advisory and updated
    # RACILY by pool workers — _fast_saw_hit is a monotonic plain store
    # and lost _fast_rejects increments merely delay the give-up, so races
    # cost at most a few extra qualification scans, never wrong output.
    _fast_rejects: int = 0
    _fast_saw_hit: bool = False

    def __init__(self, source: InputSplit, index_dtype=np.uint64):
        self.source = source
        self.index_dtype = index_dtype
        self._bytes = 0
        self._chunks_in = 0  # chunks consumed, for count-based resume
        self._native = None  # tri-state: None=unprobed, False=off, True=on
        self._emit_dense: Optional[int] = None  # num_col when dense mode is on
        # cumulative per-stage seconds: chunk fetch (IO) vs chunk->block
        # parse — the split read/parse attribution DeviceIter.stats() names
        # (two monotonic reads per ~MB chunk: noise)
        self._read_seconds = 0.0
        self._parse_seconds = 0.0

    def set_emit_dense(self, num_col: int, batch_rows: int = 0,
                       dtype: str = "float32") -> bool:
        """Opt in to emitting DenseBlock batches straight from the scanner
        (the TPU-first layout fast path). Returns False when this parser has
        no dense scanner; callers then get RowBlocks as usual. batch_rows
        and dtype are honored only by the fully-native stream parser."""
        return False

    def use_native(self) -> bool:
        if self._native is None:
            from dmlc_tpu import native

            self._native = native.available() and self._native_supported()
        return bool(self._native)

    def _native_supported(self) -> bool:
        return True

    def parse_chunk_native(self, chunk: bytes) -> Optional[RowBlock]:
        return None

    def parse_chunk(self, chunk) -> RowBlock:
        """chunk: bytes or memoryview. The native engines consume a view's
        buffer zero-copy (length-bounded C scanners); the numpy engine
        materializes bytes once, here."""
        if self.use_native():
            block = self.parse_chunk_native(chunk)
            if block is not None:
                return block
        try:
            # overflow-range decimals (1e200) cast float64->float32 as inf
            # — the same saturation strtonum.h applies, so the numpy cast
            # warning is expected noise, not a data problem
            with np.errstate(over="ignore"):
                return self.parse_chunk_py(_chunk_bytes(chunk))
        except (ValueError, TypeError) as exc:
            # numpy conversion failures (e.g. astype on a malformed token)
            # surface as the same error type the native engine raises
            raise DMLCError(f"{type(self).__name__}: malformed input: {exc}") from exc

    def parse_chunk_py(self, chunk: bytes) -> RowBlock:
        raise NotImplementedError

    def stage_seconds(self) -> Dict[str, float]:
        """Cumulative {read, parse} seconds — the per-stage attribution
        feed for DeviceIter.stats(). ``read`` is chunk-fetch time at the
        split (for a threaded split: residual wait on its producer),
        ``parse`` is chunk->RowBlock conversion."""
        return {"read": self._read_seconds, "parse": self._parse_seconds}

    def _pull_chunk(self):
        """One serial chunk pull with the bookkeeping every consumer needs:
        read-seconds accrual, byte/chunk counters, and the byte-exact
        resume annotation positioned just AFTER the chunk (SURVEY.md §5.4)
        — shared by :meth:`next_block` and the parallel fan-out's serial
        source stage so the checkpoint schema cannot diverge. Returns
        ``(chunk, annot_or_None)``; ``(None, None)`` at end of stream."""
        t0 = get_time()
        chunk = self.source.next_chunk()
        dt = get_time() - t0
        self._read_seconds += dt
        # span twin of the read-seconds accrual: same start, same duration
        # (the trace timeline and stage_seconds() can never disagree)
        _telemetry.record_span("read", t0, dt)
        if chunk is None:
            return None, None
        self._bytes += len(chunk)
        self._chunks_in += 1
        annot = None
        split_state = getattr(self.source, "chunk_resume_state", None)
        if split_state is not None:
            annot = {"kind": "split", "split": split_state,
                     "chunks": self._chunks_in}
        return chunk, annot

    def next_block(self) -> Optional[RowBlock]:
        while True:
            chunk, annot = self._pull_chunk()
            if chunk is None:
                return None
            t1 = get_time()
            block = self.parse_chunk(chunk)
            dt = get_time() - t1
            self._parse_seconds += dt
            _telemetry.record_span("parse", t1, dt)
            if len(block) > 0:
                # the annotation marks the position just AFTER this block,
                # so downstream prefetch pipelines (ThreadedParser,
                # DeviceIter) can checkpoint byte-exactly even though their
                # own view runs behind this producer
                if annot is not None:
                    block.resume_state = annot
                return block

    def before_first(self) -> None:
        self.source.before_first()
        self._chunks_in = 0

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Re-point this parser at another partition of the same corpus
        (InputSplit::ResetPartition, io.h:190-242) — the file listing and
        offset table are reused, so looping all parts in one process pays
        the setup cost once."""
        self.source.reset_partition(part_index, num_parts)
        self._chunks_in = 0

    # -------- checkpoint / resume (SURVEY.md §5.4 addition) --------

    def state_dict(self) -> dict:
        """Resume point at a block boundary. Byte-exact whenever the source
        exposes a chunk-synchronized state (undecorated splits AND the
        prefetching ThreadedInputSplit, whose chunks carry the position they
        were produced at); otherwise a chunk count replayed on restore."""
        split_state = getattr(self.source, "chunk_resume_state", None)
        if split_state is not None:
            return {"kind": "split", "split": split_state,
                    "chunks": self._chunks_in}
        if self._chunks_in == 0 and hasattr(self.source, "state_dict"):
            # epoch start: no chunk pulled yet, the live state is exact
            return {"kind": "split", "split": self.source.state_dict(),
                    "chunks": 0}
        return {"kind": "chunks", "chunks": self._chunks_in}

    def load_state(self, state: dict) -> None:
        if state.get("kind") == "split" and hasattr(self.source, "load_state"):
            self.source.load_state(state["split"])
            self._chunks_in = int(state["chunks"])
            return
        self.before_first()
        for _ in range(int(state["chunks"])):
            if self.source.next_chunk() is None:  # skip without parsing
                break
        self._chunks_in = int(state["chunks"])

    @property
    def bytes_read(self) -> int:
        return self._bytes

    def close(self) -> None:
        self.source.close()


def _chunk_bytes(chunk) -> bytes:
    """Chunk -> bytes without copying when it is a full-span view of bytes."""
    if isinstance(chunk, bytes):
        return chunk
    if (
        isinstance(chunk, memoryview)
        and isinstance(chunk.obj, bytes)
        and chunk.c_contiguous
        and len(chunk) == len(chunk.obj)
    ):
        return chunk.obj
    return bytes(chunk)


def _strip_comments(chunk: bytes) -> bytes:
    """Remove ``#``-to-EOL spans (IgnoreCommentAndBlank, libsvm_parser.h:67-84)."""
    if b"#" not in chunk:
        return chunk
    out = []
    for line in chunk.split(b"\n"):
        pos = line.find(b"#")
        out.append(line if pos < 0 else line[:pos])
    return b"\n".join(out)


def _tokenize_lines(chunk: bytes):
    """Split a text chunk into per-line token lists, skipping blanks.

    UTF-8 BOM at chunk start is skipped (text_parser.h:81-95).
    """
    if chunk.startswith(b"\xef\xbb\xbf"):
        chunk = chunk[3:]
    chunk = _strip_comments(chunk.replace(b"\r", b"\n"))
    lines = []
    for line in chunk.split(b"\n"):
        toks = line.split()
        if toks:
            lines.append(toks)
    return lines


def _apply_indexing_mode(index: np.ndarray, mode: int) -> np.ndarray:
    """1-based -> 0-based conversion per libsvm_parser.h:159-168."""
    if len(index) == 0:
        return index
    if mode > 0 or (mode < 0 and int(index.min()) > 0):
        return index - 1
    return index


# bytes.split() whitespace, as a byte-indexed lookup table
_WS_LUT = np.zeros(256, bool)
_WS_LUT[[9, 10, 11, 12, 13, 32]] = True

# _token_table rejections (with no success yet) before a parser stops
# trying the fast path for good — the corpus structure never qualifies
_FAST_PATH_GIVEUP = 4


def _token_table(chunk: bytes, stride: int):
    """Vectorized structure scan for simple ``label f f f...`` text chunks.

    Splits the whole chunk ONCE on whitespace+colon into a single token
    array reused for label / index / value extraction, and derives the
    per-line structure (feature counts, label positions) from numpy mask
    scans instead of a per-line Python loop. ``stride`` is sub-tokens per
    feature (2 = libsvm ``idx:val``, 3 = libfm ``field:idx:val``).

    Returns ``(tokens, nnz, first_idx)`` or None when the chunk needs the
    general path (comments, qid, label:weight, binary/mixed features — any
    line whose token/colon counts break the uniform stride). The general
    path materializes the chunk ~3x via join + replace blobs; this one
    costs a single colon->space replace + split.
    """
    if b"#" in chunk or b"qid:" in chunk:
        return None
    if chunk.startswith(b"\xef\xbb\xbf"):
        chunk = chunk[3:]
    if b"\r" in chunk:
        chunk = chunk.replace(b"\r", b"\n")
    if not chunk:
        return None
    # structure checks run on zero-copy mask scans FIRST; the Python-level
    # replace/split/array-build — the expensive part — happens only after
    # the chunk has qualified, so a rejecting chunk costs numpy scans only
    a = np.frombuffer(chunk, np.uint8)
    iscolon = a == 0x3A
    issep = _WS_LUT[a] | iscolon  # colons become separators in the split
    cpos = np.nonzero(iscolon)[0]
    if len(cpos):
        # every colon must be GLUED to non-separator bytes on both sides:
        # '2: 3' / '2 :3' / '2::3' / a chunk-edge colon all split into
        # tokens whose counts alias a clean 'idx:val' signature while the
        # general path reads them as missing-value/binary/malformed
        if cpos[0] == 0 or cpos[-1] == len(a) - 1:
            return None
        if issep[cpos - 1].any() or issep[cpos + 1].any():
            return None
    prev = np.empty_like(issep)
    prev[0] = True
    prev[1:] = issep[:-1]
    tstart = ~issep & prev
    if not tstart.any():
        return None
    lid = np.cumsum(a == 0x0A)  # line id = newlines before each byte
    nlines = int(lid[-1]) + 1
    counts = np.bincount(lid[tstart], minlength=nlines)
    ccounts = np.bincount(lid[iscolon], minlength=nlines)
    live = counts > 0
    if np.any(ccounts[~live] > 0):
        # colons on a token-less line (e.g. ':::') — the general path
        # rejects these loudly; never swallow them here
        return None
    lc, cc = counts[live], ccounts[live]
    # every live line must be exactly label + nnz uniform features
    nnz, rem = np.divmod(lc - 1, stride)
    if rem.any() or not np.array_equal(cc, (stride - 1) * nnz):
        return None
    first_idx = np.zeros(len(lc), np.int64)
    np.cumsum(lc[:-1], out=first_idx[1:])
    # every colon must belong to a FEATURE token: a colon attached to a
    # line's first token is a label colon (label:weight — or malformed),
    # whose sub-tokens would otherwise alias a uniform feature signature
    # (e.g. libsvm '1:2 3' = weighted label + binary feature parses with
    # the same token/colon counts as 'label idx:val'). tok_before[i] is
    # the index of the token the byte at i follows.
    line_first = np.full(nlines, -1, np.int64)
    line_first[np.nonzero(live)[0]] = first_idx
    tok_before = np.cumsum(tstart) - 1
    if np.any(tok_before[iscolon] == line_first[lid[iscolon]]):
        return None
    tokens = np.array(chunk.replace(b":", b" ").split())
    return tokens, nnz, first_idx


def _split_label_feats(tokens: np.ndarray, first_idx: np.ndarray):
    """(labels f32, feature sub-token array) from a :func:`_token_table`
    result — the one extraction both fast-path engines share."""
    label_mask = np.zeros(len(tokens), bool)
    label_mask[first_idx] = True
    return tokens[first_idx].astype(np.float32), tokens[~label_mask]


class LibSVMParser(TextParserBase):
    """libsvm text -> RowBlock (libsvm_parser.h:85-169)."""

    def __init__(self, source: InputSplit, args: Dict[str, str] | None = None,
                 index_dtype=np.uint64):
        super().__init__(source, index_dtype)
        self.param = LibSVMParserParam()
        self.param.init(dict(args or {}), allow_unknown=True)
        check(self.param.format == "libsvm", "LibSVMParser: format must be libsvm")

    def set_emit_dense(self, num_col: int, batch_rows: int = 0,
                       dtype: str = "float32") -> bool:
        if self.use_native():
            self._emit_dense = int(num_col)
            return True
        return False

    def parse_chunk_native(self, chunk: bytes) -> Optional[RowBlock]:
        from dmlc_tpu import native

        # snapshot once: a concurrent worker's NeedsCsrError fallback may
        # null _emit_dense between the check and the call (pool fan-out)
        num_col = self._emit_dense
        if num_col is not None:
            try:
                out = native.parse_libsvm_dense(
                    chunk, num_col, nthread=self._parse_nthread,
                    indexing_mode=self.param.indexing_mode)
            except native.NeedsCsrError:
                # data the dense scanner can't express (qid rows):
                # permanently fall back to the CSR path
                self._emit_dense = None
                out = None
            if out is not None:
                x, label, weight, owner, _packed = out
                return DenseBlock(x, label, weight, hold=owner)
        d = native.parse_libsvm(chunk, nthread=self._parse_nthread,
                                indexing_mode=self.param.indexing_mode)
        if d is None:
            return None
        return RowBlock(
            offset=d["offset"], label=d["label"], index=d["index"],
            value=d["value"], weight=d["weight"], qid=d["qid"],
            hold=d["_owner"],
        )

    def parse_chunk_py(self, chunk: bytes) -> RowBlock:
        fast = (_token_table(chunk, stride=2)
                if self._fast_saw_hit
                or self._fast_rejects < _FAST_PATH_GIVEUP else None)
        if fast is not None:
            self._fast_saw_hit = True
            # one splitted-token array serves label, index AND value
            tokens, nnz, first_idx = fast
            labels, feats = _split_label_feats(tokens, first_idx)
            if len(feats) == 0:
                return RowBlock(
                    offset=np.concatenate([[0], np.cumsum(nnz)]),
                    label=labels, index=np.empty(0, self.index_dtype))
            index = _apply_indexing_mode(
                feats[0::2].astype(np.int64), self.param.indexing_mode)
            return RowBlock(
                offset=np.concatenate([[0], np.cumsum(nnz)]),
                label=labels,
                index=index.astype(self.index_dtype, copy=False),
                value=feats[1::2].astype(np.float32),
            )
        self._fast_rejects += 1
        lines = _tokenize_lines(chunk)
        n = len(lines)
        label_toks = []
        qid_vals: list = []
        has_qid = False
        nnz = np.empty(n, dtype=np.int64)
        feat_toks: list = []
        for i, toks in enumerate(lines):
            label_toks.append(toks[0])
            f = toks[1:]
            if f and f[0].startswith(b"qid:"):
                qid_vals.append(int(f[0][4:]))
                f = f[1:]
                has_qid = True
            elif has_qid:
                raise DMLCError("libsvm: qid must appear on every row or none")
            nnz[i] = len(f)
            feat_toks.extend(f)
        if has_qid and len(qid_vals) != n:
            # qid first appeared on a LATER row: rows before it had none —
            # the per-row check above only trips once has_qid is set
            raise DMLCError("libsvm: qid must appear on every row or none")
        if n == 0:
            return RowBlock(np.zeros(1, np.int64), np.empty(0, np.float32),
                            np.empty(0, self.index_dtype))
        # labels (with optional :weight)
        label_arr = np.array(label_toks)
        if any(b":" in t for t in label_toks):
            pairs = np.char.partition(label_arr, b":")
            labels = pairs[:, 0].astype(np.float32)
            wcol = pairs[:, 2]
            if np.any(wcol == b""):
                raise DMLCError("libsvm: label:weight must be set on every row or none")
            weights = wcol.astype(np.float32)
        else:
            labels = label_arr.astype(np.float32)
            weights = None
        # features idx[:val]
        if feat_toks:
            blob = b" ".join(feat_toks)
            ncolon = blob.count(b":")
            if ncolon == len(feat_toks):
                # every feature has a value: one splitted-token array,
                # index/value extracted as strided views of it
                nums = np.array(blob.replace(b":", b" ").split())
                index = nums[0::2].astype(np.int64)
                value = nums[1::2].astype(np.float32)
            elif ncolon == 0:
                # all-binary features
                index = np.array(feat_toks).astype(np.int64)
                value = None
            else:
                # mixed: treat missing values as 1.0
                parts = np.char.partition(np.array(feat_toks), b":")
                index = parts[:, 0].astype(np.int64)
                vals = parts[:, 2]
                value = np.where(vals == b"", b"1", vals).astype(np.float32)
        else:
            index = np.empty(0, np.int64)
            value = None
        index = _apply_indexing_mode(index, self.param.indexing_mode)
        offset = np.concatenate([[0], np.cumsum(nnz)])
        return RowBlock(
            offset=offset,
            label=labels,
            index=index.astype(self.index_dtype, copy=False),
            value=value,
            weight=weights,
            qid=np.array(qid_vals, np.int64) if has_qid else None,
        )


class CSVParser(TextParserBase):
    """Dense csv -> RowBlock with synthetic indices (csv_parser.h:85-146)."""

    def __init__(self, source: InputSplit, args: Dict[str, str] | None = None,
                 index_dtype=np.uint64):
        super().__init__(source, index_dtype)
        self.param = CSVParserParam()
        self.param.init(dict(args or {}), allow_unknown=True)
        check(self.param.format == "csv", "CSVParser: format must be csv")
        check(len(self.param.delimiter) == 1, "CSVParser: delimiter must be one char")
        check(
            self.param.label_column != self.param.weight_column
            or self.param.label_column < 0,
            "CSVParser: label_column must differ from weight_column",
        )
        self._dtype = np.dtype(self.param.dtype)

    def _native_supported(self) -> bool:
        # the native csv scanner emits float32 cells only
        return self.param.dtype == "float32"

    def set_emit_dense(self, num_col: int, batch_rows: int = 0,
                       dtype: str = "float32") -> bool:
        if self._native_supported() and self.use_native():
            self._emit_dense = int(num_col)
            return True
        return False

    def parse_chunk_native(self, chunk: bytes) -> Optional[RowBlock]:
        from dmlc_tpu import native

        out = native.parse_csv(chunk, delimiter=self.param.delimiter,
                               nthread=self._parse_nthread)
        if out is None:
            return None
        cells, owner = out
        n, ncol = cells.shape
        if n == 0:
            return RowBlock(np.zeros(1, np.int64), np.empty(0, np.float32),
                            np.empty(0, self.index_dtype))
        if self._emit_dense is not None:
            return self._cells_to_dense(cells, n, ncol, owner)
        return self._cells_to_block(cells, n, ncol)

    def _cells_to_dense(self, cells: np.ndarray, n: int, ncol: int,
                        owner) -> DenseBlock:
        return csv_cells_to_dense(
            cells, n, ncol, int(self._emit_dense),
            self.param.label_column, self.param.weight_column, owner)

    def parse_chunk_py(self, chunk: bytes) -> RowBlock:
        if chunk.startswith(b"\xef\xbb\xbf"):
            chunk = chunk[3:]
        delim = self.param.delimiter.encode()
        norm = chunk.replace(b"\r", b"\n")
        rows = [r for r in norm.split(b"\n") if r]
        n = len(rows)
        if n == 0:
            return RowBlock(np.zeros(1, np.int64), np.empty(0, np.float32),
                            np.empty(0, self.index_dtype))
        ncol = rows[0].count(delim) + 1
        # single vectorized conversion of the whole chunk
        tokens = np.array(norm.replace(delim, b" ").split())
        if len(tokens) != n * ncol:
            raise DMLCError(
                f"csv: ragged chunk - expected {n}x{ncol} cells, got {len(tokens)}"
            )
        cells = tokens.astype(self._dtype).reshape(n, ncol)
        return self._cells_to_block(cells, n, ncol)

    def _cells_to_block(self, cells: np.ndarray, n: int, ncol: int) -> RowBlock:
        return csv_cells_to_block(
            cells, n, ncol, self.param.label_column,
            self.param.weight_column, self.index_dtype)


def csv_cells_to_dense(cells: np.ndarray, n: int, ncol: int, num_col: int,
                       label_column: int, weight_column: int, owner) -> DenseBlock:
    """Dense cell matrix -> DenseBlock; zero-copy when there are no
    label/weight columns and the width already matches."""
    lc, wc = label_column, weight_column
    check(lc < ncol, f"csv: label_column {lc} >= num columns {ncol}")
    check(wc < ncol, f"csv: weight_column {wc} >= num columns {ncol}")
    label = cells[:, lc].astype(np.float32) if lc >= 0 else np.zeros(n, np.float32)
    weight = cells[:, wc].astype(np.float32) if wc >= 0 else None
    if lc < 0 and wc < 0 and ncol == num_col:
        return DenseBlock(cells, label, weight, hold=owner)
    feat_cols = [c for c in range(ncol) if c != lc and c != wc]
    k = min(len(feat_cols), num_col)
    x = np.zeros((n, num_col), np.float32)
    x[:, :k] = cells[:, feat_cols[:k]]
    return DenseBlock(x, label, weight, hold=owner)


# synthetic CSR skeletons for CSV blocks: every row has the same k column
# indices and k-strided offsets, and block geometry repeats (chunk-sized
# blocks), so one (n, k) build serves the whole stream — rebuilding them
# per block was ~2 array builds per MB of corpus on the hot path.
# Lock-guarded: chunks parse on multiple ParallelTextParser workers, and
# an unguarded clear()+insert raced (one worker could evict the entry
# another was inserting, or two could size-check a half-updated dict).
_CSV_SKELETON_CACHE: dict = {}
_CSV_SKELETON_LOCK = threading.Lock()


def _csv_skeleton(n: int, k: int, index_dtype):
    key = (n, k, np.dtype(index_dtype).str)
    with _CSV_SKELETON_LOCK:
        hit = _CSV_SKELETON_CACHE.get(key)
        if hit is not None:
            return hit
    # build OUTSIDE the lock (array builds are the expensive part);
    # concurrent builders of the same key converge on whichever insert wins
    index = np.tile(np.arange(k, dtype=index_dtype), n)
    # k == 0 (every column is label/weight) is a legal degenerate: all
    # offsets are 0 — np.arange with step 0 would raise instead
    offset = (np.arange(0, (n + 1) * k, k, dtype=np.int64)
              if k else np.zeros(n + 1, np.int64))
    # shared across every block of the stream — freeze so an
    # accidental in-place edit cannot corrupt sibling blocks
    index.flags.writeable = False
    offset.flags.writeable = False
    with _CSV_SKELETON_LOCK:
        hit = _CSV_SKELETON_CACHE.get(key)
        if hit is None:
            if len(_CSV_SKELETON_CACHE) > 64:  # block geometries are few
                _CSV_SKELETON_CACHE.clear()
            hit = (index, offset)
            _CSV_SKELETON_CACHE[key] = hit
    return hit


def csv_cells_to_block(cells: np.ndarray, n: int, ncol: int,
                       label_column: int, weight_column: int,
                       index_dtype) -> RowBlock:
    """Dense cell matrix -> RowBlock with synthetic indices 0..k
    (csv_parser.h:120-121); shared by the native and numpy paths."""
    lc, wc = label_column, weight_column
    check(lc < ncol, f"csv: label_column {lc} >= num columns {ncol}")
    check(wc < ncol, f"csv: weight_column {wc} >= num columns {ncol}")
    feat_cols = [c for c in range(ncol) if c != lc and c != wc]
    k = len(feat_cols)
    # the feature columns are CONTIGUOUS whenever label/weight sit at the
    # edges (or are absent) — the Criteo-like common case. A basic slice +
    # ascontiguousarray is ONE copy; the general fancy-index + astype path
    # is two full copies of the feature matrix per block.
    lo = min(feat_cols) if k else 0
    contiguous = k and feat_cols == list(range(lo, lo + k))
    if contiguous and cells.dtype == np.float32:
        values = np.ascontiguousarray(cells[:, lo:lo + k])
    else:
        values = cells[:, feat_cols].astype(np.float32, copy=False)
        values = np.ascontiguousarray(values)
    label = cells[:, lc].astype(np.float32) if lc >= 0 else np.zeros(n, np.float32)
    weight = cells[:, wc].astype(np.float32) if wc >= 0 else None
    index, offset = _csv_skeleton(n, k, index_dtype)
    return RowBlock(
        offset=offset, label=label, index=index,
        value=values.reshape(-1), weight=weight,
    )


class LibFMParser(TextParserBase):
    """libfm ``label field:idx:val`` -> RowBlock (libfm_parser.h:85-143)."""

    def __init__(self, source: InputSplit, args: Dict[str, str] | None = None,
                 index_dtype=np.uint64):
        super().__init__(source, index_dtype)
        self.param = LibFMParserParam()
        self.param.init(dict(args or {}), allow_unknown=True)
        check(self.param.format == "libfm", "LibFMParser: format must be libfm")

    def parse_chunk_native(self, chunk: bytes) -> Optional[RowBlock]:
        from dmlc_tpu import native

        d = native.parse_libfm(chunk, nthread=self._parse_nthread,
                               indexing_mode=self.param.indexing_mode)
        if d is None:
            return None
        return RowBlock(
            offset=d["offset"], label=d["label"], index=d["index"],
            value=d["value"], field=d["field"], hold=d["_owner"],
        )

    def parse_chunk_py(self, chunk: bytes) -> RowBlock:
        fast = (_token_table(chunk, stride=3)
                if self._fast_saw_hit
                or self._fast_rejects < _FAST_PATH_GIVEUP else None)
        if fast is not None:
            self._fast_saw_hit = True
            tokens, nnz, first_idx = fast
            labels, feats = _split_label_feats(tokens, first_idx)
            if len(feats):
                fields = feats[0::3].astype(np.int64)
                index = feats[1::3].astype(np.int64)
                value = feats[2::3].astype(np.float32)
            else:
                fields = np.empty(0, np.int64)
                index = np.empty(0, np.int64)
                value = None
        else:
            self._fast_rejects += 1
            lines = _tokenize_lines(chunk)
            n = len(lines)
            if n == 0:
                return RowBlock(np.zeros(1, np.int64), np.empty(0, np.float32),
                                np.empty(0, self.index_dtype))
            label_toks = []
            nnz = np.empty(n, dtype=np.int64)
            feat_toks: list = []
            for i, toks in enumerate(lines):
                label_toks.append(toks[0])
                nnz[i] = len(toks) - 1
                feat_toks.extend(toks[1:])
            labels = np.array(label_toks).astype(np.float32)
            if feat_toks:
                blob = b" ".join(feat_toks)
                check(blob.count(b":") == 2 * len(feat_toks),
                      "libfm: features must be field:index:value triples")
                nums = np.array(blob.replace(b":", b" ").split())
                fields = nums[0::3].astype(np.int64)
                index = nums[1::3].astype(np.int64)
                value = nums[2::3].astype(np.float32)
            else:
                fields = np.empty(0, np.int64)
                index = np.empty(0, np.int64)
                value = None
        mode = self.param.indexing_mode
        # heuristic applies to BOTH field and index (libfm_parser.h:130-143)
        if len(index):
            if mode > 0 or (mode < 0 and int(index.min()) > 0 and int(fields.min()) > 0):
                index = index - 1
                fields = fields - 1
        offset = np.concatenate([[0], np.cumsum(nnz)])
        return RowBlock(
            offset=offset, label=labels,
            index=index.astype(self.index_dtype, copy=False),
            value=value,
            field=fields.astype(self.index_dtype, copy=False),
        )


def annot_key(state: Optional[dict]) -> str:
    """Canonical comparison key of a resume annotation — ONE
    normalization (strip the per-wrapper ``blocks`` delivery counter,
    JSON round-trip so tuples/dict-order/non-JSON scalars collapse to
    their wire form, sorted dump) shared by :class:`BlockCacheIter`'s
    stored-annotation match and the data service's remote ``find``
    (:mod:`dmlc_tpu.service.frame` re-exports it). Two implementations
    here would let a checkpoint restore locally but not over the
    service, or vice versa."""
    norm = {k: v for k, v in (state or {}).items() if k != "blocks"}
    return json.dumps(json.loads(json.dumps(norm, default=str)),
                      sort_keys=True)


class _WrappedParserMixin:
    """The delegation + checkpoint contract shared by the parse-ahead
    wrappers (:class:`ThreadedParser`, :class:`ParallelTextParser`): both
    decorate a :class:`TextParserBase`, deliver its blocks with resume
    annotations riding along, and restore via byte-exact seek
    (``kind='split'``) or deterministic block replay (``kind='blocks'``).
    Subclasses provide ``_started()`` (background production running?) and
    ``_quiesce()`` (stop it; the next pull re-arms lazily)."""

    base: TextParserBase
    _delivered: int
    _last_annot: Optional[dict]

    def _started(self) -> bool:
        raise NotImplementedError

    def _quiesce(self) -> None:
        raise NotImplementedError

    def set_emit_dense(self, num_col: int, batch_rows: int = 0,
                       dtype: str = "float32") -> bool:
        if self._started():
            # production already running: flipping block kinds mid-stream
            # would mix racily, so decline — callers handle RowBlocks too
            return False
        try:
            return self.base.set_emit_dense(num_col, batch_rows, dtype)
        except TypeError:  # legacy one-arg bases keep working when wrapped
            return self.base.set_emit_dense(num_col)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        # quiesce production before re-pointing the base
        self._quiesce()
        self.base.reset_partition(part_index, num_parts)
        self._delivered = 0
        self._last_annot = None

    def state_dict(self) -> dict:
        if self._last_annot is not None:
            return dict(self._last_annot, blocks=self._delivered)
        # no annotation (epoch start, or a base without them): count
        # delivered blocks and replay on restore
        return {"kind": "blocks", "blocks": self._delivered}

    def load_state(self, state: dict) -> None:
        self._quiesce()
        if state.get("kind") == "split":
            # seek, don't replay: the base parser restores the split's
            # byte-exact position and production continues from there
            self.base.load_state(state)
            self._delivered = int(state.get("blocks", 0))
            self._last_annot = {k: v for k, v in state.items()
                                if k != "blocks"}
            return
        n = int(state["blocks"])
        self.base.before_first()
        for _ in range(n):
            if self.base.next_block() is None:
                break
        # re-quiesce: the serial replay accrued base parse seconds, which
        # must not contaminate a subclass's post-restore efficiency span
        self._quiesce()
        self._delivered = n
        self._last_annot = None

    @property
    def bytes_read(self) -> int:
        return self.base.bytes_read

    def close(self) -> None:
        self._quiesce()
        self.base.close()


class ThreadedParser(_WrappedParserMixin, Parser):
    """Parse-ahead decorator — analog of ThreadedParser (parser.h:70-126,
    ThreadedIter capacity 8)."""

    def __init__(self, base: TextParserBase, capacity: int = 8):
        self.base = base
        self._capacity = capacity
        self._delivered = 0
        self._last_annot = None  # resume_state of the last delivered block
        # the producer thread starts on first pull, not construction, so
        # callers can still configure the base (e.g. set_emit_dense) without
        # racing blocks already in flight
        self._iter: Optional[ThreadedIter] = None

    def _started(self) -> bool:
        return self._iter is not None

    def _quiesce(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
            self._iter = None

    def _ensure_iter(self) -> ThreadedIter:
        if self._iter is None:
            self._iter = ThreadedIter(
                self._produce, self.base.before_first,
                max_capacity=self._capacity)
        return self._iter

    def _produce(self, cell):
        block = self.base.next_block()
        if block is None:
            return False, None
        return True, block

    def next_block(self) -> Optional[RowBlock]:
        block = self._ensure_iter().next()
        if block is not None:
            self._delivered += 1
            # byte-exact checkpoints ride the blocks (TextParserBase
            # annotates each with the state just after it) — the base
            # parser's live position runs ahead of delivery
            self._last_annot = getattr(block, "resume_state", None)
        return block

    def before_first(self) -> None:
        self._ensure_iter().before_first()
        self._delivered = 0
        self._last_annot = None

    @property
    def stall_seconds(self) -> float:
        return self._iter.stall_seconds if self._iter is not None else 0.0

    def stage_seconds(self) -> Dict[str, float]:
        # the base parser's counters accrue on the producer thread; for a
        # consumer blocked on this wrapper they name what the producer was
        # doing during the wait (read IO vs parse CPU)
        return self.base.stage_seconds()


class ParallelTextParser(_WrappedParserMixin, Parser):
    """Data-parallel chunk-parse fan-out — the N-worker successor of
    :class:`ThreadedParser`'s single producer thread (the reference fans
    every chunk across OS threads, text_parser.h:110-146; tf.data names
    parallel input parsing the canonical fix for host-bound pipelines,
    arXiv:2101.12127).

    Chunks are pulled SERIALLY from the base parser's ``InputSplit`` (split
    reads stay ordered and checkpointable — the pull is the
    :class:`OrderedWorkerPool`'s serialized source stage, and each chunk's
    ``chunk_resume_state`` is captured at pull time, before fan-out), then
    ``parse_chunk`` runs concurrently across ``num_workers`` threads with
    the per-chunk native scanner pinned to one lane (chunk-level
    parallelism replaces intra-chunk threading). Blocks deliver strictly
    in pull order, so the three contracts layered on parsing hold
    unchanged:

    - byte-exact ``resume_state`` annotations ride each block exactly as
      :class:`TextParserBase` attaches them (state captured at pull time +
      in-order delivery == the serial annotation stream);
    - ``stage_seconds()`` stays the {read, parse} attribution feed, now
      aggregated thread-safely across workers, with a
      :meth:`parallel_stats` sideband (``parse_workers`` /
      ``parse_parallelism_efficiency``) so the scaling is measurable;
    - fault tolerance: stream-level retries happen below (ResilientStream
      in the filesystems), errors escaping them rethrow in delivery order
      for DeviceIter's bounded pipeline restart, and an opt-in
      ``restart_policy`` additionally heals retryable chunk-pull errors
      in-pool via the shared fast-forward machinery (restarts bump the
      ``parse_restarts`` / ``parse_giveups`` resilience counters).
    """

    def __init__(self, base: TextParserBase, num_workers: int = 2,
                 max_ahead: Optional[int] = None,
                 restart_policy: Optional["_resilience.RetryPolicy"] = None):
        self.base = base
        self.num_workers = max(1, int(num_workers))
        # a couple of chunks in flight per worker: enough to ride out
        # parse-time variance without ballooning peak memory
        self._ahead = (int(max_ahead) if max_ahead is not None
                       else max(4, 2 * self.num_workers))
        self._restart_policy = restart_policy
        # chunk-level fan-out replaces intra-chunk scanner threads
        base._parse_nthread = 1 if self.num_workers > 1 else 0
        self._pool: Optional[OrderedWorkerPool] = None
        self._delivered = 0
        self._last_annot = None  # resume_state of the last delivered block
        # thread-safe stage aggregation: the serial pull accrues 'read' on
        # whichever worker holds the pull lock, 'parse' accrues on every
        # worker concurrently — all under one lock, into the base's
        # counters so count-replay paths (which parse on the base) share
        # the same books
        self._stage_lock = threading.Lock()
        self._parse_t_first: Optional[float] = None
        self._parse_t_last: Optional[float] = None
        # busy seconds at the current span's start: efficiency is scoped
        # to the span since the last quiesce (epoch reset / repartition /
        # restore), not diluted by inter-epoch idle wall
        self._parse_busy0 = base._parse_seconds

    # ---------------- pool plumbing ----------------

    def _chunk_stream(self):
        """The pool's SERIAL source: the base parser's own pull-and-
        annotate step (one shared implementation — the checkpoint schema
        cannot diverge between engines). Runs under the pool's pull lock,
        so the split sees a single-threaded consumer and the base's
        read/byte counters have one writer."""
        while True:
            chunk, annot = self.base._pull_chunk()
            if chunk is None:
                return
            yield (chunk, annot)

    def _parse_work(self, item):
        """The pool's PARALLEL stage: chunk -> RowBlock (+ annotation)."""
        chunk, annot = item
        t0 = get_time()
        try:
            block = self.base.parse_chunk(chunk)
        finally:
            t1 = get_time()
            _telemetry.record_span("parse", t0, t1 - t0)
            with self._stage_lock:
                self.base._parse_seconds += t1 - t0
                if self._parse_t_first is None or t0 < self._parse_t_first:
                    self._parse_t_first = t0
                if self._parse_t_last is None or t1 > self._parse_t_last:
                    self._parse_t_last = t1
        if annot is not None and len(block) > 0:
            block.resume_state = annot
        return block

    def _ensure_pool(self) -> OrderedWorkerPool:
        if self._pool is None:
            src = self.base.source
            # the position this pool's stream starts at, for deterministic
            # restart replay: a live state_dict when the source has one,
            # else the chunk-synchronized state a seek-restore left behind
            # (ThreadedInputSplit exposes no state_dict but its
            # chunk_resume_state IS the restored position after
            # load_state). With neither — and the stream not at its
            # start — a before_first() rewind would replay from the WRONG
            # origin, so pool-level restart is disabled and errors
            # propagate to the outer healers (DeviceIter re-arms through
            # the same checkpoint machinery, which stays byte-exact).
            origin = None
            if hasattr(src, "state_dict"):
                try:
                    origin = src.state_dict()
                except (DMLCError, AttributeError):
                    origin = None
            if origin is None:
                origin = getattr(src, "chunk_resume_state", None)
            at_start = self.base._chunks_in == 0 and self._delivered == 0
            policy = (self._restart_policy
                      if (origin is not None and hasattr(src, "load_state"))
                      or at_start else None)
            counters0 = (self.base._bytes, self.base._chunks_in)
            first = [True]

            def factory():
                if not first[0]:
                    # bounded source restart: reposition at this pool's
                    # origin (NOT the epoch start — the pool may have been
                    # armed mid-stream by a seek-restore); the pool then
                    # fast-forwards the already-pulled chunks, which the
                    # counter rewind below makes re-countable
                    self.base._bytes, self.base._chunks_in = counters0
                    if origin is not None and hasattr(src, "load_state"):
                        src.load_state(origin)
                    else:
                        src.before_first()
                first[0] = False
                return self._chunk_stream()

            self._pool = OrderedWorkerPool(
                factory, self._parse_work,
                num_workers=self.num_workers, max_ahead=self._ahead,
                restart_policy=policy, counter_label="parse")
        return self._pool

    def _started(self) -> bool:
        return self._pool is not None

    def _quiesce(self) -> None:
        if self._pool is not None:
            self._pool.destroy()
            self._pool = None
        with self._stage_lock:
            # start a fresh efficiency span: the gap until the next epoch
            # parses is consumer idle, not worker inefficiency
            self._parse_t_first = None
            self._parse_t_last = None
            self._parse_busy0 = self.base._parse_seconds

    # ---------------- Parser contract ----------------
    # (set_emit_dense / reset_partition / state_dict / load_state / close
    # come from _WrappedParserMixin — identical contract to ThreadedParser)

    def next_block(self) -> Optional[RowBlock]:
        pool = self._ensure_pool()
        while True:
            block = pool.next()
            if block is None:
                return None
            if len(block) == 0:
                continue  # empty chunks produce no block (base parity)
            self._delivered += 1
            self._last_annot = getattr(block, "resume_state", None)
            return block

    def resize_parse_workers(self, num_workers: int) -> bool:
        """Live parse-tier resize (the autotuner's ``parse_workers``
        knob): the pool grows/shrinks in place — chunks keep pulling
        serially and delivering in pull order, so the block stream (and
        every checkpoint annotation riding it) is byte-identical to a
        static-width run. Always returns True."""
        n = max(1, int(num_workers))
        self.num_workers = n
        # chunk-level fan-out replaces intra-chunk scanner threads; at
        # width 1 the base may use its own scanner threading again
        self.base._parse_nthread = 1 if n > 1 else 0
        self._ahead = max(4, 2 * n)
        if self._pool is not None:
            self._pool.resize(n)
            self._pool.set_max_ahead(self._ahead)
        return True

    def before_first(self) -> None:
        self._quiesce()
        self.base.before_first()
        self._delivered = 0
        self._last_annot = None

    # ---------------- metrics ----------------

    def stage_seconds(self) -> Dict[str, float]:
        with self._stage_lock:
            return dict(self.base.stage_seconds())

    def parallel_stats(self) -> dict:
        """The scaling sideband: worker count plus measured parallel
        efficiency — parse busy-seconds over the CURRENT span (since the
        last epoch reset / repartition / restore) / (span * workers);
        1.0 = every worker parsing the whole span, None before any parse.
        ``parse_busy_seconds`` stays cumulative, matching
        ``stage_seconds()['parse']``."""
        with self._stage_lock:
            busy = self.base._parse_seconds
            span_busy = busy - self._parse_busy0
            span = ((self._parse_t_last - self._parse_t_first)
                    if self._parse_t_first is not None
                    and self._parse_t_last is not None else 0.0)
        eff = (min(1.0, span_busy / (span * self.num_workers))
               if span > 0 else None)
        return {
            "parse_workers": self.num_workers,
            "parse_busy_seconds": busy,
            "parse_span_seconds": span,
            "parse_parallelism_efficiency": eff,
        }

    @property
    def stall_seconds(self) -> float:
        return self._pool.stall_seconds if self._pool is not None else 0.0


class BlockCacheIter(Parser):
    """Parse-once decorator: cold epochs tee parsed RowBlocks into the
    columnar on-disk block cache (:mod:`dmlc_tpu.io.block_cache`); warm
    epochs serve the blocks back as zero-copy mmap-backed numpy views,
    bypassing the parser — and the source filesystem — entirely.

    One layer above :class:`~dmlc_tpu.io.cached_split.CachedInputSplit`:
    that cache stores raw chunks *before* the parser (warm passes still
    re-pay the full text-parse cost); this one stores the parsed arrays,
    the tf.data ``cache()`` position (arXiv:2101.12127).

    ``base`` is a :class:`Parser` or a zero-arg factory for one — the
    factory is only invoked on a cold pass (or a healing rebuild), so warm
    epochs never construct the parser chain. Selected by the
    ``block_cache=`` knob of :func:`create_parser` /
    :func:`~dmlc_tpu.data.iterators.create_row_block_iter`, the
    ``DMLC_TPU_BLOCK_CACHE`` env directory, or a ``#blockcache=<path>``
    URI suffix (docs/data.md).

    Contracts preserved across cold and warm epochs:

    - **byte-exact checkpoints**: each cold block's ``resume_state``
      annotation is stored in the cache footer and re-attached to the
      warm-served block, so a ``DeviceIter`` checkpoint taken warm equals
      one taken cold at the same row; :meth:`load_state` accepts both the
      warm ``block_cache`` kind and the parser chain's ``split`` kind
      (mapped to a block index by annotation match).
    - **stage attribution**: warm supply cost reports as the
      ``cache_read`` stage (``stage_seconds()``), which
      ``DeviceIter.stats()`` carries next to read/parse; ``cache_state``
      reports ``cold``/``warm``.
    - **fault tolerance**: a failed per-block CRC is a classified cache
      fault (:class:`~dmlc_tpu.utils.check.CacheCorruptionError`): the bad
      cache is dropped, the source re-parsed (skipping already-delivered
      blocks), a fresh cache rewritten, and ``cache_corruptions`` /
      ``cache_rebuilds`` counted in the resilience counters — consumers
      see an unbroken, byte-identical block stream.

    **Shuffle-native warm epochs** (the deterministic epoch planner,
    :mod:`dmlc_tpu.data.epoch`): with ``shuffle_seed`` set, every warm
    epoch serves the cached blocks through an
    :class:`~dmlc_tpu.data.epoch.EpochPlan` — a seeded block permutation
    plus a windowed intra-block row shuffle, both pure functions of
    ``(seed, epoch)``, with ``num_hosts > 1`` restricting this host to its
    disjoint round-robin shard of the one global order. A cold pass stays
    sequential while shadow-writing (the blocks do not exist to permute
    yet — the documented cold-epoch-0 caveat); the plan applies from the
    first warm epoch, and the epoch counter advances on every
    ``before_first``. Plan-mode blocks carry ``kind='epoch_plan'``
    resume annotations — ``(seed, epoch, plan position)`` — so a
    mid-epoch ``state_dict``/``load_state`` restore replays the stream
    byte-identically, including into a fresh pipeline (docs/data.md).
    """

    def __init__(self, base, cache_file: str, signature: Optional[dict] = None,
                 verify: bool = True, shuffle_seed: Optional[int] = None,
                 shuffle_window: int = 0, host_id: int = 0,
                 num_hosts: int = 1):
        from dmlc_tpu.data import epoch as _epoch
        from dmlc_tpu.io import block_cache as _block_cache

        self._bc = _block_cache
        self._ep = _epoch
        self._base_factory = base if callable(base) else (lambda: base)
        self._base: Optional[Parser] = base if not callable(base) else None
        self.cache_file = cache_file
        self._signature = signature
        self._verify = verify
        self._reader = None
        self._writer = None
        self._mode = "cold"
        self._pos = 0        # warm: next plan position / block index
        self._skip = 0       # cold: blocks to shadow-write but not deliver
        self._shadow = True  # shadow-writing allowed for the current pass
        self._delivered = 0
        self._last_annot: Optional[dict] = None
        self._bytes = 0      # warm bytes served from the cache
        self._cache_read_seconds = 0.0
        # ---- epoch-plan state (docstring: shuffle-native warm epochs) ----
        check(num_hosts >= 1 and 0 <= host_id < num_hosts,
              f"BlockCacheIter: host_id {host_id} not in [0, {num_hosts})")
        self._seed = None if shuffle_seed is None else int(shuffle_seed)
        self._window = int(shuffle_window)
        self._host_id = int(host_id)
        self._num_hosts = int(num_hosts)
        self._epoch = 0           # advances on every before_first
        self._plan = None         # per-epoch EpochPlan, built lazily warm
        self._seq_restore = False  # serve this epoch's rest sequentially
        #                           (a legacy/cold-order state was restored)
        self._cold_seen = 0       # cold: blocks seen this pass (pre-filter)
        # plan-ordered reads fan out over a small OrderedWorkerPool: a
        # permuted serve materializes every block (crc + gather/copy —
        # ~2x the sequential path's supply work), so loading block N+1
        # must overlap delivering block N or the shuffle tax lands
        # straight on the pipeline wall. Sequential warm serving stays
        # single-threaded zero-copy.
        self._plan_pool: Optional[OrderedWorkerPool] = None
        # validated by the knob table; live-resizable via
        # resize_plan_read_workers (the autotuner's plan_read knob)
        self.plan_read_workers = _knobs.resolve("plan_read_workers")
        self._cr_lock = threading.Lock()  # _cache_read_seconds writers
        # per-block uniform-column-pattern verdicts (epoch-invariant —
        # GIL-atomic dict ops, shared across plan-read workers)
        self._uniform_cols: Dict[int, bool] = {}
        # DMLC_TPU_TRACE=1 extends profiler annotations to the warm cache
        # path (docs/data.md trace modes); cached once, not per block
        self._annotate = _telemetry.trace_mode()[0] == "annotate"
        self._open_reader()

    @property
    def _plan_armed(self) -> bool:
        """A plan governs warm serving (seeded shuffle and/or sharding)."""
        return self._seed is not None or self._num_hosts > 1

    # ---------------- mode plumbing ----------------

    @property
    def cache_state(self) -> str:
        """``warm`` when blocks come from the cache, else ``cold`` —
        surfaced by ``DeviceIter.stats()['cache_state']``."""
        return "warm" if self._mode == "warm" else "cold"

    @property
    def plan_state(self) -> Optional[dict]:
        """The epoch planner's live identity — ``None`` when no plan is
        armed, else seed/epoch/position/sharding plus ``order``:
        ``'plan'`` when the current pass serves in plan order,
        ``'sequential'`` for cold passes and sequential restores.
        Surfaced by ``DeviceIter.stats()['shuffle_seed'/'epoch']``
        (docs/observability.md)."""
        if not self._plan_armed:
            return None
        sequential = (self._mode != "warm" or self._seq_restore
                      or self._seed is None)
        return {"shuffle_seed": self._seed, "epoch": self._epoch,
                "pos": self._pos, "window": self._window,
                "host_id": self._host_id, "num_hosts": self._num_hosts,
                "order": "sequential" if sequential else "plan"}

    @property
    def base(self) -> Parser:
        if self._base is None:
            self._base = self._base_factory()
        return self._base

    def _open_reader(self) -> bool:
        reader = self._bc.open_block_cache(
            self.cache_file, self._signature, verify=self._verify)
        if reader is None:
            self._mode = "cold"
            return False
        self._reader = reader
        self._mode = "warm"
        self._pos = 0
        self._uniform_cols.clear()  # verdicts are per published cache
        return True

    def _drop_reader(self) -> None:
        reader, self._reader = self._reader, None
        if reader is not None:
            reader.close()

    def _abort_writer(self) -> None:
        writer, self._writer = self._writer, None
        if writer is not None:
            writer.abort()

    def _ensure_writer(self):
        if self._writer is None and self._shadow:
            self._writer = self._bc.BlockCacheWriter(
                self.cache_file, signature=self._signature)
        return self._writer

    @staticmethod
    def _tee_block(writer, block, annot) -> None:
        """Shadow-write one parsed block. A batch-engine block carries
        its pre-encoded ``DMLCBC01`` span (``block.encoded``) — the tee
        is then one buffer append with the native crc, no Python
        re-encode (docs/io.md); every other engine goes through the
        segment encoder as before. Both paths produce byte-identical
        cache files."""
        encoded = getattr(block, "encoded", None)
        if encoded is not None:
            writer.add_block_encoded(encoded, resume=annot)
        else:
            writer.add_block(block.to_segments(), rows=len(block),
                             num_col=block.num_col, resume=annot)

    # ---------------- block delivery ----------------

    def next_block(self) -> Optional[RowBlock]:
        if self._mode == "warm":
            if self._plan_armed and not self._seq_restore:
                return self._next_warm_plan()
            return self._next_warm()
        return self._next_cold()

    def _next_warm(self) -> Optional[RowBlock]:
        reader = self._reader
        while self._pos < reader.num_blocks:
            i = self._pos
            if self._seq_restore and self._num_hosts > 1 \
                    and i % self._num_hosts != self._host_id:
                # sequential serving of a restored sharded cold stream:
                # the round-robin delivery filter of the cold pass applies
                # by sequential block index (== cold _cold_seen)
                self._pos += 1
                continue
            t0 = get_time()
            try:
                with _telemetry.profiler_annotation("dmlc_tpu.cache_read",
                                                    self._annotate):
                    segments = reader.load_segments(i)
            except CacheCorruptionError:
                dt = get_time() - t0
                self._cache_read_seconds += dt
                _telemetry.record_span("cache_read", t0, dt)
                self._heal_corruption()
                return self._next_cold()
            block = RowBlock.from_segments(segments, hold=reader.hold)
            # span export: the block's contiguous cache span rides along
            # so downstream single-materialization consumers (cache tee,
            # service wire encode) reuse the mmap bytes with zero
            # re-encode — the reader stays open for the block's lifetime
            # via hold, which pins the same mmap
            block.encoded = reader.block_encoded(i)
            annot = reader.resume(i)
            if annot is not None:
                block.resume_state = annot
            self._bytes += reader.block_nbytes(i)
            dt = get_time() - t0
            self._cache_read_seconds += dt
            _telemetry.record_span("cache_read", t0, dt)
            self._pos += 1
            self._delivered += 1
            self._last_annot = annot
            return block
        return None

    def _ensure_plan(self):
        if self._plan is None:
            self._plan = self._ep.EpochPlan(
                self._seed, self._epoch, self._reader.num_blocks,
                num_hosts=self._num_hosts, host_id=self._host_id,
                window=self._window)
        return self._plan

    def _plan_read_work(self, pos: int):
        """One plan-ordered block load — the pool's PARALLEL stage. All
        materialization happens HERE, inside the timed ``cache_read``
        span: either the row gather copies or ``copy=`` does, so the
        permuted pattern's page faults land under cache_read and never
        leak into convert (docs/data.md)."""
        plan = self._plan
        reader = self._reader
        bidx = plan.block_at(pos)
        t0 = get_time()
        try:
            with _telemetry.profiler_annotation("dmlc_tpu.cache_read",
                                                self._annotate):
                rows = reader.block_rows(bidx)
                rowperm = plan.row_order(bidx, rows)
                segments = reader.load_segments(
                    bidx, copy=rowperm is None and plan.permuted)
                # a row-gathered block may pass permutation-invariant id
                # arrays through as views — keep the mmap pinned then
                hold = (None if rowperm is None and plan.permuted
                        else reader.hold)
                block = RowBlock.from_segments(segments, hold=hold)
                if rowperm is not None:
                    uniform = self._uniform_cols.get(bidx)
                    if uniform is None:
                        # one read-only pass, memoized: blocks recur every
                        # epoch, so only the first epoch pays the scan
                        uniform = self._ep.uniform_column_pattern(block)
                        self._uniform_cols[bidx] = uniform
                    block = self._ep.permute_block_rows(
                        block, rowperm, uniform_columns=uniform)
        finally:
            dt = get_time() - t0
            with self._cr_lock:
                self._cache_read_seconds += dt
            _telemetry.record_span("cache_read", t0, dt)
        return block, reader.block_nbytes(bidx)

    def _quiesce_plan_pool(self) -> None:
        pool, self._plan_pool = self._plan_pool, None
        if pool is not None:
            pool.destroy()

    def _ensure_plan_pool(self) -> OrderedWorkerPool:
        if self._plan_pool is None:
            plan = self._ensure_plan()
            start = self._pos
            self._plan_pool = OrderedWorkerPool(
                lambda: iter(range(start, len(plan))),
                self._plan_read_work,
                num_workers=self.plan_read_workers,
                max_ahead=2 * self.plan_read_workers,
                counter_label="cache_read")
        return self._plan_pool

    def _next_warm_plan(self) -> Optional[RowBlock]:
        plan = self._ensure_plan()
        healed = 0
        while self._pos < len(plan):
            pool = self._ensure_plan_pool()
            try:
                item = pool.next()
            except CacheCorruptionError:
                check(healed == 0,
                      f"block cache {self.cache_file}: still corrupt "
                      "after a full rebuild")
                healed += 1
                self._quiesce_plan_pool()
                self._rebuild_cache(corruption=True)
                # the rebuild is deterministic: same blocks, same plan —
                # re-arm the pool at the failed position and retry
                continue
            if item is None:
                return None
            block, nbytes = item
            annot = plan.state(self._pos + 1)
            block.resume_state = annot
            self._bytes += nbytes
            self._pos += 1
            self._delivered += 1
            self._last_annot = annot
            return block
        return None

    def _rebuild_cache(self, corruption: bool = False) -> None:
        """Plan-mode cache (re)build: drain the source into a fresh cache
        in one silent pass, publish, reopen. Parsing is deterministic, so
        the rebuilt blocks are byte-identical to the lost ones and the
        plan stream continues unbroken at the same position."""
        if corruption:
            _resilience.record_event("cache_corruptions")
            _resilience.record_event("cache_rebuilds")
        self._drop_reader()  # releases the reader's eviction pin first
        self._bc._artifact_store(self.cache_file).discard(self.cache_file)
        self._abort_writer()
        base = self.base
        base.before_first()
        writer = self._bc.BlockCacheWriter(self.cache_file,
                                           signature=self._signature)
        try:
            while True:
                block = base.next_block()
                if block is None:
                    break
                check(hasattr(block, "to_segments"),
                      "epoch plan requires columnar RowBlocks: the base "
                      "parser emits an uncacheable block kind")
                self._tee_block(writer, block,
                                getattr(block, "resume_state", None))
            writer.finish()
        except BaseException:
            writer.abort()
            raise
        pos = self._pos  # _open_reader rewinds; the plan position survives
        check(self._open_reader(),
              f"block cache {self.cache_file}: rebuild did not publish a "
              "readable cache")
        self._pos = pos

    def _heal_corruption(self) -> None:
        """Warm block ``self._pos`` failed its integrity check: drop the
        bad cache, re-parse the source (skipping the blocks already
        delivered this epoch — chunk grouping is deterministic, so block k
        cold is block k warm), rewrite the full cache, and resume delivery
        exactly at the broken block."""
        _resilience.record_event("cache_corruptions")
        _resilience.record_event("cache_rebuilds")
        self._drop_reader()  # releases the reader's eviction pin first
        self._bc._artifact_store(self.cache_file).discard(self.cache_file)
        self._abort_writer()
        self._mode = "cold"
        self._shadow = True
        self._skip = self._pos
        self._pos = 0
        self._cold_seen = 0  # re-counts through the skipped prefix
        self.base.before_first()

    def _next_cold(self) -> Optional[RowBlock]:
        while True:
            block = self.base.next_block()
            if block is None:
                writer, self._writer = self._writer, None
                if writer is not None:
                    writer.finish()  # fsync + atomic publish
                return None
            if not hasattr(block, "to_segments"):
                # non-RowBlock emits (a base with dense/COO mode already
                # armed): pass through uncached — the cache stores the
                # columnar CSR layout only. An epoch plan cannot order
                # blocks that never reach the cache, so the combination
                # is rejected rather than silently serving unshuffled.
                check(not self._plan_armed,
                      "epoch plan requires columnar RowBlocks: the base "
                      "parser emits an uncacheable block kind")
                self._abort_writer()
                self._shadow = False
            annot = getattr(block, "resume_state", None)
            writer = self._ensure_writer()
            if writer is not None:
                self._tee_block(writer, block, annot)
            seen = self._cold_seen
            self._cold_seen += 1
            if self._skip > 0:
                self._skip -= 1
                continue
            if self._num_hosts > 1 and seen % self._num_hosts != self._host_id:
                # pod-sharded cold pass: every block is shadow-written,
                # but delivery is round-robin by sequential block index —
                # the hosts' cold streams stay disjoint and union to the
                # corpus even before the first planned warm epoch
                continue
            if self._num_hosts > 1 and annot is not None:
                # the checkpoint must carry the shard cursor: a plain
                # split state restored later could not reconstruct how
                # many blocks the filter had consumed (same shape as
                # state_dict's cold wrapping — one builder, no drift)
                annot = dict(self._plan_annot(0), cold=annot,
                             seen=seen + 1)
                block.resume_state = annot
            self._delivered += 1
            self._last_annot = annot
            return block

    def before_first(self) -> None:
        # an interrupted cold pass cannot publish: drop the partial tmp
        self._abort_writer()
        self._quiesce_plan_pool()
        if self._delivered or self._pos or self._cold_seen:
            # a pass actually ran: the rewind starts the NEXT epoch (the
            # plan's permutation is keyed by this counter, so each warm
            # epoch draws a fresh order; idempotent for back-to-back
            # rewinds with nothing delivered in between)
            self._epoch += 1
        self._plan = None
        self._seq_restore = False
        self._cold_seen = 0
        self._skip = 0
        self._delivered = 0
        self._last_annot = None
        if self._mode == "warm":
            self._pos = 0
            return
        if self._open_reader():
            return  # the completed cold pass published: serve warm now
        self._shadow = True
        self.base.before_first()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise DMLCError(
            "BlockCacheIter does not support reset_partition; the cache is "
            "bound to one partition (use the partition-qualified "
            ".splitN.partK cache per part)")

    # -------- checkpoint / resume --------

    def _plan_annot(self, pos: int) -> dict:
        """``(seed, epoch, plan position)`` — the epoch-plan resume
        annotation (docs/data.md): everything a fresh pipeline needs to
        replay the stream byte-identically from ``pos``. Delegates to the
        ONE shape builder (:func:`dmlc_tpu.data.epoch.plan_state_dict`)."""
        return self._ep.plan_state_dict(self._seed, self._window,
                                        self._epoch, pos, self._host_id,
                                        self._num_hosts)

    def state_dict(self) -> dict:
        if self._mode == "warm":
            if self._plan_armed and not self._seq_restore:
                return self._plan_annot(self._pos)
            return {"kind": "block_cache", "block": self._pos}
        if hasattr(self.base, "state_dict"):
            base_state = self.base.state_dict()
        else:
            base_state = {"kind": "blocks", "blocks": self._delivered}
        if self._num_hosts > 1:
            # the sharded cold pass filters delivery by sequential block
            # index: the checkpoint must carry that cursor too
            return dict(self._plan_annot(0), cold=base_state,
                        seen=self._cold_seen)
        return base_state

    _annot_key = staticmethod(annot_key)

    def _find_block(self, state: dict) -> Optional[int]:
        """Block index to resume at for a parser-chain annotation: the
        stored annotations mark the position just AFTER each block, so a
        match at block i resumes at i + 1."""
        if not state.get("chunks") and not state.get("blocks"):
            return 0  # epoch-start state
        key = self._annot_key(state)
        reader = self._reader
        for i in range(reader.num_blocks):
            annot = reader.resume(i)
            if annot is not None and self._annot_key(annot) == key:
                return i + 1
        return None

    def load_state(self, state: dict) -> None:
        kind = state.get("kind")
        if kind == "epoch_plan":
            self._load_plan_state(state)
            return
        if self._plan_armed:
            self._load_legacy_into_plan(state)
            return
        if kind == "block_cache":
            n = int(state["block"])
            self._abort_writer()
            if self._mode == "warm" or self._open_reader():
                self._pos = n
                self._delivered = n
                self._last_annot = self._reader.resume(n - 1) if n else None
                return
            # cache gone: rebuild from source, shadow-writing the skipped
            # prefix so the rebuilt cache is still complete
            self._shadow = True
            self._skip = n
            self._delivered = n
            self._last_annot = None
            self.base.before_first()
            return
        if self._mode == "warm":
            if kind == "blocks":
                # a delivered-block count maps 1:1 onto cache block indices
                # (warm serves the exact cold block sequence)
                n = int(state["blocks"])
                self._pos = n
                self._delivered = n
                self._last_annot = (self._reader.resume(n - 1)
                                    if n else None)
                return
            idx = self._find_block(state)
            if idx is not None:
                self._pos = idx
                self._delivered = idx
                self._last_annot = (self._reader.resume(idx - 1)
                                    if idx else None)
                return
            # annotation unknown to this cache (foreign/stale state):
            # fall back to the parser chain
            self._drop_reader()
            self._mode = "cold"
        # cold mid-stream seek: this pass can no longer produce a complete
        # cache — disable shadow-writing until the next epoch start
        self._abort_writer()
        self._shadow = False
        self._skip = 0
        self.base.load_state(state)
        self._delivered = int(state.get("blocks", state.get("chunks", 0))
                              or 0)
        self._last_annot = None

    def _load_plan_state(self, state: dict) -> None:
        """Restore a ``kind='epoch_plan'`` state. The state's plan
        identity (seed/window/epoch/sharding) is adopted WHOLESALE — the
        state IS the stream position, and replay must be byte-identical
        even into a pipeline constructed with different knobs."""
        check(state.get("unit") in (None, "block"),
              "epoch_plan state over snapshot BATCHES (unit='batch') "
              "cannot restore into the block cache's block stream — "
              "restore it into a snapshot-armed DeviceIter "
              "(docs/data.md snapshot section)")
        self._abort_writer()
        self._quiesce_plan_pool()
        seed = state.get("seed")
        self._seed = None if seed is None else int(seed)
        self._window = int(state.get("window", 0))
        self._host_id = int(state.get("host_id", 0))
        self._num_hosts = int(state.get("num_hosts", 1))
        self._epoch = int(state.get("epoch", 0))
        self._plan = None
        self._skip = 0
        if "cold" in state:
            # a checkpoint from a sharded cold pass: the base annotation
            # rides under 'cold', the shard cursor under 'seen'
            cold = state["cold"]
            seen = int(state.get("seen", 0))
            if self._mode == "warm" or self._open_reader():
                idx = self._find_block(cold) if cold is not None else None
                if idx is not None:
                    # the cache (now published) holds the cold stream:
                    # serve its remainder sequentially with the shard
                    # filter — exactly what the cold pass would deliver
                    self._seq_restore = True
                    self._pos = idx
                    self._cold_seen = idx
                    self._delivered = max(
                        0, -(-(idx - self._host_id) // self._num_hosts))
                    self._last_annot = dict(state)
                    return
                self._drop_reader()
                self._mode = "cold"
            # resume the sharded cold pass itself (mid-stream seek: this
            # pass can no longer publish a complete cache)
            self._seq_restore = False
            self._shadow = False
            self._mode = "cold"
            if cold is not None and hasattr(self.base, "load_state"):
                self.base.load_state(cold)
            self._cold_seen = seen
            self._delivered = max(
                0, -(-(seen - self._host_id) // self._num_hosts))
            self._last_annot = dict(state)
            return
        # plan-position state: (seed, epoch, pos) into the warm cache
        target = int(state["pos"])
        self._seq_restore = False
        if self._mode != "warm" and not self._open_reader():
            # cache gone: one silent full rebuild pass, then serve from
            # the plan position (parsing is deterministic — the rebuilt
            # blocks are the ones the state was taken over)
            self._rebuild_cache()
        self._pos = target
        self._delivered = target
        self._cold_seen = 0
        self._last_annot = dict(state) if target else None

    def _load_legacy_into_plan(self, state: dict) -> None:
        """A sequential-order state (legacy warm ``block_cache`` position,
        delivered-``blocks`` count, or a parser-chain ``split``/``chunks``
        annotation from a cold pass) restored into a plan-armed pipeline:
        the recorded position only exists in the SEQUENTIAL stream, so the
        remainder of this epoch serves sequentially — byte-identical to
        the stream the state came from — and the plan resumes at the next
        ``before_first`` (docs/data.md)."""
        kind = state.get("kind")
        self._abort_writer()
        self._quiesce_plan_pool()
        self._skip = 0
        if self._mode != "warm" and not self._open_reader():
            if kind in ("block_cache", "blocks"):
                # cache-relative positions only exist in the cache
                self._rebuild_cache()
            else:
                self._legacy_cold_seek(state)
                return
        if kind == "block_cache":
            idx: Optional[int] = int(state["block"])
        elif kind == "blocks":
            # delivered == sequential index in the unsharded legacy runs
            # these states come from
            idx = int(state["blocks"])
        else:
            idx = self._find_block(state)
        if idx is None:
            # annotation unknown to this cache (foreign/stale state):
            # fall back to the parser chain, mid-stream
            self._drop_reader()
            self._mode = "cold"
            self._legacy_cold_seek(state)
            return
        self._seq_restore = True
        self._pos = idx
        self._cold_seen = idx
        self._delivered = idx
        self._last_annot = (self._reader.resume(idx - 1) if idx else None)

    def _legacy_cold_seek(self, state: dict) -> None:
        """Mid-stream seek of the parser chain itself (the chunk count
        approximates the shard cursor — exact for the non-empty-chunk
        corpora the parsers emit 1:1)."""
        self._seq_restore = False
        self._shadow = False
        self.base.load_state(state)
        n = int(state.get("blocks", state.get("chunks", 0)) or 0)
        self._cold_seen = n
        self._delivered = n
        self._last_annot = None

    # ---------------- metrics ----------------

    def stage_seconds(self) -> Dict[str, float]:
        out = {"read": 0.0, "parse": 0.0}
        if self._base is not None:
            fn = getattr(self._base, "stage_seconds", None)
            if callable(fn):
                out.update(fn())
        out["cache_read"] = self._cache_read_seconds
        return out

    def parallel_stats(self) -> Optional[dict]:
        if self._mode != "warm" and self._base is not None:
            fn = getattr(self._base, "parallel_stats", None)
            if callable(fn):
                return fn()
        return None

    def resize_parse_workers(self, num_workers: int) -> bool:
        """Autotune passthrough: the parse tier only exists on cold
        passes — warm epochs bypass the parser entirely, so the knob
        reports unavailable (False) until a cold pass arms the base."""
        if self._base is None:
            return False
        fn = getattr(self._base, "resize_parse_workers", None)
        return bool(fn(num_workers)) if callable(fn) else False

    def resize_plan_read_workers(self, num_workers: int) -> bool:
        """Live plan-read-pool resize (the autotuner's
        ``plan_read_workers`` knob): applies to the running pool when a
        plan-ordered warm epoch is being served, and to every pool built
        after. Delivery stays in plan order either way."""
        n = max(1, int(num_workers))
        self.plan_read_workers = n
        if self._plan_pool is not None:
            self._plan_pool.resize(n)
            self._plan_pool.set_max_ahead(2 * n)
        return True

    @property
    def bytes_read(self) -> int:
        cold = self._base.bytes_read if self._base is not None else 0
        return cold + self._bytes

    def close(self) -> None:
        self._abort_writer()
        self._quiesce_plan_pool()
        self._drop_reader()
        if self._base is not None:
            self._base.close()


# ---------------- factory & registry (src/data.cc) ----------------

def _resolve_parse_workers(parse_workers: Optional[int]) -> int:
    """None -> DMLC_TPU_PARSE_WORKERS env (validated loudly by the knob
    table, :mod:`dmlc_tpu.utils.knobs`), else min(4, cpu count); 1 keeps
    today's single-producer ThreadedParser path."""
    return _knobs.resolve("parse_workers", parse_workers)


def _parallel_chunk_source(uri: str, part_index: int, num_parts: int,
                           **split_kw) -> InputSplit:
    """Chunk source for the parse fan-out. Plain SINGLE-FILE local text
    corpora get the zero-copy mmap reader (the serial pull must stay far
    above the pool's aggregate parse rate, and the stream engine's copying
    pull costs a core per ~500 MB/s; single-file windows make its chunk
    grouping byte-identical to the stream engine's, so per-chunk-sensitive
    semantics — indexing_mode=-1 auto-detection, per-chunk validation —
    cannot diverge between parse_workers settings). Everything else —
    multi-file corpora, remote URIs, chunk caches, shuffle decorators —
    keeps the standard split stack, whose chunks ARE the workers=1
    engine's."""
    plain = ("#" not in uri
             and not any(split_kw.get(k) for k in
                         ("shuffle", "num_shuffle_parts", "index_uri",
                          "recurse_directories")))
    if plain and uri.split("?", 1)[0] not in ("stdin",):
        try:
            split = create_mmap_text_split(
                uri, part_index, num_parts,
                chunk_bytes=split_kw.get("chunk_bytes", DEFAULT_CHUNK_BYTES))
            if len(split.files) == 1:
                return split
            split.close()  # multi-file: joins change chunk grouping
        except (DMLCError, OSError, ValueError):
            pass  # not local / not mappable: the stream stack handles it
    return create_input_split(
        uri, part_index, num_parts, "text", threaded=True, **split_kw)


def _make_text_parser(cls, threaded_default: bool):
    def factory(uri, args, part_index, num_parts, index_dtype, threaded,
                parse_workers=None, **split_kw):
        workers = _resolve_parse_workers(parse_workers)
        if threaded and threaded_default and workers > 1:
            source = _parallel_chunk_source(
                uri, part_index, num_parts, **split_kw)
            base = cls(source, args, index_dtype=index_dtype)
            return ParallelTextParser(base, num_workers=workers)
        source = create_input_split(
            uri, part_index, num_parts, "text",
            threaded=threaded, **split_kw,
        )
        base = cls(source, args, index_dtype=index_dtype)
        if threaded and threaded_default:
            return ThreadedParser(base)
        return base
    return factory


# CSV is registered unthreaded in the reference (data.cc:51-60 wraps libsvm
# and libfm only); we thread it anyway — the vectorized chunk parse benefits
# identically, and tests cover both paths.
PARSER_REGISTRY.register("libsvm", "libsvm text format")(
    _make_text_parser(LibSVMParser, True))
PARSER_REGISTRY.register("libfm", "libfm field:index:value format")(
    _make_text_parser(LibFMParser, True))
PARSER_REGISTRY.register("csv", "dense csv format")(
    _make_text_parser(CSVParser, True))


def _resolve_block_cache(spec: URISpec, part_index: int, num_parts: int,
                         explicit: Optional[str]) -> Optional[str]:
    """Block-cache path resolution: explicit ``block_cache=`` knob, then
    the ``#blockcache=<path>`` URI suffix, then the ``DMLC_TPU_BLOCK_CACHE``
    env **directory** (cache file auto-named from a hash of the URI+args).
    Multi-part loads get the same ``.splitN.partK`` qualification as
    ``#cachefile`` so parts never collide."""
    path = explicit if explicit is not None else spec.block_cache
    if path is None:
        env_dir = os.environ.get("DMLC_TPU_BLOCK_CACHE", "").strip()
        if env_dir:
            key_src = spec.uri + "?" + "&".join(
                f"{k}={v}" for k, v in sorted(spec.args.items()))
            key = hashlib.sha1(key_src.encode()).hexdigest()[:16]
            path = os.path.join(env_dir, f"{key}.blockcache")
    if path is None:
        return None
    if num_parts != 1:
        path = f"{path}.split{num_parts}.part{part_index}"
    return path


# intra-block row-shuffle window the legacy ``shuffle=True`` decorator arg
# maps onto (it asked for record-level shuffling; the plan's windowed row
# shuffle is its successor — docs/data.md deprecation note)
LEGACY_SHUFFLE_WINDOW = 4096


def _signature_args(spec: URISpec) -> dict:
    """URI args as they enter a cache/snapshot signature. The ``engine``
    selector is stripped: every engine emits byte-identical blocks AND
    identical chunk grouping (the A/B parity suites), so a cache written
    under one engine serves them all — baking the knob into the key
    would force a full cold re-parse on every engine switch."""
    args = dict(spec.args)
    args.pop("engine", None)
    return args


def create_parser(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type_: str = "auto",
    index_dtype=np.uint64,
    threaded: bool = True,
    parse_workers: Optional[int] = None,
    block_cache: Optional[str] = None,
    snapshot: Optional[str] = None,
    service: Optional[str] = None,
    service_job: Optional[str] = None,
    shuffle_seed: Optional[int] = None,
    shuffle_window: int = 0,
    pod_sharding=False,
    engine: Optional[str] = None,
    **split_kw,
) -> Parser:
    """Parser factory — analog of dmlc::Parser::Create (src/data.cc:62-85).

    ``type_='auto'`` resolves from the URI's ``format=`` arg, defaulting to
    libsvm (data.cc:70-76). URI args (``?k=v``) flow into the parser params.

    ``engine`` pins the text-parse engine (explicit knob > ``?engine=``
    URI arg > ``DMLC_TPU_PARSE_ENGINE`` env > ``auto``): ``native-batch``
    selects the chunk-batch SIMD parser that materializes block-cache
    segment spans directly (the cold-path engine — docs/data.md
    engine-selection table), ``native`` the streaming C++ reader,
    ``python`` the vectorized numpy engine, ``auto`` today's routing.
    Every engine emits byte-identical blocks, so the knob stays OUTSIDE
    the block-cache signature — one cache serves them all.

    ``parse_workers`` sizes the Python engine's data-parallel chunk-parse
    fan-out (:class:`ParallelTextParser`): 1 keeps the single-producer
    :class:`ThreadedParser`, None auto-sizes to ``DMLC_TPU_PARSE_WORKERS``
    or ``min(4, cpu count)``. The fully-native reader keeps its own C++
    threading and ignores the knob (docs/data.md).

    ``block_cache`` names a parse-once columnar block cache
    (:class:`BlockCacheIter`): the first epoch shadow-writes parsed
    blocks, warm epochs serve them back as zero-copy mmap views without
    parsing. Also selectable via a ``#blockcache=<path>`` URI suffix or
    the ``DMLC_TPU_BLOCK_CACHE`` env directory; the cache self-invalidates
    when the source files, partition, or parser config drift
    (docs/data.md block cache section).

    ``snapshot`` (or a ``#snapshot=<path>`` URI suffix) names a
    device-native snapshot store (:mod:`dmlc_tpu.io.snapshot`): the path
    and its staleness signature are stamped onto the returned parser as
    ``snapshot_path`` / ``snapshot_signature``, and a ``DeviceIter``
    built over it arms the store automatically — cold epochs shadow-write
    the post-convert device-layout batches, warm epochs stream them into
    HBM with zero parse AND zero convert work (docs/data.md snapshot
    section: block cache = parser output, snapshot = device layout).
    Composable with ``block_cache`` (the cold snapshot pass then reads
    the warm cache); NOT with ``shuffle_seed`` — the snapshot freezes one
    epoch's order, so shuffled snapshot epochs come from ``DeviceIter``'s
    own ``snapshot_shuffle_seed`` (a permutation over stored batches).

    ``service`` (or a ``#service=<host:port>`` URI suffix) names a
    RowBlock data-service dispatcher: parsing then happens on a remote
    parse-worker fleet and the returned parser is the drop-in
    :class:`~dmlc_tpu.service.client.ServiceParser` streaming parsed
    blocks over TCP — the dataset spec (URI, partitioning, parser
    config) is the DISPATCHER's; every other argument here is ignored
    (docs/service.md).

    ``shuffle_seed`` arms the deterministic epoch planner
    (:mod:`dmlc_tpu.data.epoch`) on the block cache: warm epochs serve
    the cached blocks through a seeded per-epoch block permutation plus
    a windowed intra-block row shuffle (``shuffle_window`` rows per
    window; 0 = block-level shuffle only), with ``(seed, epoch, plan
    position)`` recorded in the resume annotations for byte-identical
    mid-epoch restores. ``pod_sharding`` additionally restricts this
    host to its disjoint shard of the one global order — ``True``
    resolves ``(host_id, num_hosts)`` from the tracker env contract /
    ``jax.distributed`` (:func:`dmlc_tpu.parallel.distributed.
    pod_identity`), or pass an explicit ``(host_id, num_hosts)`` tuple.
    Both require ``block_cache``; the legacy split-layer ``shuffle`` /
    ``num_shuffle_parts`` decorator args combined with ``block_cache``
    are DEPRECATED and map onto these knobs for one release
    (docs/data.md shuffle-native cache section).
    """
    spec = URISpec(uri, part_index, num_parts)
    if service is None:
        service = spec.service
    if service is not None:
        # the DISPATCHER owns partitioning: silently handing every rank
        # the full dataset would duplicate training data — reject loudly
        check(part_index == 0 and num_parts == 1,
              "create_parser(service=...): client-side part_index/"
              "num_parts are not supported — the dispatcher owns the "
              "dataset's partitioning (docs/service.md)")
        # same for the epoch plan: silently dropping the knobs would hand
        # the user unshuffled epochs they asked to shuffle
        check(shuffle_seed is None and shuffle_window == 0
              and not pod_sharding,
              "create_parser(service=...): client-side shuffle_seed/"
              "shuffle_window/pod_sharding are not supported — the "
              "dispatcher owns the dataset's plan (Dispatcher(plan=...), "
              "docs/service.md plan distribution)")
        check(snapshot is None,
              "create_parser(service=...): client-side snapshot= is not "
              "supported — the dispatcher decides whether the fleet "
              "ships device-layout snapshot frames "
              "(Dispatcher(snapshot=...), docs/service.md)")
        from dmlc_tpu.service.client import ServiceParser
        from dmlc_tpu.service.dispatcher import DEFAULT_JOB

        # the registered job this client binds to (multi-tenant service,
        # docs/service.md): explicit knob > `?job=` URI arg > default
        job = (service_job if service_job is not None
               else spec.args.get("job", DEFAULT_JOB))
        return ServiceParser(service, job=job)
    if type_ == "auto":
        type_ = spec.args.get("format", "libsvm")
    bc_path = _resolve_block_cache(spec, part_index, num_parts, block_cache)
    snap_path = snapshot if snapshot is not None else spec.snapshot
    if snap_path is not None and num_parts != 1:
        snap_path = f"{snap_path}.split{num_parts}.part{part_index}"
    # the snapshot stores one epoch's batch order: a source-side shuffle
    # would change the order under it every epoch. Reject here — shuffled
    # snapshot epochs come from DeviceIter's snapshot_shuffle_seed, a
    # permutation over the STORED batches (docs/data.md).
    check(snap_path is None or shuffle_seed is None,
          "snapshot= cannot combine with shuffle_seed= (the snapshot "
          "freezes one epoch's batch order) — use DeviceIter's "
          "snapshot_shuffle_seed for shuffled snapshot epochs "
          "(docs/data.md)")
    if spec.block_cache is not None or spec.snapshot is not None:
        # the fragment is cache/snapshot routing sugar, not a chunk
        # cachefile: strip it so downstream engines see a plain URI
        uri = uri.split("#", 1)[0]
    def _stamp_snapshot(parser: Parser) -> Parser:
        """Arm the device-native snapshot store on the built parser:
        DeviceIter reads these attributes at construction (docs/data.md
        snapshot section). The signature is the block cache's source/
        config key — any source or parser-config drift invalidates the
        stored snapshot the same way it invalidates the cache."""
        if snap_path is not None:
            from dmlc_tpu.io import block_cache as _bc

            parser.snapshot_path = snap_path
            parser.snapshot_signature = _bc.source_signature(
                spec.uri, part_index, num_parts,
                format=type_, args=_signature_args(spec),
                index_dtype=np.dtype(index_dtype).str,
                chunk_bytes=int(split_kw.get("chunk_bytes",
                                             DEFAULT_CHUNK_BYTES)),
                split={k: v for k, v in sorted(split_kw.items())
                       if k != "chunk_bytes"})
        return parser

    if bc_path is None:
        check(shuffle_seed is None and shuffle_window == 0
              and not pod_sharding,
              "shuffle_seed/shuffle_window/pod_sharding require a "
              "block_cache: the epoch plan orders cached blocks "
              "(docs/data.md)")
        return _stamp_snapshot(_create_parser_uncached(
            uri, spec, part_index, num_parts, type_, index_dtype, threaded,
            parse_workers, engine=engine, **split_kw))
    if split_kw.get("shuffle") or split_kw.get("num_shuffle_parts"):
        # the old hard rejection ("the cache would freeze the first
        # epoch's order into every warm epoch") is gone: the epoch plan
        # IS shuffled warm serving. Legacy decorator args map onto the
        # plan knobs for one release, then the combination errors
        # (docs/data.md deprecation note).
        warnings.warn(
            "block_cache + shuffle decorator args (shuffle/"
            "num_shuffle_parts) now map onto the shuffle-native epoch "
            "plan; pass shuffle_seed/shuffle_window directly — this "
            "mapping will be removed in the next release (docs/data.md)",
            DeprecationWarning, stacklevel=2)
        if shuffle_seed is None:
            shuffle_seed = int(split_kw.get("seed", 0) or 0)
        if split_kw.pop("shuffle", None) and shuffle_window == 0:
            shuffle_window = LEGACY_SHUFFLE_WINDOW
        split_kw.pop("num_shuffle_parts", None)
        # the seed now lives in the plan: leaving it in split_kw would
        # bake it into the cache signature and force a full cold
        # re-parse on every seed change (plan knobs are signature-free)
        split_kw.pop("seed", None)
        get_logger().warning(
            "create_parser: mapping legacy shuffle decorator args onto "
            "the epoch plan (effective shuffle_seed=%s, shuffle_window=%s)",
            shuffle_seed, shuffle_window)
    check(shuffle_window == 0 or shuffle_seed is not None,
          "shuffle_window requires shuffle_seed: the row-shuffle rng is "
          "keyed by the seed, so a window alone would silently serve "
          "sequential epochs (docs/data.md)")
    host_id, num_hosts = 0, 1
    if pod_sharding:
        if isinstance(pod_sharding, (tuple, list)):
            host_id, num_hosts = int(pod_sharding[0]), int(pod_sharding[1])
        else:
            from dmlc_tpu.parallel.distributed import pod_identity

            host_id, num_hosts = pod_identity()
        check(num_parts == 1,
              "pod_sharding shards the one logical epoch at the cache "
              "block level; combining it with num_parts partitioning "
              "would double-shard — use one or the other (docs/data.md)")
    from dmlc_tpu.io import block_cache as _block_cache

    # engine/worker knobs (threaded, parse_workers, engine=) are
    # deliberately OUTSIDE the signature: every engine emits byte-identical
    # blocks AND identical chunk grouping (the A/B parity suites), so a
    # cache written by one serves them all. Split-layer config that CHANGES
    # the grouping or content — chunk_bytes above all: the heal and
    # count-based resume paths skip re-parsed blocks by index, which is
    # only sound when re-parse grouping matches the cached grouping — is
    # INSIDE it, so a drifted config invalidates instead of mis-serving.
    signature = _block_cache.source_signature(
        spec.uri, part_index, num_parts,
        format=type_, args=_signature_args(spec),
        index_dtype=np.dtype(index_dtype).str,
        chunk_bytes=int(split_kw.get("chunk_bytes", DEFAULT_CHUNK_BYTES)),
        split={k: v for k, v in sorted(split_kw.items())
               if k != "chunk_bytes"})

    def build() -> Parser:
        return _create_parser_uncached(
            uri, spec, part_index, num_parts, type_, index_dtype, threaded,
            parse_workers, engine=engine, **split_kw)

    # plan knobs stay OUTSIDE the signature: the plan orders blocks at
    # read time, so one cache serves every (seed, window, sharding)
    cached = BlockCacheIter(
        build, bc_path, signature=signature,
        shuffle_seed=shuffle_seed,
        shuffle_window=shuffle_window,
        host_id=host_id, num_hosts=num_hosts)
    # the parse width the lazily-built base WILL use: the autotuner seeds
    # its parse_workers knob from this before any cold pass builds the
    # parser (seeding from the table default would let a later "grow"
    # silently shrink an explicitly wider pool)
    cached.parse_workers_hint = _resolve_parse_workers(parse_workers)
    return _stamp_snapshot(cached)


def _create_parser_uncached(
    uri: str,
    spec: URISpec,
    part_index: int,
    num_parts: int,
    type_: str,
    index_dtype,
    threaded: bool,
    parse_workers: Optional[int],
    engine: Optional[str] = None,
    **split_kw,
) -> Parser:
    # engine selection (docs/data.md engine-selection table): explicit
    # create_parser(engine=) knob > ?engine= URI arg > the validated
    # DMLC_TPU_PARSE_ENGINE env accessor > auto
    engine = _knobs.parse_engine(
        engine if engine is not None else spec.args.get("engine"))
    split_uri = spec.uri
    if "#" in uri:
        # a `#cachefile` suffix activates the chunk cache at the split
        # layer (create_input_split re-derives the partition-qualified
        # name); every engine sources through the same split stack
        split_uri = f"{spec.uri}#{uri.split('#', 1)[1]}"
    if engine == "native-batch":
        from dmlc_tpu.data import batch_parser as _bp

        if _bp.batch_engine_eligible(type_, index_dtype, spec.args):
            return _bp.create_batch_parser(
                split_uri, spec.args, part_index, num_parts, type_,
                index_dtype=index_dtype, threaded=threaded,
                parse_workers=parse_workers, **split_kw)
        # the batch kernel cannot serve this config (format / dtype /
        # missing toolchain): fall back to the Python engine LOUDLY —
        # silently running a different native path would make the knob lie
        get_logger().warning(
            "engine=native-batch unavailable for format=%r "
            "index_dtype=%s (toolchain/format/dtype); using the Python "
            "engine", type_, np.dtype(index_dtype).str)
    # hot path: fully-native streaming pipeline (read+chunk+parse in C++)
    # for plain local text corpora; decorated/remote/unsupported URIs take
    # the Python engine below (identical chunk semantics, tested A/B)
    if (engine in ("auto", "native")
            and os.environ.get("DMLC_TPU_NO_NATIVE_READER", "0") in ("", "0")):
        from dmlc_tpu.data import native_parser as _np_mod

        if _np_mod.native_reader_eligible(uri, type_, threaded, split_kw):
            try:
                return _np_mod.NativeStreamParser(
                    spec.uri, spec.args, part_index, num_parts, type_,
                    index_dtype=index_dtype,
                    chunk_bytes=split_kw.get("chunk_bytes", DEFAULT_CHUNK_BYTES),
                )
            except DMLCError:
                pass  # fall back to the Python engine
        elif _np_mod.native_feed_eligible(uri, type_, threaded, split_kw):
            # remote corpora: Python range-reads feed the C++ chunk-parser
            try:
                return _np_mod.NativeFeedParser(
                    spec.uri, spec.args, part_index, num_parts, type_,
                    index_dtype=index_dtype,
                    chunk_bytes=split_kw.get("chunk_bytes", DEFAULT_CHUNK_BYTES),
                )
            except DMLCError:
                pass  # fall back to the Python engine
    if engine == "native":
        # reaching here means the fused reader could not serve this
        # config (decorated/remote/unsupported URI, threaded=False,
        # DMLC_TPU_NO_NATIVE_READER, or a load failure): fall back
        # LOUDLY, same contract as native-batch above
        get_logger().warning(
            "engine=native unavailable for uri=%r format=%r "
            "(URI/threading outside the fused reader's eligibility, "
            "DMLC_TPU_NO_NATIVE_READER, or toolchain); using the Python "
            "engine", uri, type_)
    entry = PARSER_REGISTRY.find(type_)
    if entry is None:
        raise DMLCError(
            f"unknown parser format {type_!r}; known: {list(PARSER_REGISTRY.list_names())}"
        )
    parser = entry.body(
        split_uri, spec.args, part_index, num_parts, index_dtype, threaded,
        parse_workers=parse_workers, **split_kw
    )
    if engine == "python":
        _pin_python_scanner(parser)
    return parser


def _pin_python_scanner(parser: Parser) -> None:
    """engine='python' means the pure-numpy chunk scanner, not just the
    registry stack: the registry parsers opportunistically route
    ``parse_chunk`` through the native C scanners (``use_native``), which
    would make the explicit knob lie — an operator isolating a suspected
    native-scanner bug, or a parity referee, must get numpy all the way
    down. Walk the decorator chain and pin the base's native probe off
    (the outputs are byte-identical either way — the A/B parity suites)."""
    base = parser
    while not isinstance(base, TextParserBase):
        nxt = getattr(base, "base", None)
        if nxt is None:
            return  # non-text stack (e.g. recordio): nothing to pin
        base = nxt
    base._native = False
