"""Multi-pass row-block iterators over parsed datasets.

Equivalent of reference RowBlockIter (data.h:254-274) with its two
implementations: BasicRowIter (in-RAM, src/data/basic_row_iter.h) and
DiskRowIter (page-cached on disk, src/data/disk_row_iter.h), plus the
``#cachefile`` URI dispatch of src/data.cc:88-107.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional

import numpy as np

from dmlc_tpu.data.parsers import Parser, create_parser
from dmlc_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_tpu.io.stream import open_stream
from dmlc_tpu.io.threaded_iter import ThreadedIter
from dmlc_tpu.io.uri import URISpec
from dmlc_tpu.utils import serializer as ser
from dmlc_tpu.utils.check import DMLCError, check, get_logger
from dmlc_tpu.utils.timer import ThroughputMeter

# 64 MB cache pages (disk_row_iter.h:32 kPageSize)
CACHE_PAGE_BYTES = 64 << 20
_CACHE_MAGIC = b"DMLCTPU-RBCACHE1"

# autotuned load passes re-tune the parse tier every this many blocks
# (one chunk == one block for the text engines, so this is a few tens of
# MB between decisions — frequent enough to converge inside one load)
AUTOTUNE_LOAD_BLOCKS = 32


class RowBlockIter:
    """Multi-pass iterator interface — analog of dmlc::RowBlockIter
    (data.h:254-274)."""

    def next_block(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def before_first(self) -> None:
        raise NotImplementedError

    @property
    def num_col(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            blk = self.next_block()
            if blk is None:
                return
            yield blk

    def close(self) -> None:
        pass


class BasicRowIter(RowBlockIter):
    """Drain the parser into RAM at init; each epoch yields one big block
    (src/data/basic_row_iter.h:35-42, 61-82).

    With ``autotune`` armed (arg or ``DMLC_TPU_AUTOTUNE=1``) and a
    live-resizable parse tier underneath, the load pass re-tunes its
    fan-out width every :data:`AUTOTUNE_LOAD_BLOCKS` blocks from the
    measured parallelism efficiency (docs/data.md autotune section);
    the decision record lands on :attr:`autotune`."""

    def __init__(self, parser: Parser, silent: bool = False,
                 autotune: Optional[bool] = None):
        from dmlc_tpu.data.autotune import (
            ParseTierTuner, efficiency_window,
        )
        from dmlc_tpu.utils import knobs as _knobs

        tuner = None
        if (_knobs.autotune_enabled(autotune)
                and callable(getattr(parser, "resize_parse_workers",
                                     None))):
            tuner = ParseTierTuner()
        meter = ThroughputMeter("load", silent=silent)
        container = RowBlockContainer()
        seen = 0
        eff_prev = None
        for block in parser:
            container.push_block(block)
            meter.add(parser.bytes_read - meter.bytes, len(block))
            seen += 1
            if tuner is not None and seen % AUTOTUNE_LOAD_BLOCKS == 0:
                stats_fn = getattr(parser, "parallel_stats", None)
                stats = stats_fn() if callable(stats_fn) else None
                # each decision reads THIS window's efficiency (the raw
                # sideband is cumulative and mixes widths after a live
                # resize — see autotune.efficiency_window)
                eff, eff_prev = efficiency_window(eff_prev, stats)
                new = tuner.decide(
                    eff, workers=(stats or {}).get("parse_workers"))
                parser.resize_parse_workers(new)
        self.block = container.to_block()
        meter.log_final()
        self.load_mb_per_sec = meter.mb_per_sec
        self.autotune = tuner.snapshot() if tuner is not None else None
        self._done = False
        parser.close()

    def next_block(self) -> Optional[RowBlock]:
        if self._done:
            return None
        self._done = True
        return self.block

    def before_first(self) -> None:
        self._done = False

    @property
    def num_col(self) -> int:
        return self.block.num_col


class DiskRowIter(RowBlockIter):
    """Build a page cache of serialized RowBlocks once, then stream pages
    with prefetch each epoch (src/data/disk_row_iter.h:95-141)."""

    def __init__(
        self,
        parser: Optional[Parser],
        cache_file: str,
        page_bytes: int = CACHE_PAGE_BYTES,
        silent: bool = False,
    ):
        self.cache_file = cache_file
        self.page_bytes = page_bytes
        self._num_col = 0
        self._iter: Optional[ThreadedIter] = None
        if not self._try_load_cache():
            check(parser is not None, f"no cache at {cache_file} and no parser given")
            self._build_cache(parser, silent)
            parser.close()
            check(self._try_load_cache(), "cache build failed to produce a readable cache")

    # -- cache format: [magic][num_col u64][npages u64][page offsets...][pages] --

    def _build_cache(self, parser: Parser, silent: bool) -> None:
        meter = ThroughputMeter("cache-build", log_every_mb=64.0, silent=silent)
        pages: List[int] = []
        container = RowBlockContainer()
        cur_bytes = 0
        with open_stream(self.cache_file, "w") as f:
            f.write(_CACHE_MAGIC)
            ser.write_scalar(f, 0, "uint64")  # num_col placeholder
            ser.write_scalar(f, 0, "uint64")  # npages placeholder

            def flush_page():
                nonlocal container, cur_bytes
                if len(container) == 0:
                    return
                pages.append(f.tell())
                container.to_block().save(f)
                container = RowBlockContainer()
                cur_bytes = 0

            for block in parser:
                container.push_block(block)
                self._num_col = max(self._num_col, block.num_col)
                cur_bytes += block.mem_cost_bytes()
                meter.add(block.mem_cost_bytes(), len(block))
                if cur_bytes >= self.page_bytes:
                    flush_page()
            flush_page()
            tail = f.tell()
            ser.write_scalar(f, len(pages), "uint64")
            for off in pages:
                ser.write_scalar(f, off, "uint64")
        # back-patch header (always little-endian, like the wire format)
        import struct

        with open(self.cache_file, "r+b") as f:
            f.seek(len(_CACHE_MAGIC))
            f.write(struct.pack("<QQ", self._num_col, tail))
        meter.log_final()

    def _try_load_cache(self) -> bool:
        f = open_stream(self.cache_file, "r", allow_null=True)
        if f is None:
            return False
        with f:
            magic = f.read(len(_CACHE_MAGIC))
            if magic != _CACHE_MAGIC:
                return False
            self._num_col = ser.read_scalar(f, "uint64")
            tail = ser.read_scalar(f, "uint64")
            if tail == 0:
                return False
            f.seek(tail)
            npages = ser.read_scalar(f, "uint64")
            self._page_offsets = [ser.read_scalar(f, "uint64") for _ in range(npages)]
        self._start_iter()
        return True

    def _read_pages(self):
        for off in self._page_offsets:
            with open_stream(self.cache_file, "r") as f:
                f.seek(off)
                yield RowBlock.load(f)

    def _start_iter(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        self._iter = ThreadedIter.from_factory(self._read_pages, max_capacity=2)

    def next_block(self) -> Optional[RowBlock]:
        return self._iter.next()

    def before_first(self) -> None:
        self._iter.before_first()

    @property
    def num_col(self) -> int:
        return int(self._num_col)

    def close(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
            self._iter = None


def create_row_block_iter(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type_: str = "auto",
    index_dtype=np.uint64,
    silent: bool = False,
    parse_workers: Optional[int] = None,
    block_cache: Optional[str] = None,
    snapshot: Optional[str] = None,
    service: Optional[str] = None,
    service_job: Optional[str] = None,
    shuffle_seed: Optional[int] = None,
    shuffle_window: int = 0,
    pod_sharding=False,
    autotune: Optional[bool] = None,
    **parser_kw,
) -> RowBlockIter:
    """RowBlockIter factory — analog of RowBlockIter::Create
    (data.h:267 -> src/data.cc:88-107).

    A ``#cachefile`` URI suffix selects the disk-cached iterator; the cache
    path is partition-qualified ``.splitN.partK`` (uri_spec.h:47-53).

    ``parse_workers`` sizes the Python engine's data-parallel chunk-parse
    fan-out exactly as in :func:`~dmlc_tpu.data.parsers.create_parser`
    (1 = single-producer parse-ahead; None = auto) — it applies to the
    load/cache-build pass; cached epochs read pre-parsed pages.

    ``block_cache`` (or a ``#blockcache=<path>`` URI suffix, or the
    ``DMLC_TPU_BLOCK_CACHE`` env directory) arms the parse-once columnar
    block cache on the parser the iterator drains: the first load parses
    text once, later loads serve mmap-backed parsed blocks
    (:class:`~dmlc_tpu.data.parsers.BlockCacheIter`, docs/data.md).

    ``service`` (or a ``#service=<host:port>`` URI suffix) streams the
    blocks from a disaggregated parse-worker fleet instead of parsing
    locally — the drained parser is the drop-in
    :class:`~dmlc_tpu.service.client.ServiceParser` and the dispatcher
    owns the dataset spec (docs/service.md).

    ``snapshot`` (or a ``#snapshot=<path>`` URI suffix) stamps the
    device-native snapshot store onto the parser exactly as in
    :func:`~dmlc_tpu.data.parsers.create_parser` — it takes effect when
    the parser feeds a ``DeviceIter`` (docs/data.md snapshot section);
    the row-block iterators themselves drain host blocks and ignore it.

    ``shuffle_seed`` / ``shuffle_window`` / ``pod_sharding`` arm the
    deterministic epoch planner on the block cache exactly as in
    :func:`~dmlc_tpu.data.parsers.create_parser` — the pod entry point:
    ``create_row_block_iter(uri, block_cache=..., shuffle_seed=...,
    pod_sharding=True)`` gives every host of an N-host pod its disjoint
    shard of one globally consistent shuffled epoch, with
    ``(host_id, num_hosts)`` resolved from the tracker env contract /
    ``jax.distributed`` (docs/data.md shuffle-native cache section).

    ``autotune`` (arg or ``DMLC_TPU_AUTOTUNE=1``) lets the load pass
    re-tune its parse fan-out online from the measured parallelism
    efficiency — the load-time face of the pipeline autotuner
    (docs/data.md autotune section); the decision record lands on the
    returned iterator's ``autotune`` attribute.
    """
    spec = URISpec(uri, part_index, num_parts)
    if service is None:
        service = spec.service
    if service is not None:
        # forward the plan knobs so the service branch REJECTS them
        # loudly (the dispatcher owns the plan) instead of silently
        # serving unshuffled epochs the user asked to shuffle
        parser = create_parser(uri, part_index, num_parts, type_,
                               index_dtype=index_dtype, service=service,
                               service_job=service_job,
                               shuffle_seed=shuffle_seed,
                               shuffle_window=shuffle_window,
                               pod_sharding=pod_sharding)
        return BasicRowIter(parser, silent=silent, autotune=autotune)
    # the cache here is the parsed-page cache (DiskRowIter); strip it before
    # the parser so the split layer does not also chunk-cache to the same
    # path — but a #blockcache= fragment belongs to the parser factory,
    # which resolves (and strips) it itself
    parser_uri = uri if spec.block_cache is not None else uri.split("#", 1)[0]
    if spec.cache_file is None:
        parser = create_parser(parser_uri, part_index, num_parts, type_,
                               index_dtype=index_dtype,
                               parse_workers=parse_workers,
                               block_cache=block_cache,
                               snapshot=snapshot,
                               shuffle_seed=shuffle_seed,
                               shuffle_window=shuffle_window,
                               pod_sharding=pod_sharding, **parser_kw)
        return BasicRowIter(parser, silent=silent, autotune=autotune)
    # the #cachefile page cache replays its frozen build-pass row order
    # every epoch — it cannot serve an epoch plan, and silently dropping
    # the knobs would hand a user unshuffled epochs they asked to shuffle
    check(shuffle_seed is None and shuffle_window == 0 and not pod_sharding,
          "shuffle_seed/shuffle_window/pod_sharding cannot combine with "
          "the #cachefile page cache (DiskRowIter replays its frozen "
          "build order); use block_cache= for shuffle-native warm epochs "
          "(docs/data.md)")
    if os.path.exists(spec.cache_file):
        return DiskRowIter(None, spec.cache_file, silent=silent)
    parser = create_parser(parser_uri, part_index, num_parts, type_,
                           index_dtype=index_dtype,
                           parse_workers=parse_workers,
                           block_cache=block_cache,
                           snapshot=snapshot,
                           shuffle_seed=shuffle_seed,
                           shuffle_window=shuffle_window,
                           pod_sharding=pod_sharding, **parser_kw)
    return DiskRowIter(parser, spec.cache_file, silent=silent)
