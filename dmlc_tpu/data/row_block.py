"""Sparse row blocks: the CSR batch unit parsers emit.

Equivalent of reference include/dmlc/data.h (Row data.h:74-162, RowBlock
data.h:175-236) and src/data/row_block.h (RowBlockContainer). Arrays are
numpy (host); the device shim (:mod:`dmlc_tpu.data.device`) converts blocks
to jax BCOO / padded-dense without another copy where possible.

Layout (CSR):
    offset  int64[n+1]   row i spans index/value[offset[i]:offset[i+1]]
    label   float32[n]
    weight  float32[n]   optional (None = unweighted, data.h:91)
    qid     int64[n]     optional query ids (data.h:93)
    field   index[nnz]   optional libfm field ids (data.h:102)
    index   uint32/uint64[nnz]  feature ids
    value   float32[nnz] optional (None = binary features, data.h:106)
"""

from __future__ import annotations

from typing import BinaryIO, Iterator, List, Optional

import numpy as np

from dmlc_tpu.utils import serializer as ser
from dmlc_tpu.utils.check import DMLCError, check


class Row:
    """One sparse row view — analog of dmlc::Row (data.h:74-162)."""

    __slots__ = ("label", "weight", "qid", "field", "index", "value")

    def __init__(self, label, weight, qid, field, index, value):
        self.label = label
        self.weight = weight
        self.qid = qid
        self.field = field
        self.index = index
        self.value = value

    def __len__(self) -> int:
        return len(self.index)

    def get_value(self, i: int) -> float:
        """value of the i-th entry; binary features read as 1 (data.h:132)."""
        return 1.0 if self.value is None else float(self.value[i])

    def sdot(self, weight_vec: np.ndarray) -> float:
        """Sparse dot with a dense weight vector (Row::SDot, data.h:146-161)."""
        w = weight_vec[self.index]
        if self.value is None:
            return float(np.sum(w))
        return float(np.dot(w, self.value))


class RowBlock:
    """CSR batch — analog of dmlc::RowBlock (data.h:175-236)."""

    def __init__(
        self,
        offset: np.ndarray,
        label: np.ndarray,
        index: np.ndarray,
        value: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        qid: Optional[np.ndarray] = None,
        field: Optional[np.ndarray] = None,
        hold=None,
    ):
        # `hold` pins foreign buffer owners (the native core's malloc'd
        # results) for as long as this block's views are alive
        self.hold = hold
        self.offset = np.asarray(offset, dtype=np.int64)
        self.label = np.asarray(label, dtype=np.float32)
        self.index = np.asarray(index)
        self.value = None if value is None else np.asarray(value, dtype=np.float32)
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float32)
        self.qid = None if qid is None else np.asarray(qid, dtype=np.int64)
        self.field = None if field is None else np.asarray(field)
        n = len(self.label)
        check(len(self.offset) == n + 1, "RowBlock: offset must have size n+1")
        nnz = int(self.offset[-1])
        check(len(self.index) == nnz, "RowBlock: index size mismatch with offset[-1]")
        for name in ("value",):
            arr = getattr(self, name)
            if arr is not None:
                check(len(arr) == nnz, f"RowBlock: {name} size mismatch")
        for name in ("weight", "qid"):
            arr = getattr(self, name)
            if arr is not None:
                check(len(arr) == n, f"RowBlock: {name} size mismatch")

    def __len__(self) -> int:
        return len(self.label)

    @property
    def num_nonzero(self) -> int:
        return int(self.offset[-1])

    @property
    def num_col(self) -> int:
        """max feature id + 1 (what downstream sizes weight vectors with)."""
        return int(self.index.max()) + 1 if len(self.index) else 0

    def __getitem__(self, i):
        """Row view (RowBlock::operator[], data.h:365-394); a slice returns
        the :meth:`slice` sub-block, so ``block[10:20]`` reads naturally."""
        if isinstance(i, slice):
            check(i.step in (None, 1), "RowBlock: stepped slices unsupported")
            begin, end, _ = i.indices(len(self))
            return self.slice(begin, max(begin, end))
        if i < 0:
            i += len(self)
        check(0 <= i < len(self), f"RowBlock: row {i} out of range")
        s, e = int(self.offset[i]), int(self.offset[i + 1])
        return Row(
            float(self.label[i]),
            float(self.weight[i]) if self.weight is not None else 1.0,
            int(self.qid[i]) if self.qid is not None else None,
            self.field[s:e] if self.field is not None else None,
            self.index[s:e],
            self.value[s:e] if self.value is not None else None,
        )

    def __iter__(self) -> Iterator[Row]:
        for i in range(len(self)):
            yield self[i]

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Sub-block of rows [begin, end) (RowBlock::Slice, data.h:216)."""
        check(0 <= begin <= end <= len(self), "RowBlock.slice: bad range")
        s, e = int(self.offset[begin]), int(self.offset[end])
        return RowBlock(
            offset=self.offset[begin:end + 1] - s,
            label=self.label[begin:end],
            index=self.index[s:e],
            value=self.value[s:e] if self.value is not None else None,
            weight=self.weight[begin:end] if self.weight is not None else None,
            qid=self.qid[begin:end] if self.qid is not None else None,
            field=self.field[s:e] if self.field is not None else None,
            hold=self.hold,
        )

    def mem_cost_bytes(self) -> int:
        """Approximate memory cost (RowBlock::MemCostBytes, data.h:203)."""
        cost = self.offset.nbytes + self.label.nbytes + self.index.nbytes
        for arr in (self.value, self.weight, self.qid, self.field):
            if arr is not None:
                cost += arr.nbytes
        return cost

    def to_dense(self, num_col: Optional[int] = None) -> np.ndarray:
        """Densify to [n, num_col] float32 (feeds the padded-dense device path)."""
        ncol = num_col if num_col is not None else self.num_col
        out = np.zeros((len(self), ncol), dtype=np.float32)
        rows = np.repeat(np.arange(len(self)), np.diff(self.offset))
        vals = self.value if self.value is not None else np.ones(len(self.index), np.float32)
        keep = self.index < ncol
        out[rows[keep], self.index[keep]] = vals[keep]
        return out

    # -- columnar segment round trip (io/block_cache.py format) --

    def to_segments(self) -> dict:
        """The block's arrays as the named columnar segments the block
        cache serializes (:mod:`dmlc_tpu.io.block_cache` SEGMENT_NAMES);
        absent optional arrays map to None."""
        return {
            "offset": self.offset, "label": self.label, "weight": self.weight,
            "qid": self.qid, "field": self.field, "index": self.index,
            "value": self.value,
        }

    @staticmethod
    def from_segments(segments: dict, hold=None) -> "RowBlock":
        """Rebuild a block from :meth:`to_segments` output. Segment dtypes
        already match the block layout, so mmap-backed views pass through
        zero-copy; ``hold`` pins their buffer owner (the reader's mmap)."""
        return RowBlock(
            offset=segments["offset"], label=segments["label"],
            index=segments["index"], value=segments.get("value"),
            weight=segments.get("weight"), qid=segments.get("qid"),
            field=segments.get("field"), hold=hold,
        )

    # -- binary round trip (row_block.h:189-215) --

    def save(self, stream: BinaryIO) -> None:
        payload = {
            "offset": self.offset, "label": self.label, "index": self.index,
            "value": self.value, "weight": self.weight, "qid": self.qid,
            "field": self.field,
        }
        ser.write_obj(stream, {k: v for k, v in payload.items()})

    @staticmethod
    def load(stream: BinaryIO) -> "RowBlock":
        d = ser.read_obj(stream)
        return RowBlock(
            offset=d["offset"], label=d["label"], index=d["index"],
            value=d["value"], weight=d["weight"], qid=d["qid"], field=d["field"],
        )


class DenseBlock:
    """A parsed batch already in the dense device layout [n, num_col].

    Emitted by parsers in dense mode (``set_emit_dense``) — the TPU-first
    fast path that skips CSR materialization entirely; the reference has no
    analog (its parsers always build CSR RowBlocks, src/data/row_block.h).
    """

    __slots__ = ("x", "label", "weight", "hold", "resume_state", "packed",
                 "device_span", "trace_ctx")

    def __init__(self, x: np.ndarray, label: np.ndarray,
                 weight: Optional[np.ndarray] = None, hold=None,
                 packed: bool = False):
        # packed: x is [n, num_col + 2] with label/weight as the trailing
        # columns (label/weight here alias those columns as views) — the
        # device path ships the ONE packed array (api.h DenseResult docs)
        self.x = x
        self.label = label
        self.weight = weight
        self.hold = hold
        self.packed = packed
        self.resume_state = None  # parser position just after this block
        # optional (service snapshot frames): the block's verbatim
        # container bytes + span layout + stored kind, for a
        # device_decode=True DeviceIter to decode in HBM instead of
        # shipping the host-decoded views (ops/device_decode)
        self.device_span = None
        # optional (service clients): the (trace_id, span_id) context of
        # the grant that produced this block (docs/observability.md)
        self.trace_ctx = None

    def __len__(self) -> int:
        return len(self.label)

    def slice(self, begin: int, end: int) -> "DenseBlock":
        """Row range view [begin, end), mirroring RowBlock.slice."""
        return DenseBlock(
            self.x[begin:end], self.label[begin:end],
            self.weight[begin:end] if self.weight is not None else None,
            hold=self.hold, packed=self.packed)


class CooBlock:
    """A parsed batch already in device-ready COO layout.

    Emitted by parsers in COO mode (``set_emit_coo``) — coordinates are
    int32 [nnz_padded, 2] (row, col) with OOB padding, ``values`` is None
    when the block is all-ones and elision is on (the device synthesizes
    them), and label/weight carry the bucket-padded row dim. The native
    pass assembles these off-GIL, replacing the numpy coordinate assembly
    of ops.sparse.block_to_bcoo_host on the convert thread. ``n_rows`` and
    ``nnz`` are the REAL counts. No reference analog (its parsers always
    build CSR, src/data/row_block.h); this is the TPU-first sparse path.
    """

    __slots__ = ("coords", "values", "label", "weight", "n_rows", "nnz",
                 "num_col", "hold", "resume_state", "row_ptr",
                 "trace_ctx")

    def __init__(self, coords: np.ndarray, values: Optional[np.ndarray],
                 label: np.ndarray, weight: np.ndarray, n_rows: int,
                 nnz: int, num_col: int, hold=None,
                 row_ptr: Optional[np.ndarray] = None):
        # csr_wire blocks: coords is cols-only [nnz_padded] and row_ptr is
        # [rows_padded + 1]; the device consumer rebuilds (row, col) pairs
        self.row_ptr = row_ptr
        self.coords = coords
        self.values = values
        self.label = label
        self.weight = weight
        self.n_rows = n_rows
        self.nnz = nnz
        self.num_col = num_col
        self.hold = hold
        self.resume_state = None
        self.trace_ctx = None

    @property
    def shape(self):
        """BCOO dense shape: (padded rows, declared width)."""
        return (len(self.label), self.num_col)

    def __len__(self) -> int:
        return self.n_rows


class RowBlockContainer:
    """Growable RowBlock accumulator — analog of src/data/row_block.h.

    Parsers append per-chunk numpy arrays; ``to_block`` concatenates once.
    """

    def __init__(self, index_dtype=np.uint64):
        self.index_dtype = index_dtype
        self._offsets: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        self._indices: List[np.ndarray] = []
        self._values: List[Optional[np.ndarray]] = []
        self._weights: List[Optional[np.ndarray]] = []
        self._qids: List[Optional[np.ndarray]] = []
        self._fields: List[Optional[np.ndarray]] = []
        self._holds: List = []  # buffer owners of pushed zero-copy views
        self.max_index = 0

    def push_block(self, block: RowBlock) -> None:
        if len(block) == 0:
            return
        if block.hold is not None:
            # the stored arrays are views over the block's foreign buffers;
            # keep their owner alive for the container's lifetime
            self._holds.append(block.hold)
        self._offsets.append(np.diff(block.offset))
        self._labels.append(block.label)
        self._indices.append(block.index)
        self._values.append(block.value)
        self._weights.append(block.weight)
        self._qids.append(block.qid)
        self._fields.append(block.field)
        if len(block.index):
            self.max_index = max(self.max_index, int(block.index.max()))

    def push_row(
        self, label: float, index, value=None, weight=None, qid=None, field=None
    ) -> None:
        index = np.asarray(index, dtype=self.index_dtype)
        self._offsets.append(np.array([len(index)], dtype=np.int64))
        self._labels.append(np.array([label], dtype=np.float32))
        self._indices.append(index)
        self._values.append(None if value is None else np.asarray(value, np.float32))
        self._weights.append(None if weight is None else np.array([weight], np.float32))
        self._qids.append(None if qid is None else np.array([qid], np.int64))
        self._fields.append(None if field is None else np.asarray(field, self.index_dtype))
        if len(index):
            self.max_index = max(self.max_index, int(index.max()))

    def __len__(self) -> int:
        return sum(len(l) for l in self._labels)

    def clear(self) -> None:
        self.__init__(self.index_dtype)

    @staticmethod
    def _cat_optional(parts: List[Optional[np.ndarray]], sizes: List[int], dtype):
        """Concatenate optional per-chunk arrays; missing chunks get defaults."""
        if all(p is None for p in parts):
            return None
        filled = []
        for p, n in zip(parts, sizes):
            if p is None:
                filled.append(np.ones(n, dtype) if dtype == np.float32 else np.zeros(n, dtype))
            else:
                filled.append(p)
        return np.concatenate(filled) if filled else None

    def to_block(self) -> RowBlock:
        if not self._labels:
            empty_idx = np.empty(0, dtype=self.index_dtype)
            return RowBlock(np.zeros(1, np.int64), np.empty(0, np.float32), empty_idx)
        row_counts = [len(l) for l in self._labels]
        nnz_counts = [len(i) for i in self._indices]
        offset = np.concatenate([[0], np.cumsum(np.concatenate(self._offsets))])
        label = np.concatenate(self._labels)
        index = np.concatenate(self._indices).astype(self.index_dtype, copy=False)
        value = self._cat_optional(self._values, nnz_counts, np.float32)
        weight = self._cat_optional(self._weights, row_counts, np.float32)
        qid = self._cat_optional(self._qids, row_counts, np.int64)
        field = self._cat_optional(self._fields, nnz_counts, self.index_dtype)
        return RowBlock(offset, label, index, value, weight, qid, field)

    def save(self, stream: BinaryIO) -> None:
        self.to_block().save(stream)

    @staticmethod
    def load(stream: BinaryIO) -> RowBlock:
        return RowBlock.load(stream)
