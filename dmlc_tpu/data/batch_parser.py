"""Chunk-batch native parse engine: ``DMLC_TPU_PARSE_ENGINE=native-batch``.

The cold-path promotion of ROADMAP item 3 (arXiv:2101.12127 input
pipelines must saturate the host; arXiv:2501.10546 cold-epoch cost): a
whole chunk goes to ``native/src/batch_parse.cc``, which SIMD-scans line
boundaries (AVX2/SSE2/NEON runtime dispatch + scalar fallback), fans the
lines across C++ threads, and materializes the parsed arrays DIRECTLY as
a block-cache v1 (``DMLCBC01``) segment span — canonical segment order,
64-byte-aligned array starts, zlib-compatible crc32. The returned
:class:`~dmlc_tpu.data.row_block.RowBlock` wraps those bytes zero-copy,
and the same bytes ride along as :class:`EncodedSegments` on
``block.encoded`` so downstream consumers append them verbatim:

- the block cache's cold tee writes the span with ONE file write and no
  Python re-encode (``BlockCacheWriter.add_block_encoded``);
- the data service's BLOCK frames carry the identical payload
  (:func:`dmlc_tpu.service.frame.encode_block_frame` fast path).

One materialization serves parse output, warm cache, and wire — the
"zero re-encode" cold path.

Contracts inherited from :class:`~dmlc_tpu.data.parsers.TextParserBase`
(this class is a chunk parser over an ordinary :class:`InputSplit`):
byte-exact ``resume_state`` annotations, ``stage_seconds()`` read/parse
attribution, ``state_dict``/``load_state``, and
:class:`~dmlc_tpu.data.parsers.ParallelTextParser` fan-out compatibility
(chunks pull serially, parse across pool workers with the per-chunk
native thread count pinned to 1, blocks deliver in pull order).

Emitted blocks are byte-identical to the Python engine's — the A/B
parity matrix in ``tests/test_native_batch.py`` pins libsvm (qid,
weights, indexing modes), csv, libfm, multi-partition, fault heals, and
the cold-tee cache bytes.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from dmlc_tpu.data.parsers import (
    CSVParserParam,
    LibFMParserParam,
    LibSVMParserParam,
    ParallelTextParser,
    Parser,
    TextParserBase,
    ThreadedParser,
    _parallel_chunk_source,
    _resolve_parse_workers,
)
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io.input_split import create_input_split
from dmlc_tpu.utils.check import DMLCError, check

#: formats the batch kernel speaks (native.BATCH_FMT keys)
BATCH_FORMATS = ("libsvm", "csv", "libfm")


class EncodedSegments:
    """One chunk's block-cache-v1 segment span, pre-encoded natively.

    ``data`` is a zero-copy uint8 view of the span (keep ``hold``
    referenced while it is alive), ``arrays`` maps segment name ->
    ``[dtype_str, span_offset, nbytes]`` (the footer/meta schema with
    offsets relative to the span start), ``crc`` is the zlib-compatible
    crc32 of ``data`` — exactly the per-block integrity word the cache
    footer stores.
    """

    __slots__ = ("data", "arrays", "crc", "rows", "num_col", "hold")

    def __init__(self, data, arrays: Dict[str, list], crc: int, rows: int,
                 num_col: int, hold):
        self.data = data
        self.arrays = arrays
        self.crc = crc
        self.rows = rows
        self.num_col = num_col
        self.hold = hold

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes)


class NativeBatchParser(TextParserBase):
    """Chunk-at-a-time SIMD batch parser emitting segment-backed
    RowBlocks (``engine='native-batch'``)."""

    def __init__(self, source, args: Optional[Dict[str, str]] = None,
                 fmt_name: str = "libsvm", index_dtype=np.uint64):
        from dmlc_tpu import native

        check(fmt_name in BATCH_FORMATS,
              f"native-batch engine does not support format {fmt_name!r}")
        # segments store the on-disk uint64 index layout; a caller that
        # wants a narrower dtype routes to the Python engine instead
        check(np.dtype(index_dtype) == np.dtype(np.uint64),
              "native-batch engine emits the cache's uint64 index layout; "
              "pass index_dtype=uint64 or use engine='python'")
        check(native.available(), "native core unavailable")
        super().__init__(source, index_dtype)
        self.fmt_name = fmt_name
        args = dict(args or {})
        if fmt_name == "libsvm":
            self.param = LibSVMParserParam()
        elif fmt_name == "csv":
            self.param = CSVParserParam()
        else:
            self.param = LibFMParserParam()
        self.param.init(args, allow_unknown=True)
        if fmt_name == "csv":
            # mirror CSVParser.__init__'s validation so bad configs fail
            # loudly here instead of deep inside the C scanner
            check(self.param.dtype == "float32",
                  "native-batch engine: csv dtype must be float32")
            check(len(self.param.delimiter) == 1,
                  "CSVParser: delimiter must be one char")
            check(self.param.label_column != self.param.weight_column
                  or self.param.label_column < 0,
                  "CSVParser: label_column must differ from weight_column")

    # the whole point of this engine is the native kernel: there is no
    # Python fallback half (a toolchain-less host never constructs one —
    # the factory routes to the Python engine instead)
    def parse_chunk(self, chunk) -> RowBlock:
        from dmlc_tpu import native

        out = native.parse_batch(
            chunk, self.fmt_name, nthread=self._parse_nthread,
            indexing_mode=getattr(self.param, "indexing_mode", 0),
            delimiter=getattr(self.param, "delimiter", ","),
            label_col=getattr(self.param, "label_column", -1),
            weight_col=getattr(self.param, "weight_column", -1))
        if out is None:  # the .so vanished mid-run: fail loudly
            raise DMLCError("native core unavailable")
        if out["rows"] == 0:
            return RowBlock(np.zeros(1, np.int64), np.empty(0, np.float32),
                            np.empty(0, self.index_dtype))
        owner = out["_owner"]
        block = RowBlock.from_segments(out["segments"], hold=owner)
        block.encoded = EncodedSegments(
            out["data"], out["arrays"], out["crc"], out["rows"],
            out["num_col"], owner)
        return block


def batch_engine_eligible(type_: str, index_dtype, args: Dict) -> bool:
    """True when the native-batch engine can serve this configuration
    (format, index dtype, csv value dtype, toolchain present)."""
    from dmlc_tpu import native

    if type_ not in BATCH_FORMATS:
        return False
    if np.dtype(index_dtype) != np.dtype(np.uint64):
        return False
    if type_ == "csv" and (args or {}).get("dtype", "float32") != "float32":
        return False
    return native.available()


def create_batch_parser(uri: str, args: Optional[Dict[str, str]],
                        part_index: int, num_parts: int, type_: str,
                        index_dtype=np.uint64, threaded: bool = True,
                        parse_workers: Optional[int] = None,
                        **split_kw) -> Parser:
    """Build the native-batch engine over the standard chunk-source
    stack: plain single-file local corpora get the zero-copy mmap split
    under the :class:`ParallelTextParser` fan-out (chunk grouping
    byte-identical to the stream engine's), everything else keeps the
    stream split — exactly the Python engine's sourcing, so caches,
    checkpoints, and the A/B parity matrix carry across engines."""
    workers = _resolve_parse_workers(parse_workers)
    if threaded and workers > 1:
        source = _parallel_chunk_source(uri, part_index, num_parts,
                                        **split_kw)
        base = NativeBatchParser(source, args, type_, index_dtype)
        return ParallelTextParser(base, num_workers=workers)
    source = create_input_split(uri, part_index, num_parts, "text",
                                threaded=threaded, **split_kw)
    base = NativeBatchParser(source, args, type_, index_dtype)
    if threaded:
        return ThreadedParser(base)
    return base
