"""Attribution-driven online pipeline autotuner (ROADMAP item 4).

tf.data's AUTOTUNE result (arXiv:2101.12127 §4) is that a feedback
controller reading per-stage cost attribution recovers near-hand-tuned
input throughput online — and the tf.data-service paper (arXiv:2210.14826)
adds that it must run *per host*, because a heterogeneous fleet cannot
share one static config. This repo has carried the sensors since PRs 1/3/6
(per-stage wall attribution, ``parse_parallelism_efficiency``, stall
diagnostics, resilience counters — all on the telemetry registry); this
module closes the loop: a measurement-driven controller that
``DeviceIter`` runs between epochs (and optionally every N batches) to
re-size the pipeline's pool widths and queue depths online, hill-climbing
every knob toward the only steady state that cannot be improved from the
host side: **``gap_stage == transfer``** — the consumer is bounded by the
device link, not by read/parse/convert/dispatch.

Control law, per :meth:`AutoTuner.step` window:

1. **Verify first.** If the previous step changed a knob, compare the
   window's delivery rate against the pre-change baseline: a regression
   beyond the hysteresis margin reverts the knob and blocks that move for
   ``hold_steps`` steps (oscillation damping — a knob can only flap once
   per hold window).
2. **Cooldown.** Resilience events in the window (retries, restarts,
   corruption heals) mean the measurements are poisoned by recovery work:
   the controller holds for ``cooldown_steps`` windows instead of tuning
   on a storm.
3. **Bound check.** If the consumer's input-wait fraction is under
   ``target_wait_frac``, or the dominant window cost is transfer, the
   pipeline is keeping the device fed — steady state, no-op.
4. **Climb.** Otherwise the stage owning the largest busy share maps to
   its knob (:data:`STAGE_KNOB`) and grows one step, bounded by the knob
   table's ``[lo, hi]`` caps (:func:`dmlc_tpu.utils.knobs.bounds`, i.e.
   CPU count / ``DMLC_TPU_AUTOTUNE_*`` env) — and the change enters the
   verification state of rule 1.

Every decision lands in a bounded history with its rationale, is surfaced
by ``DeviceIter.stats()['autotune']``, and is mirrored onto the telemetry
registry (``autotune_knob`` gauges, an ``autotune_steps`` counter, one
``autotune_step`` span per invocation) so a trace timeline shows *when*
each knob moved (docs/observability.md).

Knob *application* is injected (:class:`Knob` carries ``get``/``apply``
callbacks), so the controller is a pure decision engine: the synthetic
stage-profile tests drive :meth:`AutoTuner.step` directly, and the same
class serves ``DeviceIter`` (full knob set), ``bench.py --autotune``
(offline convergence), and any future host. The lighter
:class:`ParseTierTuner` covers the two hosts that only own a parse pool —
the data-service :class:`~dmlc_tpu.service.worker.ParseWorker` (re-tunes
between parts) and the ``create_row_block_iter`` load pass.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import check
from dmlc_tpu.utils.timer import get_time

# stage -> the knob that relieves it (docs/data.md autotune section).
# read shares parse's knob: both are supply work done by the parse
# fan-out's serial pull + workers, and more lanes overlap more of each.
STAGE_KNOB: Dict[str, str] = {
    "read": "parse_workers",
    "parse": "parse_workers",
    "cache_read": "plan_read_workers",
    "snapshot_read": "snapshot_read_workers",
    "convert": "convert_ahead",
    "dispatch": "prefetch",
    # device-decode busy is jit dispatch riding the transfer queue: a
    # deeper device_put lookahead overlaps it, same as dispatch
    "device_decode": "prefetch",
}

# per-stage fallback when the primary knob is not registered on this
# pipeline: a service-fed pipeline has no local parse fan-out, so its
# read stage (frame recv waits — see ServiceParser.stage_seconds) climbs
# the client's pipelined fetch window instead (docs/service.md Wire v2)
STAGE_KNOB_FALLBACK: Dict[str, str] = {
    "read": "service_pipeline_depth",
}

# busy-attribution stages the controller ranks when picking a move
# (transfer deliberately absent: it has no host-side knob — it IS the
# convergence target)
SUPPLY_STAGES = ("read", "cache_read", "snapshot_read", "parse",
                 "convert", "dispatch", "device_decode")

# controller actions that land on the audit ledger (docs/observability.md
# Decision ledger): the actual control moves and anomaly holds. The
# per-window bookkeeping actions (skip/hold/steady) stay in the local
# history only — they would flood the ledger with no-ops.
_LEDGER_ACTIONS = frozenset(
    ("grow", "revert", "revert_failed", "cooldown", "bound"))


class Knob:
    """One live-resizable pipeline control.

    ``get()`` returns the current value; ``apply(v)`` attempts to install
    ``v`` and returns True when it took effect (False = the owning
    component cannot resize right now — e.g. the parse tier is bypassed
    by a warm cache — and the controller blocks the move instead of
    looping on it). Bounds default to the knob table's
    (:func:`dmlc_tpu.utils.knobs.bounds`: table caps narrowed by the
    ``DMLC_TPU_AUTOTUNE_MIN/MAX_*`` env)."""

    __slots__ = ("name", "get", "apply", "lo", "hi", "step")

    def __init__(self, name: str, get: Callable[[], int],
                 apply: Callable[[int], bool],
                 lo: Optional[int] = None, hi: Optional[int] = None,
                 step: int = 1):
        self.name = name
        self.get = get
        self.apply = apply
        table_lo, table_hi = _knobs.bounds(name)
        self.lo = table_lo if lo is None else max(int(lo), table_lo)
        self.hi = table_hi if hi is None else min(int(hi), table_hi)
        self.step = max(1, int(step))


class AutoTuner:
    """The feedback controller (module docstring has the control law).

    ``step(window)`` consumes one measurement window::

        {"wall": float seconds, "batches": int delivered,
         "input_wait": float seconds the consumer measurably waited for
                       input (host-batch waits + sampled transfer
                       landings — DeviceIter's input_wait_seconds delta),
         "busy": {stage: float busy-seconds delta per pipeline stage},
         "transfer_est": float estimated whole-window transfer-wait
                         seconds (the sampled sideband scaled by its
                         period; 0.0 when unsampled),
         "resilience_events": int fault-recovery events in the window}

    and returns the decision dict it appended to :attr:`history`.
    Thread-safe: DeviceIter calls it from the consumer thread only, but
    ``snapshot()`` may race a step from a stats() reader.
    """

    def __init__(self, knobs: List[Knob], *,
                 scope: Optional[str] = None,
                 target_wait_frac: float = 0.05,
                 hysteresis: float = 0.05,
                 cooldown_steps: int = 2,
                 hold_steps: int = 4,
                 min_batches: int = 4,
                 max_history: int = 256):
        check(len({k.name for k in knobs}) == len(knobs),
              "AutoTuner: duplicate knob names")
        self.knobs: Dict[str, Knob] = {k.name: k for k in knobs}
        self.scope = scope
        self.target_wait_frac = float(target_wait_frac)
        self.hysteresis = float(hysteresis)
        self.cooldown_steps = max(0, int(cooldown_steps))
        self.hold_steps = max(1, int(hold_steps))
        self.min_batches = max(1, int(min_batches))
        self.max_history = max(8, int(max_history))
        self.history: List[dict] = []
        self._lock = threading.Lock()
        self._step_no = 0
        self._adjustments = 0          # grows + reverts actually applied
        self._pending: Optional[dict] = None   # change awaiting verification
        self._blocked: Dict[str, int] = {}     # knob -> step it unblocks at
        self._cooldown_until = 0
        self._steady_streak = 0
        self._last_gap: Optional[str] = None
        self._steps_counter = _telemetry.REGISTRY.counter(
            _telemetry.AUTOTUNE_STEP_METRIC, pipeline=scope or "")
        for k in self.knobs.values():
            self._publish_knob(k.name, k.get())

    # ---------------- telemetry mirrors ----------------

    def _publish_knob(self, name: str, value: int) -> None:
        _telemetry.REGISTRY.gauge(
            _telemetry.AUTOTUNE_KNOB_METRIC, knob=name,
            pipeline=self.scope or "").set(float(value))

    # ---------------- decision engine ----------------

    @property
    def converged(self) -> bool:
        """Two consecutive steady windows: the controller has nothing
        left to move (gap_stage is transfer / the consumer never waits)."""
        return self._steady_streak >= 2

    def current(self) -> Dict[str, int]:
        return {name: k.get() for name, k in self.knobs.items()}

    def _record(self, decision: dict) -> dict:
        decision["step"] = self._step_no
        self.history.append(decision)
        if len(self.history) > self.max_history:
            del self.history[: len(self.history) - self.max_history]
        self._last_gap = decision.get("gap_stage", self._last_gap)
        if decision["action"] in _LEDGER_ACTIONS:
            _telemetry.record_decision(
                "autotune", decision["action"],
                trigger={k: decision[k]
                         for k in ("knob", "from", "to", "gap_stage",
                                   "input_wait_frac") if k in decision},
                outcome=decision.get("rationale"),
                pipeline=self.scope or "", step=self._step_no)
        return decision

    def step(self, window: dict) -> dict:
        with self._lock:
            t0 = get_time()
            try:
                return self._step_locked(window)
            finally:
                self._steps_counter.inc()
                _telemetry.record_span("autotune_step", t0,
                                       get_time() - t0)

    def _step_locked(self, window: dict) -> dict:
        self._step_no += 1
        wall = float(window.get("wall", 0.0))
        batches = int(window.get("batches", 0))
        if wall <= 0.0 or batches < self.min_batches:
            # too little signal to act on (or to judge a pending change):
            # carry everything to the next window
            return self._record({
                "action": "skip",
                "rationale": f"window too small ({batches} batches in "
                             f"{wall:.3f}s; need >= {self.min_batches})",
            })
        throughput = batches / wall
        busy = dict(window.get("busy") or {})
        input_wait = float(window.get("input_wait", 0.0))
        wait_frac = min(1.0, input_wait / wall)
        transfer = float(window.get("transfer_est", 0.0))
        events = int(window.get("resilience_events", 0))

        # 1. verify the previous change before anything else
        if self._pending is not None:
            pend, self._pending = self._pending, None
            base = pend["throughput_before"]
            knob = self.knobs[pend["knob"]]
            if base > 0 and throughput < base * (1.0 - self.hysteresis):
                # the change hurt: revert and hold this knob so the pair
                # cannot oscillate (grow -> revert -> grow ...). A revert
                # the component refuses (the tier stopped being resizable
                # between windows, e.g. a cache went warm) is recorded as
                # such — history must never claim a value the knob does
                # not actually hold.
                ok = knob.apply(pend["from"])
                self._publish_knob(knob.name, knob.get())
                self._blocked[knob.name] = self._step_no + self.hold_steps
                self._adjustments += 1
                self._steady_streak = 0
                return self._record({
                    "action": "revert" if ok else "revert_failed",
                    "knob": knob.name,
                    "from": pend["to"],
                    "to": pend["from"] if ok else knob.get(),
                    "rationale": f"throughput {throughput:.2f} b/s fell "
                                 f">{self.hysteresis:.0%} below baseline "
                                 f"{base:.2f} b/s after the change; "
                                 f"holding {self.hold_steps} steps"
                                 + ("" if ok else " (revert REFUSED by "
                                    "the component — value stands)"),
                })
            # improvement (or within noise): the change stands — fall
            # through and keep climbing on this window's evidence

        # 2. fault-recovery work poisons the window: cool down
        if events > 0:
            self._cooldown_until = self._step_no + self.cooldown_steps
            self._steady_streak = 0
            return self._record({
                "action": "cooldown",
                "rationale": f"{events} resilience event(s) in the "
                             f"window; holding {self.cooldown_steps} "
                             f"step(s) until recovery noise clears",
            })
        if self._step_no < self._cooldown_until:
            return self._record({
                "action": "hold",
                "rationale": "in post-resilience cooldown",
            })

        # 3. bound check: the convergence target
        ranked = sorted(((busy.get(s, 0.0), s) for s in SUPPLY_STAGES),
                        reverse=True)
        top_busy, top_stage = ranked[0]
        if wait_frac <= self.target_wait_frac or transfer > top_busy:
            self._steady_streak += 1
            gap = "transfer"
            return self._record({
                "action": "steady", "gap_stage": gap,
                "input_wait_frac": round(wait_frac, 4),
                "rationale": (f"input wait {wait_frac:.1%} <= target "
                              f"{self.target_wait_frac:.0%}"
                              if wait_frac <= self.target_wait_frac else
                              f"transfer ({transfer:.3f}s) dominates "
                              f"every supply stage (top {top_stage} "
                              f"{top_busy:.3f}s)") + " — pipeline is "
                             "device-bound; nothing to tune",
            })
        self._steady_streak = 0

        # 4. climb: the largest supply stage with a movable knob
        for stage_busy, stage in ranked:
            if stage_busy <= 0.0:
                break
            knob = (self.knobs.get(STAGE_KNOB.get(stage, ""))
                    or self.knobs.get(STAGE_KNOB_FALLBACK.get(stage, "")))
            if knob is None:
                continue
            # >= so a knob blocked at step S with hold H stays held for
            # exactly H windows (S+1 .. S+H) — strict '>' held H-1 and
            # with hold_steps=1 none at all, letting a reverted knob
            # flap again on the very next window
            if self._blocked.get(knob.name, 0) >= self._step_no:
                continue
            cur = knob.get()
            if cur >= knob.hi:
                continue
            new = min(knob.hi, cur + knob.step)
            if not knob.apply(new):
                # the owning component cannot resize right now (e.g. the
                # parse tier is bypassed warm): hold the move, try the
                # next stage's knob on later windows
                self._blocked[knob.name] = self._step_no + self.hold_steps
                continue
            self._publish_knob(knob.name, knob.get())
            self._adjustments += 1
            self._pending = {"knob": knob.name, "from": cur, "to": new,
                             "throughput_before": throughput}
            return self._record({
                "action": "grow", "knob": knob.name, "from": cur,
                "to": new, "gap_stage": stage,
                "input_wait_frac": round(wait_frac, 4),
                "rationale": f"input wait {wait_frac:.1%} with "
                             f"'{stage}' owning the window "
                             f"({stage_busy:.3f}s busy) -> grow "
                             f"{knob.name} {cur} -> {new} "
                             f"(cap {knob.hi})",
            })
        return self._record({
            "action": "bound", "gap_stage": top_stage,
            "input_wait_frac": round(wait_frac, 4),
            "rationale": f"input-bound on '{top_stage}' but every mapped "
                         "knob is at its cap, blocked, or unavailable — "
                         "raise DMLC_TPU_AUTOTUNE_MAX_* to allow more",
        })

    # ---------------- reporting ----------------

    def snapshot(self, history: int = 16) -> dict:
        """The ``stats()['autotune']`` block: current knob values, step
        and adjustment counts, convergence, and the last ``history``
        decisions with their rationale (docs/observability.md schema)."""
        with self._lock:
            return {
                "enabled": True,
                "steps": self._step_no,
                "adjustments": self._adjustments,
                "converged": self.converged,
                "gap_stage": self._last_gap,
                "knobs": self.current(),
                "history": [dict(d) for d in self.history[-history:]],
            }


class ParseTierTuner:
    """Parse-pool-only tuner for hosts that own nothing else.

    The measured ``parse_parallelism_efficiency`` (busy-seconds /
    (span x workers), PR 3's sideband) is the whole signal: lanes running
    near-saturated (>= ``grow_at``) earn another lane, lanes mostly idle
    (<= ``shrink_at``) give one back, bounded by the knob table's
    ``parse_workers`` caps. Used by the data-service
    :class:`~dmlc_tpu.service.worker.ParseWorker` between parts (each
    part's parse is a clean measurement window) and by the
    ``create_row_block_iter`` load pass every N blocks."""

    def __init__(self, start: Optional[int] = None,
                 grow_at: float = 0.7, shrink_at: float = 0.35,
                 max_history: int = 64):
        self.lo, self.hi = _knobs.bounds("parse_workers")
        base = _knobs.resolve("parse_workers", start)
        self.workers = min(self.hi, max(self.lo, base))
        self.grow_at = float(grow_at)
        self.shrink_at = float(shrink_at)
        self.max_history = max(8, int(max_history))
        self.history: List[dict] = []

    def decide(self, efficiency: Optional[float],
               workers: Optional[int] = None) -> int:
        """One re-tune: returns the parse tier to use next."""
        w = self.workers if workers is None else max(1, int(workers))
        new, why = w, "efficiency in band"
        if efficiency is None:
            why = "no efficiency measurement (native/serial tier)"
        elif efficiency >= self.grow_at and w < self.hi:
            new = w + 1
            why = (f"lanes saturated (eff {efficiency:.2f} >= "
                   f"{self.grow_at}) -> grow (cap {self.hi})")
        elif efficiency <= self.shrink_at and w > self.lo:
            new = w - 1
            why = (f"lanes idle (eff {efficiency:.2f} <= "
                   f"{self.shrink_at}) -> shrink (floor {self.lo})")
        self.history.append({
            "workers": w, "next": new,
            "efficiency": None if efficiency is None
            else round(float(efficiency), 4),
            "rationale": why,
        })
        if new != w:
            _telemetry.record_decision(
                "parse_tier_tuner", "grow" if new > w else "shrink",
                trigger={"efficiency": round(float(efficiency), 4),
                         "workers": w},
                outcome=why, next_workers=new)
        if len(self.history) > self.max_history:
            del self.history[: len(self.history) - self.max_history]
        self.workers = new
        return new

    def snapshot(self, history: int = 8) -> dict:
        return {"enabled": True, "parse_workers": self.workers,
                "bounds": [self.lo, self.hi],
                "history": [dict(d) for d in self.history[-history:]]}


def efficiency_window(prev: Optional[dict],
                      stats: Optional[dict]) -> tuple:
    """Per-WINDOW parse-parallelism efficiency from the cumulative
    ``parallel_stats`` sideband: ``(efficiency_or_None, next_prev)``.

    ``parse_busy_seconds`` / ``parse_span_seconds`` are cumulative since
    the pool's last quiesce, and the raw ``parse_parallelism_efficiency``
    divides by the CURRENT width — so after a live resize the cumulative
    number mixes widths and goes stale. Callers re-deciding mid-stream
    (the ``BasicRowIter`` load pass) must difference consecutive
    snapshots through this helper; the between-parts callers
    (``ParseWorker``) get a fresh pool per part and can keep using the
    raw sideband."""
    stats = stats or {}
    busy = float(stats.get("parse_busy_seconds") or 0.0)
    span = float(stats.get("parse_span_seconds") or 0.0)
    workers = stats.get("parse_workers")
    cur = {"busy": busy, "span": span}
    base = prev or {"busy": 0.0, "span": 0.0}
    d_busy = busy - base["busy"]
    d_span = span - base["span"]
    if not workers or d_span <= 0.0:
        return None, cur
    return min(1.0, max(0.0, d_busy) / (d_span * int(workers))), cur


def env_config(knob_values: Dict[str, int]) -> Dict[str, str]:
    """Map tuned knob values onto their env variable names — the JSON
    block ``bench.py --autotune`` emits so a converged config is
    reusable by exporting it verbatim (docs/benchmarks)."""
    out = {}
    for name, value in sorted(knob_values.items()):
        spec = _knobs.KNOB_TABLE.get(name)
        if spec is not None and spec.env:
            out[spec.env] = str(int(value))
    return out
