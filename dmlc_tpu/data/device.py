"""Async host->HBM batch pipeline.

This is the TPU-native replacement for the reference's ThreadedIter-based
prefetch chain (SURVEY.md north star): parsed RowBlocks are rebatched to a
fixed shape on the host (so XLA compiles one step), converted to the chosen
device layout, and ``jax.device_put`` is issued ahead of consumption —
double-buffered by default — so the accelerator never waits on input.
``jax.device_put`` on TPU is asynchronous: it returns immediately while the
DMA proceeds, which is what lets a pure-Python loop overlap transfer with
compute. Stall time (consumer waiting on host data) is tracked, because the
BASELINE target is ">=90% host->HBM line-rate with zero input-bound stalls".

Layouts: 'dense' (padded [B, D], MXU-friendly), 'ell' (static-shape sparse),
'bcoo' (jax.experimental.sparse interop). See dmlc_tpu.ops.sparse.

Stage attribution (tf.data's per-stage cost naming, arXiv:2101.12127): every
second of consumer wall is attributed to a named pipeline stage — read,
cache_read, parse, convert, dispatch, transfer — in ``stats()['stages']``, so "the
pipeline is at X% of bound" always decomposes into which stage owns the gap
(VERDICT r5 weak #4: a 50% gap with stalls reading 0.000s is an artifact of
the measurement, not a property of the pipeline). The convert stage runs on
a small :class:`~dmlc_tpu.io.threaded_iter.OrderedWorkerPool` packing into a
ring of reusable preallocated host staging buffers, so layout conversion for
batch N+1 overlaps the dispatch (and DMA) of batch N.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import deque
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from dmlc_tpu.data import autotune as _autotune
from dmlc_tpu.data.parsers import Parser
from dmlc_tpu.data.row_block import (
    CooBlock, DenseBlock, RowBlock, RowBlockContainer,
)
from dmlc_tpu.io import block_cache as _block_cache
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.io import snapshot as _snapshot
from dmlc_tpu.io.threaded_iter import OrderedWorkerPool, ThreadedIter
from dmlc_tpu.ops import device_decode as _device_decode
from dmlc_tpu.ops.sparse import (
    EllBatch, block_to_bcoo_host, block_to_dense, block_to_ell,
)
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError, check
from dmlc_tpu.utils.timer import StageMeter, get_time


def _store_counters() -> dict:
    """The tiered store's counter triple for ``stats()['store']``
    (lazy import: the store manager sits above this module's io deps)."""
    from dmlc_tpu.store import store_counters

    return store_counters()


# resume marker: yielded by the natural-block producer for skipped blocks
# (identity-compared — value comparison would touch device arrays)
_SKIPPED = object()


def rebatch_blocks(
    blocks: Iterator[RowBlock], batch_size: int, drop_remainder: bool = False
) -> Iterator[RowBlock]:
    """Re-slice a stream of variable-size RowBlocks into fixed-size batches.

    The final partial batch is emitted as-is (callers pad via
    ``pad_rows_to``) unless ``drop_remainder``.
    """
    pending = RowBlockContainer()
    pending_rows = 0
    for block in blocks:
        pending.push_block(block)
        pending_rows += len(block)
        if pending_rows >= batch_size:
            merged = pending.to_block()
            pos = 0
            while pos + batch_size <= len(merged):
                yield merged.slice(pos, pos + batch_size)
                pos += batch_size
            pending = RowBlockContainer()
            pending_rows = len(merged) - pos
            if pending_rows:
                pending.push_block(merged.slice(pos, len(merged)))
    if pending_rows and not drop_remainder:
        yield pending.to_block()


def _require_bf16_exact(packed_col, src, what: str) -> None:
    """``packed_col`` is a just-assigned bfloat16 aux column, ``src`` the
    float32 source values: raise when the cast lost precision. Shared by
    the local convert-pool pack and the service worker's snapshot-frame
    pack, so no bf16 path can silently corrupt labels/weights."""
    if not np.array_equal(np.asarray(packed_col, dtype=np.float32),
                          np.asarray(src, dtype=np.float32)):
        raise DMLCError(
            f"bfloat16 aux packing: this batch's {what}s are not "
            "bf16-exact — packing would silently corrupt them. Keep the "
            f"{what}s float32-packable (pack_aux=False locally, or an "
            "f32 snapshot geometry on the service) or use "
            "x_dtype='float32' (docs/data.md pack_aux)")


def pack_dense_batches(blocks, batch_size: int, num_col: int,
                       dtype=None, drop_remainder: bool = False):
    """Pack a RowBlock stream into fixed-geometry ``[B, num_col + 2]``
    slabs (features | label | weight) — the exact layout
    :class:`PackedDenseBatch` ships and the snapshot store persists.
    Yields ``(packed, resume_annotation)`` per batch; the epoch tail is
    row-padded to ``B`` (pad rows carry weight 0 -> masked downstream)
    unless ``drop_remainder``. Used by the data service's snapshot frames
    (worker-side packing, docs/service.md) so a fleet can ship
    device-layout bf16 batches at half the CSR wire bytes. A bfloat16
    target validates label/weight losslessness per batch, like the local
    pack path."""
    B, nc = int(batch_size), int(num_col)
    dt = np.dtype(np.float32) if dtype is None else np.dtype(dtype)
    aux_check = dt.kind == "V" or dt.itemsize < 4  # narrower than f32
    for block in rebatch_blocks(iter(blocks), B,
                                drop_remainder=drop_remainder):
        x, y, w = block_to_dense(block, nc,
                                 pad_rows_to=(B if len(block) != B
                                              else None))
        packed = np.empty((B, nc + 2), dt)
        packed[:, :nc] = x
        packed[:, nc] = y
        packed[:, nc + 1] = w
        if aux_check:
            _require_bf16_exact(packed[:, nc], y, "label")
            _require_bf16_exact(packed[:, nc + 1], w, "weight")
        yield packed, getattr(block, "resume_state", None)


_RING_FREE = object()  # sentinel: slot never attached / explicitly released


class _StagingRing:
    """Ring of reusable preallocated host staging buffers.

    Convert workers pack batches into these instead of allocating fresh
    arrays per batch. A slot cycles free -> packing (acquired) -> in-flight
    (attached to the device array built from it) -> free again when that
    device array is garbage-collected — reuse is gated on OBJECT LIFETIME
    via a weakref, never on elapsed time, so a backend that aliases or
    defers reading the host buffer (zero-copy CPU puts, an in-flight DMA)
    can never observe a recycled buffer being overwritten. When every slot
    is busy a fresh unpooled allocation is handed out (counted as a miss):
    the ring is an allocator fast path, never a blocking resource.
    """

    def __init__(self, make_bufs, depth: int):
        self._make = make_bufs
        self._depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._slots: list = []  # [bufs_dict, _RING_FREE | None | weakref]
        self.hits = 0
        self.misses = 0

    def acquire(self) -> dict:
        with self._lock:
            for slot in self._slots:
                refs = slot[1]
                if refs is None:  # acquired, not yet attached: busy
                    continue
                if refs is _RING_FREE or all(r() is None for r in refs):
                    slot[1] = None
                    self.hits += 1
                    return slot[0]
            if len(self._slots) < self._depth:
                bufs = self._make()
                self._slots.append([bufs, None])
                return bufs
            self.misses += 1
            return self._make()

    def attach(self, bufs: dict, handles) -> None:
        """Tie the slot to EVERY device object built from it (a batch can
        fan one slot's buffers into several arrays — x/y/w — and any one
        of them staying alive must pin the whole slot); ``handles=None``
        or empty releases the slot immediately (batch dropped before any
        transfer, e.g. a resume replay)."""
        with self._lock:
            for slot in self._slots:
                if slot[0] is bufs:
                    if not handles:
                        slot[1] = _RING_FREE
                    else:
                        try:
                            slot[1] = [weakref.ref(h) for h in handles]
                        except TypeError:  # un-weakref-able handle: retire
                            slot[1] = None  # the slot rather than risk reuse
                    return

    def set_depth(self, depth: int) -> None:
        """Live depth resize (the autotuner's staging-ring follow-on to
        prefetch/convert_ahead changes): growing allows more pooled
        slots to be allocated on demand; shrinking only stops NEW slots —
        already-allocated ones keep recycling (their memory is already
        paid for, and in-flight weakrefs must stay valid)."""
        with self._lock:
            self._depth = max(1, int(depth))

    def stats(self) -> dict:
        with self._lock:
            return {"depth": len(self._slots), "hits": self.hits,
                    "misses": self.misses}


# dense rebatch part descriptors: ("packed", x2d) carries a [n, D+2] slab
# (features|label|weight columns), ("arr", x, y, w_or_None) split views,
# ("blk", RowBlock) defers the CSR->dense scatter to the convert worker

def _plen(part) -> int:
    if part[0] == "arr":
        return len(part[2])
    return len(part[1])


def _pslice(part, a: int, b: int):
    kind = part[0]
    if kind == "packed":
        return ("packed", part[1][a:b])
    if kind == "arr":
        return ("arr", part[1][a:b], part[2][a:b],
                part[3][a:b] if part[3] is not None else None)
    return ("blk", part[1].slice(a, b))


def _adopt_pipeline_scope(source, label: str, max_depth: int = 8) -> None:
    """Stamp a pipeline label onto the thread primitives a parser chain
    built BEFORE its DeviceIter existed (a threaded input split starts
    prefetching at parser construction). Walks the chain's wrapper
    attributes and calls ``adopt_scope`` on every ThreadedIter /
    OrderedWorkerPool found — monotonic None -> label, so primitives that
    already have a scope are untouched."""
    seen = set()
    stack = [(source, 0)]
    while stack:
        obj, depth = stack.pop()
        if obj is None or id(obj) in seen or depth > max_depth:
            continue
        seen.add(id(obj))
        adopt = getattr(obj, "adopt_scope", None)
        if callable(adopt):
            adopt(label)
        for name in ("source", "base", "_base", "_iter", "_pool"):
            stack.append((getattr(obj, name, None), depth + 1))


def _csr_coords_impl(cols, row_ptr):
    """Rebuild BCOO (row, col) coordinate pairs from the CSR wire format.

    ``row_ptr`` is [rows_padded + 1] with pad rows pointing at the real
    nnz, so row id of entry j = #{i >= 1 : row_ptr[i] <= j} — computed as
    a scatter-add of 1 at each row start followed by an inclusive prefix
    sum. Entries past the real nnz count every row and land on the OOB
    row rows_padded, which every BCOO op masks (same padding contract as
    the native (row, col) emit, native/src/api.h CooResult). O(nnz) VPU
    work per batch in exchange for HALF the coordinate bytes over the
    host->device link.
    """
    import jax.numpy as jnp

    nnz = cols.shape[0]
    incr = jnp.zeros((nnz + 1,), jnp.int32).at[row_ptr[1:]].add(
        1, mode="drop")
    rows = jnp.cumsum(incr)[:nnz]
    return jnp.stack([rows, cols], axis=1)


_csr_coords = jax.jit(_csr_coords_impl)


@jax.tree_util.register_pytree_node_class
class PackedDenseBatch:
    """One [B, num_col + 2] device array: features in columns [:num_col],
    label in column num_col, weight in column num_col + 1.

    Shipping the batch as ONE array instead of [x, y, w] removes the
    per-array device_put overhead (measured ~2x on the 3-array put,
    benchmarks/bench_transfer_floor.py aux leg). Registered as a pytree so
    it passes straight into jit: ``x, y, w = batch`` works both eagerly
    and under trace (the slices then fuse into the consumer's graph for
    free — the TPU-first contract: one contiguous HBM buffer, views carved
    where XLA can fuse them). y/w are cast to float32 so consumers see the
    same dtypes as the unpacked path even for bf16-packed batches.
    """

    __slots__ = ("packed", "num_col")

    def __init__(self, packed, num_col: int):
        self.packed = packed
        self.num_col = int(num_col)

    @property
    def x(self):
        return self.packed[:, : self.num_col]

    @property
    def y(self):
        return _device_decode.widen_f32(self.packed[:, self.num_col])

    @property
    def w(self):
        return _device_decode.widen_f32(self.packed[:, self.num_col + 1])

    def __iter__(self):
        return iter((self.x, self.y, self.w))

    def __getitem__(self, i):
        # tuple-compatibility: batch[0]/batch[1]/batch[2] == x/y/w, so
        # consumers written against the split-array contract keep working.
        # Dispatch lazily — building all three would launch discarded
        # slice/cast ops on every single-element access.
        if i == 0 or i == -3:
            return self.x
        if i == 1 or i == -2:
            return self.y
        if i == 2 or i == -1:
            return self.w
        if isinstance(i, slice):
            return (self.x, self.y, self.w)[i]
        raise IndexError(i)

    def __len__(self) -> int:
        # 3, like the (x, y, w) tuple this stands in for — row count is
        # batch.packed.shape[0] / batch.x.shape[0]
        return 3

    def tree_flatten(self):
        return (self.packed,), self.num_col

    @classmethod
    def tree_unflatten(cls, num_col, children):
        return cls(children[0], num_col)


class _SnapshotFeed:
    """The warm-snapshot producer in the ``_host_iter`` slot: wraps a
    :class:`~dmlc_tpu.io.snapshot.SnapshotIter` and emits the pool item
    shape ``(host_batch, None, annot)`` the consumer fill loop expects —
    no staging bufs (the batch views alias the snapshot mmap; numpy pins
    it via the view base chain until the transfer's arrays die) and the
    resume annotation resolved per serving order: the stored pipeline
    annotation for sequential epochs, a ``(seed, epoch, position)``
    plan annotation for plan-ordered ones."""

    def __init__(self, feed, start: int = 0, plan_annot=None):
        self._feed = feed
        self._pos = int(start)  # plan/sequential position of the next batch
        self._plan_annot = plan_annot  # pos-after -> annot dict (plan order)
        self.served_bytes = 0

    @property
    def stall_seconds(self) -> float:
        return self._feed.stall_seconds

    @stall_seconds.setter
    def stall_seconds(self, value: float) -> None:
        self._feed.stall_seconds = value

    def next(self):
        item = self._feed.next()
        if item is None:
            return None
        host_batch, resume, nbytes = item
        self.served_bytes += nbytes
        self._pos += 1
        if self._plan_annot is not None:
            annot = self._plan_annot(self._pos)
        else:
            annot = resume
        return host_batch, None, annot

    def resize_read_workers(self, num_workers: int) -> bool:
        """Autotune passthrough to the snapshot read pool."""
        return self._feed.resize(num_workers)

    def destroy(self) -> None:
        self._feed.destroy()


class DeviceIter:
    """Double-buffered host->device batch iterator with stage attribution.

    Pipeline stages, each ahead of the next:
      1. parser/iterator thread (already prefetched upstream),
      2. serial rebatch stage + a ``convert_workers``-wide
         :class:`OrderedWorkerPool` packing batches into reusable host
         staging buffers (layout conversion for batch N+1 overlaps the
         dispatch of batch N),
      3. this object: ``device_put`` issued ``prefetch`` batches ahead.

    ``stats()['stages']`` decomposes consumer wall time into named costs
    (read / parse / convert / dispatch / device_decode / transfer) — see
    the module docstring; ``stats()['stage_busy']`` carries the raw
    per-stage busy counters the attribution is derived from.
    """

    def __init__(
        self,
        source,
        num_col: int,
        batch_size: int,
        layout: str = "dense",
        *,
        mesh=None,
        data_axis: str = "data",
        shardings=None,
        max_nnz: Optional[int] = None,
        prefetch: Optional[int] = None,
        convert_ahead: Optional[int] = None,
        convert_workers: Optional[int] = None,
        transfer_sample: Optional[int] = None,
        drop_remainder: bool = False,
        device=None,
        elide_unit_values: bool = False,
        x_dtype: str = "float32",
        nnz_bucket: Optional[int] = None,
        row_bucket: int = 1024,
        csr_wire: bool = True,
        pack_aux: Optional[bool] = None,
        pipeline_label: Optional[str] = None,
        snapshot: Optional[str] = None,
        snapshot_signature: Optional[dict] = None,
        snapshot_quant: Optional[str] = None,
        snapshot_shuffle_seed: Optional[int] = None,
        snapshot_read_workers: Optional[int] = None,
        device_decode: Optional[bool] = None,
        autotune: Optional[bool] = None,
        autotune_interval: Optional[int] = None,
    ):
        check(layout in ("dense", "ell", "bcoo"), f"unknown layout {layout!r}")
        check(batch_size is not None or layout == "bcoo",
              "batch_size=None (natural blocks) requires layout='bcoo'")
        check(layout != "bcoo" or (mesh is None and shardings is None),
              "layout='bcoo' emits single-device batches; mesh/shardings "
              "sharding is supported for 'dense' and 'ell' only")
        self.source = source
        self.num_col = num_col
        self.batch_size = batch_size
        self.layout = layout
        self.mesh = mesh
        self.data_axis = data_axis
        self.shardings = tuple(shardings) if shardings is not None else None
        self.max_nnz = max_nnz
        # queue-depth knobs resolve through the knob table (explicit arg
        # > DMLC_TPU_PREFETCH / DMLC_TPU_CONVERT_AHEAD env > default), so
        # a config the autotuner emitted is reusable by exporting it
        self.prefetch = _knobs.resolve("prefetch", prefetch)
        self.drop_remainder = drop_remainder
        self.device = device
        # opt-in: skip transferring all-ones value arrays (binary-feature
        # corpora) and synthesize them on device — saves 4 B/nnz of
        # host->HBM traffic. Off by default: each synthesis is one extra
        # device op per batch, which pays on a TPU-VM but loses on hosts
        # where per-op dispatch is expensive (e.g. a tunneled device).
        self.elide_unit_values = bool(elide_unit_values)
        # 'bfloat16' ships dense x at half the bytes in the MXU's preferred
        # operand width; the native repack converts in its single copy pass,
        # the python fallback converts per block (round-to-nearest-even)
        check(x_dtype in ("float32", "bfloat16"),
              f"unknown x_dtype {x_dtype!r}")
        check(x_dtype == "float32" or layout == "dense",
              "x_dtype='bfloat16' applies to the dense layout only")
        self.x_dtype = x_dtype
        # bcoo shape quantization: round nnz (and, in natural-block mode,
        # rows) UP to bucket multiples so batch shapes repeat instead of
        # being unique per batch. A novel-shape transfer costs a fresh
        # transfer plan (measured ~100x a repeated-shape device_put on a
        # tunneled device) and a recompile in any downstream jit. The nnz
        # padding uses OUT-OF-BOUNDS coords, which every BCOO op masks —
        # load-bearing for elide_unit_values, where the device synthesizes
        # ones for pad slots too (see block_to_bcoo_host). NOTE: batches
        # then carry mat.nse > true nnz — padding is part of the shape;
        # consumers needing the true count must track it themselves.
        # Default (None) derives the bucket: batch_size * max_nnz when both
        # are known (one exact repeating shape), a small 4096 quantum for
        # fixed small batches, 16384 for chunk-sized natural blocks. Set 0
        # to disable (exact shapes, e.g. for interop tests).
        # The derived bucket is CAPPED at 512k nnz: the bucket is also the
        # worst-case per-batch pad (coords+values ~12 B/nnz -> ~6 MB), and
        # batch_size * max_nnz is a ceiling, not a density estimate — for
        # corpora whose rows run far below max_nnz the uncapped product
        # multiplies host->HBM bytes without bound. Under the cap every
        # batch still pads to one exact shape; above it, shapes are a small
        # set of bucket multiples (closed per epoch by the tail handling in
        # _convert).
        if nnz_bucket is None:
            if batch_size is not None and max_nnz:
                nnz_bucket = min(int(batch_size) * int(max_nnz), 512 * 1024)
            elif batch_size is not None:
                nnz_bucket = 4096
            else:
                nnz_bucket = 16384
        self.nnz_bucket = int(nnz_bucket)
        # nse values already emitted (bucket multiples — a tiny set): the
        # fixed-batch tail pads up into this set so the last batch of an
        # epoch never introduces a novel transfer shape
        self._emitted_nse: set = set()
        self.row_bucket = int(row_bucket)
        self._skip_blocks = 0  # producer-put resume: blocks to drop unput
        self._ones_cache: dict = {}  # elided-values ones, keyed by length
        self.stall_seconds = 0.0        # consumer wait for a ready batch
        self.host_stall_seconds = 0.0   # of which: waiting on host convert
        self.batches_fed = 0
        self.bytes_to_device = 0
        # the telemetry scope every span/metric this pipeline causes is
        # labeled with — down to filesystem retries on producer threads.
        # Two concurrent DeviceIters therefore keep fully disjoint books
        # (docs/observability.md).
        self.pipeline_label = (pipeline_label
                               or _telemetry.new_pipeline_label())
        # thread primitives the parser chain already constructed (a
        # threaded input split starts prefetching at parser build, before
        # this pipeline exists) capture the scope NOW, at iterator
        # construction — without this their pre-first-pull work landed in
        # the process-wide books only (the old adoption-window caveat)
        _adopt_pipeline_scope(source, self.pipeline_label)
        # DMLC_TPU_TRACE modes (docs/data.md): '1' wraps transfer /
        # convert / dispatch / cache_read in jax profiler annotations;
        # 'chrome:<path>' dumps the span rings as a Chrome trace on close
        trace_mode, trace_path = _telemetry.trace_mode()
        self._trace = trace_mode == "annotate"
        self._trace_export = trace_path if trace_mode == "chrome" else None
        if (layout == "bcoo" and batch_size is None
                and hasattr(source, "set_emit_coo")):
            # ask the parser for device-ready COO batches: coordinate
            # assembly, bucket padding, and unit-value elision move off-GIL
            # into the C++ parse threads; the convert thread then only
            # issues the (async) device_put. Safe to ignore the answer —
            # _convert handles CooBlock and RowBlock alike. csr_wire
            # (default) ships cols + row_ptr instead of (row, col) pairs —
            # half the coordinate bytes over the link; _put_inner rebuilds
            # the row ids on device (the link is the scarce resource on a
            # tunneled TPU, the VPU prefix-sum is noise). Requires shape
            # bucketing: _csr_coords is jit-cached by shape, so exact-shape
            # mode (bucket 0) would retrace per batch — pair wire there.
            csr_wire = csr_wire and self.nnz_bucket > 0 and self.row_bucket > 0
            try:
                source.set_emit_coo(num_col, row_bucket=self.row_bucket,
                                    nnz_bucket=self.nnz_bucket,
                                    elide_unit=self.elide_unit_values,
                                    csr_wire=bool(csr_wire))
            except TypeError:  # sources without the extended signature
                source.set_emit_coo(num_col, row_bucket=self.row_bucket,
                                    nnz_bucket=self.nnz_bucket,
                                    elide_unit=self.elide_unit_values)
        # aux packing (label/weight as two trailing x columns -> ONE
        # device_put per dense batch; PackedDenseBatch). Auto: on for f32
        # single-device dense (lossless always); bf16 packs the aux in
        # bf16 too, so it needs the caller's explicit promise that labels/
        # weights are bf16-exact; mesh batches keep split arrays (their
        # shardings are per-array).
        if pack_aux is None:
            pack_aux = (layout == "dense" and mesh is None
                        and x_dtype == "float32")
        self.pack_aux = bool(pack_aux) and layout == "dense" and mesh is None
        # bf16 aux packing casts labels/weights to bfloat16 too — sound
        # ONLY when they are bf16-exact. That used to be an undocumented
        # caller promise; it is now VALIDATED at pack time (a round-trip
        # compare per batch) so a lossy corpus raises instead of silently
        # training on corrupted labels (docs/data.md pack_aux).
        self._aux_exact_check = (self.pack_aux
                                 and self.x_dtype == "bfloat16")
        # ---- device-native snapshot store (docs/data.md snapshot) ----
        # cold epochs shadow-write the post-convert batches; warm epochs
        # mmap them straight into the transfer path with zero convert
        # work (a new 'snapshot_read' stage), bounded by transfer instead
        # of host packing (ROADMAP item 3, arXiv:2501.10546).
        if snapshot is None:
            snapshot = getattr(source, "snapshot_path", None)
            if snapshot is not None and snapshot_signature is None:
                snapshot_signature = getattr(source, "snapshot_signature",
                                             None)
        self.snapshot_path = snapshot
        self._snap_sig = snapshot_signature
        self._snap_quant = snapshot_quant
        self._snap_seed = (None if snapshot_shuffle_seed is None
                           else int(snapshot_shuffle_seed))
        self._snap_read_workers = (
            None if snapshot is None
            else _knobs.resolve("snapshot_read_workers",
                                snapshot_read_workers))
        # ---- device-decode tier (docs/data.md three-tier decode) ----
        # armed, warm snapshot epochs (and service snapshot spans)
        # device_put each batch's raw container span VERBATIM and decode
        # in HBM (ops/device_decode) — zero per-batch host numpy decode;
        # host convert busy reads 0 and a 'device_decode' stage appears
        self.device_decode = _knobs.device_decode(device_decode)
        self.device_decode_bytes = 0  # verbatim span bytes transferred
        self._snap_epoch = 0    # advances per reset() while snapshot armed
        self._snap_pos0 = 0     # warm start position (mid-epoch restore)
        self._snap_reader = None
        self._snap_writer = None
        self._snap_serving = False   # current producer is the warm feed
        self._snap_seq_restore = False  # serve this epoch sequentially
        self._snap_shadow = True  # a fresh pass may publish the snapshot
        # a restore the snapshot cannot reproduce (e.g. a BLOCK-plan
        # state replayed by the source) suspends warm serving for the
        # rest of the epoch — the seeked source owns the stream
        self._snap_suspend = False
        if snapshot is not None:
            check(batch_size is not None,
                  "snapshot= requires a fixed batch_size: the store "
                  "persists one batch geometry (docs/data.md)")
            check(layout == "dense" or (layout == "ell" and max_nnz),
                  "snapshot v1 stores fixed-geometry batches: layout "
                  "'dense', or 'ell' with max_nnz pinned (docs/io.md)")
            check(mesh is None and shardings is None,
                  "snapshot= serves single-put batches; mesh/shardings "
                  "pipelines are not snapshot-servable")
            check(snapshot_quant in (None, "int8"),
                  f"unknown snapshot_quant {snapshot_quant!r}")
            check(snapshot_quant is None or (layout == "dense"
                                             and self.pack_aux),
                  "snapshot_quant='int8' applies to packed dense "
                  "batches (layout='dense' with pack_aux)")
            src_plan = getattr(source, "plan_state", None) or {}
            check(src_plan.get("shuffle_seed") is None,
                  "snapshot= cannot combine with a source-side epoch "
                  "plan (shuffle_seed on the block cache): the snapshot "
                  "freezes one epoch's batch order — shuffle snapshot "
                  "epochs with snapshot_shuffle_seed= instead "
                  "(docs/data.md)")
        if layout == "dense" and hasattr(source, "set_emit_dense"):
            # ask the parser for HBM-ready dense batches (skips CSR), repacked
            # to this batch size (and target dtype) off-GIL when the native
            # reader is in play; safe to ignore the answer —
            # _host_batches_dense handles all kinds
            try:
                source.set_emit_dense(num_col, batch_rows=batch_size,
                                      dtype=x_dtype,
                                      pack_aux=self.pack_aux)
            except TypeError:  # sources without the extended signature
                source.set_emit_dense(num_col)
        # the host pipeline starts LAZILY on first pull: load_state must be
        # able to arm the skip-counter before the producer thread begins
        # converting/transferring (otherwise resume re-transfers whatever
        # the eager pipeline already prefetched)
        self._convert_ahead = _knobs.resolve("convert_ahead", convert_ahead)
        # conversion-worker pool width (fixed-batch layouts): >= 1. The
        # packing work is numpy slice-assignment (GIL released), so two
        # workers overlap convert-for-N+1 with the consumer's dispatch of
        # N even before true multi-core parallelism.
        self.convert_workers = _knobs.resolve("convert_workers",
                                              convert_workers)
        # transfer-completion sideband: every Nth delivered batch is
        # block_until_ready'd and the wait recorded as the 'transfer'
        # stage — the async-dispatch blind spot (bench.py's final-drain
        # note) sampled instead of invisible. 0 disables.
        if transfer_sample is None:
            transfer_sample = int(
                os.environ.get("DMLC_TPU_TRANSFER_SAMPLE", "32") or 32)
        self.transfer_sample = max(0, int(transfer_sample))
        self._host_iter_obj = None  # OrderedWorkerPool | ThreadedIter
        self._inflight: deque = deque()
        # ---- stage attribution state (module docstring) ----
        # raw busy/blocked counters, written by pipeline threads
        # (cache_read: warm block-cache supply, docs/data.md block cache).
        # Both meters are registry-backed under this pipeline's label, so
        # stats(), the pod snapshot, and the trace all read one set of
        # books (docs/observability.md).
        self._busy = StageMeter("read", "cache_read", "snapshot_read",
                                "parse", "convert", "dispatch",
                                "device_decode",
                                metric=_telemetry.STAGE_BUSY_METRIC,
                                scope=self.pipeline_label)
        # consumer-wall attribution (the partition stats() reports)
        self._attr = StageMeter("read", "cache_read", "snapshot_read",
                                "parse", "convert", "dispatch",
                                "device_decode", "transfer",
                                metric=_telemetry.STAGE_WALL_METRIC,
                                scope=self.pipeline_label)
        self._transfer_samples = 0
        self._t_first: Optional[float] = None  # first consumer pull
        self._t_last: Optional[float] = None   # latest consumer activity
        self._ring: Optional[_StagingRing] = None
        self._ring_init_lock = threading.Lock()
        # byte-exact resume (SURVEY.md §5.4): blocks annotated by the parser
        # chain carry the source state just after them; the convert thread
        # maps each produced batch to (latest block boundary, rows past it)
        # and the consumer keeps the annotation of the last delivered batch
        self._annot_fifo: deque = deque()
        self._boundaries: deque = deque()
        self._cur_boundary = None          # (rows_at_end, source_state)
        self._last_resume: Optional[dict] = None
        self._drop_rows = 0                # rows to drop after a seek-restore
        self._suppress_before_first = False
        # last trace context seen on a source block (service clients stamp
        # block.trace_ctx from the grant's wire context) — links the
        # dispatch span into the (job, part) trace even though rebatching
        # and the convert pool detach the device_put from the block object
        self._last_trace_ctx: Optional[tuple] = None
        # ---- fault tolerance (docs/resilience.md) ----
        # stream-level retries/resumes happen below, in the filesystems; a
        # retryable error that ESCAPES them (budget exhausted, producer
        # died) re-arms the whole host pipeline at the last delivered batch
        # via the checkpoint machinery, bounded by this policy's attempts.
        self._retry_policy = _resilience.RetryPolicy.from_env()
        # resilience deltas are scoped to THIS pipeline's label: events
        # from a concurrent pipeline (or ambient filesystem use) can no
        # longer contaminate stats()['resilience']
        self._res_base = _resilience.counters_snapshot(self.pipeline_label)
        self.pipeline_restarts = 0
        self.pipeline_giveups = 0
        # lifetime restart/giveup tally: pipeline_restarts is a PER-EPOCH
        # budget counter (reset() zeroes it), so the autotuner's
        # resilience sensor must read this monotonic twin or restarts
        # early in a new epoch hide behind the previous epoch's count
        self._faults_lifetime = 0
        # ---- consumer-side input-wait counter (VERDICT r5 weak #4) ----
        # every second the consumer MEASURABLY waited for input: the wait
        # for a batch handle (stall_seconds' feed) PLUS the sampled
        # transfer landings — registry-backed under this pipeline's
        # label, so the autotuner (and the pod table) can trust one
        # counter where stall_seconds alone reads 0.000 on a
        # transfer-bound epoch whose waits hide in the async blind spot
        self._input_wait = _telemetry.REGISTRY.counter(
            _telemetry.INPUT_WAIT_METRIC, pipeline=self.pipeline_label)
        self._batches_total = 0  # monotonic across epochs (reset() zeroes
        #                          batches_fed; the tuner needs a cursor)
        # ---- online autotuner (docs/data.md autotune; ROADMAP item 4) --
        # a feedback controller that re-sizes the pipeline's pool widths
        # and queue depths between epochs (and every autotune_interval
        # batches) toward gap_stage == transfer, reading only the
        # registry counters above. Armed by autotune=True or
        # DMLC_TPU_AUTOTUNE=1.
        self.autotuner: Optional[_autotune.AutoTuner] = None
        self._autotune_interval = 0
        self._tune_mark: Optional[dict] = None
        if _knobs.autotune_enabled(autotune):
            self._autotune_interval = _knobs.autotune_interval(
                autotune_interval)
            self.autotuner = _autotune.AutoTuner(
                self._autotune_knobs(), scope=self.pipeline_label)

    @property
    def _host_iter(self):
        if self._host_iter_obj is None:
            if (self.snapshot_path is not None and not self._snap_suspend
                    and self._open_snapshot()):
                # warm snapshot epoch: the source chain (parse AND
                # convert) is bypassed entirely — batches stream off the
                # snapshot mmap into device_put
                self._host_iter_obj = self._snapshot_feed()
                self._snap_serving = True
            elif self.batch_size is None:
                # natural-block mode: convert + (async) device_put on ONE
                # producer thread — puts must not interleave across workers
                # because the skip-credit resume counts whole blocks
                self._host_iter_obj = ThreadedIter.from_factory(
                    self._host_batches, max_capacity=self._convert_ahead
                )
            else:
                if self.snapshot_path is not None and self._snap_shadow:
                    # cold snapshot epoch: the convert stage's output
                    # tees into the shadow writer (published at epoch
                    # end, served warm from the next epoch on)
                    self._arm_snapshot_writer()
                self._host_iter_obj = OrderedWorkerPool(
                    self._serial_batches, self._convert_work,
                    num_workers=self.convert_workers,
                    max_ahead=self._convert_ahead,
                )
        return self._host_iter_obj

    # ---------------- snapshot store (docs/data.md snapshot) ----------------

    def _snapshot_geometry(self) -> dict:
        """The batch-shape identity a snapshot is bound to: any drift
        (batch size, width, dtype, layout, padding policy, quantization)
        self-invalidates the stored file at open instead of serving
        wrong-shaped batches."""
        return {
            "v": _snapshot.SNAPSHOT_VERSION,
            "batch_size": int(self.batch_size),
            "num_col": int(self.num_col),
            "layout": self.layout,
            "x_dtype": self.x_dtype,
            "pack_aux": bool(self.pack_aux),
            "quant": self._snap_quant,
            "drop_remainder": bool(self.drop_remainder),
            "max_nnz": (int(self.max_nnz)
                        if self.layout == "ell" and self.max_nnz else None),
        }

    def _open_snapshot(self) -> bool:
        if self._snap_reader is None:
            self._snap_reader = _snapshot.open_snapshot(
                self.snapshot_path, signature=self._snap_sig,
                geometry=self._snapshot_geometry())
        return self._snap_reader is not None

    def _drop_snap_reader(self) -> None:
        reader, self._snap_reader = self._snap_reader, None
        if reader is not None:
            reader.close()

    def _arm_snapshot_writer(self) -> None:
        if self._snap_writer is None:
            self._snap_writer = _snapshot.SnapshotWriter(
                self.snapshot_path, signature=self._snap_sig,
                geometry=self._snapshot_geometry())

    def _abort_snapshot_writer(self) -> None:
        writer, self._snap_writer = self._snap_writer, None
        if writer is not None:
            writer.abort()

    def _finish_snapshot_writer(self) -> None:
        """End of a complete cold pass: fsync + atomically publish the
        shadow-written snapshot (idempotent; a partial pass never gets
        here — mid-epoch restores abort the writer instead)."""
        writer, self._snap_writer = self._snap_writer, None
        if writer is not None:
            writer.finish()

    def _write_snapshot_batch(self, host_batch, annot) -> None:
        """Tee one converted batch into the shadow writer (consumer
        thread — production order IS delivery order here). ``dense_packed``
        batches optionally quantize to int8 + per-column scale."""
        kind = host_batch[0]
        arrays = host_batch[1:]
        if self._snap_quant == "int8" and kind == "dense_packed":
            q, scale = _snapshot.quantize_int8(
                np.asarray(arrays[0], dtype=np.float32))
            kind, arrays = "dense_packed_q8", (q, scale)
        self._snap_writer.add_batch(kind, arrays, rows=self.batch_size,
                                    resume=annot)

    def _snapshot_feed(self) -> _SnapshotFeed:
        """Build the warm feed for this epoch: sequential, or — with a
        ``snapshot_shuffle_seed`` armed — the epoch plan's permutation
        over snapshot BATCH indices (PR 8's planner, one tier up:
        :func:`dmlc_tpu.data.epoch.block_permutation` keyed by
        ``(seed, epoch)``), with ``(seed, epoch, position)`` resume
        annotations so mid-epoch restores replay byte-identically."""
        from dmlc_tpu.data import epoch as _epoch

        reader = self._snap_reader
        order = None
        plan_annot = None
        if self._snap_seed is not None and not self._snap_seq_restore:
            order = _epoch.block_permutation(
                self._snap_seed, self._snap_epoch, reader.num_batches)
            seed, ep = self._snap_seed, self._snap_epoch

            def plan_annot(pos):
                return {"source": _epoch.plan_state_dict(
                    seed, 0, ep, pos, 0, 1, unit="batch"),
                    "skip_rows": 0}
        start = self._snap_pos0
        self._snap_pos0 = 0
        feed = _snapshot.SnapshotIter(
            reader, order=order, start=start,
            read_workers=self._snap_read_workers,
            on_read=lambda dt: self._add_busy("snapshot_read", dt),
            annotate=self._trace, raw=self.device_decode)
        return _SnapshotFeed(feed, start=start, plan_annot=plan_annot)

    def _invalidate_snapshot(self) -> None:
        """A warm batch failed its integrity check: classified snapshot
        corruption — drop the file so the restart path re-arms COLD from
        the source (sequential states) or rebuilds deterministically
        (plan states); the stream stays byte-identical either way."""
        _resilience.record_event("snapshot_corruptions")
        self._drop_snap_reader()  # releases the reader's eviction pin
        _block_cache._artifact_store(self.snapshot_path).discard(
            self.snapshot_path)

    def _rebuild_snapshot(self) -> None:
        """Deterministic full rebuild (vanished/corrupt snapshot under a
        plan-position restore): drive the cold convert pipeline end to
        end, writing every batch and delivering none — parsing and
        packing are deterministic, so the rebuilt batches are
        byte-identical to the lost ones and the plan stream continues
        unbroken at the same position."""
        _resilience.record_event("snapshot_rebuilds")
        self._drop_snap_reader()  # releases the reader's eviction pin
        _block_cache._artifact_store(self.snapshot_path).discard(
            self.snapshot_path)
        self._teardown_producer()
        self._snap_serving = False
        self._abort_snapshot_writer()
        self._arm_snapshot_writer()
        pool = OrderedWorkerPool(
            self._serial_batches, self._convert_work,
            num_workers=self.convert_workers,
            max_ahead=self._convert_ahead)
        try:
            while True:
                item = pool.next()
                if item is None:
                    break
                host_batch, bufs, annot = item
                self._write_snapshot_batch(host_batch, annot)
                if bufs is not None and self._ring is not None:
                    self._ring.attach(bufs, None)  # nothing transferred
            self._finish_snapshot_writer()
        except BaseException:
            self._abort_snapshot_writer()
            raise
        finally:
            pool.destroy()
        self._teardown_producer()  # clear the silent pass's bookkeeping
        check(self._open_snapshot(),
              f"snapshot {self.snapshot_path}: rebuild did not publish a "
              "readable snapshot")

    # ------------- online autotuner (docs/data.md autotune) -------------

    def _autotune_knobs(self) -> list:
        """The live-resizable knob set for this pipeline's shape: queue
        depths always; the parse tier when the source chain can resize
        (ParallelTextParser, possibly behind a BlockCacheIter); the plan
        and snapshot read pools when those tiers exist. convert_workers
        stays static (one knob per stage — convert pressure grows
        convert_ahead; docs/data.md)."""
        knobs = [
            _autotune.Knob("prefetch", lambda: self.prefetch,
                           self._apply_prefetch),
            _autotune.Knob("convert_ahead", lambda: self._convert_ahead,
                           self._apply_convert_ahead),
        ]
        if callable(getattr(self.source, "resize_parse_workers", None)):
            pstats = None
            fn = getattr(self.source, "parallel_stats", None)
            if callable(fn):
                try:
                    pstats = fn()
                except Exception:  # noqa: BLE001 - sensor, never fatal
                    pstats = None
            # seed order: the live pool's real width > the width the
            # source chain will build with (BlockCacheIter stamps the
            # resolved hint before its lazy base exists) > table default
            self._knob_parse_workers = int(
                (pstats or {}).get("parse_workers")
                or getattr(self.source, "parse_workers_hint", 0)
                or _knobs.resolve("parse_workers"))
            knobs.append(_autotune.Knob(
                "parse_workers", lambda: self._knob_parse_workers,
                self._apply_parse_workers))
        if callable(getattr(self.source, "resize_plan_read_workers",
                            None)):
            knobs.append(_autotune.Knob(
                "plan_read_workers",
                lambda: int(getattr(self.source, "plan_read_workers",
                                    0) or _knobs.resolve(
                                        "plan_read_workers")),
                self._apply_plan_read_workers))
        if self.snapshot_path is not None:
            knobs.append(_autotune.Knob(
                "snapshot_read_workers",
                lambda: int(self._snap_read_workers
                            or _knobs.resolve("snapshot_read_workers")),
                self._apply_snapshot_read_workers))
        if callable(getattr(self.source, "resize_pipeline_depth", None)):
            # service-fed pipeline: the read stage's relief knob is the
            # client's pipelined fetch window (STAGE_KNOB_FALLBACK —
            # there is no local parse fan-out to widen)
            knobs.append(_autotune.Knob(
                "service_pipeline_depth",
                lambda: int(getattr(self.source, "pipeline_depth", 0)
                            or _knobs.resolve("service_pipeline_depth")),
                self.source.resize_pipeline_depth))
        return knobs

    def _apply_prefetch(self, n: int) -> bool:
        self.prefetch = max(1, int(n))
        self._refresh_ring_depth()
        return True  # takes effect on the consumer's next _fill

    def _apply_convert_ahead(self, n: int) -> bool:
        self._convert_ahead = max(1, int(n))
        obj = self._host_iter_obj
        if isinstance(obj, OrderedWorkerPool):
            obj.set_max_ahead(self._convert_ahead)
        elif isinstance(obj, ThreadedIter):
            obj.set_capacity(self._convert_ahead)
        self._refresh_ring_depth()
        return True

    def _apply_parse_workers(self, n: int) -> bool:
        fn = getattr(self.source, "resize_parse_workers", None)
        if not callable(fn) or not fn(int(n)):
            return False  # tier bypassed (warm cache) or not resizable
        self._knob_parse_workers = max(1, int(n))
        return True

    def _apply_plan_read_workers(self, n: int) -> bool:
        fn = getattr(self.source, "resize_plan_read_workers", None)
        return callable(fn) and bool(fn(int(n)))

    def _apply_snapshot_read_workers(self, n: int) -> bool:
        self._snap_read_workers = max(1, int(n))
        obj = self._host_iter_obj
        if isinstance(obj, _SnapshotFeed):
            obj.resize_read_workers(self._snap_read_workers)
        return True

    def _autotune_mark_now(self) -> dict:
        """One sensor reading — the tuner's windows are deltas between
        consecutive marks, all read off the registry-backed books."""
        res = _resilience.counters_snapshot(self.pipeline_label)
        return {
            "t": get_time(),
            "batches": self._batches_total,
            "busy": self._busy.seconds(),
            "transfer_wall": self._attr.seconds().get("transfer", 0.0),
            "input_wait": self._input_wait.value,
            # monotonic: registry counters never rewind, and the restart
            # tally is the lifetime twin, not the per-epoch budget —
            # otherwise a new epoch's early restarts would clamp away
            # under the previous epoch's count and skip the cooldown
            "res": sum(res.values()) + self._faults_lifetime,
        }

    def _autotune_step(self) -> None:
        """Run one controller step over the window since the last mark
        (called at every reset() epoch boundary, and every
        ``autotune_interval`` delivered batches)."""
        if self.autotuner is None:
            return
        mark, now = self._tune_mark, self._autotune_mark_now()
        self._tune_mark = now
        if mark is None:
            return  # first mark: no window yet
        busy = {k: max(0.0, now["busy"].get(k, 0.0) - mark["busy"].get(k, 0.0))
                for k in now["busy"]}
        self.autotuner.step({
            "wall": now["t"] - mark["t"],
            "batches": now["batches"] - mark["batches"],
            "input_wait": max(0.0, now["input_wait"] - mark["input_wait"]),
            "busy": busy,
            # the sampled transfer sideband scaled to the whole window:
            # every transfer_sample-th batch blocks until its bytes land
            "transfer_est": max(0.0, now["transfer_wall"]
                                - mark["transfer_wall"])
            * max(1, self.transfer_sample),
            "resilience_events": max(0, now["res"] - mark["res"]),
        })

    def _ring_depth(self) -> int:
        # every buffer that can be referenced concurrently: pool-ahead
        # converted batches + put-issued prefetch + one per worker
        # mid-pack + slack
        return (self._convert_ahead + self.prefetch
                + self.convert_workers + 2)

    def _refresh_ring_depth(self) -> None:
        if self._ring is not None:
            self._ring.set_depth(self._ring_depth())

    # ---------------- host side ----------------

    def _add_busy(self, stage: str, seconds: float) -> None:
        self._busy.add(stage, seconds)

    def _blocks(self) -> Iterator[RowBlock]:
        if self._suppress_before_first:
            # seek-restored: the source already sits at the resume position
            self._suppress_before_first = False
        else:
            self.source.before_first()
        stage_fn = getattr(self.source, "stage_seconds", None)
        while True:
            # supply-wait attribution: time blocked on the source, split
            # read vs parse via the source's own stage counters when it
            # has them (the Python parser chain); the fused native reader
            # reports none, so its whole supply cost lands under 'parse'
            # (read+parse in one C++ pipeline — documented in docs/data.md)
            s0 = stage_fn() if stage_fn is not None else None
            t0 = get_time()
            blk = self.source.next_block()
            dt = get_time() - t0
            read = cache_read = parse_delta = 0.0
            if s0 is not None:
                s1 = stage_fn()
                read = min(max(0.0, s1["read"] - s0["read"]), dt)
                # warm block-cache supply (mmap read + crc) reports under
                # its own stage — a warm epoch's "parse" is then honestly
                # ~zero, which is the whole claim of the cache
                cache_read = min(
                    max(0.0, s1.get("cache_read", 0.0)
                        - s0.get("cache_read", 0.0)),
                    dt - read)
                parse_delta = max(0.0, s1.get("parse", 0.0)
                                  - s0.get("parse", 0.0))
            if read + cache_read + parse_delta <= 0.0 and dt > 0.0:
                # fused native supply (read+parse in one C++ pipeline,
                # with or without a BlockCacheIter in front): no parser-
                # side span sites fired in this window, so record the
                # supply wait as the 'parse' span — exactly what the busy
                # attribution charges it to below
                _telemetry.record_span("parse", t0, dt)
            self._add_busy("read", read)
            self._add_busy("cache_read", cache_read)
            self._add_busy("parse", dt - read - cache_read)
            if blk is None:
                return
            ctx = getattr(blk, "trace_ctx", None)
            if ctx is not None:
                self._last_trace_ctx = ctx
            yield blk

    def _tracked_blocks(self) -> Iterator[RowBlock]:
        """Source blocks with (a) a resume-prefix drop after a seek-restore
        and (b) block-boundary bookkeeping for byte-exact checkpoints."""
        self._boundaries.clear()
        self._cur_boundary = None
        rows = 0
        drop = self._drop_rows
        self._drop_rows = 0
        for block in self._blocks():
            # read the annotation BEFORE any drop-slice: it marks the
            # position AFTER the block, which the tail slice still ends at
            annot = getattr(block, "resume_state", None)
            if drop > 0:
                if drop >= len(block):
                    drop -= len(block)
                    continue
                block = block.slice(drop, len(block))
                drop = 0
            rows += len(block)
            if annot is not None:
                self._boundaries.append((rows, annot))
            yield block

    def _push_annot(self, rows_emitted: int) -> Optional[dict]:
        """Record the resume annotation for the batch ending at
        ``rows_emitted`` (rows of real data since stream/resume start).
        Returns the annotation so the serial stage can also ride it on
        the work item (the snapshot shadow writer stores it per batch)."""
        while self._boundaries and self._boundaries[0][0] <= rows_emitted:
            self._cur_boundary = self._boundaries.popleft()
        if self._cur_boundary is None:
            self._annot_fifo.append(None)
            return None
        r, state = self._cur_boundary
        annot = {"source": state, "skip_rows": rows_emitted - r}
        self._annot_fifo.append(annot)
        return annot

    def _host_batches(self):
        # natural-block mode only (BCOO interop: nnz varies per batch
        # anyway, so fixed-shape rebatching buys no compile reuse — skip
        # the merge/slice copies and convert parser blocks as they come).
        # device_put is issued HERE on the convert thread (it is async:
        # returns a handle while the DMA proceeds), so the consumer thread
        # only pops ready handles — one pipeline thread instead of a GIL
        # ping-pong between convert and put
        for block in self._blocks():
            if self._skip_blocks > 0:
                # resume fast-path: skip without converting/transferring
                self._skip_blocks -= 1
                yield _SKIPPED
                continue
            t0 = get_time()
            hb = self._convert(block)
            dt = get_time() - t0
            self._add_busy("convert", dt)
            _telemetry.record_span("convert", t0, dt)
            yield self._put(hb)

    def _serial_batches(self):
        """The pool's SERIAL stage: pull blocks, rebatch to fixed size,
        emit per-batch work descriptors (no per-batch copies here — the
        packing/conversion runs in the pool's parallel stage). Whatever
        time this stage spends beyond waiting on the source (merge/slice
        bookkeeping) is charged to 'convert'."""
        inner = (self._serial_batches_dense() if self.layout == "dense"
                 else self._serial_batches_sparse())
        while True:
            b0 = self._busy.seconds()
            t0 = get_time()
            try:
                item = next(inner)
            except StopIteration:
                return
            dt = get_time() - t0
            b1 = self._busy.seconds()
            # supply = everything the SOURCE spent inside this pull —
            # including warm cache reads, which previously leaked into
            # 'convert' and inflated it by the cache_read amount
            supply = ((b1["read"] - b0["read"])
                      + (b1["parse"] - b0["parse"])
                      + (b1["cache_read"] - b0["cache_read"]))
            residue = max(0.0, dt - supply)
            self._add_busy("convert", residue)
            _telemetry.record_span("convert", t0, residue)
            yield item

    def _serial_batches_sparse(self):
        emitted = 0
        for block in rebatch_blocks(
            self._tracked_blocks(), self.batch_size, self.drop_remainder
        ):
            emitted += len(block)
            annot = self._push_annot(emitted)
            # bcoo nnz-bucket planning stays HERE, in stream order: the
            # tail batch pads its nse into the set of already-emitted
            # shapes, which must be complete by then — pool workers
            # convert out of order, so they cannot own this bookkeeping
            pad = (self._plan_bcoo_pad_nnz(block)
                   if self.layout == "bcoo" else None)
            yield ("convert_block", block, pad, annot)

    def _serial_batches_dense(self):
        """Dense serial stage: group incoming blocks into exact-B part
        lists using views only (DenseBlock/RowBlock slices); the per-batch
        copy — one packing pass into a staging-ring buffer — is deferred
        to the convert workers (:meth:`_pack_dense_parts`)."""
        B = self.batch_size
        parts: list = []  # part descriptors, total rows pending < B
        pending = 0
        emitted = 0
        for block in self._tracked_blocks():
            if (isinstance(block, DenseBlock) and block.packed
                    and not parts and len(block) == B):
                # native packed batch at exactly B rows: zero further host
                # work — the whole (x|label|weight) batch is ONE array
                emitted += B
                annot = self._push_annot(emitted)
                span = getattr(block, "device_span", None)
                if (span is not None and self.device_decode
                        and self.snapshot_path is None):
                    # wire-v2/fast-path snapshot frame: the service client
                    # kept the frame's verbatim payload bytes + layout —
                    # ship the raw span and decode in HBM instead of
                    # device_put'ing the host-decoded view. (With a local
                    # snapshot tee armed the host arrays are still needed
                    # by the shadow writer, so keep the decoded route.)
                    yield ("span_ready", span, annot)
                else:
                    yield ("dense_ready", block.x, annot)
                continue
            if (isinstance(block, DenseBlock) and block.packed
                    and not parts and len(block) < B):
                # partial packed block — for the native reader this only
                # occurs at the stream tail (flush) or right before an
                # error surfaces, so treat it as the epoch remainder:
                # dropped under drop_remainder, else padded into a full
                # packed batch so the epoch's pytree kind and shape stay
                # uniform (pad rows carry weight 0 -> masked)
                if self.drop_remainder:
                    continue
                n = len(block)
                emitted += n
                annot = self._push_annot(emitted)
                yield ("dense_parts", [("packed", block.x)], annot)
                continue
            if isinstance(block, DenseBlock) and block.packed:
                # parts pending from non-packed blocks (mixed engines) or
                # an oversize block: keep the packed slab as a part — the
                # pack stage reads its feature/label/weight columns
                parts.append(("packed", block.x))
            elif isinstance(block, DenseBlock):
                parts.append(("arr", block.x, block.label, block.weight))
            else:
                parts.append(("blk", block))
            pending += len(block)
            while pending >= B:
                take, need = [], B
                while need > 0:
                    p = parts[0]
                    n = _plen(p)
                    if n <= need:
                        take.append(parts.pop(0))
                        need -= n
                    else:
                        take.append(_pslice(p, 0, need))
                        parts[0] = _pslice(p, need, n)
                        need = 0
                pending -= B
                emitted += B
                annot = self._push_annot(emitted)
                yield ("dense_parts", take, annot)
        if pending and not self.drop_remainder:
            emitted += pending
            annot = self._push_annot(emitted)
            yield ("dense_parts", parts, annot)

    def _convert_work(self, item):
        """The pool's PARALLEL stage: per-batch layout conversion/packing.
        Returns ``(host_batch, staging_bufs_or_None, resume_annot)`` —
        the bufs ride to :meth:`_put` so the ring slot can be tied to the
        device array; the annotation rides to the snapshot shadow
        writer."""
        t0 = get_time()
        try:
            with _telemetry.profiler_annotation("dmlc_tpu.convert",
                                                self._trace):
                kind = item[0]
                if kind == "dense_ready":
                    return ("dense_packed", item[1]), None, item[2]
                if kind == "span_ready":
                    # (raw u8 payload, layout, stored kind) from the
                    # service client — already device-decodable, no host
                    # conversion at all
                    raw, layout, skind = item[1]
                    return ("device_span", raw, layout, skind), None, item[2]
                if kind == "dense_parts":
                    hb, bufs = self._pack_dense_parts(item[1])
                    return hb, bufs, item[2]
                # ("convert_block", block, bcoo pad plan, annot)
                return (self._convert(item[1], pad_plan=(item[2],)), None,
                        item[3])
        finally:
            dt = get_time() - t0
            self._add_busy("convert", dt)
            _telemetry.record_span("convert", t0, dt)

    def _staging_ring(self) -> _StagingRing:
        # called concurrently by pool workers: double-checked under the
        # ring-init lock, or two rings would race into existence and the
        # loser's buffers could never recycle (attach() would scan the
        # survivor and no-op)
        if self._ring is None:
            with self._ring_init_lock:
                if self._ring is None:
                    B, nc = self.batch_size, self.num_col
                    xdt = self._x_np_dtype()
                    if self.pack_aux:
                        def make():
                            return {"packed": np.empty((B, nc + 2), xdt)}
                    else:
                        def make():
                            return {"x": np.empty((B, nc), xdt),
                                    "y": np.empty(B, np.float32),
                                    "w": np.empty(B, np.float32)}
                    self._ring = _StagingRing(make, self._ring_depth())
        return self._ring

    def _part_xyw(self, part):
        if part[0] == "arr":
            return part[1], part[2], part[3]
        # ("blk", RowBlock): the CSR->dense scatter, on the worker
        return block_to_dense(part[1], self.num_col, copy=False)

    def _pack_dense_parts(self, parts):
        """One packing pass: copy part views into a staging-ring buffer
        (slice assignment casts to the target dtype in the same pass) and
        zero-fill rows past the parts' total (the epoch-tail pad). Returns
        the host batch + its ring bufs."""
        B, nc = self.batch_size, self.num_col
        bufs = self._staging_ring().acquire()
        pos = 0
        if self.pack_aux:
            xp = bufs["packed"]
            for p in parts:
                n = _plen(p)
                if p[0] == "packed":
                    xp[pos:pos + n] = p[1]
                else:
                    x, y, w = self._part_xyw(p)
                    xp[pos:pos + n, :nc] = x[:, :nc] if x.shape[1] > nc else x
                    xp[pos:pos + n, nc] = y
                    if w is None:
                        xp[pos:pos + n, nc + 1] = 1.0
                    else:
                        xp[pos:pos + n, nc + 1] = w
                    if self._aux_exact_check:
                        # the slice assignment above just cast label/
                        # weight to bfloat16 — verify the round trip is
                        # lossless NOW, instead of silently training on
                        # corrupted aux values (the old undocumented
                        # caller promise, made checkable)
                        self._require_bf16_exact(
                            xp[pos:pos + n, nc], y, "label")
                        if w is not None:
                            self._require_bf16_exact(
                                xp[pos:pos + n, nc + 1], w, "weight")
                pos += n
            if pos < B:
                xp[pos:] = 0  # pad rows: weight 0 -> masked downstream
            return ("dense_packed", xp), bufs
        xb, yb, wb = bufs["x"], bufs["y"], bufs["w"]
        for p in parts:
            n = _plen(p)
            if p[0] == "packed":
                xb[pos:pos + n] = p[1][:, :nc]
                yb[pos:pos + n] = p[1][:, nc]
                wb[pos:pos + n] = p[1][:, nc + 1]
            else:
                x, y, w = self._part_xyw(p)
                xb[pos:pos + n] = x[:, :nc] if x.shape[1] > nc else x
                yb[pos:pos + n] = y
                if w is None:
                    wb[pos:pos + n] = 1.0
                else:
                    wb[pos:pos + n] = w
            pos += n
        if pos < B:
            xb[pos:] = 0
            yb[pos:] = 0
            wb[pos:] = 0
        return ("dense", xb, yb, wb), bufs

    # one guard for every bf16 aux-packing site (module docstring)
    _require_bf16_exact = staticmethod(_require_bf16_exact)

    def _x_np_dtype(self):
        if self.x_dtype == "bfloat16":
            from dmlc_tpu.native import bf16_dtype

            return bf16_dtype()
        return np.dtype(np.float32)

    def _plan_bcoo_pad_nnz(self, block) -> Optional[int]:
        """nnz-bucket pad target for a fixed-batch bcoo block, with the
        epoch shape-set bookkeeping (VERDICT r4 #5 / ADVICE r3 #4): the
        tail batch is row-padded to batch_size, but with fewer rows it
        carries fewer nnz and would round to a SMALLER bucket multiple
        than any full batch — one novel shape (fresh transfer plan +
        downstream jit recompile) on the last batch of every epoch. Pad
        its nse up to the smallest already-emitted value that fits; full
        batches keep natural rounding and register their nse. MUST run in
        stream order (the serial stage) — the tail's lookup assumes every
        earlier full batch already registered."""
        if isinstance(block, CooBlock) or not self.nnz_bucket:
            return None
        nnz = len(block.index)
        pad_nnz = -(-max(nnz, 1) // self.nnz_bucket) * self.nnz_bucket
        if self.batch_size is not None:
            if len(block) < self.batch_size:
                fits = [s for s in self._emitted_nse if s >= pad_nnz]
                if fits:
                    pad_nnz = min(fits)
            self._emitted_nse.add(pad_nnz)
        return pad_nnz

    def _convert(self, block: RowBlock, pad_plan: Optional[tuple] = None):
        if isinstance(block, CooBlock):
            # native COO emit: already device-layout (coords/values/label/
            # weight assembled + bucket-padded off-GIL) — nothing to do here
            if block.row_ptr is not None:
                return ("bcoo_csr", block.coords, block.row_ptr,
                        block.values, block.label, block.weight, block.shape)
            return ("bcoo", block.coords, block.values, block.label,
                    block.weight, block.shape)
        pad = (self.batch_size
               if self.batch_size is not None and len(block) != self.batch_size
               else None)
        if self.layout == "dense":
            x, y, w = block_to_dense(block, self.num_col, pad_rows_to=pad)
            return ("dense", x, y, w)
        if self.layout == "ell":
            ell = block_to_ell(block, self.num_col, max_nnz=self.max_nnz, pad_rows_to=pad)
            return ("ell",) + tuple(ell)
        # bcoo: all host-side work (coords/values/label assembly) happens
        # here on the convert thread; the device transfer is async
        if pad is None and self.batch_size is None and self.row_bucket:
            # natural-block mode: quantize the row dimension too
            pad = -(-len(block) // self.row_bucket) * self.row_bucket
        # nse planning: precomputed in stream order by the serial stage
        # (pool mode); computed here for the single-thread natural mode
        pad_nnz = (pad_plan[0] if pad_plan is not None
                   else self._plan_bcoo_pad_nnz(block))
        return ("bcoo",) + block_to_bcoo_host(
            block, self.num_col, pad_rows_to=pad,
            unit_values_as_none=self.elide_unit_values,
            pad_nnz_to=pad_nnz)

    # ---------------- device side ----------------

    def _ones_for(self, n: int):
        """Device ones for an elided-value batch (binary-feature corpora):
        created on the SAME device the puts target (BCOO must not mix
        committed arrays across devices) and CACHED per length — every
        batch in an nnz bucket shares the identical ones array, so one
        device allocation serves the whole epoch instead of one dispatch
        per batch. With nnz_bucket=0 (exact shapes) every batch could pin
        a new length forever — don't cache there."""
        dv = self._ones_cache.get(n)
        if dv is None:
            if self.device is not None:
                with jax.default_device(self.device):
                    dv = jax.numpy.ones(n, jax.numpy.float32)
            else:
                dv = jax.numpy.ones(n, jax.numpy.float32)
            if self.nnz_bucket:
                self._ones_cache[n] = dv
        return dv

    def _put(self, host_batch, ring_bufs=None):
        # optional tracing hook (SURVEY.md §5.1): annotate transfers so they
        # are attributable in a jax.profiler / Perfetto trace
        t0 = get_time()
        dd0 = self._busy.seconds()["device_decode"]
        try:
            with _telemetry.profiler_annotation("dmlc_tpu.device_put",
                                                self._trace):
                out = self._put_inner(host_batch)
        finally:
            # the device_span branch meters its decode dispatch as its own
            # 'device_decode' stage NESTED in this window — subtract it so
            # the busy meters stay disjoint (attribution partitions wall)
            dt = get_time() - t0
            dt -= self._busy.seconds()["device_decode"] - dd0
            self._add_busy("dispatch", dt)
            ctx = self._last_trace_ctx
            if ctx is not None:
                # device_put joins the (job, part) trace the source block
                # carried — the timeline shows grant -> parse -> recv ->
                # decode -> dispatch as one causal chain
                _telemetry.record_span("dispatch", t0, dt,
                                       trace_id=ctx[0], parent_id=ctx[1])
            else:
                _telemetry.record_span("dispatch", t0, dt)
        if ring_bufs is not None and self._ring is not None:
            # tie the staging slot to ALL device arrays of the batch: the
            # slot frees only when the consumer has dropped every one of
            # them (weakrefs), never before — a retained label/weight
            # array must pin the slot as surely as the feature matrix
            self._ring.attach(ring_bufs, jax.tree_util.tree_leaves(out))
        return out

    def _put_inner(self, host_batch):
        kind = host_batch[0]
        if kind == "device_span":
            return self._put_device_span(host_batch)
        if kind == "dense_packed":
            xp = host_batch[1]
            self.bytes_to_device += xp.nbytes
            d = (jax.device_put(xp, self.device)
                 if self.device is not None else jax.device_put(xp))
            return PackedDenseBatch(d, self.num_col)
        if kind == "dense_packed_q8":
            # int8-quantized snapshot batch: ship q + per-column scale
            # (1/4 the f32 bytes over the link) and dequantize with one
            # fused device multiply — still zero HOST convert work
            q, scale = host_batch[1], host_batch[2]
            self.bytes_to_device += q.nbytes + scale.nbytes
            out = (jax.device_put([q, scale], self.device)
                   if self.device is not None
                   else jax.device_put([q, scale]))
            return PackedDenseBatch(_device_decode.dequant_q8(*out),
                                    self.num_col)
        if kind == "bcoo_csr":
            from jax.experimental import sparse as jsparse

            cols, row_ptr, vals, label, weight, shape = host_batch[1:]
            arrs = [cols, row_ptr, label, weight] if vals is None else [
                vals, cols, row_ptr, label, weight]
            self.bytes_to_device += sum(a.nbytes for a in arrs)
            out = (jax.device_put(arrs, self.device)
                   if self.device is not None else jax.device_put(arrs))
            if vals is None:
                dc, dp, dl, dw = out
                dv = self._ones_for(len(cols))
            else:
                dv, dc, dp, dl, dw = out
            coords = _csr_coords(dc, dp)
            return jsparse.BCOO((dv, coords), shape=shape), dl, dw
        if kind == "bcoo":
            from jax.experimental import sparse as jsparse

            coords, vals, label, weight, shape = host_batch[1:]
            arrs = [coords, label, weight] if vals is None else [
                vals, coords, label, weight]
            self.bytes_to_device += sum(a.nbytes for a in arrs)
            out = (jax.device_put(arrs, self.device)
                   if self.device is not None else jax.device_put(arrs))
            if vals is None:
                dc, dl, dw = out
                dv = self._ones_for(len(coords))
            else:
                dv, dc, dl, dw = out
            return jsparse.BCOO((dv, dc), shape=shape), dl, dw
        arrays = host_batch[1:]
        self.bytes_to_device += sum(a.nbytes for a in arrays)
        if self.mesh is not None:
            from dmlc_tpu.parallel.mesh import local_batch_to_global

            if self.shardings is not None:
                # exact placement the consumer's jit expects (e.g. a learner's
                # batch_shardings()) — committed arrays must match in JAX
                out = tuple(
                    jax.make_array_from_process_local_data(sh, np.asarray(a))
                    for sh, a in zip(self.shardings, arrays)
                )
            else:
                out = local_batch_to_global(self.mesh, arrays, axis=self.data_axis)
        elif self.device is not None:
            out = tuple(jax.device_put(arrays, self.device))
        else:
            out = tuple(jax.device_put(arrays))
        if kind == "ell":
            return EllBatch(*out)
        return out  # (x, y, w)

    def _put_device_span(self, host_batch):
        """The third warm tier (``device_decode=True``): the snapshot
        batch's verbatim container bytes crossed the pipeline as ONE
        contiguous u8 span — ship it as-is and decode in HBM
        (``ops/device_decode``). Zero per-batch host numpy work; the
        decode dispatch is metered as its own 'device_decode' stage
        (disjoint from 'dispatch' — see :meth:`_put`)."""
        _, span, layout, snap_kind = host_batch
        self.bytes_to_device += span.nbytes
        self.device_decode_bytes += span.nbytes
        d = (jax.device_put(span, self.device)
             if self.device is not None else jax.device_put(span))
        t0 = get_time()
        try:
            segs = _device_decode.decode_span(d, layout)
            out = [segs[name] for name, *_ in layout]
            if snap_kind == "dense_packed":
                return PackedDenseBatch(out[0], self.num_col)
            if snap_kind == "dense_packed_q8":
                return PackedDenseBatch(
                    _device_decode.dequant_q8(out[0], out[1]), self.num_col)
            if snap_kind == "ell":
                return EllBatch(*out)
            return tuple(out)  # "dense": (x, y, w)
        finally:
            dt = get_time() - t0
            self._add_busy("device_decode", dt)
            _telemetry.record_span("device_decode", t0, dt)

    def _maybe_restart_pipeline(self, exc: BaseException) -> bool:
        """Bounded consumer-side recovery from a retryable pipeline error.

        The host pipeline (pool/ThreadedIter) is poisoned once an error
        reaches the consumer; instead of failing the epoch, tear it down
        and re-arm at the batch after the last one DELIVERED, through the
        same state_dict/load_state machinery checkpoint resume uses —
        byte-exact seek when the source chain annotates blocks, a
        deterministic replay otherwise. Returns True when re-armed (caller
        keeps pulling); False when ``exc`` must propagate (fatal class, or
        restart budget exhausted).
        """
        verdict = _resilience.restart_verdict(
            self._retry_policy, self.pipeline_restarts, exc)
        if verdict == "giveup":
            self.pipeline_giveups += 1
            self._faults_lifetime += 1
            return False
        if verdict != "restart":
            return False
        used = self.pipeline_restarts
        self.pipeline_restarts += 1
        self._faults_lifetime += 1
        _resilience.restart_backoff(self._retry_policy, used, exc)
        try:
            self.load_state(self.state_dict())
        except BaseException as nxt:  # noqa: BLE001 - replay hit the fault
            # the replay consumed more budget-worthy failures: recurse
            # (bounded by the same attempts counter) until re-armed or out
            return self._maybe_restart_pipeline(nxt)
        return True

    def _fill(self) -> None:
        producer_put = self.batch_size is None  # natural-block mode put already
        while len(self._inflight) < self.prefetch:
            try:
                item = self._host_iter.next()
            except BaseException as exc:  # noqa: BLE001 - classified below
                if self._snap_serving and isinstance(exc,
                                                     CacheCorruptionError):
                    # corrupt warm snapshot batch: drop the file FIRST so
                    # the restart below re-arms from the source (or a
                    # deterministic rebuild) instead of re-reading the
                    # same bad bytes forever
                    self._invalidate_snapshot()
                if self._maybe_restart_pipeline(exc):
                    continue
                raise
            if item is None:
                # a COMPLETE cold pass publishes its shadow snapshot here
                # (mid-epoch restores abort the writer before this point)
                self._finish_snapshot_writer()
                return
            if item is _SKIPPED:
                # resume marker that load_state's drain missed (stream
                # shorter than the recorded position) — never hand it out
                continue
            if producer_put:
                self._inflight.append(item)
            else:
                host_batch, bufs, annot = item
                if self._snap_writer is not None:
                    self._write_snapshot_batch(host_batch, annot)
                if self._snap_serving:
                    # warm feed: the source-side fifo is idle (nothing is
                    # parsed) — pair the stored annotation with delivery
                    # through the same fifo the cold path uses
                    self._annot_fifo.append(annot)
                self._inflight.append(self._put(host_batch, bufs))

    def __iter__(self):
        return self

    def _account_window(self, t0: float, busy0: dict, t1: float) -> None:
        """Attribute the consumer-wall window [t0, t1] to named stages.

        The window is partitioned: dispatch measured on this thread is
        charged directly; the remainder (time blocked on the pipeline) is
        split over the read/parse/convert busy DELTAS the pipeline threads
        accrued during the window, scaled down when they overlap (pool
        workers running concurrently can accrue more busy-seconds than the
        window holds). Whatever the deltas don't explain stays
        unattributed — it shows up as the 'other' residue against
        wall_seconds instead of being smeared over stages.
        """
        busy1 = self._busy.seconds()
        d_disp = busy1["dispatch"] - busy0["dispatch"]
        d_decode = busy1["device_decode"] - busy0["device_decode"]
        consumer_put = self.batch_size is not None
        window = (t1 - t0) - ((d_disp + d_decode) if consumer_put else 0.0)
        weights = {k: busy1[k] - busy0[k]
                   for k in ("read", "cache_read", "snapshot_read",
                             "parse", "convert")}
        if not consumer_put:
            # natural-block mode dispatches on the producer thread: its put
            # time is part of what the consumer waited on
            weights["dispatch"] = d_disp
            weights["device_decode"] = d_decode
        wsum = sum(weights.values())
        if wsum > 0 and window > 0:
            scale = min(1.0, window / wsum)
            for k, v in weights.items():
                if v > 0:
                    self._attr.add(k, v * scale)
        if consumer_put:
            # measured directly on this thread (not pipeline-blocked time):
            # charged unscaled, like dispatch — the device_decode share is
            # the jit dispatch of the on-device span decode
            self._attr.add("dispatch", d_disp)
            if d_decode > 0:
                self._attr.add("device_decode", d_decode)

    def __next__(self):
        # every consumer-side step runs under this pipeline's telemetry
        # scope, so the pools/threads it lazily creates inherit the label
        with _telemetry.scope(self.pipeline_label):
            return self._next_scoped()

    def _next_scoped(self):
        # stall = wall time the consumer spends in here before a batch is
        # available (covers host-parse waits AND device-side transfer setup
        # — everything between "consumer wants a batch" and "batch handed
        # out"); with the prefetch pipeline keeping up this is ~0.
        # NOTE: device_put is async, so this times the wait for a batch
        # HANDLE — a transfer still in flight at first on-device use is
        # invisible here; the sampled transfer sideband below (and
        # bench.py's final drain) makes that blind spot measurable
        t0 = get_time()
        if self._t_first is None:
            self._t_first = t0
        busy0 = self._busy.seconds()
        self._fill()
        if not self._inflight:
            t_end = get_time()
            self._account_window(t0, busy0, t_end)
            self._t_last = t_end
            raise StopIteration
        out = self._inflight.popleft()
        waited = get_time() - t0
        self.stall_seconds += waited
        # the trustworthy input-bound counter (module docstring): handle
        # waits land here AND in stall_seconds; sampled transfer
        # landings below land here only
        self._input_wait.inc(waited)
        self.host_stall_seconds += self._host_iter.stall_seconds
        self._host_iter.stall_seconds = 0.0
        self.batches_fed += 1
        self._batches_total += 1
        if self._annot_fifo:
            # production order == delivery order, so the head annotation
            # belongs to the batch just handed out
            self._last_resume = self._annot_fifo.popleft()
        # issue the replacement transfer before handing the batch out —
        # pipeline work, not consumer stall, so outside the stall metric
        # (still inside the attribution window: it is consumer wall)
        self._fill()
        self._account_window(t0, busy0, get_time())
        if (self.transfer_sample
                and self.batches_fed % self.transfer_sample == 0):
            # transfer-completion sideband: block until THIS batch's bytes
            # actually land — the per-batch residue async dispatch hides
            ts = get_time()
            jax.block_until_ready(out)
            dt = get_time() - ts
            self._attr.add("transfer", dt)
            # a sampled landing IS consumer-side input waiting: without
            # this, a transfer-bound epoch reads stall 0.000 while half
            # the wall hides in the async blind spot (VERDICT r5 weak #4)
            self._input_wait.inc(dt)
            _telemetry.record_span("transfer", ts, dt)
            self._transfer_samples += 1
        if (self._autotune_interval
                and self._batches_total % self._autotune_interval == 0):
            self._autotune_step()
        self._t_last = get_time()
        return out

    def reset(self) -> None:
        """New epoch: restart the host pipeline. The producer thread is
        JOINED (not just signalled) before annotation state is cleared —
        an in-flight produce step could otherwise append a stale old-epoch
        annotation after the clear and desync the fifo for the whole next
        epoch. With a snapshot armed this is also the epoch boundary the
        store keys on: the next pass serves warm once a snapshot is
        published, and the plan epoch advances so each warm epoch draws a
        fresh batch permutation."""
        advanced = self.batches_fed > 0
        if advanced:
            # epoch-boundary tuning step over the finished epoch's window
            # (no-op unless autotune is armed); knob changes apply to the
            # pools the NEXT epoch builds
            self._autotune_step()
        self._teardown_producer()
        self._skip_blocks = 0
        self._drop_rows = 0
        self._suppress_before_first = False
        self._last_resume = None
        self.batches_fed = 0
        self.pipeline_restarts = 0  # fresh fault budget per epoch
        self.pipeline_giveups = 0
        if self.snapshot_path is not None:
            self._abort_snapshot_writer()  # mid-epoch reset: partial pass
            self._snap_shadow = True
            self._snap_seq_restore = False
            self._snap_suspend = False
            self._snap_pos0 = 0
            if advanced:
                self._snap_epoch += 1

    # -------- checkpoint / resume (SURVEY.md §5.4 addition) --------

    def state_dict(self) -> dict:
        """Mid-epoch resume point. When the source chain annotates blocks
        (the Python parser stack), the state composes the split layer's
        byte-exact position — restore SEEKS there, O(1) in epoch position.
        Otherwise: batch count, replayed deterministically on restore.
        Transfers in flight (not yet handed out) are dropped either way."""
        if self._last_resume is not None:
            return {"kind": "source", "batches": self.batches_fed,
                    **self._last_resume}
        return {"kind": "batches", "batches": self.batches_fed}

    def _teardown_producer(self) -> None:
        self._inflight.clear()
        if self._host_iter_obj is not None:
            self._host_iter_obj.destroy()
            self._host_iter_obj = None
        self._snap_serving = False
        self._annot_fifo.clear()
        # drop the staging ring with the producer: slots acquired by
        # now-dead workers would otherwise stay busy forever
        self._ring = None

    def load_state(self, state: dict) -> None:
        with _telemetry.scope(self.pipeline_label):
            self._load_state_scoped(state)

    def _load_snapshot_state(self, state: dict) -> bool:
        """Restore into warm snapshot serving when possible. Returns True
        when the state was fully handled; False hands it to the normal
        source-seek/replay machinery (cold restore).

        Snapshot batches are 1:1 with pipeline batches at one geometry,
        so the delivered-batch count IS the warm resume position — a
        checkpoint taken against a block-cache (or plain) pipeline
        restores into a warm snapshot pipeline byte-identically, and vice
        versa (the stored per-batch annotations are the cold pipeline's
        own states). Plan-position states (``kind='epoch_plan'`` with
        ``unit='batch'`` under ``source``) adopt the state's plan
        identity wholesale; a vanished snapshot under a plan state
        triggers a deterministic full rebuild."""
        kind = state.get("kind")
        n = int(state.get("batches", 0))
        src = state.get("source") if kind == "source" else None
        plan = (src if isinstance(src, dict)
                and src.get("kind") == "epoch_plan"
                and src.get("unit") == "batch" else None)
        if plan is not None:
            self._teardown_producer()
            self._abort_snapshot_writer()
            self._snap_shadow = False
            self._snap_suspend = False
            self._snap_seq_restore = False
            seed = plan.get("seed")
            self._snap_seed = None if seed is None else int(seed)
            self._snap_epoch = int(plan.get("epoch", 0))
            pos = int(plan.get("pos", n))
            if not self._open_snapshot():
                self._rebuild_snapshot()
            self._snap_pos0 = pos
            self.batches_fed = n
            self._last_resume = ({"source": dict(plan), "skip_rows": 0}
                                 if pos else None)
            return True
        if isinstance(src, dict) and src.get("kind") == "epoch_plan":
            # a BLOCK-plan state (shuffled/sharded block cache): its
            # position lives in the cache's permuted block stream, which
            # this snapshot (always sequential-order — snapshot + source
            # plan is rejected at construction) cannot reproduce. Hand it
            # to the source, which replays the plan byte-identically.
            return False
        if kind not in ("source", "batches") or not self._open_snapshot():
            return False
        if n > self._snap_reader.num_batches:
            # stale count (shrunk source rebuilt elsewhere): the cold
            # machinery owns foreign states
            return False
        self._teardown_producer()
        self._abort_snapshot_writer()
        self._snap_shadow = False
        self._snap_suspend = False
        # a sequential position restored into a plan-armed pipeline: the
        # position only exists in the SEQUENTIAL stream, so the rest of
        # this epoch serves sequentially — byte-identical to the stream
        # the state came from — and the plan resumes next epoch (the
        # same contract as the block cache's legacy restores)
        self._snap_seq_restore = self._snap_seed is not None
        self._snap_pos0 = n
        self.batches_fed = n
        if kind == "source":
            self._last_resume = {k: state[k]
                                 for k in ("source", "skip_rows")}
        else:
            self._last_resume = (self._snap_reader.resume(n - 1)
                                 if n else None)
        return True

    def _load_state_scoped(self, state: dict) -> None:
        if self.snapshot_path is not None:
            if self._load_snapshot_state(state):
                return
            # cold restore below: a mid-epoch seek can no longer shadow-
            # write a complete snapshot, and the seeked SOURCE owns the
            # stream for the rest of this epoch (a warm snapshot cannot
            # reproduce e.g. a block-plan order) — both resume at the
            # next reset()
            self._abort_snapshot_writer()
            self._snap_shadow = False
            self._snap_suspend = True
        if state.get("kind") == "source":
            # byte-exact restore: seek the source (parser -> split) to the
            # block boundary, drop the few rows into it, rebatch from there
            # — no prefix bytes are re-read or re-parsed
            self._teardown_producer()
            self._skip_blocks = 0
            self.source.load_state(state["source"])
            self._drop_rows = int(state["skip_rows"])
            self._suppress_before_first = True
            self._last_resume = {k: state[k] for k in ("source", "skip_rows")}
            self.batches_fed = int(state["batches"])
            return
        n = int(state["batches"])
        # natural-block mode puts on the producer thread, so skipping must
        # happen THERE (before conversion/transfer): tear down any running
        # producer first, THEN arm the skip counter — the replacement
        # producer (lazily started by the drain below) sees the credits
        # from its first iteration, with no thread racing the hand-off
        self._teardown_producer()
        self._skip_blocks = n if self.batch_size is None else 0
        self._drop_rows = 0
        self._suppress_before_first = False
        self._last_resume = None
        for _ in range(n):
            item = self._host_iter.next()
            if item is None:  # replay: nothing transferred
                break
            if (self.batch_size is not None and item is not _SKIPPED
                    and item[1] is not None and self._ring is not None):
                # replayed batch never reaches _put: free its staging slot
                self._ring.attach(item[1], None)
            if self._annot_fifo:
                # keep the 1-push/1-pop pairing: each replayed batch pushed
                # an annotation; consume it like a delivery would (it also
                # upgrades later checkpoints to byte-exact)
                self._last_resume = self._annot_fifo.popleft()
        self.batches_fed = n

    def dump_trace(self, path: str) -> int:
        """Export the span rings as a Chrome-trace/Perfetto JSON at
        ``path`` (docs/observability.md trace-export workflow). Returns
        the number of span events written. The trace covers the whole
        process — load it in Perfetto / ``chrome://tracing`` and filter by
        the ``pipeline`` arg to isolate this iterator's spans."""
        return _telemetry.export_chrome_trace(path)

    def close(self) -> None:
        if self._host_iter_obj is not None:
            self._host_iter_obj.destroy()
        self._abort_snapshot_writer()
        self._drop_snap_reader()
        if hasattr(self.source, "close"):
            self.source.close()
        if self._trace_export:
            # DMLC_TPU_TRACE=chrome:<path> — dump on close, when every
            # stage has finished writing spans
            try:
                self.dump_trace(self._trace_export)
            except OSError as exc:
                from dmlc_tpu.utils.check import get_logger

                get_logger().warning("trace export to %s failed: %s",
                                     self._trace_export, exc)

    def stats(self) -> dict:
        """Throughput counters + per-stage wall attribution.

        ``stages`` partitions consumer wall (``wall_seconds``, first pull
        to latest delivery) into read / cache_read / snapshot_read / parse
        / convert / dispatch / device_decode / transfer; by construction
        their sum never exceeds wall, and the
        difference is unattributed consumer time ('other': the caller's
        own compute between pulls, e.g. a training step). ``stage_busy``
        carries the raw per-thread busy counters the attribution is
        scaled from (these may legitimately exceed wall when pool workers
        overlap). ``transfer`` is a SAMPLED sideband (every
        ``transfer_sample`` batches) — multiply by the sample period for
        a rough whole-stream estimate.

        ``resilience`` sits next to the stage attribution: retry / resume /
        giveup counters accrued by the I/O stack since this iterator was
        built (process-wide deltas — see docs/resilience.md), plus this
        iterator's own bounded pipeline-restart counts.

        ``parse_workers`` / ``parse_parallelism_efficiency`` (with the full
        ``parse_parallel`` sideband) report the source chain's data-parallel
        parse fan-out — how many chunk-parse lanes fed this pipeline and
        how fully they ran in parallel (docs/data.md ``parse_workers``).
        """
        wall = 0.0
        if self._t_first is not None and self._t_last is not None:
            wall = max(0.0, self._t_last - self._t_first)
        # scoped to this pipeline's label: a concurrent DeviceIter's
        # retries/restarts no longer bleed into this one's delta
        resilience = _resilience.counters_delta(self._res_base,
                                                self.pipeline_label)
        resilience["pipeline_restarts"] = self.pipeline_restarts
        resilience["pipeline_giveups"] = self.pipeline_giveups
        # parse-parallelism sideband: the source chain reports its fan-out
        # width + measured efficiency (ParallelTextParser / the native
        # reader); single-lane sources report 1 worker, no efficiency
        pstats = None
        fn = getattr(self.source, "parallel_stats", None)
        if callable(fn):
            try:
                pstats = fn()
            except Exception:  # noqa: BLE001 - stats must never break stats
                pstats = None
        plan_state = getattr(self.source, "plan_state", None) or {}
        return {
            "batches": self.batches_fed,
            "bytes_to_device": self.bytes_to_device,
            # the telemetry scope label every span/metric of this
            # pipeline carries (docs/observability.md)
            "pipeline": self.pipeline_label,
            # block-cache mode of the source chain: 'cold' (parsing +
            # shadow-writing), 'warm' (serving mmap'd parsed blocks), or
            # None when no block cache is armed (docs/data.md)
            "cache_state": getattr(self.source, "cache_state", None),
            # device-native snapshot store: None when not armed, 'warm'
            # while this epoch streams stored device-layout batches
            # (convert busy stays ~0), 'cold' while converting +
            # shadow-writing (docs/data.md snapshot section)
            "snapshot_state": (None if self.snapshot_path is None
                               else ("warm" if self._snap_serving
                                     else "cold")),
            # the snapshot plan identity (permutation over BATCH indices,
            # pure function of (seed, epoch)) — None seed = sequential
            "snapshot_seed": (self._snap_seed
                              if self.snapshot_path is not None else None),
            "snapshot_epoch": (self._snap_epoch
                               if self.snapshot_path is not None
                               else None),
            # third warm tier (docs/data.md three-tier decode table): is
            # device-side span decode armed, and how many verbatim
            # container bytes crossed as raw u8 spans (decoded in HBM —
            # each such batch does ZERO per-batch host numpy decode)
            "device_decode": self.device_decode,
            "device_decode_bytes": self.device_decode_bytes,
            # the epoch planner's identity when the source serves a
            # shuffle-native / pod-sharded cache: the seed and epoch every
            # delivered byte is a function of, None with no plan armed
            # (docs/data.md shuffle-native cache; docs/observability.md)
            "shuffle_seed": plan_state.get("shuffle_seed"),
            "epoch": plan_state.get("epoch"),
            "stall_seconds": self.stall_seconds,
            "host_stall_seconds": self.host_stall_seconds,
            # consumer-side input-bound waiting the tuner can trust:
            # handle waits + sampled transfer landings (a transfer-bound
            # epoch shows it even when stall_seconds reads ~0 — the
            # VERDICT r5 weak #4 artifact, closed)
            "input_wait_seconds": self._input_wait.value,
            # the online controller's full decision record: None when
            # autotune is off (docs/observability.md schema)
            "autotune": (self.autotuner.snapshot()
                         if self.autotuner is not None else None),
            "stages": self._attr.seconds(),
            "stage_busy": self._busy.seconds(),
            "wall_seconds": wall,
            "transfer_samples": self._transfer_samples,
            "convert_workers": self.convert_workers,
            "parse_workers": (pstats or {}).get("parse_workers", 1),
            "parse_parallelism_efficiency": (pstats or {}).get(
                "parse_parallelism_efficiency"),
            "parse_parallel": pstats,
            "staging_ring": (self._ring.stats() if self._ring is not None
                             else None),
            "resilience": resilience,
            # tiered artifact store (docs/store.md): live on-disk bytes
            # under management across every store this process touched,
            # plus the process-wide eviction / eviction-triggered-rebuild
            # tallies — process-wide because budget pressure from ANY
            # pipeline is what evicts this one's artifacts
            "store": _store_counters(),
        }
