"""Deterministic epoch planner: shuffle-native warm cache + pod sharding.

The parse-once block cache (:mod:`dmlc_tpu.io.block_cache`) froze the cold
epoch's order into every warm epoch, so production training loops had to
choose between warm epochs and shuffled epochs (`create_parser` rejected
the combination outright). This module supplies the missing contract —
seeded, resumable, globally consistent shuffling as a *function of*
``(seed, epoch)`` rather than of streaming history (tf.data,
arXiv:2101.12127; reproducible-pipeline determinism, arXiv:2604.21275):

- :func:`block_permutation` — the seeded visitation order of the cached
  block indices for one epoch;
- :func:`row_permutation` — a windowed intra-block row shuffle whose rng
  is keyed by ``(seed, epoch, block_index)``, so ANY block's row order is
  computable in O(rows) without streaming its predecessors (the property
  mid-epoch resume and pod sharding both rely on);
- :class:`EpochPlan` — one epoch's plan for one host: the host's disjoint
  shard slice of the global permutation, plus the row orders.

Every ordering decision derives from ``numpy.random.Generator`` over a
counter-based :class:`numpy.random.Philox` bit stream whose 128-bit key
is built from ``(seed, domain, epoch[, block_index])`` — no rng object is
ever carried across blocks, epochs, or hosts, which is what makes the
plan a pure function: two processes (or the same process before and after
a restore) that agree on ``(seed, epoch, num_blocks, num_hosts)`` agree
on every byte of the epoch.

Pod sharding: the global permutation is dealt round-robin
(``order[host_id::num_hosts]``), so the per-host shards are disjoint,
their union is exactly the epoch, and shard sizes differ by at most one
block. ``host_id``/``num_hosts`` resolve from the tracker env contract or
``jax.distributed`` via
:func:`dmlc_tpu.parallel.distributed.pod_identity`.

Consumed by :class:`dmlc_tpu.data.parsers.BlockCacheIter` (warm epochs
serve blocks in plan order) behind the ``shuffle_seed`` /
``shuffle_window`` / ``pod_sharding`` knobs of
:func:`~dmlc_tpu.data.parsers.create_parser` (docs/data.md).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.utils.check import check

_MASK64 = (1 << 64) - 1
_MASK32 = (1 << 32) - 1
# domain tag of the block-permutation stream in the key's high word —
# row streams put the epoch there, so the two can only collide at
# epoch == 2**32 - 1 (epochs are checked below that)
_BLOCK_DOMAIN = _MASK32


def _rng(seed: int, hi: int, lo: int) -> np.random.Generator:
    """Generator over a Philox stream keyed by ``(seed, hi, lo)``.

    Philox keys are 2x64 bits: word 0 carries the seed, word 1 packs
    ``hi``/``lo`` as two 32-bit halves. Counter-based, so construction is
    O(1) — the planner builds one throwaway generator per decision.
    """
    key = np.array([seed & _MASK64,
                    ((hi & _MASK32) << 32) | (lo & _MASK32)],
                   dtype=np.uint64)
    return np.random.Generator(np.random.Philox(key=key))


def block_permutation(seed: int, epoch: int, num_blocks: int) -> np.ndarray:
    """The epoch's global visitation order of cached block indices —
    a seeded permutation of ``arange(num_blocks)``, a pure function of
    ``(seed, epoch)``."""
    check(0 <= epoch < _MASK32, f"epoch {epoch} out of the planner's range")
    if num_blocks <= 1:
        return np.arange(max(0, int(num_blocks)), dtype=np.int64)
    return _rng(seed, _BLOCK_DOMAIN, epoch).permutation(
        int(num_blocks)).astype(np.int64, copy=False)


def row_permutation(seed: int, epoch: int, block_index: int, rows: int,
                    window: int) -> Optional[np.ndarray]:
    """The windowed intra-block row order of one block, or ``None`` for
    identity (``window <= 1`` disables the row shuffle — the epoch then
    shuffles at block granularity only).

    Rows are shuffled within consecutive windows of ``window`` rows
    (``window >= rows`` = a full-block shuffle), so the shuffle quality /
    memory-locality trade-off is one knob, exactly tf.data's
    ``shuffle(buffer_size)`` dial. The rng is keyed by
    ``(seed, epoch, block_index)``: block k's order never depends on
    blocks 0..k-1 having been streamed.
    """
    check(0 <= epoch < _MASK32, f"epoch {epoch} out of the planner's range")
    if window <= 1 or rows <= 1:
        return None
    rng = _rng(seed, epoch, block_index)
    if window >= rows:
        return rng.permutation(int(rows)).astype(np.int64, copy=False)
    perm = np.arange(int(rows), dtype=np.int64)
    for start in range(0, int(rows), int(window)):
        rng.shuffle(perm[start:start + int(window)])
    return perm


def uniform_column_pattern(block: RowBlock) -> bool:
    """True when every row has the SAME feature-column pattern (identical
    nnz AND identical ``index``/``field`` entries row for row) — the
    dense-text common case (HIGGS/Criteo-like corpora). Such a block's
    nnz-id arrays are invariant under any row permutation, so
    :func:`permute_block_rows` can skip their gathers entirely — they are
    the widest arrays (uint64), so this removes ~2/3 of the shuffle's
    copy traffic. One read-only ufunc pass; callers memoize per block."""
    n = len(block)
    if n <= 1:
        return True
    nnz = np.diff(block.offset)
    if int(nnz.min()) != int(nnz.max()):
        return False
    k = int(nnz[0])
    if k == 0:
        return True
    idx2d = block.index.reshape(n, k)
    if not np.array_equal(idx2d, np.broadcast_to(idx2d[0], idx2d.shape)):
        return False
    if block.field is not None:
        f2d = block.field.reshape(n, k)
        return bool(np.array_equal(f2d,
                                   np.broadcast_to(f2d[0], f2d.shape)))
    return True


def permute_block_rows(block: RowBlock, perm: np.ndarray,
                       uniform_columns: bool = False) -> RowBlock:
    """A new RowBlock whose row ``i`` is ``block[perm[i]]`` — one
    vectorized CSR gather (no per-row Python loop). Gathered arrays own
    fresh memory, which is deliberate: a shuffled warm block is
    materialized off the cache mmap inside the caller's timed
    ``cache_read`` region, so permuted-pattern page faults are attributed
    to the cache, not to whichever later stage first touched the views.

    ``uniform_columns=True`` is the caller's assertion (via
    :func:`uniform_column_pattern`, typically memoized) that every row's
    index/field pattern is identical — those arrays then pass through
    un-gathered (they are permutation-invariant), keeping the shuffle's
    copy cost to the value/label arrays.
    """
    check(len(perm) == len(block), "permute_block_rows: perm/rows mismatch")
    offset = block.offset
    nnz = np.diff(offset)
    new_offset = np.zeros(len(perm) + 1, np.int64)
    np.cumsum(nnz[perm], out=new_offset[1:])
    if len(nnz) and int(nnz.min()) == int(nnz.max()):
        # uniform rows (the dense-corpus common case): the nnz gather is
        # an axis-0 np.take over the (n, k) view — measurably faster than
        # fancy indexing (~1.7x here) and ~3x over the repeat+arange
        # scatter index build below on HIGGS-like rows
        k = int(nnz[0])

        def g(arr):
            return np.take(arr.reshape(len(perm), k), perm,
                           axis=0).reshape(-1)
    else:
        uniform_columns = False  # ragged rows always gather
        # source position of each nnz entry: row r's span starts at
        # offset[perm[r]] and lands at new_offset[r]
        gather = (np.repeat(offset[:-1][perm] - new_offset[:-1], nnz[perm])
                  + np.arange(int(new_offset[-1]), dtype=np.int64))

        def g(arr):
            return np.take(arr, gather)

    def g_ids(arr):
        return arr if uniform_columns else g(arr)

    return RowBlock(
        offset=new_offset,
        label=block.label[perm],
        index=g_ids(block.index),
        value=g(block.value) if block.value is not None else None,
        weight=block.weight[perm] if block.weight is not None else None,
        qid=block.qid[perm] if block.qid is not None else None,
        field=g_ids(block.field) if block.field is not None else None,
        hold=block.hold,
    )


def plan_state_dict(seed: Optional[int], window: int, epoch: int, pos: int,
                    host_id: int, num_hosts: int,
                    unit: str = "block") -> dict:
    """THE ``kind='epoch_plan'`` resume-annotation shape — ``(seed,
    epoch, plan position)`` plus the sharding identity. One builder:
    delivered-block annotations (:meth:`EpochPlan.state`), checkpoint
    states, and the sharded-cold wrapping all come through here, so the
    shape cannot drift between producers
    (``BlockCacheIter._load_plan_state`` adopts every field).

    ``unit`` names what the plan permutes: ``'block'`` (the block cache's
    cached parser blocks — the default, omitted from the state so
    pre-existing checkpoints stay byte-identical) or ``'batch'`` (the
    device-native snapshot store's fixed-geometry batches,
    :mod:`dmlc_tpu.io.snapshot` — the SAME permutation machinery one tier
    up, consumed by ``DeviceIter``'s ``snapshot_shuffle_seed``). The two
    streams' positions are not interchangeable, so each consumer rejects
    the other's unit loudly instead of restoring a wrong position."""
    state = {"kind": "epoch_plan",
             "seed": None if seed is None else int(seed),
             "window": int(window), "epoch": int(epoch), "pos": int(pos),
             "host_id": int(host_id), "num_hosts": int(num_hosts)}
    if unit != "block":
        state["unit"] = str(unit)
    return state


class EpochPlan:
    """One epoch's deterministic serving plan for one host.

    ``seed=None`` plans a *sequential* epoch (identity order, no row
    shuffle) — the degenerate plan pod sharding without shuffling rides
    on. ``num_hosts > 1`` restricts :attr:`order` to this host's
    round-robin shard of the global order; the shards of one
    ``(seed, epoch)`` are disjoint and union to the whole epoch.
    """

    __slots__ = ("seed", "epoch", "num_blocks", "num_hosts", "host_id",
                 "window", "_order")

    def __init__(self, seed: Optional[int], epoch: int, num_blocks: int,
                 num_hosts: int = 1, host_id: int = 0, window: int = 0):
        check(num_hosts >= 1, "EpochPlan: num_hosts must be >= 1")
        check(0 <= host_id < num_hosts,
              f"EpochPlan: host_id {host_id} not in [0, {num_hosts})")
        self.seed = None if seed is None else int(seed)
        self.epoch = int(epoch)
        self.num_blocks = int(num_blocks)
        self.num_hosts = int(num_hosts)
        self.host_id = int(host_id)
        self.window = int(window)
        self._order: Optional[np.ndarray] = None

    @property
    def order(self) -> np.ndarray:
        """This host's block visitation order (read-only)."""
        if self._order is None:
            if self.seed is None:
                full = np.arange(self.num_blocks, dtype=np.int64)
            else:
                full = block_permutation(self.seed, self.epoch,
                                         self.num_blocks)
            order = full[self.host_id::self.num_hosts]
            order.flags.writeable = False
            self._order = order
        return self._order

    def __len__(self) -> int:
        return len(self.order)

    @property
    def permuted(self) -> bool:
        """True when blocks serve out of sequential order (a seeded
        permutation is armed) — the signal for materializing mmap views
        inside the ``cache_read`` stage."""
        return self.seed is not None and self.num_blocks > 1

    def block_at(self, pos: int) -> int:
        """Cache block index at local plan position ``pos``."""
        return int(self.order[pos])

    def row_order(self, block_index: int, rows: int) -> Optional[np.ndarray]:
        """The intra-block row order of ``block_index`` (None = identity).
        Keyed by ``(seed, epoch, block_index)`` — host-independent, so
        sharded and unsharded serves of one block are byte-identical."""
        if self.seed is None:
            return None
        return row_permutation(self.seed, self.epoch, block_index, rows,
                               self.window)

    def state(self, pos: int) -> dict:
        """The resume annotation for plan position ``pos`` — everything a
        fresh pipeline needs to replay the stream byte-identically
        (``BlockCacheIter.load_state`` adopts these fields wholesale)."""
        return plan_state_dict(self.seed, self.window, self.epoch, pos,
                               self.host_id, self.num_hosts)
