"""dmlc_tpu — a TPU-native rebuild of the dmlc-core data substrate.

The reference (octaviansima/dmlc-core) is the common substrate of the DMLC
ecosystem: URI-addressed stream IO, partitioned record-aware input splitting,
multi-threaded parsing of ML text formats into sparse row blocks,
producer/consumer prefetch pipelines, parameter/registry/serialization
utilities, and a distributed job tracker.

This package re-designs those capabilities TPU-first:

- parsers emit HBM-resident ``jax.Array`` / BCOO batches
  (:mod:`dmlc_tpu.data.device`),
- the prefetch pipeline (`ThreadedIter`, reference include/dmlc/threadediter.h)
  becomes an async host->device double-buffered pipeline,
- input sharding (`InputSplit`, reference src/io/input_split_base.cc) maps a
  partition per ``jax.process_index()`` and assembles global sharded arrays,
- the tracker (reference tracker/dmlc_tracker/tracker.py) gains a ``tpu-pod``
  backend wired to the ``jax.distributed`` coordinator,
- hot parse loops run in a C++ host library (:mod:`dmlc_tpu.native`), with a
  pure-numpy fallback.

Layout (mirrors SURVEY.md layer map):

- ``utils/``    — layers 0-2: logging/check, registry, Parameter, config,
                  serializer, timers.
- ``io/``       — layers 3-4: Stream/FileSystem/URI, RecordIO, InputSplit,
                  ThreadedIter.
- ``data/``     — layer 5: RowBlock, parsers (libsvm/csv/libfm), row iterators,
                  device pipeline.
- ``ops/``      — device-side transforms: CSR->BCOO, padded dense, sparse
                  matvec (XLA + Pallas).
- ``parallel/`` — mesh/sharding helpers, collectives, jax.distributed
                  bootstrap from the DMLC_* env contract.
- ``models/``   — reference-style linear learners (the reference's Row::SDot,
                  data.h:146-161, exists to serve exactly these) used as the
                  flagship end-to-end slice.
- ``tracker/``  — layer 7: rank-coordination tracker + dmlc-submit launchers.
"""

__version__ = "0.1.0"

from dmlc_tpu.utils.registry import Registry
from dmlc_tpu.utils.params import Parameter

__all__ = ["Registry", "Parameter", "__version__"]
