"""dmlc-submit argument parsing — analog of tracker/dmlc_tracker/opts.py.

All clusters registered here are dispatched by submit.py (the reference
registers slurm/kubernetes in opts.py:72-75 but forgets them in
submit.py:43-56 — fixed here), plus the new ``tpu-pod`` backend.
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

CLUSTERS = ["local", "ssh", "mpi", "sge", "slurm", "yarn", "kubernetes", "tpu-pod"]


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="Submit a distributed job through the dmlc_tpu tracker.",
    )
    parser.add_argument(
        "--cluster", choices=CLUSTERS,
        default=os.environ.get("DMLC_SUBMIT_CLUSTER"),
        help="Cluster backend (default from DMLC_SUBMIT_CLUSTER).")
    parser.add_argument("--num-workers", type=int, required=True,
                        help="Number of workers.")
    parser.add_argument("--num-servers", type=int, default=0,
                        help="Number of parameter servers (0 = allreduce job).")
    parser.add_argument("--worker-cores", type=int, default=1)
    parser.add_argument("--worker-memory-mb", type=int, default=1024)
    parser.add_argument("--server-cores", type=int, default=1)
    parser.add_argument("--server-memory-mb", type=int, default=1024)
    parser.add_argument("--jobname", default="dmlc-job")
    parser.add_argument("--queue", default="default")
    parser.add_argument("--host-file", default=None,
                        help="File with one 'ip[:port]' per line (ssh/mpi/tpu-pod).")
    parser.add_argument("--host-ip", default=None,
                        help="Tracker bind IP (default: auto-detect).")
    parser.add_argument("--env", action="append", default=[],
                        help="KEY=VALUE to forward to workers (repeatable).")
    parser.add_argument("--local-num-attempt", type=int,
                        default=int(os.environ.get("DMLC_NUM_ATTEMPT", "1")),
                        help="Retry count for failed local workers.")
    parser.add_argument("--sync-dst-dir", default=None,
                        help="rsync the working dir to this path on each host (ssh).")
    parser.add_argument("--log-level", default="INFO",
                        choices=["DEBUG", "INFO", "WARNING", "ERROR"])
    parser.add_argument("--log-file", default=None)
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="The command to launch on every node.")
    return parser


def parse_opts(argv: Optional[List[str]] = None) -> argparse.Namespace:
    args = build_parser().parse_args(argv)
    if not args.cluster:
        raise SystemExit("dmlc-submit: --cluster required (or set DMLC_SUBMIT_CLUSTER)")
    if not args.command:
        raise SystemExit("dmlc-submit: no command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    args.pass_envs = {}
    for kv in args.env:
        if "=" not in kv:
            raise SystemExit(f"dmlc-submit: bad --env {kv!r} (need KEY=VALUE)")
        key, value = kv.split("=", 1)
        args.pass_envs[key] = value
    return args


def read_host_file(path: str) -> List[str]:
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line)
    return hosts
