"""Rabit-compatible rank-coordination tracker.

Wire-compatible with the reference tracker protocol
(tracker/dmlc_tracker/tracker.py) so existing rabit/ps-lite clients can
rendezvous against it:

- framing: native-endian int32 + length-prefixed strings (tracker.py:24-47),
- handshake: magic ``0xff99`` both ways (tracker.py:50, 64-66),
- worker hello: ``rank, world_size, jobid, cmd`` with
  cmd in {start, print, shutdown, recover} (tracker.py:67-70, 278-301),
- rank assignment: rank, parent, world, tree neighbors, ring prev/next,
  then the connect-brokering loop (goodset -> conset host/port/rank,
  wait_accept bookkeeping) (tracker.py:81-136),
- topology: binary-heap tree + node-sharing ring + link map
  (tracker.py:166-261),
- lazy world size from the first worker, batch rank assignment once all
  pending workers arrived (tracker.py:290-326), rank-stable ``recover``
  (tracker.py:288-301).

On TPU the data plane is XLA collectives, so these topologies exist for
rabit-client compatibility; the ``tpu-pod`` backend instead maps the same
env contract onto ``jax.distributed`` (dmlc_tpu/parallel/distributed.py).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.utils import telemetry as _telemetry

MAGIC = 0xFF99

logger = logging.getLogger("dmlc_tpu.tracker")


class Conn:
    """Framed socket: native int32 + length-prefixed utf-8 strings."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def recvall(self, nbytes: int) -> bytes:
        chunks = []
        nread = 0
        while nread < nbytes:
            chunk = self.sock.recv(min(nbytes - nread, 4096))
            if not chunk:
                raise ConnectionError("tracker: peer closed mid-message")
            nread += len(chunk)
            chunks.append(chunk)
        return b"".join(chunks)

    def recv_int(self) -> int:
        return struct.unpack("@i", self.recvall(4))[0]

    def send_int(self, value: int) -> None:
        self.sock.sendall(struct.pack("@i", value))

    def send_str(self, value: str) -> None:
        data = value.encode()
        self.send_int(len(data))
        self.sock.sendall(data)

    def recv_str(self) -> str:
        return self.recvall(self.recv_int()).decode()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------- topology (tracker.py:166-261) ----------------

def tree_neighbors(rank: int, n: int) -> List[int]:
    """Binary-heap neighbors of ``rank`` in an n-node tree."""
    r = rank + 1
    out = []
    if r > 1:
        out.append(r // 2 - 1)
    if r * 2 - 1 < n:
        out.append(r * 2 - 1)
    if r * 2 < n:
        out.append(r * 2)
    return out


def get_tree(n: int) -> Tuple[Dict[int, List[int]], Dict[int, int]]:
    tree_map = {r: tree_neighbors(r, n) for r in range(n)}
    parent_map = {r: (r + 1) // 2 - 1 for r in range(n)}
    return tree_map, parent_map


def get_star(n: int) -> Tuple[Dict[int, List[int]], Dict[int, int]]:
    tree_map = {r: ([0] if r != 0 else list(range(1, n))) for r in range(n)}
    parent_map = {r: (0 if r != 0 else -1) for r in range(n)}
    return tree_map, parent_map


def find_share_ring(tree_map, parent_map, root: int) -> List[int]:
    """DFS order that shares links with the tree (tracker.py:202-219)."""
    children = set(tree_map[root]) - {parent_map[root]}
    if not children:
        return [root]
    out = [root]
    for i, child in enumerate(children):
        sub = find_share_ring(tree_map, parent_map, child)
        if i == len(children) - 1:
            sub.reverse()
        out += sub
    return out


def get_ring(tree_map, parent_map) -> Dict[int, Tuple[int, int]]:
    order = find_share_ring(tree_map, parent_map, 0)
    n = len(tree_map)
    assert len(order) == n
    ring = {}
    for i in range(n):
        ring[order[i]] = (order[(i - 1) % n], order[(i + 1) % n])
    return ring


def get_link_map(n: int):
    """Tree + parent + ring maps with ranks renumbered along the ring
    (tracker.py:236-261)."""
    tree_map, parent_map = get_tree(n)
    ring_map = get_ring(tree_map, parent_map)
    rmap = {0: 0}
    k = 0
    for i in range(n - 1):
        k = ring_map[k][1]
        rmap[k] = i + 1
    ring2 = {rmap[k]: (rmap[a], rmap[b]) for k, (a, b) in ring_map.items()}
    tree2 = {rmap[k]: [rmap[x] for x in v] for k, v in tree_map.items()}
    parent2 = {rmap[k]: (rmap[v] if k != 0 else -1) for k, v in parent_map.items()}
    return tree2, parent2, ring2


# ---------------- worker bookkeeping ----------------

class WorkerEntry:
    """One accepted connection — analog of SlaveEntry (tracker.py:58-136)."""

    def __init__(self, sock: socket.socket, addr):
        self.conn = Conn(sock)
        self.host = socket.getaddrinfo(addr[0], None)[0][4][0]
        magic = self.conn.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"invalid magic {magic:#x} from {self.host}")
        self.conn.send_int(MAGIC)
        self.rank = self.conn.recv_int()
        self.world_size = self.conn.recv_int()
        self.jobid = self.conn.recv_str()
        self.cmd = self.conn.recv_str()
        self.wait_accept = 0
        self.port: Optional[int] = None
        # (rank, entry) pairs settled during the CURRENT assign_rank call,
        # so a worker dying mid-brokering can have its settles rolled back
        # — without this its relaunch re-links the same peers and settles
        # them AGAIN, driving wait_accept negative and popping peers from
        # wait_conn early (ADVICE r4 #1)
        self.settled_in_call: list = []

    def decide_rank(self, job_map: Dict[str, int]) -> int:
        if self.rank >= 0:
            return self.rank
        if self.jobid != "NULL" and self.jobid in job_map:
            return job_map[self.jobid]
        return -1

    def assign_rank(self, rank, wait_conn, tree_map, parent_map, ring_map,
                    known_addr=None):
        """Send topology + broker peer connections (tracker.py:81-136).

        ``known_addr`` (rank -> (host, port) of every previously assigned
        worker) is passed on RECOVERY: the recovered worker then dials ALL
        its live peers itself instead of waiting for them to redial. Real
        rabit peers redial when their socket to the dead worker breaks
        (their next allreduce fails); on the TPU plane the data path is XLA
        collectives and peer sockets are topology bookkeeping only, so no
        redial ever comes — without this, a recovered rank would sit in
        ``wait_conn`` forever and its eventual shutdown would kill the
        accept loop (SURVEY.md §2.4 data-plane mapping).
        """
        self.rank = rank
        conn = self.conn
        nnset = set(tree_map[rank])
        rprev, rnext = ring_map[rank]
        conn.send_int(rank)
        conn.send_int(parent_map[rank])
        conn.send_int(len(tree_map))
        conn.send_int(len(nnset))
        for r in nnset:
            conn.send_int(r)
        if rprev not in (-1, rank):
            nnset.add(rprev)
            conn.send_int(rprev)
        else:
            conn.send_int(-1)
        if rnext not in (-1, rank):
            nnset.add(rnext)
            conn.send_int(rnext)
        else:
            conn.send_int(-1)
        all_done = []
        pending_conset: list = []
        self.settled_in_call = []

        def settle(rank_):
            # exactly-once wait_accept accounting for a linked peer — used
            # by both the pending-round and final-round paths below
            entry = wait_conn[rank_]
            entry.wait_accept -= 1
            self.settled_in_call.append((rank_, entry))
            if entry.wait_accept == 0:
                all_done.append(rank_)
                wait_conn.pop(rank_, None)

        while True:
            ngood = conn.recv_int()
            goodset = {conn.recv_int() for _ in range(ngood)}
            assert goodset.issubset(nnset), (goodset, nnset)
            # settle peers handed out in the PREVIOUS round that the client
            # did link (their rank is now in goodset). The original
            # final-round-only accounting was correct when clients always
            # finished in one round; the client's nerr-retry loop means a
            # peer can be linked in a non-final round and must be settled
            # here, not skipped.
            for r in pending_conset:
                if r in goodset and r in wait_conn:
                    settle(r)
            badset = nnset - goodset
            conset = [r for r in badset if r in wait_conn]
            extra = ([r for r in badset
                      if r not in wait_conn and r in known_addr]
                     if known_addr else [])
            conn.send_int(len(conset) + len(extra))
            conn.send_int(len(badset) - len(conset) - len(extra))
            for r in conset:
                conn.send_str(wait_conn[r].host)
                conn.send_int(wait_conn[r].port)
                conn.send_int(r)
            for r in extra:
                host, port = known_addr[r]
                conn.send_str(host)
                conn.send_int(port)
                conn.send_int(r)
            nerr = conn.recv_int()
            if nerr != 0:
                pending_conset = conset
                continue
            self.port = conn.recv_int()
            for r in conset:
                settle(r)
            self.wait_accept = len(badset) - len(conset) - len(extra)
            return all_done


def _rollback_settles(worker: "WorkerEntry", wait_conn: dict) -> None:
    """Undo the wait_accept settles a failed assign_rank call applied.

    Each settled peer gets its credit back and is re-inserted into
    ``wait_conn`` (settle pops peers whose count hits 0), so the dead
    worker's relaunch re-brokers against exact accounting.
    """
    for r, entry in reversed(worker.settled_in_call):
        entry.wait_accept += 1
        wait_conn[r] = entry
    worker.settled_in_call = []


class RabitTracker:
    """The rendezvous server (tracker.py:138-349)."""

    def __init__(self, host_ip: str, num_workers: int,
                 port: int = 9091, port_end: int = 9999,
                 liveness_timeout: Optional[float] = None,
                 on_worker_lost=None):
        family = socket.getaddrinfo(host_ip, None)[0][0]
        sock = socket.socket(family, socket.SOCK_STREAM)
        if port_end <= port:
            port_end = port + 908  # keep the reference's default span width
        bound = False
        for p in range(port, port_end):
            try:
                sock.bind((host_ip, p))
                self.port = p
                bound = True
                break
            except OSError as exc:
                if exc.errno in (98, 48):  # EADDRINUSE linux/mac
                    continue
                raise
        if not bound:
            raise OSError(f"tracker: no free port in [{port}, {port_end})")
        sock.listen(256)
        self.sock = sock
        self.host_ip = host_ip
        self.num_workers = num_workers
        self.thread: Optional[threading.Thread] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        # liveness (SURVEY.md §5.3: the reference tracker blocks on accept
        # with no failure detection): workers running our WorkerClient send
        # periodic `heartbeat` commands — a new cmd legacy rabit clients
        # simply never send, so the wire protocol stays compatible.
        # Detection is opt-in per worker: only ranks that have heartbeat at
        # least once are tracked, so a legacy client in the same job is
        # never flagged. Tracked ranks silent for `liveness_timeout`
        # seconds are reported via on_worker_lost.
        self.liveness_timeout = liveness_timeout
        self.on_worker_lost = on_worker_lost
        self.last_seen: Dict[int, float] = {}
        self.lost_workers: set = set()
        # pod-scale telemetry aggregation (docs/observability.md): workers
        # running our WorkerClient ship periodic registry snapshots over a
        # `metrics` command — a cmd legacy rabit clients never send, so the
        # wire protocol stays compatible. Latest snapshot per rank; the
        # merged per-rank × per-stage table is logged as they arrive.
        self.metrics_by_rank: Dict[int, dict] = {}
        self._metrics_lock = threading.Lock()
        self._metrics_logged = 0.0  # last table log (rate-limited)
        self._shutdown_ranks: set = set()
        self._liveness_lock = threading.Lock()
        self._processing_since: Optional[float] = None
        self._monitor = None
        if liveness_timeout is not None:
            from dmlc_tpu.utils.thread_group import ThreadGroup, timer_thread

            self._monitor_group = ThreadGroup()
            self._monitor = timer_thread(
                self._monitor_group, "liveness",
                max(liveness_timeout / 3.0, 0.05), self._check_liveness)
        logger.info("tracker listening on %s:%d", host_ip, self.port)

    def _mark_alive(self, rank: int) -> None:
        if rank < 0:
            return
        with self._liveness_lock:
            self.last_seen[rank] = time.time()
            self.lost_workers.discard(rank)

    def _check_liveness(self) -> None:
        if self.liveness_timeout is None:
            return
        now = time.time()
        # suspend judgment while the single-threaded accept loop is busy
        # (e.g. blocked brokering a recovery): heartbeats queue unprocessed
        # in the TCP backlog and every healthy rank would look stale
        busy_since = self._processing_since
        if busy_since is not None and now - busy_since > 0.2:
            return
        newly_lost = []
        with self._liveness_lock:
            for rank, seen in self.last_seen.items():
                if (rank in self._shutdown_ranks or rank in self.lost_workers):
                    continue
                if now - seen > self.liveness_timeout:
                    self.lost_workers.add(rank)
                    newly_lost.append(rank)
        for rank in newly_lost:
            logger.warning("tracker: worker rank %d missed heartbeats "
                           "(last seen %.1fs ago)", rank,
                           now - self.last_seen[rank])
            if self.on_worker_lost is not None:
                self.on_worker_lost(rank)

    def worker_envs(self) -> Dict[str, str]:
        """Env contract for workers (slave_envs, tracker.py:178-184)."""
        return {
            "DMLC_TRACKER_URI": self.host_ip,
            "DMLC_TRACKER_PORT": str(self.port),
        }

    # -------- pod-scale telemetry aggregation --------

    def _ingest_metrics(self, rank: int, payload: str) -> None:
        if rank < 0:
            return
        try:
            snap = json.loads(payload)
        except ValueError as exc:
            logger.warning("tracker: unparseable metrics from rank %d: %s",
                           rank, exc)
            return
        if not isinstance(snap, dict):
            return
        with self._metrics_lock:
            self.metrics_by_rank[rank] = snap
            now = time.time()
            do_log = now - self._metrics_logged >= self._metrics_log_every()
            if do_log:
                self._metrics_logged = now
        if do_log:
            logger.info("@tracker pod telemetry (%d rank(s)):\n%s",
                        len(self.metrics_by_rank), self.format_pod_table())

    @staticmethod
    def _metrics_log_every() -> float:
        """Seconds between merged-table log lines (DMLC_METRICS_LOG_EVERY;
        0 logs on every snapshot — handy in tests)."""
        try:
            return float(os.environ.get("DMLC_METRICS_LOG_EVERY", "30") or 30)
        except ValueError:
            return 30.0

    def pod_metrics(self) -> Dict[int, dict]:
        """Latest telemetry snapshot per rank (copy)."""
        with self._metrics_lock:
            return {r: dict(s) for r, s in self.metrics_by_rank.items()}

    def pod_job_metrics(self) -> Dict[str, dict]:
        """Fleet-wide per-job service breakdown, summed across ranks:
        ``{job: {"input_wait_seconds", "parts"}}`` from the snapshots'
        ``jobs`` sections (docs/observability.md per-job pod-table
        rows). This is the aggregate input-starvation signal the fleet
        autoscaler's tracker source reads (docs/service.md fleet
        autoscaling)."""
        out: Dict[str, dict] = {}
        for snap in self.pod_metrics().values():
            for job, rec in (snap.get("jobs") or {}).items():
                tot = out.setdefault(str(job), {"input_wait_seconds": 0.0,
                                                "parts": 0})
                tot["input_wait_seconds"] += float(
                    (rec or {}).get("input_wait_seconds", 0.0))
                tot["parts"] += int((rec or {}).get("parts", 0))
        return out

    def pod_decisions(self) -> Dict[str, int]:
        """Fleet-wide control-decision counts summed across ranks:
        ``{"component.action": count}`` from the snapshots' ``decisions``
        sections (docs/observability.md Decision ledger) — one line of
        who-did-what for the whole pod without pulling every ledger."""
        out: Dict[str, int] = {}
        for snap in self.pod_metrics().values():
            for key, n in (snap.get("decisions") or {}).items():
                out[str(key)] = out.get(str(key), 0) + int(n)
        return out

    def format_pod_table(self) -> str:
        """The merged per-rank × per-stage seconds table
        (telemetry.format_pod_table over the latest snapshots)."""
        return _telemetry.format_pod_table(self.pod_metrics())

    def _accept_loop(self, num_workers: int, master_ip: Optional[str] = None):
        shutdown: Dict[int, WorkerEntry] = {}
        wait_conn: Dict[int, WorkerEntry] = {}
        job_map: Dict[str, int] = {}
        pending: List[WorkerEntry] = []
        tree_map = None
        parent_map = ring_map = None
        todo_nodes: List[int] = []
        # ranks whose start brokering failed (worker died mid-call) and
        # whose relaunch has not completed yet — the all-started log and
        # start_time stamp wait for this to drain
        failed_start_ranks: set = set()
        # latest (host, listen-port) per assigned rank — the recovery
        # brokering source (see WorkerEntry.assign_rank known_addr)
        rank_addr: Dict[int, tuple] = {}

        while len(shutdown) != num_workers:
            self._processing_since = None
            fd, addr = self.sock.accept()
            self._processing_since = time.time()
            try:
                worker = WorkerEntry(fd, addr)
            except (ConnectionError, AssertionError) as exc:
                logger.warning("tracker: rejected connection: %s", exc)
                fd.close()
                continue
            if worker.cmd == "print":
                logger.info("%s", worker.conn.recv_str().strip())
                continue
            if worker.cmd == "heartbeat":
                self._mark_alive(worker.rank)
                worker.conn.close()
                continue
            if worker.cmd == "metrics":
                # heartbeat + telemetry snapshot in one round trip: the
                # payload is one JSON string (telemetry.pod_snapshot())
                try:
                    payload = worker.conn.recv_str()
                except (ConnectionError, OSError) as exc:
                    logger.warning("tracker: metrics recv from rank %d "
                                   "failed: %s", worker.rank, exc)
                    worker.conn.close()
                    continue
                self._mark_alive(worker.rank)
                worker.conn.close()
                self._ingest_metrics(worker.rank, payload)
                continue
            if worker.cmd == "shutdown":
                assert worker.rank >= 0 and worker.rank not in shutdown
                assert worker.rank not in wait_conn
                shutdown[worker.rank] = worker
                with self._liveness_lock:
                    self._shutdown_ranks.add(worker.rank)
                logger.debug("shutdown from rank %d", worker.rank)
                continue
            assert worker.cmd in ("start", "recover"), worker.cmd
            if tree_map is None:
                assert worker.cmd == "start"
                if worker.world_size > 0:
                    # lazy world size from the first worker (tracker.py:290-293)
                    num_workers = worker.world_size
                    self.num_workers = num_workers
                tree_map, parent_map, ring_map = get_link_map(num_workers)
                todo_nodes = list(range(num_workers))
            else:
                assert worker.world_size in (-1, num_workers)
            if worker.cmd == "recover":
                assert worker.rank >= 0
            rank = worker.decide_rank(job_map)
            if rank == -1:
                assert todo_nodes
                pending.append(worker)
                if len(pending) == len(todo_nodes):
                    # batch assignment; optionally pin rank 0 to the master
                    if master_ip:
                        for i, w in enumerate(pending):
                            if w.host == master_ip:
                                pending.insert(0, pending.pop(i))
                                break
                    for w in pending:
                        r = todo_nodes.pop(0)
                        if w.jobid != "NULL":
                            job_map[w.jobid] = r
                        try:
                            w.assign_rank(r, wait_conn, tree_map,
                                          parent_map, ring_map)
                        except (ConnectionError, OSError, EOFError) as exc:
                            # a worker dying mid-start-brokering (e.g. its
                            # peer-dial retries ran dry and it hung up) must
                            # fail ALONE, not take the rendezvous down with
                            # an unhandled EOF (ADVICE r4 #5). Undo its
                            # settles so peer accounting is exact again;
                            # with a jobid its relaunch re-claims rank r via
                            # job_map and re-brokers. Without one no relaunch
                            # can ever reclaim the rank — fail loudly.
                            _rollback_settles(w, wait_conn)
                            w.conn.close()
                            if w.jobid == "NULL":
                                raise ConnectionError(
                                    f"worker {w.host} (rank {r}) died during "
                                    f"start brokering and carries no jobid; "
                                    f"rendezvous cannot complete") from exc
                            logger.warning(
                                "tracker: start brokering for rank %d "
                                "failed (%s); awaiting relaunch of jobid "
                                "%s", r, exc, w.jobid)
                            failed_start_ranks.add(r)
                            continue
                        if w.wait_accept > 0:
                            wait_conn[r] = w
                        rank_addr[r] = (w.host, w.port)
                        logger.debug("%s from %s -> rank %d", w.cmd, w.host, w.rank)
                    pending = []
                if not todo_nodes and not failed_start_ranks:
                    # only when every rank ACTUALLY completed brokering — a
                    # worker that died mid-start is assigned but not
                    # started, and logging success there would hand an
                    # operator a healthy-looking log for a stalled world
                    logger.info("@tracker all %d nodes started", num_workers)
                    self.start_time = time.time()
            else:
                known_addr = None
                if worker.cmd == "recover":
                    # never hand out a dead peer's listener: a rank flagged
                    # lost by the liveness monitor may be dead or
                    # relaunching, and a rank that already sent shutdown has
                    # exited (listener closed) — either address would fail
                    # the recovered worker's dial. A lost rank re-links when
                    # it recovers; a shut-down one never needs to.
                    with self._liveness_lock:
                        dead = set(self.lost_workers) | self._shutdown_ranks
                    known_addr = {r: a for r, a in rank_addr.items()
                                  if r not in dead}
                try:
                    worker.assign_rank(rank, wait_conn, tree_map, parent_map,
                                       ring_map, known_addr=known_addr)
                except (ConnectionError, OSError, EOFError) as exc:
                    # a worker dying mid-brokering must not kill the accept
                    # loop: it relaunches under DMLC_NUM_ATTEMPT and
                    # re-enters (recover keeps its rank; a jobid start
                    # re-claims it via job_map). Roll back this call's
                    # settles first — leaving them applied would let the
                    # relaunch settle the same peers twice, driving
                    # wait_accept negative (ADVICE r4 #1).
                    _rollback_settles(worker, wait_conn)
                    logger.warning(
                        "tracker: %s brokering for rank %d failed (%s); "
                        "awaiting its relaunch", worker.cmd, rank, exc)
                    if worker.cmd == "start":
                        failed_start_ranks.add(rank)
                    worker.conn.close()
                    continue
                if worker.wait_accept > 0:
                    wait_conn[rank] = worker
                rank_addr[rank] = (worker.host, worker.port)
                logger.debug("%s from rank %d", worker.cmd, worker.rank)
                if worker.cmd == "start" and rank in failed_start_ranks:
                    failed_start_ranks.discard(rank)
                    if (not todo_nodes and not failed_start_ranks
                            and self.start_time is None):
                        logger.info("@tracker all %d nodes started",
                                    num_workers)
                        self.start_time = time.time()
        self.end_time = time.time()
        if self.start_time is not None:
            logger.info("@tracker %.3f secs between node start and job finish",
                        self.end_time - self.start_time)

    def start(self, num_workers: Optional[int] = None,
              master_ip: Optional[str] = None) -> None:
        n = num_workers if num_workers is not None else self.num_workers
        self.thread = threading.Thread(
            target=self._accept_loop, args=(n, master_ip), daemon=True
        )
        self.thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.time() + timeout
        while self.thread is not None and self.thread.is_alive():
            self.thread.join(0.1)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("tracker: join timed out")

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def close(self) -> None:
        if self._monitor is not None:
            self._monitor.request_shutdown()
            self._monitor = None
        try:
            self.sock.close()
        except OSError:
            pass


class PSTracker:
    """Parameter-server bootstrap: export scheduler env + run the scheduler
    locally (tracker.py:351-401). Rank brokering is done by ps-lite itself."""

    def __init__(self, host_ip: str, cmd: Optional[str] = None,
                 port: int = 9091, port_end: int = 9999,
                 envs: Optional[Dict[str, str]] = None):
        self.host_ip = host_ip
        self.cmd = cmd
        self.envs = dict(envs or {})
        if cmd:
            # probe a free port the same way the reference does
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            for p in range(port, port_end):
                try:
                    sock.bind(("", p))
                    self.port = p
                    break
                except OSError:
                    continue
            sock.close()
            self.thread = threading.Thread(target=self._run_scheduler, daemon=True)
            self.thread.start()
        else:
            self.thread = None

    def _run_scheduler(self) -> None:
        import os
        import subprocess

        env = os.environ.copy()
        env.update(self.envs)
        env["DMLC_ROLE"] = "scheduler"
        env.update(self.worker_envs())
        subprocess.check_call(self.cmd, shell=True, env=env)

    def worker_envs(self) -> Dict[str, str]:
        if self.cmd:
            return {
                "DMLC_PS_ROOT_URI": self.host_ip,
                "DMLC_PS_ROOT_PORT": str(self.port),
            }
        return {}

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()


def get_host_ip(host_ip: Optional[str] = None) -> str:
    """Best-effort local IP discovery (tracker.py submit's hostIP handling)."""
    if host_ip is None or host_ip == "auto":
        host_ip = "ip"
    if host_ip == "dns":
        return socket.getfqdn()
    if host_ip == "ip":
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            s.close()
            return ip
        except OSError:
            return "127.0.0.1"
    return host_ip


def start_rabit_tracker(args) -> None:
    """Standalone rabit tracker (reference tracker.py:450-470): start the
    rendezvous server, print the worker env contract between the
    ``DMLC_TRACKER_ENV_START`` / ``DMLC_TRACKER_ENV_END`` sentinels on
    stdout — the machine-readable block external launchers scrape for
    rank/coordinator env — then block until every worker has sent
    ``shutdown``."""
    import sys

    envs = {"DMLC_NUM_WORKER": args.num_workers,
            "DMLC_NUM_SERVER": args.num_servers}
    lt = float(os.environ.get("DMLC_LIVENESS_TIMEOUT") or 0)
    rabit = RabitTracker(get_host_ip(args.host_ip), args.num_workers,
                         liveness_timeout=lt if lt > 0 else None)
    envs.update(rabit.worker_envs())
    rabit.start(args.num_workers)
    sys.stdout.write("DMLC_TRACKER_ENV_START\n")
    # simply write configuration to stdout (the reference's exact shape:
    # one KEY=value line per env, values str()'d)
    for k, v in envs.items():
        sys.stdout.write(f"{k}={v}\n")
    sys.stdout.write("DMLC_TRACKER_ENV_END\n")
    sys.stdout.flush()
    rabit.join()
    rabit.close()


def main() -> None:
    """``python -m dmlc_tpu.tracker.tracker --num-workers N ...`` — the
    standalone tracker CLI (reference tracker.py:473-502): external
    launchers start it, parse the env block off stdout, export those
    variables to their workers, and wait for the process to exit when
    the job's ranks all shut down."""
    import argparse

    parser = argparse.ArgumentParser(
        description="Start a standalone rabit tracker and print the "
                    "DMLC_TRACKER_ENV_START/END worker env block.")
    parser.add_argument("--num-workers", required=True, type=int,
                        help="number of worker ranks to rendezvous")
    parser.add_argument("--num-servers", default=0, type=int,
                        help="number of parameter servers (only 0 is "
                             "supported standalone, as in the reference)")
    parser.add_argument("--host-ip", default=None, type=str,
                        help="tracker bind/advertise IP (default: "
                             "auto-discover; 'dns' uses the FQDN)")
    parser.add_argument("--log-level", default="INFO", type=str,
                        choices=["INFO", "DEBUG"],
                        help="logging level")
    args = parser.parse_args()
    fmt = "%(asctime)s-%(levelname)s:%(name)s:%(message)s"
    level = logging.DEBUG if args.log_level == "DEBUG" else logging.INFO
    logging.basicConfig(format=fmt, level=level)
    if args.num_servers == 0:
        start_rabit_tracker(args)
    else:
        raise RuntimeError(
            "do not yet support start ps tracker in standalone mode.")


def submit(num_workers: int, num_servers: int, fun_submit,
           host_ip: Optional[str] = None, pscmd: Optional[str] = None):
    """Start the right tracker, call the backend launcher, wait
    (tracker.py:425-448)."""
    ip = get_host_ip(host_ip)
    envs = {"DMLC_NUM_WORKER": str(num_workers),
            "DMLC_NUM_SERVER": str(num_servers)}
    rabit: Optional[RabitTracker] = None
    pserver: Optional[PSTracker] = None
    if num_servers == 0:
        # DMLC_LIVENESS_TIMEOUT (seconds) arms heartbeat-based failure
        # detection for workers using our WorkerClient; unset = off (legacy
        # rabit clients send no heartbeats and must not be flagged)
        lt = float(os.environ.get("DMLC_LIVENESS_TIMEOUT") or 0)
        rabit = RabitTracker(ip, num_workers,
                             liveness_timeout=lt if lt > 0 else None)
        envs.update(rabit.worker_envs())
        rabit.start(num_workers)
    else:
        pserver = PSTracker(ip, pscmd, envs=envs)
        envs.update(pserver.worker_envs())
    try:
        fun_submit(num_workers, num_servers, envs)
    except BaseException:
        if rabit is not None:
            rabit.close()
        raise
    if num_servers == 0:
        # all worker processes have exited; if the tracker is still waiting
        # for shutdown commands the job died mid-flight — fail fast instead
        # of blocking on accept forever (the reference hangs here; SURVEY.md
        # §5.3 "no heartbeat/timeout detection")
        try:
            rabit.join(timeout=10.0)
        except TimeoutError:
            rabit.close()
            raise RuntimeError(
                "tracker: worker processes exited but not all ranks sent "
                "shutdown — distributed job failed") from None
        rabit.close()
    else:
        pserver.join()


if __name__ == "__main__":
    main()
