"""Kubernetes backend — analog of tracker/dmlc_tracker/kubernetes.py.

Builds Job manifests for scheduler/servers/workers plus a Service for the
scheduler's stable DNS (kubernetes.py:40-63, 102-137). Manifest
construction is pure (testable); submission shells out to kubectl.
"""

from __future__ import annotations

import json
import subprocess
from typing import Dict, List


def job_manifest(name: str, image: str, command: List[str],
                 envs: Dict[str, str], replicas: int = 1,
                 cores: int = 1, memory_mb: int = 1024) -> dict:
    env_list = [{"name": k, "value": str(v)} for k, v in sorted(envs.items())]
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name},
        "spec": {
            "completions": replicas,
            "parallelism": replicas,
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [{
                        "name": name,
                        "image": image,
                        "command": command,
                        "env": env_list,
                        "resources": {"requests": {
                            "cpu": str(cores),
                            "memory": f"{memory_mb}Mi",
                        }},
                    }],
                },
            },
        },
    }


def scheduler_service_manifest(name: str, port: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name},
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def build_manifests(args, envs: Dict[str, str], image: str = "python:3.11"):
    """All manifests for a PS-style job (kubernetes.py:102-137)."""
    out = []
    base = dict(envs)
    base.update(args.pass_envs)
    name = args.jobname.replace("_", "-")
    scheduler_name = f"{name}-scheduler"
    port = int(base.get("DMLC_PS_ROOT_PORT", "9091"))
    if args.num_servers > 0:
        sched_env = dict(base, DMLC_ROLE="scheduler")
        out.append(job_manifest(scheduler_name, image, args.command, sched_env))
        out.append(scheduler_service_manifest(scheduler_name, port))
        server_env = dict(base, DMLC_ROLE="server")
        out.append(job_manifest(f"{name}-server", image, args.command,
                                server_env, replicas=args.num_servers,
                                cores=args.server_cores,
                                memory_mb=args.server_memory_mb))
    worker_env = dict(base, DMLC_ROLE="worker")
    out.append(job_manifest(f"{name}-worker", image, args.command,
                            worker_env, replicas=args.num_workers,
                            cores=args.worker_cores,
                            memory_mb=args.worker_memory_mb))
    return out


def submit(args):
    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        for manifest in build_manifests(args, envs):
            proc = subprocess.run(
                ["kubectl", "apply", "-f", "-"],
                input=json.dumps(manifest), text=True, capture_output=True)
            if proc.returncode != 0:
                raise RuntimeError(f"kubectl apply failed: {proc.stderr}")

    return run
