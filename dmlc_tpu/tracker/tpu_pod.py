"""tpu-pod backend: the TPU-native launcher (BASELINE.json north star).

The reference's YARN/MPI backends place processes and let rabit broker
ranks over sockets. On a TPU pod slice the placement is per-host
(one process per TPU-VM worker) and rank brokering is
``jax.distributed.initialize`` — so this backend:

1. starts the rabit tracker (rank-stable coordination + the env contract),
2. launches one process per pod host — over ssh when a ``--host-file``
   lists the TPU-VM workers, or locally (multi-process simulation /
   single-host v5e) otherwise,
3. exports ``DMLC_TRACKER_URI/PORT``, ``DMLC_NUM_WORKER``,
   ``DMLC_TASK_ID``; workers call
   :func:`dmlc_tpu.parallel.init_from_env`, which maps that contract onto
   the JAX coordinator (coordinator = tracker host, port + 1), and their
   InputSplit shard index is their process index (SURVEY.md §2.3 row 1).
   The same ``DMLC_TASK_ID``/``DMLC_NUM_WORKER`` pair doubles as the pod
   identity the deterministic epoch planner's ``pod_sharding`` resolves
   (:func:`dmlc_tpu.parallel.distributed.pod_identity`): each launched
   worker reads its disjoint shard of one globally consistent shuffled
   epoch straight from the launcher env (docs/data.md).

The job's data plane is XLA collectives over ICI — no peer sockets to
broker, which is why this backend needs nothing beyond placement + env.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List

from dmlc_tpu.tracker.local import run_with_retry
from dmlc_tpu.tracker.opts import read_host_file
from dmlc_tpu.tracker.ssh import build_remote_command, build_ssh_argv, parse_host
from dmlc_tpu.utils.check import get_logger


def worker_env(envs: Dict[str, str], task_id: int) -> Dict[str, str]:
    env = dict(envs)
    env["DMLC_ROLE"] = "worker"
    env["DMLC_TASK_ID"] = str(task_id)
    env["DMLC_JOB_CLUSTER"] = "tpu-pod"
    # jax.distributed.initialize args are derived from DMLC_TRACKER_URI/PORT
    # by dmlc_tpu.parallel.init_from_env; nothing else to export.
    return env


def submit(args):
    hosts: List[str] = []
    if args.host_file:
        hosts = read_host_file(args.host_file)

    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        assert nserver == 0, "tpu-pod jobs are allreduce-style (no PS role)"
        threads = []
        errors: List[BaseException] = []
        base = dict(envs)
        base.update(args.pass_envs)

        def guarded(fn, *fn_args) -> None:
            try:
                fn(*fn_args)
            except BaseException as exc:  # noqa: BLE001 - reported to launcher
                errors.append(exc)

        if hosts:
            assert len(hosts) >= nworker, (
                f"tpu-pod: host file lists {len(hosts)} hosts < {nworker} workers")
            for i in range(nworker):
                host, port = parse_host(hosts[i])
                env = worker_env(base, i)
                remote = build_remote_command(
                    args.command, env, host, args.sync_dst_dir or os.getcwd())
                argv = build_ssh_argv(host, port, remote)
                t = threading.Thread(
                    target=guarded, args=(subprocess.check_call, argv))
                t.daemon = True
                t.start()
                threads.append(t)
        else:
            get_logger().info(
                "tpu-pod: no --host-file, launching %d local processes", nworker)
            num_attempt = max(1, getattr(args, "local_num_attempt", 1))
            for i in range(nworker):
                env = os.environ.copy()
                env.update(worker_env(base, i))
                t = threading.Thread(
                    target=guarded,
                    args=(run_with_retry, args.command, env,
                          f"tpu-pod worker {i}", num_attempt))
                t.daemon = True
                t.start()
                threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"tpu-pod job failed ({len(errors)} worker thread(s)): "
                f"{'; '.join(str(e) for e in errors)}") from errors[0]

    return run
