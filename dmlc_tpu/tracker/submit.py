"""dmlc-submit entry point — analog of tracker/dmlc_tracker/submit.py.

Dispatches every registered cluster (the reference forgot slurm/kubernetes,
submit.py:43-56). YARN keeps its CLI slot but the Java ApplicationMaster is
deferred (SURVEY.md §7 non-goals); mesos is dropped (deprecated ecosystem).

Usage::

    python -m dmlc_tpu.tracker.submit --cluster local --num-workers 4 -- cmd...
"""

from __future__ import annotations

import logging
import sys
from typing import List, Optional

from dmlc_tpu.tracker import tracker as tracker_mod
from dmlc_tpu.tracker.opts import parse_opts


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_opts(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level),
        filename=args.log_file,
        format="%(asctime)s %(levelname)s %(message)s",
    )
    if args.cluster == "local":
        from dmlc_tpu.tracker import local as backend
    elif args.cluster == "ssh":
        from dmlc_tpu.tracker import ssh as backend
    elif args.cluster == "mpi":
        from dmlc_tpu.tracker import mpi as backend
    elif args.cluster == "sge":
        from dmlc_tpu.tracker import sge as backend
    elif args.cluster == "slurm":
        from dmlc_tpu.tracker import slurm as backend
    elif args.cluster == "kubernetes":
        from dmlc_tpu.tracker import kubernetes as backend
    elif args.cluster == "tpu-pod":
        from dmlc_tpu.tracker import tpu_pod as backend
    elif args.cluster == "yarn":
        raise SystemExit(
            "dmlc-submit: yarn is a documented non-goal (PARITY.md): the "
            "ApplicationMaster protocol is JVM-only protobuf RPC with no "
            "REST surface, and TPU fleets are provisioned via GKE/TPU pod "
            "tooling instead. Use --cluster kubernetes or --cluster tpu-pod "
            "(same DMLC_* env contract).")
    else:  # pragma: no cover - argparse enforces choices
        raise SystemExit(f"dmlc-submit: unknown cluster {args.cluster!r}")
    fun_submit = backend.submit(args)
    pscmd = " ".join(args.command) if args.num_servers > 0 else None
    tracker_mod.submit(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip, pscmd=pscmd,
    )


if __name__ == "__main__":
    main(sys.argv[1:])
