"""Slurm backend — analog of tracker/dmlc_tracker/slurm.py.

Launches workers and servers as ``srun`` job steps (slurm.py:38-60). The
reference registers slurm in opts but never dispatches it (submit.py bug);
here it is first-class.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List


def build_srun_argv(command: List[str], nnodes: int, ntasks: int,
                    jobname: str) -> List[str]:
    return ["srun", f"--job-name={jobname}", f"--nodes={nnodes}",
            f"--ntasks={ntasks}", "--kill-on-bad-exit=1"] + command


def submit(args):
    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        import os

        threads = []
        for role, count in (("worker", nworker), ("server", nserver)):
            if count == 0:
                continue
            env = os.environ.copy()
            env.update(envs)
            env.update(args.pass_envs)
            env["DMLC_ROLE"] = role
            env["DMLC_JOB_CLUSTER"] = "slurm"
            argv = build_srun_argv(args.command, min(count, count), count,
                                   f"{args.jobname}-{role}")
            t = threading.Thread(
                target=subprocess.check_call, kwargs={"args": argv, "env": env})
            t.daemon = True
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    return run
