"""Inside-container bootstrap for launched workers.

Analog of reference tracker/dmlc_tracker/launcher.py (used by the YARN and
container backends): prepare the environment a worker binary expects, then
exec the user command —
- unpack job archives listed in ``DMLC_JOB_ARCHIVES`` (launcher.py:18-40);
- extend ``PYTHONPATH``/``LD_LIBRARY_PATH`` from ``DMLC_EXTRA_PYTHONPATH``/
  ``DMLC_EXTRA_LDPATH`` (the reference hardwires Hadoop CLASSPATH/libhdfs
  here, launcher.py:41-70 — a TPU-VM needs no JVM, so the generic hooks
  replace it);
- on a TPU pod slice, surface the ``DMLC_*`` contract as the
  ``jax.distributed`` coordinator variables (tpu_pod backend contract).

Run as ``python -m dmlc_tpu.tracker.launcher <cmd> [args...]``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import zipfile
from typing import Dict, List, Optional


def unpack_archives(spec: Optional[str], dest: str = ".") -> List[str]:
    """Unzip each archive in the '#'-aliased, ':'-separated spec.

    ``a.zip#alias`` extracts a.zip into ``dest/alias`` (the YARN convention
    the reference launcher follows); plain ``a.zip`` extracts in place.
    Returns the extraction directories.
    """
    out: List[str] = []
    for item in (spec or "").split(":"):
        if not item:
            continue
        if "#" in item:
            path, alias = item.split("#", 1)
        else:
            path, alias = item, ""
        target = os.path.join(dest, alias) if alias else dest
        if not os.path.exists(path):
            continue
        os.makedirs(target, exist_ok=True)
        with zipfile.ZipFile(path) as zf:
            zf.extractall(target)
        out.append(target)
    return out


def build_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Worker environment: pass DMLC_* through, extend search paths, and
    map the tracker contract onto jax.distributed's variables."""
    env = dict(os.environ if base is None else base)

    def _extend(var: str, extra_var: str) -> None:
        extra = env.get(extra_var)
        if extra:
            env[var] = extra + os.pathsep + env[var] if env.get(var) else extra

    _extend("PYTHONPATH", "DMLC_EXTRA_PYTHONPATH")
    _extend("LD_LIBRARY_PATH", "DMLC_EXTRA_LDPATH")
    # DMLC_* -> jax.distributed coordinator contract (SURVEY.md §2.4): set
    # only when the tracker vars exist and the JAX ones are not already set
    tracker_uri = env.get("DMLC_TRACKER_URI")
    tracker_port = env.get("DMLC_TRACKER_PORT")
    if tracker_uri and tracker_port and "JAX_COORDINATOR_ADDRESS" not in env:
        env["JAX_COORDINATOR_ADDRESS"] = f"{tracker_uri}:{tracker_port}"
    if "DMLC_NUM_WORKER" in env and "JAX_NUM_PROCESSES" not in env:
        env["JAX_NUM_PROCESSES"] = env["DMLC_NUM_WORKER"]
    if "DMLC_TASK_ID" in env and "JAX_PROCESS_ID" not in env:
        env["JAX_PROCESS_ID"] = env["DMLC_TASK_ID"]
    return env


def main(argv: Optional[List[str]] = None, use_exec: bool = True) -> int:
    """Bootstrap then run the worker. With ``use_exec`` (the default, and
    what ``-m`` invocation does) the worker replaces this process via
    ``os.execvpe`` so cluster-manager signals reach it directly — the
    reference launcher does the same. ``use_exec=False`` runs it as a child
    and returns the exit code (for embedding/tests)."""
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m dmlc_tpu.tracker.launcher <cmd> [args...]",
              file=sys.stderr)
        return 2
    unpack_archives(os.environ.get("DMLC_JOB_ARCHIVES"))
    env = build_env()
    if use_exec:
        os.execvpe(argv[0], argv, env)  # no return
    proc = subprocess.run(argv, env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
