"""Worker-side client for the rabit tracker protocol.

The reference ships no Python client (workers are C++ rabit binaries); this
client speaks the same wire protocol (tracker.py:58-136) so that

- the tracker gets real in-process integration tests (the reference has
  none — SURVEY.md §4 gap),
- ``tpu-pod`` workers can fetch a stable rank assignment from the tracker
  before handing coordination to ``jax.distributed``.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

from dmlc_tpu.io.resilience import RetryPolicy
from dmlc_tpu.tracker.tracker import MAGIC, Conn
from dmlc_tpu.utils import telemetry as _telemetry


class Assignment(NamedTuple):
    rank: int
    parent: int
    world_size: int
    tree_neighbors: List[int]
    ring_prev: int
    ring_next: int
    connected_peers: List[Tuple[str, int, int]]  # (host, port, rank) we dialed
    num_incoming: int                            # peers that will dial us


class WorkerClient:
    """One worker's view of the tracker."""

    def __init__(self, tracker_uri: str, tracker_port: int, jobid: str = "NULL"):
        self.tracker_uri = tracker_uri
        self.tracker_port = tracker_port
        self.jobid = jobid
        self.rank = -1
        self._listen_sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._peer_socks: List[socket.socket] = []
        self._hb_group = None  # ThreadGroup, created on first start_heartbeat
        self._hb_thread = None
        self._hb_seq = 0

    # ---------------- protocol ----------------

    def _hello(self, cmd: str, rank: int, world_size: int) -> Conn:
        sock = socket.create_connection(
            (self.tracker_uri, self.tracker_port), timeout=30)
        conn = Conn(sock)
        conn.send_int(MAGIC)
        magic = conn.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"tracker: bad magic {magic:#x}")
        conn.send_int(rank)
        conn.send_int(world_size)
        conn.send_str(self.jobid)
        conn.send_str(cmd)
        return conn

    def _listen(self) -> int:
        self._listen_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen_sock.bind(("", 0))
        self._listen_sock.listen(16)
        return self._listen_sock.getsockname()[1]

    def _accept_incoming(self, count: int) -> None:
        for _ in range(count):
            try:
                peer, _ = self._listen_sock.accept()
                self._peer_socks.append(peer)
            except OSError:
                return

    def start(self, world_size: int = -1, rank: int = -1,
              cmd: str = "start") -> Assignment:
        """Join the job; blocks until the tracker assigns a rank and all
        outgoing peer links are dialed (tracker.py:81-136 client side)."""
        port = self._listen() if self._listen_sock is None else \
            self._listen_sock.getsockname()[1]
        conn = self._hello(cmd, rank, world_size)
        self.rank = conn.recv_int()
        parent = conn.recv_int()
        world = conn.recv_int()
        num_nn = conn.recv_int()
        neighbors = [conn.recv_int() for _ in range(num_nn)]
        rprev = conn.recv_int()
        rnext = conn.recv_int()
        # brokering loop: report linked ranks, dial what the tracker hands
        # out, and report dial FAILURES via the protocol's nerr field (the
        # tracker then re-brokers) instead of dying on the first refused
        # connection — recovery can be handed a peer that died in the same
        # window (tracker.py assign_rank known_addr). Bounded: persistent
        # failures raise, and the DMLC_NUM_ATTEMPT relaunch re-enters
        # recover with a fresh, liveness-filtered peer map.
        good: List[int] = []
        peers: List[Tuple[str, int, int]] = []
        nwait = 0
        # backoff between brokering rounds delegates to the shared policy
        # (make lint-retry bans ad-hoc sleep-in-retry-loop patterns)
        broker = RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=0.8)
        for attempt in range(3):
            conn.send_int(len(good))
            for r in good:
                conn.send_int(r)
            nconn = conn.recv_int()
            nwait = conn.recv_int()
            todo = []
            for _ in range(nconn):
                host = conn.recv_str()
                pport = conn.recv_int()
                prank = conn.recv_int()
                todo.append((host, pport, prank))
            nerr = 0
            for host, pport, prank in todo:
                try:
                    sock_ = socket.create_connection((host, pport), timeout=30)
                except OSError:
                    nerr += 1
                    continue
                self._peer_socks.append(sock_)
                good.append(prank)
                peers.append((host, pport, prank))
            conn.send_int(nerr)
            if nerr == 0:
                break
            if attempt == 2:
                conn.close()
                raise ConnectionError(
                    f"rank {self.rank}: could not link {nerr} peer(s) "
                    f"after {attempt + 1} brokering rounds")
            broker.sleep(broker.backoff(attempt, floor=0.1))
        conn.send_int(port)
        conn.close()
        if nwait > 0:
            self._accept_thread = threading.Thread(
                target=self._accept_incoming, args=(nwait,), daemon=True)
            self._accept_thread.start()
        return Assignment(self.rank, parent, world, neighbors, rprev, rnext,
                          peers, nwait)

    def recover(self, rank: int) -> Assignment:
        """Rejoin after failure keeping the prior rank (tracker.py:288-301)."""
        return self.start(world_size=-1, rank=rank, cmd="recover")

    def heartbeat(self) -> None:
        """One liveness ping (tracker-side SURVEY.md §5.3 failure detection);
        requires an assigned rank."""
        conn = self._hello("heartbeat", self.rank, -1)
        conn.close()

    def report_metrics(self, snapshot: Optional[dict] = None) -> None:
        """Ship one telemetry snapshot to the tracker (pod-scale
        aggregation, docs/observability.md): the ``metrics`` command
        carries ``telemetry.pod_snapshot()`` — per-stage seconds,
        resilience totals, span counts — as one JSON string, and doubles
        as a liveness ping. Requires an assigned rank."""
        snap = snapshot if snapshot is not None else _telemetry.pod_snapshot()
        conn = self._hello("metrics", self.rank, -1)
        conn.send_str(json.dumps(snap))
        conn.close()

    def start_heartbeat(self, interval: float = 5.0, metrics: bool = False):
        """Ping the tracker every `interval` seconds from a managed thread
        until :meth:`stop_heartbeat` (or close). With ``metrics=True``
        every ping also carries this process's telemetry snapshot
        (:meth:`report_metrics`) — the periodic feed behind the tracker's
        merged per-rank stage table. Idempotent: a running heartbeat
        thread is stopped (and, if stuck in a socket op, simply
        superseded — names are unique). Returns the thread."""
        from dmlc_tpu.utils.thread_group import ThreadGroup, timer_thread

        self.stop_heartbeat()
        if self._hb_group is None:
            self._hb_group = ThreadGroup()
        self._hb_seq += 1
        fn = self._safe_report_metrics if metrics else self._safe_heartbeat
        self._hb_thread = timer_thread(
            self._hb_group, f"heartbeat-{self._hb_seq}", interval,
            fn, run_first_immediately=True)
        return self._hb_thread

    def _safe_heartbeat(self) -> None:
        try:
            self.heartbeat()
        except OSError:
            pass  # tracker gone; shutdown paths report the real error

    def _safe_report_metrics(self) -> None:
        try:
            self.report_metrics()
        except OSError:
            pass  # tracker gone; shutdown paths report the real error

    def stop_heartbeat(self) -> None:
        if self._hb_thread is not None:
            self._hb_thread.request_shutdown()
            self._hb_thread.join(2)
            self._hb_thread = None

    def print_to_tracker(self, message: str) -> None:
        conn = self._hello("print", -1, -1)
        conn.send_str(message)
        conn.close()

    def shutdown(self) -> None:
        assert self.rank >= 0, "shutdown before rank assignment"
        conn = self._hello("shutdown", self.rank, -1)
        conn.close()
        self.close()

    def close(self) -> None:
        self.stop_heartbeat()
        for s in self._peer_socks:
            try:
                s.close()
            except OSError:
                pass
        self._peer_socks = []
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
            self._listen_sock = None
