"""MPI backend — analog of tracker/dmlc_tracker/mpi.py.

MPI is used as a *process launcher* only (the reference has no MPI data
plane either, SURVEY.md §2.4): builds an ``mpirun`` line with env
forwarding in the dialect the installed MPI speaks — OpenMPI ``-x K=V`` vs
MPICH ``-env K V`` (mpi.py:12-36).
"""

from __future__ import annotations

import subprocess
from typing import Dict, List, Optional


def detect_mpi_dialect(version_text: Optional[str] = None) -> str:
    """'openmpi' | 'mpich' from `mpirun --version` output."""
    if version_text is None:
        try:
            version_text = subprocess.run(
                ["mpirun", "--version"], capture_output=True, text=True,
                timeout=10).stdout
        except (OSError, subprocess.TimeoutExpired):
            return "openmpi"
    text = version_text.lower()
    if "open mpi" in text or "open-mpi" in text:
        return "openmpi"
    if "mpich" in text or "hydra" in text:
        return "mpich"
    return "openmpi"


def build_mpirun_argv(command: List[str], nprocs: int, envs: Dict[str, str],
                      dialect: str, host_file: Optional[str] = None) -> List[str]:
    argv = ["mpirun", "-n", str(nprocs)]
    if host_file:
        argv += ["--hostfile", host_file]
    for key, value in envs.items():
        if dialect == "openmpi":
            argv += ["-x", f"{key}={value}"]
        else:
            argv += ["-env", key, str(value)]
    return argv + command


def submit(args):
    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        dialect = detect_mpi_dialect()
        for role, count in (("worker", nworker), ("server", nserver)):
            if count == 0:
                continue
            env = dict(envs)
            env.update(args.pass_envs)
            env["DMLC_ROLE"] = role
            env["DMLC_JOB_CLUSTER"] = "mpi"
            argv = build_mpirun_argv(args.command, count, env, dialect,
                                     args.host_file)
            subprocess.check_call(argv)

    return run
