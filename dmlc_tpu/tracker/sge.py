"""SGE backend — analog of tracker/dmlc_tracker/sge.py.

Generates a run script and submits a ``qsub -t 1-N`` array job; the task id
comes from ``$SGE_TASK_ID`` (sge.py:22-40).
"""

from __future__ import annotations

import os
import subprocess
from typing import Dict, List


def build_run_script(command: List[str], envs: Dict[str, str], role: str) -> str:
    lines = ["#!/bin/bash"]
    for key, value in envs.items():
        lines.append(f"export {key}={value}")
    lines.append(f"export DMLC_ROLE={role}")
    lines.append("export DMLC_TASK_ID=$((SGE_TASK_ID - 1))")
    lines.append("export DMLC_JOB_CLUSTER=sge")
    lines.append(" ".join(command))
    return "\n".join(lines) + "\n"


def build_qsub_argv(script_path: str, count: int, jobname: str, queue: str,
                    cores: int) -> List[str]:
    return ["qsub", "-cwd", "-t", f"1-{count}", "-S", "/bin/bash",
            "-N", jobname, "-q", queue, "-pe", "smp", str(cores),
            script_path]


def submit(args):
    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        env = dict(envs)
        env.update(args.pass_envs)
        for role, count, cores in (
            ("worker", nworker, args.worker_cores),
            ("server", nserver, args.server_cores),
        ):
            if count == 0:
                continue
            script = build_run_script(args.command, env, role)
            path = f"rundmlc-{role}.sh"
            with open(path, "w") as f:
                f.write(script)
            os.chmod(path, 0o755)
            subprocess.check_call(
                build_qsub_argv(path, count, f"{args.jobname}-{role}",
                                args.queue, cores))

    return run
