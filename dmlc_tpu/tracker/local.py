"""Local multiprocess backend — analog of tracker/dmlc_tracker/local.py.

Spawns worker/server subprocesses on this machine with the DMLC_* env
contract; failed workers retry up to DMLC_NUM_ATTEMPT times
(local.py:12-49).
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List

from dmlc_tpu.utils.check import get_logger


def run_with_retry(cmd: List[str], env: Dict[str, str], label: str,
                   num_attempt: int = 1) -> None:
    """THE DMLC_NUM_ATTEMPT retry contract (reference local.py:26-49),
    shared by every process-spawning backend: relaunch a failed worker with
    the same identity env up to ``num_attempt`` times, exporting the
    attempt counter in DMLC_NUM_ATTEMPT so a restarted worker can take its
    recovery path (e.g. rabit ``recover`` with its old rank)."""
    ntrial = 0
    while True:
        returncode = subprocess.call(cmd, env=env)
        if returncode == 0:
            return
        ntrial += 1
        if ntrial >= num_attempt:
            raise RuntimeError(
                f"{label} failed with code {returncode} "
                f"after {ntrial} attempt(s)")
        env["DMLC_NUM_ATTEMPT"] = str(ntrial)
        get_logger().warning(
            "%s failed (code %d), relaunching %d/%d",
            label, returncode, ntrial, num_attempt)


def exec_cmd(cmd: List[str], role: str, taskid: int, pass_env: Dict[str, str],
             num_attempt: int = 1) -> None:
    env = os.environ.copy()
    env.update(pass_env)
    env["DMLC_TASK_ID"] = str(taskid)
    env["DMLC_ROLE"] = role
    env["DMLC_JOB_CLUSTER"] = "local"
    run_with_retry(cmd, env, f"local worker {role}:{taskid}", num_attempt)


def submit(args):
    """Backend entry: returns the fun_submit callback for tracker.submit."""

    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        pass_env = dict(envs)
        pass_env.update(args.pass_envs)
        threads = []
        errors: List[BaseException] = []

        def guarded(role: str, i: int) -> None:
            try:
                exec_cmd(args.command, role, i, pass_env, args.local_num_attempt)
            except BaseException as exc:  # noqa: BLE001 - reported to launcher
                errors.append(exc)

        for i in range(nworker):
            t = threading.Thread(target=guarded, args=("worker", i))
            t.daemon = True
            t.start()
            threads.append(t)
        for i in range(nserver):
            t = threading.Thread(target=guarded, args=("server", i))
            t.daemon = True
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"local job failed ({len(errors)} worker thread(s)): "
                f"{'; '.join(str(e) for e in errors)}") from errors[0]

    return run
