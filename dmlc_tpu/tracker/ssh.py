"""SSH backend — analog of tracker/dmlc_tracker/ssh.py.

Reads a host file (``ip[:port]`` per line), optionally rsyncs the working
dir (ssh.py:14-22), exports a whitelisted env set plus the DMLC contract,
and launches the command on each host over ssh (ssh.py:77-86).
Command construction is separated from execution so it is testable.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List, Tuple

from dmlc_tpu.tracker.opts import read_host_file

# env whitelist forwarded to remote nodes (ssh.py:24-36)
FORWARD_ENV = [
    "OMP_NUM_THREADS", "LD_LIBRARY_PATH", "PATH", "PYTHONPATH",
    "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY", "DMLC_INTERFACE",
    "JAX_PLATFORMS", "XLA_FLAGS", "TPU_WORKER_HOSTNAMES",
]


def parse_host(entry: str) -> Tuple[str, int]:
    if ":" in entry:
        host, port = entry.rsplit(":", 1)
        return host, int(port)
    return entry, 22


def build_remote_command(
    command: List[str], envs: Dict[str, str], host: str, workdir: str
) -> str:
    """The shell line run on the remote host (ssh.py:60-86)."""
    exports = []
    for key in FORWARD_ENV:
        if key in os.environ:
            exports.append(f"export {key}={_q(os.environ[key])};")
    for key, value in envs.items():
        exports.append(f"export {key}={_q(str(value))};")
    exports.append(f"export DMLC_NODE_HOST={_q(host)};")
    return " ".join(exports) + f" cd {_q(workdir)}; " + " ".join(command)


def _q(s: str) -> str:
    return "'" + s.replace("'", "'\"'\"'") + "'"


def build_ssh_argv(host: str, port: int, remote_cmd: str) -> List[str]:
    return ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(port),
            host, remote_cmd]


def sync_dir(local_dir: str, host: str, port: int, remote_dir: str) -> List[str]:
    """rsync argv for shipping the working dir (ssh.py:14-22)."""
    return ["rsync", "-az", "--rsh", f"ssh -o StrictHostKeyChecking=no -p {port}",
            local_dir + "/", f"{host}:{remote_dir}"]


def submit(args):
    hosts = [parse_host(h) for h in read_host_file(args.host_file)]

    def run(nworker: int, nserver: int, envs: Dict[str, str]):
        assert len(hosts) > 0, "ssh backend: empty host file"
        threads = []
        workdir = args.sync_dst_dir or os.getcwd()
        for i in range(nworker + nserver):
            host, port = hosts[i % len(hosts)]
            role = "worker" if i < nworker else "server"
            env = dict(envs)
            env.update(args.pass_envs)
            env["DMLC_ROLE"] = role
            env["DMLC_TASK_ID"] = str(i if role == "worker" else i - nworker)
            env["DMLC_JOB_CLUSTER"] = "ssh"
            if args.sync_dst_dir:
                subprocess.check_call(
                    sync_dir(os.getcwd(), host, port, args.sync_dst_dir))
            argv = build_ssh_argv(
                host, port, build_remote_command(args.command, env, host, workdir))
            t = threading.Thread(target=subprocess.check_call, args=(argv,))
            t.daemon = True
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    return run
