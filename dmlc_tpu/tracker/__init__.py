"""Distributed launch + rank coordination (reference layer 7).

A wire-compatible rebuild of the reference rabit tracker
(tracker/dmlc_tracker/tracker.py): TCP rendezvous, rank assignment with
allreduce tree + ring topology computation, peer brokering, recovery — plus
the ``dmlc-submit`` launcher backends, extended with a ``tpu-pod`` backend
that wires the same env contract into ``jax.distributed``.
"""

from dmlc_tpu.tracker.tracker import RabitTracker, PSTracker, submit
from dmlc_tpu.tracker.client import WorkerClient

__all__ = ["RabitTracker", "PSTracker", "submit", "WorkerClient"]
