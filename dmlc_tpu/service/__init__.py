"""Disaggregated RowBlock data service (tf.data service, arXiv:2210.14826).

One shared **multi-tenant** preprocessing tier feeds many trainer jobs:
a :class:`~dmlc_tpu.service.dispatcher.Dispatcher` owns a registry of N
jobs (``register_job``) and their split assignment (exactly-once per
epoch, round-robin grant rotation across jobs, re-issue on worker
death), tracker-launchable
:class:`~dmlc_tpu.service.worker.ParseWorker` s multiplex every job
through the existing parser/block-cache stack — sharing published
artifacts cross-job by store signature, so one corpus parses once
fleet-wide — and stream parsed RowBlocks as length-prefixed CRC'd
frames in the block-cache v1 segment encoding
(:mod:`~dmlc_tpu.service.frame`); the
:class:`~dmlc_tpu.service.client.ServiceParser` is a job-bound drop-in
parser with classified retry + worker failover that feeds ``DeviceIter``
unchanged, and the
:class:`~dmlc_tpu.service.autoscale.FleetAutoscaler` grows/drains the
worker fleet from the jobs' aggregated input-wait signal.
See docs/service.md.
"""

from dmlc_tpu.service.autoscale import FleetAutoscaler
from dmlc_tpu.service.client import ServiceParser
from dmlc_tpu.service.dispatcher import (
    DEFAULT_JOB,
    Dispatcher,
    ServiceConfigError,
    register_job,
)
from dmlc_tpu.service.fleet import LocalFleet
from dmlc_tpu.service.worker import ParseWorker

__all__ = ["DEFAULT_JOB", "Dispatcher", "FleetAutoscaler", "LocalFleet",
           "ParseWorker", "ServiceConfigError", "ServiceParser",
           "register_job"]
