"""Disaggregated RowBlock data service (tf.data service, arXiv:2210.14826).

One shared preprocessing tier feeds many trainer clients: a
:class:`~dmlc_tpu.service.dispatcher.Dispatcher` owns split assignment
(first-come-first-served, exactly-once per epoch, re-issue on worker
death), tracker-launchable
:class:`~dmlc_tpu.service.worker.ParseWorker` s run the existing
parser/block-cache stack and stream parsed RowBlocks as length-prefixed
CRC'd frames in the block-cache v1 segment encoding
(:mod:`~dmlc_tpu.service.frame`), and the
:class:`~dmlc_tpu.service.client.ServiceParser` is a drop-in parser with
classified retry + worker failover that feeds ``DeviceIter`` unchanged.
See docs/service.md.
"""

from dmlc_tpu.service.client import ServiceParser
from dmlc_tpu.service.dispatcher import Dispatcher
from dmlc_tpu.service.fleet import LocalFleet
from dmlc_tpu.service.worker import ParseWorker

__all__ = ["Dispatcher", "LocalFleet", "ParseWorker", "ServiceParser"]
