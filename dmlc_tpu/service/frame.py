"""Service wire format v1: length-prefixed, CRC'd RowBlock frames.

The payload of a BLOCK frame is the block-cache v1 **segment encoding**
(:func:`dmlc_tpu.io.block_cache.write_segments` — canonical
:data:`~dmlc_tpu.io.block_cache.SEGMENT_NAMES` order, 64-byte-aligned
array starts, raw little-endian C-order bytes), so a parse worker's wire
frame and its on-disk cache block are the same bytes modulo framing, and
the client decodes with the exact zero-copy view machinery the warm
cache reader uses (:func:`~dmlc_tpu.io.block_cache.read_segments`,
:meth:`~dmlc_tpu.data.row_block.RowBlock.from_segments`).

Frame layout (pinned by ``tests/data/service_frame_v1.golden``)::

    [header]  magic "DSRV" (4B) + version u8 + kind u8 + 2 zero pad bytes
              + meta_len u32 LE + payload_len u64 LE
    [meta]    utf-8 JSON (sort_keys, compact): BLOCK frames carry
              {"arrays": {name: [dtype_str, payload_offset, nbytes]},
               "num_col", "resume", "rows"}; END frames {"blocks", "part"};
              ERROR frames {"error"}
    [payload] BLOCK only: the segment encoding (offset 0 is aligned)
    [crc]     u32 LE crc32 over meta + payload

Kinds: ``BLOCK`` (one RowBlock), ``END`` (part finished — carries the
part's total block count so clients can cross-check delivery), ``ERROR``
(the worker cannot serve; the client treats it as a retryable fault and
fails over via the dispatcher). ``resume`` is the block's byte-exact
resume annotation, shipped verbatim — a client-side checkpoint is
therefore indistinguishable from one taken against local parsing.

Integrity: the trailing crc covers meta + payload; a mismatch (torn
write, flaky link) raises :class:`ServiceFrameError`, which classifies
retryable — the client re-requests the block index from the dispatcher's
current owner instead of delivering corrupt data.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Optional, Tuple

from dmlc_tpu.data.parsers import annot_key  # noqa: F401  (re-export: the
# ONE annotation normalization the local cache match and the remote find
# share — the service layer imports it from here, next to the codec)
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io.block_cache import read_segments, write_segments
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.timer import get_time

FRAME_MAGIC = b"DSRV"
FRAME_VERSION = 1

KIND_BLOCK = 1
KIND_END = 2
KIND_ERROR = 3
# a device-layout snapshot batch (dmlc_tpu/io/snapshot.py positional
# segment encoding): the worker ships post-convert packed batches — bf16
# halves the wire bytes vs the f32 CSR block frames (docs/service.md)
KIND_SNAPSHOT = 4

_HEADER_FMT = "<4sBB2xIQ"  # magic, version, kind, meta_len, payload_len
HEADER_LEN = struct.calcsize(_HEADER_FMT)
_CRC_FMT = "<I"
_CRC_LEN = struct.calcsize(_CRC_FMT)

# frames above this are refused at decode: a corrupt length prefix must
# not make the client try to allocate terabytes (1 GiB >> any real block)
MAX_FRAME_BYTES = 1 << 30


class ServiceFrameError(DMLCError):
    """Malformed/corrupt wire frame. Classified RETRYABLE by
    :func:`dmlc_tpu.io.resilience.classify` (chained from ConnectionError)
    — the client heals by re-requesting the block from the service."""

    def __init__(self, msg: str):
        # chain a ConnectionError cause so the shared classifier walks to
        # a retryable class without a service-specific branch
        super().__init__(msg)
        self.__cause__ = ConnectionError(msg)


def _pack(kind: int, meta: dict, payload: bytes = b"") -> bytes:
    meta_raw = json.dumps(meta, sort_keys=True,
                          separators=(",", ":")).encode()
    crc = zlib.crc32(payload, zlib.crc32(meta_raw)) & 0xFFFFFFFF
    header = struct.pack(_HEADER_FMT, FRAME_MAGIC, FRAME_VERSION, kind,
                         len(meta_raw), len(payload))
    return b"".join((header, meta_raw, payload, struct.pack(_CRC_FMT, crc)))


def encode_block_frame(block: RowBlock,
                       resume: Optional[dict] = None) -> bytes:
    """One RowBlock (+ its resume annotation) as a BLOCK frame.

    The annotation is JSON-normalized exactly as the block cache stores
    it (tuples -> lists, key order fixed), so a block decoded from the
    wire carries a byte-for-byte identical ``resume_state`` to one
    delivered by local parsing through a cache.
    """
    t0 = get_time()
    encoded = getattr(block, "encoded", None)
    if encoded is not None:
        # batch-engine block: the native parse already materialized the
        # exact segment payload (offsets span-relative == payload-
        # relative) — the frame reuses those bytes with zero re-encode,
        # the same single materialization the cache tee appends
        payload = memoryview(encoded.data)
        arrays = {name: [dt, int(off), int(nb)]
                  for name, (dt, off, nb) in encoded.arrays.items()}
        rows, num_col = int(encoded.rows), int(encoded.num_col)
    else:
        buf = io.BytesIO()
        _, _, arrays = write_segments(buf, block.to_segments())
        payload = buf.getvalue()
        rows, num_col = len(block), block.num_col
    resume_json = (json.loads(json.dumps(resume))
                   if resume is not None else None)
    meta = {
        "rows": rows,
        "num_col": num_col,
        "resume": resume_json,
        "arrays": arrays,
    }
    out = _pack(KIND_BLOCK, meta, payload)
    _telemetry.record_span("service_encode", t0, get_time() - t0,
                           rows=rows)
    return out


def encode_snapshot_frame(kind: str, arrays, rows: int,
                          resume: Optional[dict] = None) -> bytes:
    """One device-layout batch as a SNAPSHOT frame: the positional
    snapshot segment encoding (:mod:`dmlc_tpu.io.snapshot`
    ``a0..aN`` names, shapes in the meta) over the same
    :func:`~dmlc_tpu.io.block_cache.write_segments` machinery as BLOCK
    frames — so a worker's snapshot frame and an on-disk snapshot batch
    are the same bytes modulo framing. ``kind`` is the host-batch kind
    (``dense_packed`` / ``dense_packed_q8`` / ...)."""
    import numpy as np

    from dmlc_tpu.io.snapshot import SNAPSHOT_SEGMENT_NAMES

    t0 = get_time()
    arrs = [np.ascontiguousarray(a) for a in arrays]
    buf = io.BytesIO()
    _, _, arr_meta = write_segments(
        buf, {SNAPSHOT_SEGMENT_NAMES[i]: a.reshape(-1)
              for i, a in enumerate(arrs)},
        names=SNAPSHOT_SEGMENT_NAMES)
    resume_json = (json.loads(json.dumps(resume))
                   if resume is not None else None)
    meta = {
        "kind": str(kind),
        "rows": int(rows),
        "resume": resume_json,
        "arrays": arr_meta,
        "shapes": {SNAPSHOT_SEGMENT_NAMES[i]: list(a.shape)
                   for i, a in enumerate(arrs)},
    }
    out = _pack(KIND_SNAPSHOT, meta, buf.getvalue())
    _telemetry.record_span("service_encode", t0, get_time() - t0,
                           rows=int(rows))
    return out


def snapshot_from_frame(meta: dict, payload: bytes) -> tuple:
    """Rebuild ``(kind, arr0, arr1, ...)`` from a SNAPSHOT frame — the
    arrays are zero-copy views over ``payload`` reshaped to the stored
    shapes (callers pin ``payload`` as the hold)."""
    from dmlc_tpu.io.snapshot import SNAPSHOT_SEGMENT_NAMES

    t0 = get_time()
    segments = read_segments(payload, meta["arrays"])
    shapes = meta.get("shapes") or {}
    out = []
    for name in SNAPSHOT_SEGMENT_NAMES:
        if name not in segments:
            break
        arr = segments[name]
        shape = shapes.get(name)
        if shape is not None and len(shape) != 1:
            arr = arr.reshape(shape)
        out.append(arr)
    _telemetry.record_span("service_decode", t0, get_time() - t0,
                           rows=int(meta.get("rows", 0)))
    return (meta["kind"], *out)


def encode_end_frame(part: int, blocks: int,
                     draining: bool = False) -> bytes:
    """End-of-part marker carrying the part's total block count.

    ``draining=True`` marks an END served by a worker mid-drain: the
    client confirms the handoff to the dispatcher (``drain_handoffs``)
    so the drain can complete before its deadline (docs/service.md
    elastic membership). The key is only present when set, so default
    END frames stay byte-identical to the v1 golden pin.
    """
    meta = {"part": int(part), "blocks": int(blocks)}
    if draining:
        meta["draining"] = True
    return _pack(KIND_END, meta)


def encode_error_frame(message: str, draining: bool = False) -> bytes:
    """ERROR frame; ``draining=True`` marks a *graceful* drain notice —
    the part was proactively re-issued and the client should relocate
    without blaming (no ``report_lost``) or spending retry budget."""
    meta = {"error": str(message)}
    if draining:
        meta["draining"] = True
    return _pack(KIND_ERROR, meta)


def decode_frame(data: bytes) -> Tuple[int, dict, bytes]:
    """Split one raw frame into ``(kind, meta, payload)``; verifies magic,
    version, and the trailing crc."""
    if len(data) < HEADER_LEN + _CRC_LEN:
        raise ServiceFrameError(f"service frame truncated ({len(data)}B)")
    magic, version, kind, meta_len, payload_len = struct.unpack(
        _HEADER_FMT, data[:HEADER_LEN])
    if magic != FRAME_MAGIC:
        raise ServiceFrameError(f"service frame: bad magic {magic!r}")
    if version != FRAME_VERSION:
        raise ServiceFrameError(
            f"service frame: version {version} != {FRAME_VERSION}")
    end = HEADER_LEN + meta_len + payload_len
    if end + _CRC_LEN != len(data):
        raise ServiceFrameError("service frame: length mismatch")
    meta_raw = data[HEADER_LEN:HEADER_LEN + meta_len]
    payload = data[HEADER_LEN + meta_len:end]
    (crc,) = struct.unpack(_CRC_FMT, data[end:end + _CRC_LEN])
    if zlib.crc32(payload, zlib.crc32(meta_raw)) & 0xFFFFFFFF != crc:
        raise ServiceFrameError("service frame: crc mismatch")
    try:
        meta = json.loads(meta_raw)
    except ValueError as exc:
        raise ServiceFrameError(f"service frame: bad meta: {exc}") from exc
    return kind, meta, payload


def block_from_frame(meta: dict, payload: bytes) -> RowBlock:
    """Rebuild the RowBlock a BLOCK frame carries; the arrays are
    zero-copy views over ``payload`` (pinned via ``hold``), and the
    stored resume annotation is re-attached verbatim."""
    t0 = get_time()
    segments = read_segments(payload, meta["arrays"])
    block = RowBlock.from_segments(segments, hold=payload)
    resume = meta.get("resume")
    if resume is not None:
        block.resume_state = resume
    _telemetry.record_span("service_decode", t0, get_time() - t0,
                           rows=len(block))
    return block


# ---------------- socket plumbing ----------------

def recvall(sock, nbytes: int) -> bytes:
    """Read exactly ``nbytes``; a peer hangup mid-message raises
    ConnectionError (retryable — the client fails over)."""
    chunks = []
    nread = 0
    while nread < nbytes:
        chunk = sock.recv(min(nbytes - nread, 1 << 20))
        if not chunk:
            raise ConnectionError("service: peer closed mid-frame")
        nread += len(chunk)
        chunks.append(chunk)
    return b"".join(chunks)


def send_frame(sock, frame: bytes) -> None:
    """Ship one encoded frame (``service_send`` span)."""
    t0 = get_time()
    sock.sendall(frame)
    _telemetry.record_span("service_send", t0, get_time() - t0,
                           nbytes=len(frame))


def recv_frame(sock) -> Tuple[int, dict, bytes]:
    """Read one frame off the socket (``service_recv`` span covers the
    wire wait; decode is spanned separately by :func:`block_from_frame`)."""
    t0 = get_time()
    header = recvall(sock, HEADER_LEN)
    magic, version, kind, meta_len, payload_len = struct.unpack(
        _HEADER_FMT, header)
    if magic != FRAME_MAGIC or version != FRAME_VERSION:
        raise ServiceFrameError(
            f"service frame: bad header (magic {magic!r} version {version})")
    if meta_len + payload_len > MAX_FRAME_BYTES:
        raise ServiceFrameError(
            f"service frame: implausible length {meta_len + payload_len}")
    rest = recvall(sock, meta_len + payload_len + _CRC_LEN)
    _telemetry.record_span("service_recv", t0, get_time() - t0,
                           nbytes=HEADER_LEN + len(rest))
    return decode_frame(header + rest)
