"""Service wire format v1: length-prefixed, CRC'd RowBlock frames.

The payload of a BLOCK frame is the block-cache v1 **segment encoding**
(:func:`dmlc_tpu.io.block_cache.write_segments` — canonical
:data:`~dmlc_tpu.io.block_cache.SEGMENT_NAMES` order, 64-byte-aligned
array starts, raw little-endian C-order bytes), so a parse worker's wire
frame and its on-disk cache block are the same bytes modulo framing, and
the client decodes with the exact zero-copy view machinery the warm
cache reader uses (:func:`~dmlc_tpu.io.block_cache.read_segments`,
:meth:`~dmlc_tpu.data.row_block.RowBlock.from_segments`).

Frame layout (pinned by ``tests/data/service_frame_v1.golden``)::

    [header]  magic "DSRV" (4B) + version u8 + kind u8 + 2 zero pad bytes
              + meta_len u32 LE + payload_len u64 LE
    [meta]    utf-8 JSON (sort_keys, compact): BLOCK frames carry
              {"arrays": {name: [dtype_str, payload_offset, nbytes]},
               "num_col", "resume", "rows"}; END frames {"blocks", "part"};
              ERROR frames {"error"}
    [payload] BLOCK only: the segment encoding (offset 0 is aligned)
    [crc]     u32 LE crc32 over meta + payload

Kinds: ``BLOCK`` (one RowBlock), ``END`` (part finished — carries the
part's total block count so clients can cross-check delivery), ``ERROR``
(the worker cannot serve; the client treats it as a retryable fault and
fails over via the dispatcher). ``resume`` is the block's byte-exact
resume annotation, shipped verbatim — a client-side checkpoint is
therefore indistinguishable from one taken against local parsing.

Integrity: the trailing crc covers meta + payload; a mismatch (torn
write, flaky link) raises :class:`ServiceFrameError`, which classifies
retryable — the client re-requests the block index from the dispatcher's
current owner instead of delivering corrupt data.

Wire v2 (pinned by ``tests/data/service_frame_v2.golden``) keeps the
header/crc layout with version byte 2 and adds: ``HELLO`` stream-open
replies (negotiated codec + co-located fast-path offer), per-segment
compression (meta gains ``codec``/``raw_len`` and a ``wire`` map;
``arrays`` keeps the RAW layout so :func:`decode_frame` rebuilds the
byte-identical v1 payload), and pipelined block fetches
(docs/service.md "Wire v2"). The crc does not cover the header, so the
v2-identity encoding of a stored v1 frame is the same body bytes with
only the version byte rewritten (:func:`reframe_v2`).
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Optional, Tuple

from dmlc_tpu.data.parsers import annot_key  # noqa: F401  (re-export: the
# ONE annotation normalization the local cache match and the remote find
# share — the service layer imports it from here, next to the codec)
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io.block_cache import read_segments, write_segments
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.timer import get_time

FRAME_MAGIC = b"DSRV"
FRAME_VERSION = 1
# wire v2: same header/crc layout, version byte 2. Adds HELLO frames
# (stream-open negotiation), per-segment compression (meta carries a
# "wire" map; "arrays" keeps the RAW layout so decode rebuilds the
# byte-identical v1 payload), and pipelined fetch (docs/service.md).
FRAME_VERSION_2 = 2

KIND_BLOCK = 1
KIND_END = 2
KIND_ERROR = 3
# a device-layout snapshot batch (dmlc_tpu/io/snapshot.py positional
# segment encoding): the worker ships post-convert packed batches — bf16
# halves the wire bytes vs the f32 CSR block frames (docs/service.md)
KIND_SNAPSHOT = 4
# v2 stream-open reply: negotiated codec, part block count, and (when
# worker and client are co-located) the mmap fast-path cache offer
KIND_HELLO = 5

_HEADER_FMT = "<4sBB2xIQ"  # magic, version, kind, meta_len, payload_len
HEADER_LEN = struct.calcsize(_HEADER_FMT)
_CRC_FMT = "<I"
_CRC_LEN = struct.calcsize(_CRC_FMT)

# frames above this are refused at decode: a corrupt length prefix must
# not make the client try to allocate terabytes (1 GiB >> any real block)
MAX_FRAME_BYTES = 1 << 30

# optional trace-context key on JSON request lines (docs/service.md
# Distributed tracing): control RPCs and v1/v2 stream-open / block-fetch
# requests may carry ``{"trace": {"tid", "sid"}}``. Peers that predate
# tracing ignore unknown JSON keys, and no FRAME bytes change, so the
# v1/v2 wire goldens stay byte-pinned.
TRACE_KEY = "trace"


def attach_trace(req: dict, ctx=None) -> dict:
    """Attach a trace context (default: this thread's) to a JSON request
    dict under :data:`TRACE_KEY` — only when propagation is enabled and
    a context exists, so untraced requests stay byte-identical to the
    historical wire. Returns ``req`` for chaining."""
    wire = _telemetry.trace_context_wire(ctx)
    if wire is not None:
        req[TRACE_KEY] = wire
    return req


def extract_trace(req: dict):
    """The ``(trace_id, span_id)`` context a request line carries, or
    None — malformed/absent keys never fail the request."""
    return _telemetry.trace_context_from_wire(
        req.get(TRACE_KEY) if isinstance(req, dict) else None)


# ---------------- wire v2 compression codecs ----------------
#
# Registry of per-segment codecs: name -> (compress, decompress). zlib
# ships with CPython so it is always present; zstd/lz4 register only
# when their modules exist (no hard dependency — negotiation falls back
# through the preference order, and identity is always the floor).

def _zlib_compress(buf) -> bytes:
    return zlib.compress(bytes(buf), 6)


def _zlib_decompress(buf, raw_len: int) -> bytes:
    out = zlib.decompress(bytes(buf))
    if len(out) != raw_len:
        raise ServiceFrameError(
            f"service frame: segment inflates to {len(out)}B != {raw_len}B")
    return out


WIRE_CODECS = {"zlib": (_zlib_compress, _zlib_decompress)}

try:  # optional: python-zstandard
    import zstandard as _zstd

    def _zstd_compress(buf) -> bytes:
        return _zstd.ZstdCompressor(level=3).compress(bytes(buf))

    def _zstd_decompress(buf, raw_len: int) -> bytes:
        out = _zstd.ZstdDecompressor().decompress(
            bytes(buf), max_output_size=raw_len)
        if len(out) != raw_len:
            raise ServiceFrameError(
                f"service frame: segment inflates to {len(out)}B "
                f"!= {raw_len}B")
        return out

    WIRE_CODECS["zstd"] = (_zstd_compress, _zstd_decompress)
except ImportError:  # pragma: no cover - environment-dependent
    pass

try:  # optional: python-lz4
    import lz4.frame as _lz4

    def _lz4_compress(buf) -> bytes:
        return _lz4.compress(bytes(buf))

    def _lz4_decompress(buf, raw_len: int) -> bytes:
        out = _lz4.decompress(bytes(buf))
        if len(out) != raw_len:
            raise ServiceFrameError(
                f"service frame: segment inflates to {len(out)}B "
                f"!= {raw_len}B")
        return out

    WIRE_CODECS["lz4"] = (_lz4_compress, _lz4_decompress)
except ImportError:  # pragma: no cover - environment-dependent
    pass

# negotiation preference, best ratio/speed first among what both ends
# have; identity (None) is the implicit floor when nothing intersects
WIRE_CODEC_PREFERENCE = ("zstd", "lz4", "zlib")

# break-even table per segment dtype kind, derived from measured ratios
# on libsvm corpora (docs/service.md): delta-friendly integer segments
# (offset/index/qid/field) compress 2-5x, float value/label/weight
# segments are near-incompressible noise — attempting them burns CPU to
# ship ~100% of the bytes. A compressed segment is kept only when it
# actually beats _KEEP_RATIO, so the table is an *attempt* filter, not a
# correctness gate. Decisions are static per dtype so frames stay
# deterministic (the v2 golden byte-pin depends on it); the measured
# ratios per dtype are exported live via wire_dtype_ratios().
_COMPRESS_DTYPE_KINDS = ("i", "u")  # np dtype kind chars: int / uint
_KEEP_RATIO = 0.9
_MIN_COMPRESS_BYTES = 64

# measured compression ledger per dtype: dtype_str -> [raw_bytes, wire_bytes]
_DTYPE_RATIOS: dict = {}


def wire_dtype_ratios() -> dict:
    """Measured per-dtype compression ratios (wire/raw) accumulated by
    every v2 encode in this process — the live break-even table."""
    return {dt: (wire / raw if raw else 1.0)
            for dt, (raw, wire) in sorted(_DTYPE_RATIOS.items())}


def _dtype_compressible(dtype_str: str) -> bool:
    # segment dtype strings are numpy ``.str`` form ("<i8", "<u8",
    # "<f4"); strip the byte-order prefix and test the kind char
    kind = str(dtype_str).lstrip("<>|=")[:1]
    return kind in _COMPRESS_DTYPE_KINDS


def negotiate_codec(accept) -> Optional[str]:
    """Pick the preferred codec both ends support, or None (identity)."""
    offered = {str(a) for a in (accept or ())}
    for name in WIRE_CODEC_PREFERENCE:
        if name in offered and name in WIRE_CODECS:
            return name
    return None


class ServiceFrameError(DMLCError):
    """Malformed/corrupt wire frame. Classified RETRYABLE by
    :func:`dmlc_tpu.io.resilience.classify` (chained from ConnectionError)
    — the client heals by re-requesting the block from the service."""

    def __init__(self, msg: str):
        # chain a ConnectionError cause so the shared classifier walks to
        # a retryable class without a service-specific branch
        super().__init__(msg)
        self.__cause__ = ConnectionError(msg)


def _pack(kind: int, meta: dict, payload: bytes = b"",
          version: int = FRAME_VERSION) -> bytes:
    meta_raw = json.dumps(meta, sort_keys=True,
                          separators=(",", ":")).encode()
    crc = zlib.crc32(payload, zlib.crc32(meta_raw)) & 0xFFFFFFFF
    header = struct.pack(_HEADER_FMT, FRAME_MAGIC, version, kind,
                         len(meta_raw), len(payload))
    return b"".join((header, meta_raw, payload, struct.pack(_CRC_FMT, crc)))


def encode_hello_frame(meta: dict) -> bytes:
    """V2 stream-open reply: ``{"wire": 2, "codec": <name|None>,
    "blocks": <known part total|None>}`` plus an optional ``"fastpath"``
    offer (``{"path", "blocks"}``) when the peer is co-located."""
    return _pack(KIND_HELLO, meta, version=FRAME_VERSION_2)


def encode_block_frame(block: RowBlock,
                       resume: Optional[dict] = None) -> bytes:
    """One RowBlock (+ its resume annotation) as a BLOCK frame.

    The annotation is JSON-normalized exactly as the block cache stores
    it (tuples -> lists, key order fixed), so a block decoded from the
    wire carries a byte-for-byte identical ``resume_state`` to one
    delivered by local parsing through a cache.
    """
    t0 = get_time()
    encoded = getattr(block, "encoded", None)
    if encoded is not None:
        # batch-engine block: the native parse already materialized the
        # exact segment payload (offsets span-relative == payload-
        # relative) — the frame reuses those bytes with zero re-encode,
        # the same single materialization the cache tee appends
        payload = memoryview(encoded.data)
        arrays = {name: [dt, int(off), int(nb)]
                  for name, (dt, off, nb) in encoded.arrays.items()}
        rows, num_col = int(encoded.rows), int(encoded.num_col)
    else:
        buf = io.BytesIO()
        _, _, arrays = write_segments(buf, block.to_segments())
        payload = buf.getvalue()
        rows, num_col = len(block), block.num_col
    resume_json = (json.loads(json.dumps(resume))
                   if resume is not None else None)
    meta = {
        "rows": rows,
        "num_col": num_col,
        "resume": resume_json,
        "arrays": arrays,
    }
    out = _pack(KIND_BLOCK, meta, payload)
    _telemetry.record_span("service_encode", t0, get_time() - t0,
                           rows=rows)
    return out


def reframe_v2(frame) -> Tuple[bytes, memoryview]:
    """A stored v1 frame as v2-identity send buffers, zero-copy.

    The crc trails meta+payload and does not cover the header, so the v2
    identity encoding of a v1 frame is the same bytes with only the
    header's version byte rewritten: return a fresh 20-byte header plus
    a memoryview of the original body for a vectored send.
    """
    view = memoryview(frame)
    magic, _, kind, meta_len, payload_len = struct.unpack_from(
        _HEADER_FMT, view)
    header = struct.pack(_HEADER_FMT, magic, FRAME_VERSION_2, kind,
                         meta_len, payload_len)
    return header, view[HEADER_LEN:]


def encode_block_frame_v2(meta: dict, payload,
                          codec: str) -> Optional[bytes]:
    """Re-encode a decoded v1 BLOCK frame with per-segment compression.

    ``meta["arrays"]`` keeps the RAW segment layout; a ``"wire"`` map
    (name -> [wire_offset, wire_len, compressed_flag]) plus ``"codec"``
    and ``"raw_len"`` describe the on-wire payload, so decode rebuilds
    the byte-identical raw payload (alignment gaps are zeros on both
    sides). Only break-even-eligible dtypes are attempted and a
    compressed segment is kept only when it beats ``_KEEP_RATIO``;
    returns None when nothing compressed (caller ships identity).
    """
    compress = WIRE_CODECS[codec][0]
    view = memoryview(payload)
    wire: dict = {}
    chunks = []
    woff = 0
    compressed_any = False
    for name, (dt, off, nb) in sorted(meta["arrays"].items(),
                                      key=lambda kv: kv[1][1]):
        off, nb = int(off), int(nb)
        seg = view[off:off + nb]
        raw_tot, wire_tot = _DTYPE_RATIOS.setdefault(str(dt), [0, 0])
        if _dtype_compressible(dt) and nb >= _MIN_COMPRESS_BYTES:
            comp = compress(seg)
            if len(comp) < nb * _KEEP_RATIO:
                wire[name] = [woff, len(comp), 1]
                chunks.append(comp)
                woff += len(comp)
                _DTYPE_RATIOS[str(dt)] = [raw_tot + nb,
                                          wire_tot + len(comp)]
                compressed_any = True
                continue
        wire[name] = [woff, nb, 0]
        chunks.append(bytes(seg))
        woff += nb
        _DTYPE_RATIOS[str(dt)] = [raw_tot + nb, wire_tot + nb]
    if not compressed_any:
        return None
    out_meta = dict(meta)
    out_meta["codec"] = codec
    out_meta["wire"] = wire
    out_meta["raw_len"] = len(view)
    return _pack(KIND_BLOCK, out_meta, b"".join(chunks),
                 version=FRAME_VERSION_2)


def _inflate_payload(meta: dict, payload) -> memoryview:
    """Rebuild the raw v1 payload from a compressed v2 payload; the
    result is byte-identical to what the v1 wire would have carried
    (alignment gaps restore as zeros in the fresh buffer)."""
    codec = meta.get("codec")
    if codec not in WIRE_CODECS:
        raise ServiceFrameError(f"service frame: unknown codec {codec!r}")
    decompress = WIRE_CODECS[codec][1]
    arrays = meta["arrays"]
    raw = bytearray(int(meta["raw_len"]))
    view = memoryview(payload)
    for name, (woff, wlen, enc) in meta["wire"].items():
        try:
            _, off, nb = arrays[name]
        except KeyError as exc:
            raise ServiceFrameError(
                f"service frame: wire segment {name!r} not in arrays"
            ) from exc
        off, nb = int(off), int(nb)
        chunk = view[int(woff):int(woff) + int(wlen)]
        raw[off:off + nb] = (decompress(chunk, nb) if enc
                             else chunk)
    return memoryview(raw)


def encode_snapshot_frame(kind: str, arrays, rows: int,
                          resume: Optional[dict] = None) -> bytes:
    """One device-layout batch as a SNAPSHOT frame: the positional
    snapshot segment encoding (:mod:`dmlc_tpu.io.snapshot`
    ``a0..aN`` names, shapes in the meta) over the same
    :func:`~dmlc_tpu.io.block_cache.write_segments` machinery as BLOCK
    frames — so a worker's snapshot frame and an on-disk snapshot batch
    are the same bytes modulo framing. ``kind`` is the host-batch kind
    (``dense_packed`` / ``dense_packed_q8`` / ...)."""
    import numpy as np

    from dmlc_tpu.io.snapshot import SNAPSHOT_SEGMENT_NAMES

    t0 = get_time()
    arrs = [np.ascontiguousarray(a) for a in arrays]
    buf = io.BytesIO()
    _, _, arr_meta = write_segments(
        buf, {SNAPSHOT_SEGMENT_NAMES[i]: a.reshape(-1)
              for i, a in enumerate(arrs)},
        names=SNAPSHOT_SEGMENT_NAMES)
    resume_json = (json.loads(json.dumps(resume))
                   if resume is not None else None)
    meta = {
        "kind": str(kind),
        "rows": int(rows),
        "resume": resume_json,
        "arrays": arr_meta,
        "shapes": {SNAPSHOT_SEGMENT_NAMES[i]: list(a.shape)
                   for i, a in enumerate(arrs)},
    }
    out = _pack(KIND_SNAPSHOT, meta, buf.getvalue())
    _telemetry.record_span("service_encode", t0, get_time() - t0,
                           rows=int(rows))
    return out


def snapshot_from_frame(meta: dict, payload: bytes) -> tuple:
    """Rebuild ``(kind, arr0, arr1, ...)`` from a SNAPSHOT frame — the
    arrays are zero-copy views over ``payload`` reshaped to the stored
    shapes (callers pin ``payload`` as the hold)."""
    from dmlc_tpu.io.snapshot import SNAPSHOT_SEGMENT_NAMES

    t0 = get_time()
    segments = read_segments(payload, meta["arrays"])
    shapes = meta.get("shapes") or {}
    out = []
    for name in SNAPSHOT_SEGMENT_NAMES:
        if name not in segments:
            break
        arr = segments[name]
        shape = shapes.get(name)
        if shape is not None and len(shape) != 1:
            arr = arr.reshape(shape)
        out.append(arr)
    _telemetry.record_span("service_decode", t0, get_time() - t0,
                           rows=int(meta.get("rows", 0)))
    return (meta["kind"], *out)


def encode_end_frame(part: int, blocks: int,
                     draining: bool = False) -> bytes:
    """End-of-part marker carrying the part's total block count.

    ``draining=True`` marks an END served by a worker mid-drain: the
    client confirms the handoff to the dispatcher (``drain_handoffs``)
    so the drain can complete before its deadline (docs/service.md
    elastic membership). The key is only present when set, so default
    END frames stay byte-identical to the v1 golden pin.
    """
    meta = {"part": int(part), "blocks": int(blocks)}
    if draining:
        meta["draining"] = True
    return _pack(KIND_END, meta)


def encode_error_frame(message: str, draining: bool = False) -> bytes:
    """ERROR frame; ``draining=True`` marks a *graceful* drain notice —
    the part was proactively re-issued and the client should relocate
    without blaming (no ``report_lost``) or spending retry budget."""
    meta = {"error": str(message)}
    if draining:
        meta["draining"] = True
    return _pack(KIND_ERROR, meta)


def decode_frame(data) -> Tuple[int, dict, bytes]:
    """Split one raw frame into ``(kind, meta, payload)``; verifies magic,
    version, and the trailing crc. Accepts ``bytes``, ``bytearray`` or a
    ``memoryview`` (the recv path hands in its preallocated buffer —
    no ``header + rest`` concat copy). A compressed v2 payload is
    inflated here, so callers always see the raw v1 segment bytes."""
    data = memoryview(data)
    if len(data) < HEADER_LEN + _CRC_LEN:
        raise ServiceFrameError(f"service frame truncated ({len(data)}B)")
    magic, version, kind, meta_len, payload_len = struct.unpack_from(
        _HEADER_FMT, data)
    if magic != FRAME_MAGIC:
        raise ServiceFrameError(f"service frame: bad magic {magic!r}")
    if version not in (FRAME_VERSION, FRAME_VERSION_2):
        raise ServiceFrameError(
            f"service frame: version {version} not in "
            f"({FRAME_VERSION}, {FRAME_VERSION_2})")
    end = HEADER_LEN + meta_len + payload_len
    if end + _CRC_LEN != len(data):
        raise ServiceFrameError("service frame: length mismatch")
    meta_raw = data[HEADER_LEN:HEADER_LEN + meta_len]
    payload = data[HEADER_LEN + meta_len:end]
    (crc,) = struct.unpack_from(_CRC_FMT, data, end)
    if zlib.crc32(payload, zlib.crc32(meta_raw)) & 0xFFFFFFFF != crc:
        raise ServiceFrameError("service frame: crc mismatch")
    try:
        meta = json.loads(bytes(meta_raw))
    except ValueError as exc:
        raise ServiceFrameError(f"service frame: bad meta: {exc}") from exc
    if version == FRAME_VERSION_2 and isinstance(meta, dict) \
            and isinstance(meta.get("wire"), dict):
        payload = _inflate_payload(meta, payload)
    return kind, meta, payload


def block_from_frame(meta: dict, payload: bytes) -> RowBlock:
    """Rebuild the RowBlock a BLOCK frame carries; the arrays are
    zero-copy views over ``payload`` (pinned via ``hold``), and the
    stored resume annotation is re-attached verbatim."""
    t0 = get_time()
    segments = read_segments(payload, meta["arrays"])
    block = RowBlock.from_segments(segments, hold=payload)
    resume = meta.get("resume")
    if resume is not None:
        block.resume_state = resume
    _telemetry.record_span("service_decode", t0, get_time() - t0,
                           rows=len(block))
    return block


# ---------------- socket plumbing ----------------

def recvall_into(sock, buf: memoryview) -> None:
    """Fill ``buf`` exactly via ``recv_into``; a peer hangup mid-message
    raises ConnectionError (retryable — the client fails over)."""
    nread = 0
    nbytes = buf.nbytes
    while nread < nbytes:
        got = sock.recv_into(buf[nread:], min(nbytes - nread, 1 << 20))
        if not got:
            raise ConnectionError("service: peer closed mid-frame")
        nread += got


def recvall(sock, nbytes: int) -> bytearray:
    """Read exactly ``nbytes`` into one preallocated buffer (no
    chunk-list join; the quadratic-ish copying is gone)."""
    buf = bytearray(nbytes)
    recvall_into(sock, memoryview(buf))
    return buf


def send_frame(sock, frame: bytes) -> None:
    """Ship one encoded frame (``service_send`` span)."""
    t0 = get_time()
    sock.sendall(frame)
    _telemetry.record_span("service_send", t0, get_time() - t0,
                           nbytes=len(frame))


def send_frame_vectored(sock, buffers) -> int:
    """Ship one frame given as scatter buffers — the worker's v2 send
    path hands the mmap'd payload span straight to ``sendmsg`` instead
    of re-buffering it next to the header. Falls back to per-buffer
    ``sendall`` on sockets without ``sendmsg``. Returns bytes sent."""
    t0 = get_time()
    views = [memoryview(b).cast("B") for b in buffers if len(b)]
    total = sum(v.nbytes for v in views)
    if hasattr(sock, "sendmsg"):
        while views:
            sent = sock.sendmsg(views)
            while sent:
                if views[0].nbytes <= sent:
                    sent -= views[0].nbytes
                    views.pop(0)
                else:
                    views[0] = views[0][sent:]
                    sent = 0
    else:  # pragma: no cover - sendmsg exists on all posix pythons
        for v in views:
            sock.sendall(v)
    _telemetry.record_span("service_send", t0, get_time() - t0,
                           nbytes=total)
    return total


def recv_frame(sock) -> Tuple[int, dict, bytes]:
    """Read one frame off the socket (``service_recv`` span covers the
    wire wait; decode is spanned separately by :func:`block_from_frame`).

    The frame lands in ONE preallocated buffer: the 20-byte header is
    read first (to size the allocation), copied in, and the body is
    ``recv_into`` the remainder — no ``header + rest`` concat copy."""
    t0 = get_time()
    header = recvall(sock, HEADER_LEN)
    magic, version, kind, meta_len, payload_len = struct.unpack(
        _HEADER_FMT, bytes(header))
    if magic != FRAME_MAGIC or version not in (FRAME_VERSION,
                                               FRAME_VERSION_2):
        raise ServiceFrameError(
            f"service frame: bad header (magic {magic!r} version {version})")
    if meta_len + payload_len > MAX_FRAME_BYTES:
        raise ServiceFrameError(
            f"service frame: implausible length {meta_len + payload_len}")
    body_len = meta_len + payload_len + _CRC_LEN
    frame = bytearray(HEADER_LEN + body_len)
    frame[:HEADER_LEN] = header
    recvall_into(sock, memoryview(frame)[HEADER_LEN:])
    _telemetry.record_span("service_recv", t0, get_time() - t0,
                           nbytes=len(frame))
    return decode_frame(frame)
