"""Data-service dispatcher: split assignment + worker registry.

The control plane of the disaggregated RowBlock service (tf.data
service's dispatcher role, arXiv:2210.14826 §3): it owns ONE dataset —
a URI, its partition count, and the parser config every worker must use
— and hands the ``num_parts`` :class:`~dmlc_tpu.io.input_split.InputSplit`
partitions to parse workers **first-come-first-served, exactly once per
epoch**. A split is re-issued only when its owner is declared dead (a
client reported a broken stream, or heartbeats went stale), and re-issued
splits jump the queue so a mid-stream failover heals before new work
starts.

Protocol: one JSON object per connection (newline-terminated request,
newline-terminated response — the same short-lived-connection shape the
rabit tracker uses for ``heartbeat``/``metrics``). Commands:

``config``                      -> the dataset spec workers/clients parse
``register worker host port``   -> join the fleet (re-registration of a
                                   worker already seen alive THIS
                                   generation is treated as a crash-
                                   restart: its parts re-queue at the
                                   front until a ``reclaim`` adopts them
                                   back). A brand-new worker id arriving
                                   after work has started is a **live
                                   join** (journaled ``join`` event,
                                   ``worker_joins`` counter): it enters
                                   the grant rotation immediately
``drain worker [deadline]``     -> begin a graceful drain: no new grants,
                                   unstarted parts re-issue at the front
                                   immediately, frame-store-complete
                                   parts keep serving until clients
                                   confirm ``handoff`` or the drain
                                   deadline expires (docs/service.md
                                   elastic membership)
``handoff worker part``         -> a client confirms it finished
                                   streaming ``part`` from the draining
                                   ``worker``; when every served part is
                                   confirmed the drain completes early
``next_split worker``           -> ``{"part": k}`` | ``{"part": null}``
                                   (nothing to do) — doubles as liveness
``heartbeat worker``            -> liveness only
``locate part``                 -> ``{"worker", "host", "port"}`` of the
                                   live owner, or ``{"wait": true}`` while
                                   the part awaits (re)assignment
``report_lost worker``          -> a client observed the worker dead: all
                                   its parts re-queue at the FRONT
``part_done part worker``       -> the owner finished parsing the part
                                   (journaled: a restarted dispatcher
                                   keeps it done instead of re-issuing)
``reclaim worker parts``        -> the worker re-announces the fully-
                                   parsed parts its frame store still
                                   holds: a restarted dispatcher ADOPTS
                                   them (no fleet-wide re-parse), and
                                   journal-complete parts the worker no
                                   longer holds re-queue
``status``                      -> registry snapshot (tests, operators)

Every response is stamped with the dispatcher's monotonic ``gen``
generation token, so workers and clients detect a restart at their next
control exchange (docs/service.md control-plane recovery).

**Crash recovery**: with ``journal_path=`` set, every state transition —
dataset registration, worker register/death, part grant / complete /
re-issue / reclaim — is appended to a flock'd JSONL journal (the shared
:class:`~dmlc_tpu.store.journal.AppendJournal` substrate: torn-tail skip
at replay, atomic compaction). A restarted ``Dispatcher(journal_path=
...)`` replays it into the exact assignment state: **completed parts
stay done** (their owners get a liveness grace window to re-attach),
**in-flight parts re-queue at the front**, and the generation token
bumps so the fleet re-registers and reclaims. The journal records no
epoch state by design: epochs live with clients and worker frame stores
(``before_first`` re-serves without dispatcher involvement), so the
assignment journal is epoch-invariant.

**Worker lifecycle** (docs/service.md elastic membership): every worker
walks JOINING -> ACTIVE -> DRAINING -> DEAD. ``JOINING`` is a
journal-restored worker awaiting its re-attach handshake (it keeps
serving completed parts but gets no grants); ``register`` makes it
``ACTIVE`` (grant rotation); a ``drain`` request makes it ``DRAINING``
(no new grants, unstarted parts proactively re-issued, completed parts
keep serving until ``handoff``-confirmed or the drain deadline — clients
learn re-assignments from ``moved``/``draining`` hints on ``locate``, so
failover happens before the socket dies); ``DEAD`` is terminal (stale
heartbeats, ``report_lost``, or a completed drain). Transitions journal,
so membership state survives dispatcher restarts.

**Straggler hedging**: the dispatcher tracks per-part grant->complete
latency; once at least :data:`HEDGE_MIN_SAMPLES` parts have completed,
an in-flight part stuck past ``DMLC_TPU_HEDGE_FACTOR`` times the fleet
median (and past :data:`HEDGE_MIN_AGE_S`) is **speculatively re-issued**
to a second active worker (journaled ``spec_grant``,
``speculative_reissues``). First ``part_done`` wins — a win by the
speculative worker counts ``speculative_wins`` and flips ``locate`` to
the winner; the loser's completion is deduped (exactly-once preserved:
parsing is deterministic, so either stream is byte-identical).

A background **reaper tick thread** (interval derived from
``liveness_timeout``) drives liveness, drain deadlines, and the hedging
check on wall-clock time, so a quiet fleet — no poll or heartbeat
traffic at all — still reaps dead workers, expires drains, and hedges
stragglers.

The dispatcher is deliberately dataset-state-free about *blocks*: block
ordering, resume, and exactly-once delivery live with the client (global
order is part-major), so the dispatcher never becomes a data-plane
bottleneck — it serves O(workers + failovers) tiny requests per epoch.
Concurrent connection handlers are capped (``DMLC_TPU_DISPATCH_WORKERS``
via the knob table); excess connections shed with a retryable ``busy``
reply, so a reconnect storm from a recovering fleet cannot exhaust
threads exactly when the dispatcher must stay responsive.
"""

from __future__ import annotations

import json
import logging
import socket
import statistics
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Set

from dmlc_tpu.io import faults as _faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.store import journal as _journal_mod
from dmlc_tpu.store.journal import AppendJournal
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils.check import check
from dmlc_tpu.utils.timer import get_time

logger = logging.getLogger("dmlc_tpu.service")

# journal compaction threshold: past this many lines at replay the
# journal is rewritten as the live state (dataset + start + registers +
# grant/complete pairs). Assignment journals are naturally small —
# O(parts + workers + failovers), epochs append nothing — so this only
# triggers after many restart cycles.
JOURNAL_COMPACT_LINES = 4096

# worker lifecycle states (docs/service.md elastic membership)
JOINING = "joining"      # journal-restored, awaiting register+reclaim
ACTIVE = "active"        # in the grant rotation
DRAINING = "draining"    # no new grants; serving until handoff/deadline
DEAD = "dead"            # terminal

# straggler hedging guards: never hedge before this many completion
# latency samples exist (a 2-part dataset can never produce a meaningful
# median), and never hedge a part younger than this wall-clock floor —
# hedging targets seconds-scale stalls, and the floor must sit well
# above any plausible healthy-part latency (a loaded CI host pausing a
# smoke-scale part for a second must not fire a speculative parse, or
# the bench-smoke zero gate on `speculative_reissues` turns flaky)
HEDGE_MIN_SAMPLES = 3
HEDGE_MIN_AGE_S = 5.0
# completion-latency window the fleet median is computed over
HEDGE_LATENCY_WINDOW = 64


class _WorkerInfo:
    __slots__ = ("worker", "host", "port", "last_seen", "state",
                 "registered_gen", "drain_deadline", "handed_off",
                 "drained")

    def __init__(self, worker: str, host: str, port: int, now: float,
                 registered_gen: Optional[int] = None,
                 state: Optional[str] = None):
        self.worker = worker
        self.host = host
        self.port = port
        self.last_seen = now
        # the generation this worker last sent `register` in; None for a
        # worker restored from the journal that has not re-attached yet
        # (its frame-store contents are unknown until it reclaims)
        self.registered_gen = registered_gen
        # lifecycle: a journal-restored worker is JOINING until its
        # re-attach handshake lands; a registered one is ACTIVE
        self.state = state or (ACTIVE if registered_gen is not None
                               else JOINING)
        self.drain_deadline: Optional[float] = None
        self.handed_off: Set[int] = set()
        # True only for a worker whose DRAIN completed (handoffs
        # confirmed or deadline expired): its next poll reads `drained`
        # and exits instead of re-attaching as a zombie
        self.drained = False

    @property
    def alive(self) -> bool:
        return self.state != DEAD


class Dispatcher:
    """Split-assignment server for one dataset.

    ``parser`` is the config dict every worker builds its parser from
    (``format``/``type_``, ``chunk_bytes``, ``threaded``, ... — the
    kwargs of :func:`dmlc_tpu.data.parsers.create_parser`); shipping it
    from one place is what makes N workers' output byte-identical to a
    local parse with the same config. ``liveness_timeout`` (seconds)
    declares a worker dead when its polls/heartbeats go stale; client
    ``report_lost`` reports short-circuit that wait.

    ``journal_path`` arms crash recovery: state transitions journal to
    an append-only JSONL file and a restart on the same address replays
    them (see the module docstring). Without it the dispatcher is the
    historical in-memory-only control plane (generation fixed at 1).
    """

    def __init__(self, uri: str, num_parts: int,
                 parser: Optional[dict] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout: float = 10.0,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 journal_path: Optional[str] = None,
                 journal_compact_lines: int = JOURNAL_COMPACT_LINES):
        self.uri = uri
        self.num_parts = int(num_parts)
        self.parser = dict(parser or {})
        # the epoch-plan identity of the dataset (shuffle_seed /
        # shuffle_window, dmlc_tpu/data/epoch.py): shipped in `config` so
        # every worker arms its block cache with the SAME plan and every
        # client learns the seed its epochs are a function of — the one
        # place the fleet's shuffle is decided (docs/service.md)
        self.plan = dict(plan or {})
        # snapshot-frame geometry ({batch_size, num_col, x_dtype}): when
        # set, workers ALSO pack each part into fixed-geometry device-
        # layout batches (dmlc_tpu/io/snapshot.py encoding) and clients
        # stream those instead of CSR blocks — x_dtype='bfloat16' halves
        # the wire bytes. One dispatcher-owned knob, like the plan: the
        # whole fleet serves one batch geometry or none (docs/service.md)
        self.snapshot = dict(snapshot or {})
        self.liveness_timeout = float(liveness_timeout)
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerInfo] = {}
        # FCFS visitation queue: parts not yet assigned this epoch.
        # Re-issued parts (dead owner) go to the FRONT so failover work
        # heals before fresh parts are handed out.
        self._todo: Deque[int] = deque(range(self.num_parts))
        self._assigned: Dict[int, str] = {}   # part -> worker id
        self._completed: Set[int] = set()     # parts whose parse finished
        # ---- elastic membership + hedging state ----
        # True once a client has located a part: a brand-new worker id
        # registering after that point is a mid-epoch LIVE JOIN
        # (worker_joins) — capacity added under load. Grant activity
        # alone does not qualify: fleet bootstrap interleaves sibling
        # registrations with the first workers' polls, and those are
        # founding members, not joins.
        self._clients_active = False
        # per-part grant timestamps (in-flight ages) and the fleet's
        # recent grant->complete latencies (the hedging median)
        self._grant_times: Dict[int, float] = {}
        self._latencies: Deque[float] = deque(maxlen=HEDGE_LATENCY_WINDOW)
        # part -> second (speculative) owner; the primary stays in
        # _assigned until one of them completes (first part_done wins).
        # _spec_times stamps the speculative grant so a win's latency
        # sample measures the HEDGE parse — sampling from the stuck
        # primary's grant would append > threshold by construction and
        # progressively desensitize the median
        self._spec: Dict[int, str] = {}
        self._spec_times: Dict[int, float] = {}
        # parts flagged for speculative re-issue, awaiting a poll from a
        # worker that is not the stuck primary
        self._hedge_todo: Deque[int] = deque()
        self._hedge_factor = _knobs.resolve("hedge_factor")
        self._drain_deadline_s = float(_knobs.resolve("drain_deadline"))
        self.generation = 1
        self._journal: Optional[AppendJournal] = None
        if journal_path:
            self._journal = AppendJournal(journal_path)
            self._recover(int(journal_compact_lines))
        # connection-handler cap (knob table; docs/service.md): excess
        # connections shed with a retryable `busy` reply instead of
        # spawning an unbounded thread per connection — a reconnect storm
        # from a recovering fleet must not exhaust threads exactly when
        # the control plane needs to stay responsive
        self._handler_slots = threading.Semaphore(
            _knobs.resolve("dispatch_workers"))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        # in-flight handler connections, force-closed at close()/kill():
        # a dead process's sockets drop with it, and a restart must be
        # able to rebind the SAME port immediately (lingering accepted
        # sockets without SO_REUSEADDR would hold it)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="service-dispatcher")
        self._thread.start()
        # background reaper tick: liveness used to be checked only inside
        # RPC handling, so a QUIET fleet (no poll/heartbeat traffic at
        # all) never reaped a dead worker. The tick makes liveness, drain
        # deadlines, and the straggler-hedging check wall-clock-driven;
        # interval derives from liveness_timeout (several checks per
        # window) with a floor so drain/hedge stay responsive even when
        # liveness detection is disabled (liveness_timeout <= 0).
        if self.liveness_timeout > 0:
            tick = min(max(self.liveness_timeout / 4.0, 0.05), 2.0)
        else:
            tick = 0.25
        self._tick_interval = tick
        self._tick_stop = threading.Event()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, daemon=True,
            name="service-dispatcher-tick")
        self._tick_thread.start()
        logger.info("dispatcher for %s (%d parts) on %s:%d gen %d",
                    uri, num_parts, self.host, self.port, self.generation)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ---------------- journal + replay ----------------

    def _journal_append(self, event: dict, sync: bool = True) -> None:
        """Journal one state transition (no-op without a journal). All
        assignment events fsync: the journal IS the recovery contract,
        and its volume is O(parts + workers + failovers) per run."""
        if self._journal is not None:
            self._journal.append(event, sync=sync)

    def _recover(self, compact_lines: int) -> None:
        """Replay the journal into the exact assignment state: completed
        parts stay done with their owner, in-flight parts re-queue at
        the FRONT (lowest first — clients consume part-major), replayed
        workers get a fresh liveness window to re-attach, and the
        generation token bumps past every `start` ever journaled."""
        with self._journal.locked():
            lines = self._journal.read_lines()
            events = _journal_mod.decode_events(lines)
            last_gen = 0
            seen_dataset = False
            todo = self._todo
            in_todo = set(todo)
            assigned, completed = self._assigned, self._completed
            workers: Dict[str, tuple] = {}
            draining: Set[str] = set()
            for ev in events:
                op = ev.get("op")
                if op == "dataset":
                    check(int(ev.get("num_parts", self.num_parts))
                          == self.num_parts,
                          f"dispatcher journal {self._journal.path}: "
                          f"journaled dataset has "
                          f"{ev.get('num_parts')} parts, constructor "
                          f"says {self.num_parts} — a restart must "
                          f"recover the SAME dataset")
                    seen_dataset = True
                elif op == "start":
                    last_gen = max(last_gen, int(ev.get("gen", 0) or 0))
                elif op == "register":
                    workers[str(ev.get("worker"))] = (
                        str(ev.get("host", "")), int(ev.get("port", 0)))
                    draining.discard(str(ev.get("worker")))
                elif op == "dead":
                    workers.pop(str(ev.get("worker")), None)
                    draining.discard(str(ev.get("worker")))
                elif op == "drain":
                    # a drain in flight at the crash: the worker stays out
                    # of the grant rotation after replay (its completed
                    # parts keep serving; the drain deadline re-arms)
                    if str(ev.get("worker")) in workers:
                        draining.add(str(ev.get("worker")))
                elif op == "join":
                    pass  # membership rides `register`; join is the record
                elif op == "grant":
                    part = int(ev.get("part", -1))
                    if part in in_todo:
                        in_todo.discard(part)
                        todo.remove(part)
                    assigned[part] = str(ev.get("worker"))
                elif op == "spec_grant":
                    # the speculative twin of a grant: the part is already
                    # out of todo; whoever journals `complete` first owns
                    # it (the dedupe below), so replay needs no side state
                    pass
                elif op == "complete":
                    part = int(ev.get("part", -1))
                    if 0 <= part < self.num_parts:
                        if part in in_todo:
                            in_todo.discard(part)
                            todo.remove(part)
                        # the completing worker wins the part — for a
                        # hedged part this is the first-complete owner,
                        # which may be the speculative worker
                        assigned[part] = str(ev.get("worker"))
                        completed.add(part)
                elif op == "reissue":
                    part = int(ev.get("part", -1))
                    assigned.pop(part, None)
                    completed.discard(part)
                    if 0 <= part < self.num_parts and part not in in_todo:
                        in_todo.add(part)
                        todo.appendleft(part)
                elif op == "reclaim":
                    part = int(ev.get("part", -1))
                    if part in in_todo:
                        in_todo.discard(part)
                        todo.remove(part)
                    assigned[part] = str(ev.get("worker"))
                    completed.add(part)
            # in-flight at the crash (granted, never completed): the
            # owner's frames may be partial — re-queue at the front,
            # lowest part first; reclaim re-adopts what survived
            inflight = sorted(p for p in assigned if p not in completed)
            for part in inflight:
                assigned.pop(part)
            # parts completed by a worker the journal no longer knows
            # (dead without a reissue line — a torn tail can lose one):
            # nothing serves them, so they re-queue behind the in-flight
            orphaned = sorted(p for p, w in assigned.items()
                              if w not in workers)
            for part in orphaned:
                assigned.pop(part)
                completed.discard(part)
            for part in reversed(inflight + orphaned):
                if part not in in_todo:
                    in_todo.add(part)
                    todo.appendleft(part)
            now = get_time()
            # replayed workers start a fresh liveness window in the
            # JOINING state: a worker that survived the dispatcher
            # re-attaches within it (its next poll sees the generation
            # bump), one that died with the dispatcher goes stale and
            # its parts re-issue normally. A worker that was DRAINING at
            # the crash replays as draining — still out of the grant
            # rotation, still serving, deadline re-armed fresh.
            self._workers = {}
            for w, (h, p) in workers.items():
                info = _WorkerInfo(w, h, p, now)
                if w in draining:
                    info.state = DRAINING
                    info.drain_deadline = now + self._drain_deadline_s
                self._workers[w] = info
            self.generation = last_gen + 1
            if len(lines) > compact_lines:
                self._journal.rewrite(self._live_events())
            if not seen_dataset:
                self._journal.append(
                    {"op": "dataset", "uri": self.uri,
                     "num_parts": self.num_parts}, sync=True)
            self._journal.append(
                {"op": "start", "gen": self.generation}, sync=True)
            if events:
                logger.info(
                    "dispatcher: recovered from %s — gen %d, %d parts "
                    "done, %d re-queued, %d workers awaiting re-attach",
                    self._journal.path, self.generation,
                    len(self._completed), len(inflight) + len(orphaned),
                    len(self._workers))

    def _live_events(self) -> List[dict]:
        """The current state as a canonical journal (compaction): the
        dataset, the last start, live workers, and grant+complete pairs
        for done parts. Unassigned parts are implicit (replay seeds the
        queue from ``range(num_parts)``); the queue's front-ordering
        normalizes to ascending across a compaction."""
        events: List[dict] = [
            {"op": "dataset", "uri": self.uri,
             "num_parts": self.num_parts},
            {"op": "start", "gen": self.generation - 1},
        ]
        for info in self._workers.values():
            if info.alive:
                events.append({"op": "register", "worker": info.worker,
                               "host": info.host, "port": info.port})
        for info in self._workers.values():
            # a drain in progress must survive compaction, or a restart
            # would put the draining worker back in the grant rotation
            if info.state == DRAINING:
                events.append({"op": "drain", "worker": info.worker})
        for part in sorted(self._completed):
            worker = self._assigned.get(part)
            if worker is None:
                continue
            events.append({"op": "grant", "part": part, "worker": worker})
            events.append({"op": "complete", "part": part,
                           "worker": worker})
        return events

    # ---------------- assignment core (lock held) ----------------

    def _requeue_locked(self, parts, worker: str, why: str) -> None:
        """Re-issue ``parts`` at the FRONT, lowest part first (clients
        consume part-major, so the earliest lost part is the one
        blocking them), journaling each re-queue."""
        parts = sorted(parts)
        for part in parts:
            self._assigned.pop(part, None)
            self._completed.discard(part)
            self._drop_spec_locked(part)
            self._grant_times.pop(part, None)
            try:
                self._hedge_todo.remove(part)
            except ValueError:
                pass
        for part in reversed(parts):
            self._todo.appendleft(part)
            self._journal_append({"op": "reissue", "part": part,
                                  "worker": worker})
        if parts:
            logger.warning("dispatcher: worker %s %s; re-issuing parts %s",
                           worker, why, parts)

    def _drop_spec_locked(self, part: int) -> Optional[str]:
        """Forget a part's speculative grant (and its grant stamp);
        returns the speculative worker, if any."""
        self._spec_times.pop(part, None)
        return self._spec.pop(part, None)

    def _drop_worker_specs_locked(self, worker: str) -> None:
        """Forget every speculative grant ``worker`` holds — its
        speculative parses die with it (death, drain, departure)."""
        for part in [p for p, w in self._spec.items() if w == worker]:
            self._drop_spec_locked(part)

    def _inherit_or_requeue_locked(self, worker: str, parts,
                                   why: str) -> List[int]:
        """``worker`` is giving up ``parts``: promote each hedged part's
        speculative twin to primary (the hedge already has a live parse
        going — re-queuing would waste it) and re-queue the rest at the
        front. Returns the re-queued parts."""
        requeue = []
        for part in parts:
            spec_stamp = self._spec_times.get(part)
            spec = self._drop_spec_locked(part)
            if spec is not None and part not in self._completed:
                # the hedge worker inherits the part outright; its clock
                # restarts at ITS spec grant — keeping the stuck
                # primary's stamp would re-flag the part for hedging at
                # the very next tick and poison the latency median
                self._assigned[part] = spec
                self._grant_times[part] = (spec_stamp if spec_stamp
                                           is not None else get_time())
                self._journal_append({"op": "grant", "part": part,
                                      "worker": spec})
                logger.info("dispatcher: part %d inherited by hedge "
                            "worker %s (%s %s)", part, spec, worker, why)
            else:
                requeue.append(part)
        self._requeue_locked(requeue, worker, why)
        return requeue

    def _release_worker_parts_locked(self, worker: str, why: str) -> None:
        """A worker left (death or completed drain): drop speculative
        grants it held itself, then inherit-or-requeue everything it
        owned (completed parts re-queue too — its frame store is gone)."""
        self._drop_worker_specs_locked(worker)
        parts = sorted(p for p, o in self._assigned.items()
                       if o == worker)
        self._inherit_or_requeue_locked(worker, parts, why)

    def _mark_dead_locked(self, worker: str) -> None:
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return
        info.state = DEAD
        self._journal_append({"op": "dead", "worker": worker})
        self._release_worker_parts_locked(worker, "lost")

    def _reap_stale_locked(self, now: float) -> None:
        if self.liveness_timeout <= 0:
            return
        for info in list(self._workers.values()):
            if info.alive and now - info.last_seen > self.liveness_timeout:
                logger.warning("dispatcher: worker %s missed heartbeats "
                               "(last seen %.1fs ago)", info.worker,
                               now - info.last_seen)
                self._mark_dead_locked(info.worker)

    # ---------------- drain + hedging (lock held) ----------------

    def _finish_drain_locked(self, info: _WorkerInfo, why: str) -> None:
        """Complete a drain: the worker leaves the fleet for good — its
        next poll reads ``drained`` and exits instead of re-attaching.
        Handoff-confirmed completed parts stay ASSIGNED to the departed
        worker and re-queue lazily at the next ``locate``: every client
        that confirmed already streamed them, so an eager re-issue here
        would make the always-polling fleet re-parse frames nobody asked
        for. Everything else (unconfirmed completed parts included —
        their frames die with the worker) releases through the normal
        death path (re-queue / hedge inheritance) right now."""
        if info.state != DRAINING:
            return
        info.drained = True
        logger.info("dispatcher: drain of worker %s complete (%s)",
                    info.worker, why)
        info.state = DEAD
        self._journal_append({"op": "dead", "worker": info.worker})
        keep = {p for p in info.handed_off
                if self._assigned.get(p) == info.worker
                and p in self._completed}
        self._drop_worker_specs_locked(info.worker)
        self._inherit_or_requeue_locked(
            info.worker,
            sorted(p for p, o in self._assigned.items()
                   if o == info.worker and p not in keep),
            why)

    def _maybe_finish_drain_locked(self, info: _WorkerInfo) -> None:
        """Complete the drain as soon as every still-assigned
        frame-store-complete part is handoff-confirmed — vacuously so
        for a worker with nothing to serve out (preempted before any
        part completed), which must exit within its notice window, not
        idle out the full deadline."""
        if info.state != DRAINING:
            return
        serving = {p for p, w in self._assigned.items()
                   if w == info.worker and p in self._completed}
        if serving <= info.handed_off:
            self._finish_drain_locked(
                info, "all served parts handed off"
                if serving else "nothing left to serve")

    def _expire_drains_locked(self, now: float) -> None:
        for info in list(self._workers.values()):
            if info.state != DRAINING:
                continue
            # the serving set can shrink without a handoff RPC (e.g. a
            # report_lost re-queued a part): re-check completion on the
            # wall-clock tick too, then the deadline backstop
            self._maybe_finish_drain_locked(info)
            if (info.state == DRAINING and info.drain_deadline is not None
                    and now >= info.drain_deadline):
                self._finish_drain_locked(info, "drain deadline expired")

    def _hedge_check_locked(self, now: float) -> None:
        """Flag in-flight parts stuck past ``hedge_factor`` times the
        fleet's median grant->complete latency for speculative re-issue.
        Guarded by a minimum sample count and an absolute age floor so
        ordinary jitter on fast parts can never trigger a duplicate
        parse; the flagged part is granted to the next polling worker
        that is not the stuck primary."""
        if len(self._latencies) < HEDGE_MIN_SAMPLES:
            return
        threshold = max(self._hedge_factor
                        * statistics.median(self._latencies),
                        HEDGE_MIN_AGE_S)
        for part, granted_at in list(self._grant_times.items()):
            if (part in self._completed or part in self._spec
                    or part in self._hedge_todo):
                continue
            owner = self._assigned.get(part)
            info = self._workers.get(owner) if owner is not None else None
            if info is None or info.state != ACTIVE:
                continue  # death/drain paths own those parts
            age = now - granted_at
            if age <= threshold:
                continue
            if not any(w.state == ACTIVE and w.worker != owner
                       and w.registered_gen == self.generation
                       for w in self._workers.values()):
                continue  # nobody to hedge onto
            self._hedge_todo.append(part)
            logger.warning(
                "dispatcher: part %d on worker %s stuck %.2fs "
                "(> %.2fs = %dx fleet median); flagging for "
                "speculative re-issue", part, owner, age, threshold,
                self._hedge_factor)

    def _tick_loop(self) -> None:
        """The wall-clock driver behind liveness, drain deadlines, and
        hedging — RPC traffic is no longer required for any of them."""
        while not self._tick_stop.wait(self._tick_interval):
            now = get_time()
            with self._lock:
                self._reap_stale_locked(now)
                self._expire_drains_locked(now)
                self._hedge_check_locked(now)

    # ---------------- request handlers ----------------

    def _handle(self, req: dict) -> dict:
        resp = self._dispatch_cmd(req)
        # the monotonic generation token: peers detect a restart at
        # their next control exchange and re-register/revalidate
        resp["gen"] = self.generation
        return resp

    def _dispatch_cmd(self, req: dict) -> dict:
        cmd = req.get("cmd")
        now = get_time()
        with self._lock:
            if cmd == "config":
                return {"uri": self.uri, "num_parts": self.num_parts,
                        "parser": self.parser, "plan": self.plan,
                        "snapshot": self.snapshot}
            if cmd == "register":
                worker = str(req["worker"])
                prev = self._workers.get(worker)
                if (prev is not None and prev.alive
                        and prev.registered_gen == self.generation):
                    # a worker id already seen alive THIS generation is
                    # re-registering: the process crash-restarted fast
                    # (before the liveness reaper fired) and its frame
                    # store is presumed gone — re-queue everything it
                    # owned; the reclaim that follows adopts back what
                    # actually survived (docs/service.md)
                    self._release_worker_parts_locked(
                        worker, "re-registered (crash-restart)")
                self._workers[worker] = _WorkerInfo(
                    worker, str(req["host"]), int(req["port"]), now,
                    registered_gen=self.generation)
                self._journal_append({"op": "register", "worker": worker,
                                      "host": str(req["host"]),
                                      "port": int(req["port"])})
                if prev is None and self._clients_active:
                    # a brand-new worker id arriving while clients are
                    # consuming: a mid-epoch LIVE JOIN — it is in the
                    # grant rotation and the re-issue serving set from
                    # this very reply
                    self._journal_append({"op": "join", "worker": worker})
                    _resilience.record_event("worker_joins")
                    logger.info("dispatcher: worker %s joined the live "
                                "fleet", worker)
                return {"ok": True}
            if cmd == "heartbeat":
                info = self._workers.get(str(req.get("worker")))
                if info is not None and info.alive:
                    info.last_seen = now
                return {"ok": True}
            if cmd == "next_split":
                worker = str(req["worker"])
                info = self._workers.get(worker)
                if info is None or not info.alive:
                    if info is not None and info.drained:
                        # drain complete: tell the worker to exit instead
                        # of re-attaching as a zombie
                        return {"part": None, "drained": True}
                    # unregistered/declared-dead workers get no splits —
                    # a zombie must re-register before it can own parts
                    return {"part": None, "register": True}
                if info.state == DRAINING:
                    # draining workers get NO new work; the poll doubles
                    # as liveness while they serve out their parts
                    info.last_seen = now
                    return {"part": None, "draining": True}
                if info.registered_gen != self.generation:
                    # journal-restored worker that has not re-attached
                    # this generation: its frame-store contents are
                    # unknown until the register+reclaim handshake, and
                    # a grant riding the SAME reply as the generation
                    # bump would race the reclaim into a duplicate parse
                    info.last_seen = now
                    return {"part": None, "register": True}
                info.last_seen = now
                self._reap_stale_locked(now)
                # speculative re-issues first: a flagged straggler part
                # goes to the first polling worker that is NOT the stuck
                # primary (journaled spec_grant; first part_done wins)
                for _ in range(len(self._hedge_todo)):
                    part = self._hedge_todo.popleft()
                    if (part in self._completed or part in self._spec
                            or part not in self._assigned):
                        continue  # stale flag
                    if self._assigned.get(part) == worker:
                        self._hedge_todo.append(part)
                        continue
                    self._spec[part] = worker
                    self._spec_times[part] = now
                    self._journal_append({"op": "spec_grant",
                                          "part": part, "worker": worker})
                    _resilience.record_event("speculative_reissues")
                    logger.warning("dispatcher: part %d speculatively "
                                   "re-issued to worker %s (primary %s)",
                                   part, worker, self._assigned.get(part))
                    return {"part": part}
                if not self._todo:
                    return {"part": None}
                part = self._todo.popleft()
                self._assigned[part] = worker
                self._grant_times[part] = now
                self._journal_append({"op": "grant", "part": part,
                                      "worker": worker})
                logger.info("dispatcher: part %d -> worker %s", part, worker)
                return {"part": part}
            if cmd == "part_done":
                worker = str(req["worker"])
                part = int(req["part"])
                primary = self._assigned.get(part)
                spec = self._spec.get(part)
                if (part not in self._completed
                        and worker in (primary, spec)):
                    # journaled completion: a restarted dispatcher keeps
                    # the part done instead of re-queuing it as in-flight.
                    # For a hedged part the FIRST completion wins; the
                    # loser's later part_done is deduped right here.
                    self._completed.add(part)
                    # the latency sample measures the WINNER's own
                    # grant->complete time (the spec grant stamp for a
                    # speculative win) — never the stuck primary's age,
                    # which exceeds the hedge threshold by construction
                    # and would desensitize the median
                    granted_at = self._grant_times.pop(part, None)
                    if spec is not None and worker == spec:
                        self._assigned[part] = worker
                        granted_at = self._spec_times.get(part, granted_at)
                        _resilience.record_event("speculative_wins")
                        logger.info("dispatcher: speculative worker %s "
                                    "won part %d over %s", worker, part,
                                    primary)
                    self._drop_spec_locked(part)
                    self._journal_append({"op": "complete", "part": part,
                                          "worker": worker})
                    if granted_at is not None:
                        self._latencies.append(max(0.0, now - granted_at))
                elif part not in self._completed:
                    # a completion for a part we had RE-QUEUED (its
                    # grant didn't survive a dispatcher restart, or a
                    # report_lost blamed a still-live worker): the
                    # frames exist, so adopt it exactly as `reclaim`
                    # would instead of letting the queue force a
                    # duplicate parse (no latency sample — the grant
                    # stamp died with the re-queue)
                    info = self._workers.get(worker)
                    if (info is not None and info.alive
                            and part in self._todo):
                        self._todo.remove(part)
                        self._assigned[part] = worker
                        self._completed.add(part)
                        self._journal_append(
                            {"op": "complete", "part": part,
                             "worker": worker})
                        logger.info("dispatcher: adopted completion of "
                                    "re-queued part %d from worker %s",
                                    part, worker)
                return {"ok": True}
            if cmd == "drain":
                return self._drain_locked(req, now)
            if cmd == "handoff":
                worker = str(req["worker"])
                part = int(req["part"])
                info = self._workers.get(worker)
                if info is not None and info.state == DRAINING:
                    info.handed_off.add(part)
                    self._maybe_finish_drain_locked(info)
                return {"ok": True}
            if cmd == "reclaim":
                return self._reclaim_locked(req)
            if cmd == "locate":
                part = int(req["part"])
                if not 0 <= part < self.num_parts:
                    return {"error": f"part {part} out of range"}
                self._clients_active = True  # a consumer is attached
                self._reap_stale_locked(now)
                owner = self._assigned.get(part)
                info = self._workers.get(owner) if owner is not None else None
                if info is None or not info.alive:
                    if owner is not None:
                        # the part stayed assigned to a departed drained
                        # worker (handoff-confirmed — see
                        # _finish_drain_locked) for exactly this moment:
                        # a client still wants it, so NOW it re-queues
                        self._requeue_locked(
                            [part], owner, "located after its drained "
                            "owner left")
                    return {"wait": True}
                resp = {"worker": info.worker, "host": info.host,
                        "port": info.port}
                if info.state == DRAINING:
                    # the owner is leaving: clients should finish this
                    # stream promptly and confirm with `handoff`
                    resp["draining"] = True
                have = req.get("have")
                if have is not None and str(have) != info.worker:
                    # the part moved off the worker the client last
                    # used: the client takes this hint as confirmation
                    # that a drain re-issue landed (drain_handoffs) —
                    # no dead-socket timeout involved (docs/service.md)
                    resp["moved"] = True
                return resp
            if cmd == "report_lost":
                self._mark_dead_locked(str(req["worker"]))
                return {"ok": True}
            if cmd == "status":
                return {
                    "workers": {w: {"host": i.host, "port": i.port,
                                    "alive": i.alive, "state": i.state}
                                for w, i in self._workers.items()},
                    "assigned": {str(p): w
                                 for p, w in self._assigned.items()},
                    "todo": list(self._todo),
                    "completed": sorted(self._completed),
                    "hedged": {str(p): w for p, w in self._spec.items()},
                    "generation": self.generation,
                }
        return {"error": f"unknown command {cmd!r}"}

    def _drain_locked(self, req: dict, now: float) -> dict:
        """Begin (or report) a graceful drain: the worker leaves the
        grant rotation immediately, its unstarted/in-flight parts
        proactively re-issue at the front (hedged parts are inherited by
        their speculative worker), and its frame-store-complete parts
        keep serving until every one is ``handoff``-confirmed or the
        drain deadline expires. Idempotent — repeats report state."""
        worker = str(req["worker"])
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return {"ok": False, "unknown": True}
        # an EXPLICIT deadline of 0 means "leave now" — only an absent
        # field falls back to the knob default (0 is falsy, so `or`
        # would silently re-arm the 30s window the caller opted out of)
        raw_deadline = req.get("deadline")
        deadline_s = (float(raw_deadline) if raw_deadline is not None
                      else self._drain_deadline_s)
        if info.state == DRAINING:
            # a repeat drain may TIGHTEN the window (eviction imminent:
            # drain(deadline=0) means leave now), never loosen it
            if raw_deadline is not None:
                new_at = now + deadline_s
                if (info.drain_deadline is None
                        or new_at < info.drain_deadline):
                    info.drain_deadline = new_at
        else:
            info.state = DRAINING
            info.drain_deadline = now + deadline_s
            info.handed_off = set()
            self._journal_append({"op": "drain", "worker": worker})
            _resilience.record_event("worker_drains")
            # speculative grants the drainer held die with the drain
            self._drop_worker_specs_locked(worker)
            # proactive re-issue of everything NOT frame-store-complete
            # (those keep serving out): failover starts now, not when
            # the worker's sockets die. A hedged part is inherited by
            # its speculative worker instead of re-queued.
            pending = self._inherit_or_requeue_locked(
                worker,
                sorted(p for p, w in self._assigned.items()
                       if w == worker and p not in self._completed),
                "draining")
            logger.warning(
                "dispatcher: draining worker %s (deadline %.1fs, "
                "%d unstarted parts re-issued, %d complete parts "
                "serving out)", worker, deadline_s, len(pending),
                sum(1 for p, w in self._assigned.items()
                    if w == worker and p in self._completed))
            # nothing to serve out (preempted before any part
            # completed)? the drain is already done — exit within the
            # notice window instead of idling out the deadline
            self._maybe_finish_drain_locked(info)
        serving = sorted(p for p, w in self._assigned.items()
                         if w == worker and p in self._completed)
        return {"ok": True, "serving": serving,
                "deadline_s": round(
                    max(0.0, (info.drain_deadline or now) - now), 3)}

    def _reclaim_locked(self, req: dict) -> dict:
        """Adopt the fully-parsed parts a (re-)registered worker's frame
        store still holds — instead of forcing a fleet-wide re-parse —
        and re-queue the journal-complete parts it no longer announces
        (its store lost them, e.g. dispatcher AND worker both died).
        Parts owned by ANOTHER live worker are never stolen; parts
        granted this generation and still mid-parse are left alone (the
        announce lists complete parts only)."""
        worker = str(req["worker"])
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return {"error": f"reclaim from unregistered worker "
                             f"{worker!r} (register first)"}
        held = {int(p) for p in (req.get("parts") or [])
                if 0 <= int(p) < self.num_parts}
        adopted: List[int] = []
        for part in sorted(held):
            owner = self._assigned.get(part)
            if owner == worker:
                if part not in self._completed:
                    self._completed.add(part)
                    self._journal_append({"op": "complete", "part": part,
                                          "worker": worker})
                adopted.append(part)
            elif owner is None and part in self._todo:
                self._todo.remove(part)
                self._assigned[part] = worker
                self._completed.add(part)
                self._journal_append({"op": "reclaim", "part": part,
                                      "worker": worker})
                adopted.append(part)
            # else: owned by another live worker — exactly-once wins
        stale = [p for p, w in self._assigned.items()
                 if w == worker and p in self._completed
                 and p not in held]
        self._requeue_locked(stale, worker, "reclaimed without")
        if adopted:
            logger.info("dispatcher: worker %s reclaimed parts %s",
                        worker, adopted)
        return {"ok": True, "adopted": adopted}

    # ---------------- server loop ----------------

    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            try:
                # accepted sockets do NOT inherit the listener's
                # SO_REUSEADDR: without it, one lingering half-closed
                # handler conn blocks a same-address restart's bind
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            except OSError:
                pass
            # one thread per connection — requests are tiny, but a
            # half-open client blocking the ONLY serve thread for its
            # read timeout would queue every worker heartbeat behind it —
            # capped by the handler semaphore: excess connections shed
            # with a retryable busy reply instead of a new thread
            if not self._handler_slots.acquire(blocking=False):
                self._shed(conn)
                continue
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _shed(self, conn) -> None:
        """Refuse one connection with a retryable busy reply (callers
        heal through the shared RetryPolicy — see :func:`request`)."""
        try:
            conn.settimeout(1.0)
            conn.sendall(b'{"busy": true}\n')
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn) -> None:
        try:
            conn.settimeout(10.0)
            with conn.makefile("rwb") as f:
                line = f.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except (ValueError, KeyError, TypeError) as exc:
                    resp = {"error": f"bad request: {exc}",
                            "gen": self.generation}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
        except OSError as exc:
            logger.debug("dispatcher: connection error: %s", exc)
        finally:
            self._handler_slots.release()
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Crash-simulate the dispatcher (``kill -9``): the listener
        drops with no goodbye and the in-memory assignment state is
        abandoned — the fsync'd journal is all a restart recovers from.
        Mechanically identical to :meth:`close` (the journal is
        append-only, so there is nothing graceful to skip); kept
        separate so chaos tests state their intent."""
        self.close()

    def close(self) -> None:
        self._closed = True
        # stop the background reaper tick first (clean shutdown: the
        # tick must never fire against a half-closed dispatcher)
        self._tick_stop.set()
        if threading.current_thread() is not self._tick_thread:
            self._tick_thread.join(timeout=5.0)
        # shutdown BEFORE close: a thread blocked in accept() holds a
        # kernel reference to the fd, so close() alone leaves the old
        # LISTEN socket alive until the syscall returns — and a restart
        # on the same address then cannot bind. shutdown wakes accept
        # immediately; the join guarantees the reference is dropped.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)
        # force-drop in-flight handler connections, exactly like the
        # kernel does for a dead process — otherwise a lingering
        # half-open peer keeps the port and a same-address restart
        # cannot bind
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


def request(address: str, req: dict, timeout: float = 10.0) -> dict:
    """One dispatcher round trip (shared by workers and clients).
    ``address`` is ``host:port``. Transport failures surface as their
    natural ConnectionError/OSError classes; a torn or empty reply (the
    dispatcher died mid-response) and a shed ``busy`` reply are wrapped
    in retryable ``ConnectionError`` HERE, so every caller — workers,
    clients, fleet bootstrap — heals through the shared
    :class:`~dmlc_tpu.io.resilience.RetryPolicy` instead of re-deriving
    the classification at call sites. The ``dispatch_rpc`` fault-plan op
    fires on every round trip (docs/resilience.md grammar)."""
    _faults.maybe_fail("dispatch_rpc", f"{address} {req.get('cmd', '')}")
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        with s.makefile("rwb") as f:
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            line = f.readline()
    if not line:
        raise ConnectionError(f"dispatcher {address}: empty reply "
                              f"(died mid-response)")
    try:
        resp = json.loads(line)
    except ValueError as exc:
        # a torn reply mid-crash is JSON garbage — the same transient
        # fault as the connection dropping, classified ONCE here
        raise ConnectionError(
            f"dispatcher {address}: torn reply "
            f"{line[:64]!r}") from exc
    if resp.get("busy"):
        raise ConnectionError(
            f"dispatcher {address}: busy (handler slots exhausted; "
            f"retry after backoff)")
    if "error" in resp:
        from dmlc_tpu.utils.check import DMLCError

        raise DMLCError(f"dispatcher {address}: {resp['error']}")
    return resp
