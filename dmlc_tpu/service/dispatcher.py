"""Data-service dispatcher: split assignment + worker registry.

The control plane of the disaggregated RowBlock service (tf.data
service's dispatcher role, arXiv:2210.14826 §3): it owns ONE dataset —
a URI, its partition count, and the parser config every worker must use
— and hands the ``num_parts`` :class:`~dmlc_tpu.io.input_split.InputSplit`
partitions to parse workers **first-come-first-served, exactly once per
epoch**. A split is re-issued only when its owner is declared dead (a
client reported a broken stream, or heartbeats went stale), and re-issued
splits jump the queue so a mid-stream failover heals before new work
starts.

Protocol: one JSON object per connection (newline-terminated request,
newline-terminated response — the same short-lived-connection shape the
rabit tracker uses for ``heartbeat``/``metrics``). Commands:

``config``                      -> the dataset spec workers/clients parse
``register worker host port``   -> join the fleet (idempotent; a re-
                                   registration after death re-queues
                                   nothing — the worker starts fresh)
``next_split worker``           -> ``{"part": k}`` | ``{"part": null}``
                                   (nothing to do) — doubles as liveness
``heartbeat worker``            -> liveness only
``locate part``                 -> ``{"worker", "host", "port"}`` of the
                                   live owner, or ``{"wait": true}`` while
                                   the part awaits (re)assignment
``report_lost worker``          -> a client observed the worker dead: all
                                   its parts re-queue at the FRONT
``status``                      -> registry snapshot (tests, operators)

The dispatcher is deliberately dataset-state-free about *blocks*: block
ordering, resume, and exactly-once delivery live with the client (global
order is part-major), so the dispatcher never becomes a data-plane
bottleneck — it serves O(workers + failovers) tiny requests per epoch.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from collections import deque
from typing import Dict, Optional

from dmlc_tpu.utils.timer import get_time

logger = logging.getLogger("dmlc_tpu.service")


class _WorkerInfo:
    __slots__ = ("worker", "host", "port", "last_seen", "alive")

    def __init__(self, worker: str, host: str, port: int, now: float):
        self.worker = worker
        self.host = host
        self.port = port
        self.last_seen = now
        self.alive = True


class Dispatcher:
    """Split-assignment server for one dataset.

    ``parser`` is the config dict every worker builds its parser from
    (``format``/``type_``, ``chunk_bytes``, ``threaded``, ... — the
    kwargs of :func:`dmlc_tpu.data.parsers.create_parser`); shipping it
    from one place is what makes N workers' output byte-identical to a
    local parse with the same config. ``liveness_timeout`` (seconds)
    declares a worker dead when its polls/heartbeats go stale; client
    ``report_lost`` reports short-circuit that wait.
    """

    def __init__(self, uri: str, num_parts: int,
                 parser: Optional[dict] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout: float = 10.0,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None):
        self.uri = uri
        self.num_parts = int(num_parts)
        self.parser = dict(parser or {})
        # the epoch-plan identity of the dataset (shuffle_seed /
        # shuffle_window, dmlc_tpu/data/epoch.py): shipped in `config` so
        # every worker arms its block cache with the SAME plan and every
        # client learns the seed its epochs are a function of — the one
        # place the fleet's shuffle is decided (docs/service.md)
        self.plan = dict(plan or {})
        # snapshot-frame geometry ({batch_size, num_col, x_dtype}): when
        # set, workers ALSO pack each part into fixed-geometry device-
        # layout batches (dmlc_tpu/io/snapshot.py encoding) and clients
        # stream those instead of CSR blocks — x_dtype='bfloat16' halves
        # the wire bytes. One dispatcher-owned knob, like the plan: the
        # whole fleet serves one batch geometry or none (docs/service.md)
        self.snapshot = dict(snapshot or {})
        self.liveness_timeout = float(liveness_timeout)
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerInfo] = {}
        # FCFS visitation queue: parts not yet assigned this epoch.
        # Re-issued parts (dead owner) go to the FRONT so failover work
        # heals before fresh parts are handed out.
        self._todo: deque = deque(range(self.num_parts))
        self._assigned: Dict[int, str] = {}   # part -> worker id
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="service-dispatcher")
        self._thread.start()
        logger.info("dispatcher for %s (%d parts) on %s:%d",
                    uri, num_parts, self.host, self.port)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ---------------- assignment core (lock held) ----------------

    def _mark_dead_locked(self, worker: str) -> None:
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return
        info.alive = False
        lost = sorted(p for p, w in self._assigned.items() if w == worker)
        for part in lost:
            del self._assigned[part]
        # re-issue at the front, lowest part first (clients consume
        # part-major, so the earliest lost part is the one blocking them)
        for part in reversed(lost):
            self._todo.appendleft(part)
        if lost:
            logger.warning("dispatcher: worker %s lost; re-issuing parts %s",
                           worker, lost)

    def _reap_stale_locked(self, now: float) -> None:
        if self.liveness_timeout <= 0:
            return
        for info in list(self._workers.values()):
            if info.alive and now - info.last_seen > self.liveness_timeout:
                logger.warning("dispatcher: worker %s missed heartbeats "
                               "(last seen %.1fs ago)", info.worker,
                               now - info.last_seen)
                self._mark_dead_locked(info.worker)

    # ---------------- request handlers ----------------

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        now = get_time()
        with self._lock:
            if cmd == "config":
                return {"uri": self.uri, "num_parts": self.num_parts,
                        "parser": self.parser, "plan": self.plan,
                        "snapshot": self.snapshot}
            if cmd == "register":
                worker = str(req["worker"])
                self._workers[worker] = _WorkerInfo(
                    worker, str(req["host"]), int(req["port"]), now)
                return {"ok": True}
            if cmd == "heartbeat":
                info = self._workers.get(str(req.get("worker")))
                if info is not None and info.alive:
                    info.last_seen = now
                return {"ok": True}
            if cmd == "next_split":
                worker = str(req["worker"])
                info = self._workers.get(worker)
                if info is None or not info.alive:
                    # unregistered/declared-dead workers get no splits —
                    # a zombie must re-register before it can own parts
                    return {"part": None, "register": True}
                info.last_seen = now
                self._reap_stale_locked(now)
                if not self._todo:
                    return {"part": None}
                part = self._todo.popleft()
                self._assigned[part] = worker
                logger.info("dispatcher: part %d -> worker %s", part, worker)
                return {"part": part}
            if cmd == "locate":
                part = int(req["part"])
                if not 0 <= part < self.num_parts:
                    return {"error": f"part {part} out of range"}
                self._reap_stale_locked(now)
                owner = self._assigned.get(part)
                info = self._workers.get(owner) if owner is not None else None
                if info is None or not info.alive:
                    return {"wait": True}
                return {"worker": info.worker, "host": info.host,
                        "port": info.port}
            if cmd == "report_lost":
                self._mark_dead_locked(str(req["worker"]))
                return {"ok": True}
            if cmd == "status":
                return {
                    "workers": {w: {"host": i.host, "port": i.port,
                                    "alive": i.alive}
                                for w, i in self._workers.items()},
                    "assigned": {str(p): w
                                 for p, w in self._assigned.items()},
                    "todo": list(self._todo),
                }
        return {"error": f"unknown command {cmd!r}"}

    # ---------------- server loop ----------------

    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            # one thread per connection: requests are tiny, but a
            # half-open client blocking the ONLY serve thread for its
            # read timeout would queue every worker heartbeat behind it —
            # long enough to trip the liveness reaper on a healthy fleet
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn) -> None:
        try:
            conn.settimeout(10.0)
            with conn.makefile("rwb") as f:
                line = f.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except (ValueError, KeyError, TypeError) as exc:
                    resp = {"error": f"bad request: {exc}"}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
        except OSError as exc:
            logger.debug("dispatcher: connection error: %s", exc)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def request(address: str, req: dict, timeout: float = 10.0) -> dict:
    """One dispatcher round trip (shared by workers and clients).
    ``address`` is ``host:port``. Transport failures surface as their
    natural ConnectionError/OSError classes — callers run this under a
    :class:`~dmlc_tpu.io.resilience.RetryPolicy` where retry is wanted."""
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        with s.makefile("rwb") as f:
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            line = f.readline()
    if not line:
        raise ConnectionError(f"dispatcher {address}: empty response")
    resp = json.loads(line)
    if "error" in resp:
        from dmlc_tpu.utils.check import DMLCError

        raise DMLCError(f"dispatcher {address}: {resp['error']}")
    return resp
