"""Data-service dispatcher: multi-job split assignment + worker registry.

The control plane of the disaggregated RowBlock service (tf.data
service's dispatcher role, arXiv:2210.14826 §3): it owns a registry of
**jobs** — each a dataset URI, its partition count, and the parser
config every worker must use for it — and hands each job's ``num_parts``
:class:`~dmlc_tpu.io.input_split.InputSplit` partitions to parse workers
**exactly once per epoch**, rotating grants round-robin across jobs with
pending work so one greedy job can never starve another (per-job
fairness; docs/service.md multi-tenant service). A split is re-issued
only when its owner is declared dead (a client reported a broken stream,
or heartbeats went stale), and re-issued splits jump their job's queue so
a mid-stream failover heals before new work starts.

One dispatcher, MANY trainers: the constructor's ``uri``/``num_parts``
register the backward-compatible ``default`` job, and ``register_job``
(RPC or :meth:`Dispatcher.register_job`) adds more at any point — each
with its own parser config, epoch-plan identity, and snapshot geometry.
With ``share_dir=`` set, jobs that do not pin their own ``block_cache``
are assigned one keyed by the job's **store signature** (a digest of
``uri + num_parts + parser config``): two jobs over the same corpus with
the same config resolve to the SAME published ``DMLCBC01`` artifacts
through the PR 11 store manifest, so the fleet parses that corpus
exactly once — the second job's parts serve warm (docs/store.md
share-by-signature).

Protocol: one JSON object per connection (newline-terminated request,
newline-terminated response — the same short-lived-connection shape the
rabit tracker uses for ``heartbeat``/``metrics``). Commands (``job``
defaults to ``"default"`` wherever it appears, so the one-dataset
protocol of PR 7-14 is a strict subset):

``config [job]``                -> the job's dataset spec
``register_job job uri num_parts [parser plan snapshot]``
                                -> add a job to the registry (idempotent
                                   for an identical spec; a conflicting
                                   spec for an existing job is refused —
                                   job identity is immutable)
``register worker host port``   -> join the fleet (re-registration of a
                                   worker already seen alive THIS
                                   generation is treated as a crash-
                                   restart: its parts re-queue at the
                                   front until a ``reclaim`` adopts them
                                   back). A brand-new worker id arriving
                                   after work has started is a **live
                                   join** (journaled ``join`` event,
                                   ``worker_joins`` counter): it enters
                                   the grant rotation immediately
``drain worker [deadline]``     -> begin a graceful drain: no new grants,
                                   unstarted parts re-issue at the front
                                   immediately, frame-store-complete
                                   parts keep serving until clients
                                   confirm ``handoff`` or the drain
                                   deadline expires (docs/service.md
                                   elastic membership)
``handoff worker part [job]``   -> a client confirms it finished
                                   streaming ``part`` from the draining
                                   ``worker``; when every served part is
                                   confirmed the drain completes early
``next_split worker``           -> ``{"part": k, "job": j}`` |
                                   ``{"part": null}`` (nothing to do) —
                                   doubles as liveness
``heartbeat worker``            -> liveness only
``locate part [job]``           -> ``{"worker", "host", "port"}`` of the
                                   live owner, or ``{"wait": true}`` while
                                   the part awaits (re)assignment
``report_lost worker``          -> a client observed the worker dead: all
                                   its parts (every job) re-queue at the
                                   FRONT
``part_done part worker [job]`` -> the owner finished parsing the part
                                   (journaled: a restarted dispatcher
                                   keeps it done instead of re-issuing)
``reclaim worker parts``        -> the worker re-announces the fully-
                                   parsed parts its frame store still
                                   holds (a flat list for the default
                                   job, or ``{job: [parts]}``): a
                                   restarted dispatcher ADOPTS them (no
                                   fleet-wide re-parse), and journal-
                                   complete parts the worker no longer
                                   holds re-queue
``status``                      -> registry snapshot (tests, operators);
                                   legacy top-level assignment fields
                                   mirror the default job, ``jobs``
                                   carries every job's state

Every response is stamped with the dispatcher's monotonic ``gen``
generation token, so workers and clients detect a restart at their next
control exchange (docs/service.md control-plane recovery).

**Crash recovery**: with ``journal_path=`` set, every state transition —
job registration, worker register/death, part grant / complete /
re-issue / reclaim — is appended to a flock'd JSONL journal (the shared
:class:`~dmlc_tpu.store.journal.AppendJournal` substrate: torn-tail skip
at replay, atomic compaction). Events are job-scoped (``job`` rides
every assignment event of a non-default job; default-job events keep
the exact PR 12 shapes, so legacy journals replay unchanged). A
restarted ``Dispatcher(journal_path=...)`` replays into the exact
per-job assignment state: **completed parts stay done** (their owners
get a liveness grace window to re-attach), **in-flight parts re-queue
at the front**, registered jobs come back with their full spec, and the
generation token bumps so the fleet re-registers and reclaims. A journal
that records a DIFFERENT dataset than the constructor supplies is a
**fatal, non-retryable configuration error**
(:class:`ServiceConfigError`): recovery must never silently serve the
wrong corpus, and retrying cannot fix a disagreement between the journal
on disk and the code constructing the dispatcher. The journal records no
epoch state by design: epochs live with clients and worker frame stores
(``before_first`` re-serves without dispatcher involvement), so the
assignment journal is epoch-invariant.

**Worker lifecycle** (docs/service.md elastic membership): every worker
walks JOINING -> ACTIVE -> DRAINING -> DEAD. ``JOINING`` is a
journal-restored worker awaiting its re-attach handshake (it keeps
serving completed parts but gets no grants); ``register`` makes it
``ACTIVE`` (grant rotation); a ``drain`` request makes it ``DRAINING``
(no new grants, unstarted parts proactively re-issued, completed parts
keep serving until ``handoff``-confirmed or the drain deadline — clients
learn re-assignments from ``moved``/``draining`` hints on ``locate``, so
failover happens before the socket dies); ``DEAD`` is terminal (stale
heartbeats, ``report_lost``, or a completed drain). Transitions journal,
so membership state survives dispatcher restarts.

**Straggler hedging**: the dispatcher tracks per-job, per-part
grant->complete latency; once at least :data:`HEDGE_MIN_SAMPLES` parts
of a job have completed, an in-flight part stuck past
``DMLC_TPU_HEDGE_FACTOR`` times that job's median (and past
:data:`HEDGE_MIN_AGE_S`) is **speculatively re-issued** to a second
active worker (journaled ``spec_grant``, ``speculative_reissues``).
First ``part_done`` wins — a win by the speculative worker counts
``speculative_wins`` and flips ``locate`` to the winner; the loser's
completion is deduped (exactly-once preserved: parsing is
deterministic, so either stream is byte-identical). Medians are per job
so a slow-corpus job can never poison a fast job's hedge threshold.

A background **reaper tick thread** (interval derived from
``liveness_timeout``) drives liveness, drain deadlines, and the hedging
check on wall-clock time, so a quiet fleet — no poll or heartbeat
traffic at all — still reaps dead workers, expires drains, and hedges
stragglers.

The dispatcher is deliberately dataset-state-free about *blocks*: block
ordering, resume, and exactly-once delivery live with the client (global
order is part-major per job), so the dispatcher never becomes a
data-plane bottleneck — it serves O(jobs × (workers + failovers)) tiny
requests per epoch. Concurrent connection handlers are capped
(``DMLC_TPU_DISPATCH_WORKERS`` via the knob table); excess connections
shed with a retryable ``busy`` reply, so a reconnect storm from a
recovering fleet cannot exhaust threads exactly when the dispatcher must
stay responsive.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import statistics
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple, Union

from dmlc_tpu.io import faults as _faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.store import journal as _journal_mod
from dmlc_tpu.store.journal import AppendJournal
from dmlc_tpu.store.manager import signature_hash
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError, check
from dmlc_tpu.utils.timer import get_time

logger = logging.getLogger("dmlc_tpu.service")

# per-address clock-offset estimates (peer monotonic clock minus ours),
# fed by every `request()` round trip whose reply carries a `now` stamp:
# offset = peer_now - (t_send + t_recv) / 2, EWMA-smoothed so one
# GC-paused round trip cannot skew a whole timeline. Consumed by
# LocalFleet.dump_trace to place every peer's spans on ONE clock
# (docs/observability.md Distributed tracing).
_CLOCK_OFFSETS: Dict[str, float] = {}
_CLOCK_OFFSETS_LOCK = threading.Lock()
_CLOCK_OFFSET_ALPHA = 0.3


def _note_clock_offset(address: str, offset: float) -> None:
    with _CLOCK_OFFSETS_LOCK:
        prev = _CLOCK_OFFSETS.get(address)
        _CLOCK_OFFSETS[address] = (
            offset if prev is None
            else prev + _CLOCK_OFFSET_ALPHA * (offset - prev))


def peer_clock_offset(address: str) -> Optional[float]:
    """Latest clock-offset estimate (seconds to ADD to ``address``'s
    monotonic timestamps to land on this process's clock), or None when
    no stamped reply from that address has been seen yet."""
    with _CLOCK_OFFSETS_LOCK:
        return _CLOCK_OFFSETS.get(address)

# the job the one-dataset constructor/protocol of PR 7-14 maps onto:
# requests without a `job` field, journal events without one, and the
# legacy reply shapes all refer to this job
DEFAULT_JOB = "default"

# journal compaction threshold: past this many lines at replay the
# journal is rewritten as the live state (jobs + start + registers +
# grant/complete pairs). Assignment journals are naturally small —
# O(jobs × parts + workers + failovers), epochs append nothing — so this
# only triggers after many restart cycles.
JOURNAL_COMPACT_LINES = 4096

# worker lifecycle states (docs/service.md elastic membership)
JOINING = "joining"      # journal-restored, awaiting register+reclaim
ACTIVE = "active"        # in the grant rotation
DRAINING = "draining"    # no new grants; serving until handoff/deadline
DEAD = "dead"            # terminal

# straggler hedging guards: never hedge before this many completion
# latency samples exist for the part's JOB (a 2-part dataset can never
# produce a meaningful median), and never hedge a part younger than this
# wall-clock floor — hedging targets seconds-scale stalls, and the floor
# must sit well above any plausible healthy-part latency (a loaded CI
# host pausing a smoke-scale part for a second must not fire a
# speculative parse, or the bench-smoke zero gate on
# `speculative_reissues` turns flaky)
HEDGE_MIN_SAMPLES = 3
HEDGE_MIN_AGE_S = 5.0
# completion-latency window each job's hedging median is computed over
HEDGE_LATENCY_WINDOW = 64


class ServiceConfigError(DMLCError):
    """Fatal service-configuration disagreement: the assignment journal
    (or the live job registry) records a dataset identity that
    contradicts what the caller supplies. Deliberately NOT retryable —
    :func:`dmlc_tpu.io.resilience.classify` reads it as ``fatal``
    (no transient cause is chained on), because re-attempting cannot
    reconcile a journal on disk with conflicting constructor arguments;
    the operator must either point the dispatcher at the dataset the
    journal records or at a fresh ``journal_path``."""


class _WorkerInfo:
    __slots__ = ("worker", "host", "port", "last_seen", "state",
                 "registered_gen", "drain_deadline", "handed_off",
                 "drained")

    def __init__(self, worker: str, host: str, port: int, now: float,
                 registered_gen: Optional[int] = None,
                 state: Optional[str] = None):
        self.worker = worker
        self.host = host
        self.port = port
        self.last_seen = now
        # the generation this worker last sent `register` in; None for a
        # worker restored from the journal that has not re-attached yet
        # (its frame-store contents are unknown until it reclaims)
        self.registered_gen = registered_gen
        # lifecycle: a journal-restored worker is JOINING until its
        # re-attach handshake lands; a registered one is ACTIVE
        self.state = state or (ACTIVE if registered_gen is not None
                               else JOINING)
        self.drain_deadline: Optional[float] = None
        # (job, part) pairs clients confirmed streaming from a drainer
        self.handed_off: Set[Tuple[str, int]] = set()
        # True only for a worker whose DRAIN completed (handoffs
        # confirmed or deadline expired): its next poll reads `drained`
        # and exits instead of re-attaching as a zombie
        self.drained = False

    @property
    def alive(self) -> bool:
        return self.state != DEAD


class _JobState:
    """One registered job: its immutable dataset spec plus the mutable
    assignment state (FCFS queue, grants, completions, hedging books)
    the dispatcher serves it from."""

    __slots__ = ("job", "uri", "num_parts", "parser", "plan", "snapshot",
                 "share_sig", "todo", "assigned", "completed",
                 "clients_active", "grant_times", "latencies", "spec",
                 "spec_times", "hedge_todo", "priority", "weight",
                 "slo_wait_frac", "max_inflight", "deficit", "traces")

    def __init__(self, job: str, uri: str, num_parts: int,
                 parser: Optional[dict] = None,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 share_sig: Optional[str] = None,
                 priority: int = 0, weight: int = 1,
                 slo_wait_frac: Optional[float] = None,
                 max_inflight: Optional[int] = None):
        self.job = str(job)
        self.uri = uri
        self.num_parts = int(num_parts)
        self.parser = dict(parser or {})
        # the epoch-plan identity of the job (shuffle_seed /
        # shuffle_window, dmlc_tpu/data/epoch.py): shipped in `config` so
        # every worker arms its block cache with the SAME plan and every
        # client learns the seed its epochs are a function of — the one
        # place each job's shuffle is decided (docs/service.md)
        self.plan = dict(plan or {})
        # snapshot-frame geometry ({batch_size, num_col, x_dtype}): when
        # set, workers ALSO pack this job's parts into fixed-geometry
        # device-layout batches (dmlc_tpu/io/snapshot.py encoding) and
        # clients stream those instead of CSR blocks — per job, so a
        # bf16-wire trainer and a CSR trainer can share one fleet
        self.snapshot = dict(snapshot or {})
        # the job's store signature when share-by-signature resolved its
        # block cache (None for jobs that pinned their own or share_dir
        # is off) — surfaced in status for operators/tests
        self.share_sig = share_sig
        # FCFS visitation queue: parts not yet assigned this epoch.
        # Re-issued parts (dead owner) go to the FRONT so failover work
        # heals before fresh parts are handed out.
        self.todo: Deque[int] = deque(range(self.num_parts))
        self.assigned: Dict[int, str] = {}   # part -> worker id
        self.completed: Set[int] = set()     # parts whose parse finished
        # True once a client has located a part of this job: a brand-new
        # worker id registering after any job saw a client is a
        # mid-epoch LIVE JOIN (worker_joins)
        self.clients_active = False
        # per-part grant timestamps (in-flight ages) and this job's
        # recent grant->complete latencies (the hedging median)
        self.grant_times: Dict[int, float] = {}
        self.latencies: Deque[float] = deque(maxlen=HEDGE_LATENCY_WINDOW)
        # part -> second (speculative) owner; the primary stays in
        # `assigned` until one of them completes (first part_done wins)
        self.spec: Dict[int, str] = {}
        self.spec_times: Dict[int, float] = {}
        # parts flagged for speculative re-issue, awaiting a poll from a
        # worker that is not the stuck primary
        self.hedge_todo: Deque[int] = deque()
        # --- QoS class (docs/service.md Production QoS) ---
        # priority band: higher bands fully preempt lower ones in the
        # grant rotation; weight shapes the deficit-round-robin share
        # WITHIN a band; slo_wait_frac is the job's input-wait SLO target
        # the autoscaler steers toward; max_inflight bounds this job's
        # granted-not-completed parts (admission control). All four are
        # part of the immutable job identity and journal with the spec.
        self.priority = int(priority)
        self.weight = int(weight)
        self.slo_wait_frac = (None if slo_wait_frac is None
                              else float(slo_wait_frac))
        self.max_inflight = (None if max_inflight is None
                             else int(max_inflight))
        # DRR running credit: replenished by `weight` when the band's
        # eligible set runs dry, spent 1.0 per grant. Scheduler state,
        # not identity — rebuilt implicitly across restarts (grants
        # already journal; credit restarts at 0 for everyone, which
        # preserves relative shares).
        self.deficit = 0.0
        # part -> (trace_id, root span_id): the trace each in-flight
        # part's grant opened. Grant replies and locate replies hand the
        # SAME context to the worker and the client, so one (job, part)
        # is one trace from next_split to device_put. Observability
        # state, not identity — never journaled, dies with a restart.
        self.traces: Dict[int, Tuple[str, str]] = {}

    def qos_dict(self) -> dict:
        """The job's QoS class as a wire/journal sub-dict (only the
        non-default knobs — the default job's flat PR 12 shape stays
        byte-compatible when nothing was asked for)."""
        qos: dict = {"priority": self.priority, "weight": self.weight}
        if self.slo_wait_frac is not None:
            qos["slo_wait_frac"] = self.slo_wait_frac
        if self.max_inflight is not None:
            qos["max_inflight"] = self.max_inflight
        return qos

    def inflight(self) -> int:
        """Granted-not-completed parts charged to this job's admission
        budget (primary grants only — a hedge duplicates work already
        admitted, it is not a new admission)."""
        return len(self.grant_times)

    def default_qos(self) -> bool:
        """True when no QoS knob was asked for — such jobs keep the
        pre-QoS wire/journal shape byte-compatible."""
        return (self.priority == 0 and self.weight == 1
                and self.slo_wait_frac is None
                and self.max_inflight is None)

    def spec_dict(self) -> dict:
        """The wire-shape dataset spec (`config` reply sans job key).
        ``wire`` advertises the fleet's newest data-plane protocol
        (docs/service.md Wire v2) — informational: the binding
        negotiation happens per stream at open, so mixed fleets and old
        peers interoperate regardless of what this says."""
        spec = {"uri": self.uri, "num_parts": self.num_parts,
                "parser": self.parser, "plan": self.plan,
                "snapshot": self.snapshot, "wire": 2}
        if not self.default_qos():
            spec["qos"] = self.qos_dict()
        return spec


class Dispatcher:
    """Split-assignment server for N registered jobs.

    The constructor's ``uri``/``num_parts``/``parser``/``plan``/
    ``snapshot`` register the ``default`` job (the PR 7-14 one-dataset
    protocol is a strict subset of the multi-tenant one); more jobs
    arrive via :meth:`register_job` / the ``register_job`` RPC. ``uri``
    may be None for a dispatcher born empty (jobs registered later).

    ``parser`` is the config dict every worker builds its parser from
    (``format``/``type_``, ``chunk_bytes``, ``threaded``, ... — the
    kwargs of :func:`dmlc_tpu.data.parsers.create_parser`); shipping it
    from one place is what makes N workers' output byte-identical to a
    local parse with the same config. ``liveness_timeout`` (seconds)
    declares a worker dead when its polls/heartbeats go stale; client
    ``report_lost`` reports short-circuit that wait.

    ``share_dir`` arms cross-job artifact sharing: a registering job
    whose parser config carries no ``block_cache`` is assigned one at
    ``share_dir/svc-<signature>.bc`` where the signature digests the
    job's dataset identity (uri + num_parts + parser config), so jobs
    over the same corpus with the same config converge on the same
    published ``DMLCBC01`` artifacts and the fleet parses that corpus
    exactly once (docs/store.md share-by-signature).

    ``journal_path`` arms crash recovery: state transitions journal to
    an append-only JSONL file and a restart on the same address replays
    them (see the module docstring). Without it the dispatcher is the
    historical in-memory-only control plane (generation fixed at 1).
    """

    def __init__(self, uri: Optional[str] = None, num_parts: int = 0,
                 parser: Optional[dict] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout: float = 10.0,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 journal_path: Optional[str] = None,
                 journal_compact_lines: int = JOURNAL_COMPACT_LINES,
                 share_dir: Optional[str] = None):
        self.liveness_timeout = float(liveness_timeout)
        self.share_dir = share_dir
        if share_dir:
            os.makedirs(share_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerInfo] = {}
        # the job registry, insertion-ordered (the grant rotation walks
        # it round-robin); the constructor's dataset is the default job
        self._jobs: Dict[str, _JobState] = {}
        self._rr = 0  # grant-rotation cursor over the job order
        if uri is not None:
            check(int(num_parts) >= 1,
                  f"Dispatcher: num_parts {num_parts} must be >= 1 for "
                  f"dataset {uri!r}")
            self._jobs[DEFAULT_JOB] = self._make_job(
                DEFAULT_JOB, uri, int(num_parts), parser, plan, snapshot)
        self._hedge_factor = _knobs.resolve("hedge_factor")
        self._drain_deadline_s = float(_knobs.resolve("drain_deadline"))
        self.generation = 1
        self._journal: Optional[AppendJournal] = None
        if journal_path:
            self._journal = AppendJournal(journal_path)
            self._recover(int(journal_compact_lines))
        # connection-handler cap (knob table; docs/service.md): excess
        # connections shed with a retryable `busy` reply instead of
        # spawning an unbounded thread per connection — a reconnect storm
        # from a recovering fleet must not exhaust threads exactly when
        # the control plane needs to stay responsive
        self._handler_slots = threading.Semaphore(
            _knobs.resolve("dispatch_workers"))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        # in-flight handler connections, force-closed at close()/kill():
        # a dead process's sockets drop with it, and a restart must be
        # able to rebind the SAME port immediately (lingering accepted
        # sockets without SO_REUSEADDR would hold it)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="service-dispatcher")
        self._thread.start()
        # background reaper tick: liveness used to be checked only inside
        # RPC handling, so a QUIET fleet (no poll/heartbeat traffic at
        # all) never reaped a dead worker. The tick makes liveness, drain
        # deadlines, and the straggler-hedging check wall-clock-driven;
        # interval derives from liveness_timeout (several checks per
        # window) with a floor so drain/hedge stay responsive even when
        # liveness detection is disabled (liveness_timeout <= 0).
        if self.liveness_timeout > 0:
            tick = min(max(self.liveness_timeout / 4.0, 0.05), 2.0)
        else:
            tick = 0.25
        self._tick_interval = tick
        self._tick_stop = threading.Event()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, daemon=True,
            name="service-dispatcher-tick")
        self._tick_thread.start()
        logger.info("dispatcher (%d job(s): %s) on %s:%d gen %d",
                    len(self._jobs),
                    ", ".join(f"{j.job}={j.uri}({j.num_parts})"
                              for j in self._jobs.values()) or "none",
                    self.host, self.port, self.generation)

    # ---------------- default-job compatibility views ----------------

    def _default(self) -> Optional[_JobState]:
        return self._jobs.get(DEFAULT_JOB)

    @property
    def uri(self) -> Optional[str]:
        job = self._default()
        return job.uri if job is not None else None

    @property
    def num_parts(self) -> int:
        job = self._default()
        return job.num_parts if job is not None else 0

    @property
    def parser(self) -> dict:
        job = self._default()
        return job.parser if job is not None else {}

    @property
    def plan(self) -> dict:
        job = self._default()
        return job.plan if job is not None else {}

    @property
    def snapshot(self) -> dict:
        job = self._default()
        return job.snapshot if job is not None else {}

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def jobs(self) -> List[str]:
        """Registered job names, grant-rotation order."""
        with self._lock:
            return list(self._jobs)

    def job_qos(self) -> Dict[str, dict]:
        """Every registered job's QoS class ({job: {priority, weight
        [, slo_wait_frac][, max_inflight]}}) — the FleetAutoscaler's
        SLO/priority input (docs/service.md Production QoS)."""
        with self._lock:
            return {name: j.qos_dict() for name, j in self._jobs.items()}

    # ---------------- job registry ----------------

    def _make_job(self, job: str, uri: str, num_parts: int,
                  parser: Optional[dict], plan: Optional[dict],
                  snapshot: Optional[dict],
                  share_sig: Optional[str] = None,
                  qos: Optional[dict] = None) -> _JobState:
        """Build a _JobState, resolving the share-by-signature block
        cache when armed: a job without its own ``block_cache`` gets one
        keyed by its dataset identity, so identical jobs converge on the
        same published artifacts (store manifest sharing)."""
        cfg = dict(parser or {})
        if self.share_dir and not cfg.get("block_cache"):
            share_sig = signature_hash(
                {"uri": uri, "num_parts": int(num_parts), "parser": cfg})
            cfg["block_cache"] = os.path.join(self.share_dir,
                                              f"svc-{share_sig}.bc")
        qos = dict(qos or {})
        return _JobState(job, uri, num_parts, cfg, plan, snapshot,
                         share_sig=share_sig,
                         priority=qos.get("priority", 0),
                         weight=qos.get("weight", 1),
                         slo_wait_frac=qos.get("slo_wait_frac"),
                         max_inflight=qos.get("max_inflight"))

    @staticmethod
    def _validate_qos(job: str, req: dict) -> Union[dict, str]:
        """Normalize the QoS knobs of a registration request into a qos
        sub-dict, or return an error string. Loud validation: a typo'd
        class must fail the registration, not silently round-robin."""
        qos = dict(req.get("qos") or {})
        for key in ("priority", "weight", "slo_wait_frac", "max_inflight"):
            if req.get(key) is not None:
                qos[key] = req[key]
        try:
            priority = int(qos.get("priority", 0))
        except (TypeError, ValueError):
            return (f"register_job {job!r}: priority "
                    f"{qos.get('priority')!r} is not an integer")
        if priority < 0:
            return (f"register_job {job!r}: priority {priority} must be "
                    f">= 0 (higher bands preempt lower)")
        try:
            weight = int(qos.get("weight", 1))
        except (TypeError, ValueError):
            return (f"register_job {job!r}: weight "
                    f"{qos.get('weight')!r} is not an integer")
        if weight < 1:
            return (f"register_job {job!r}: weight {weight} must be >= 1 "
                    f"(the DRR share within the priority band)")
        out = {"priority": priority, "weight": weight}
        if qos.get("slo_wait_frac") is not None:
            try:
                slo = float(qos["slo_wait_frac"])
            except (TypeError, ValueError):
                return (f"register_job {job!r}: slo_wait_frac "
                        f"{qos.get('slo_wait_frac')!r} is not a number")
            if not (0.0 < slo <= 1.0):
                return (f"register_job {job!r}: slo_wait_frac {slo} must "
                        f"be in (0, 1] — the input-wait fraction the "
                        f"autoscaler keeps the job under")
            out["slo_wait_frac"] = slo
        if qos.get("max_inflight") is not None:
            try:
                max_inflight = int(qos["max_inflight"])
            except (TypeError, ValueError):
                return (f"register_job {job!r}: max_inflight "
                        f"{qos.get('max_inflight')!r} is not an integer")
            if max_inflight < 1:
                return (f"register_job {job!r}: max_inflight "
                        f"{max_inflight} must be >= 1 (admission budget "
                        f"of granted-not-completed parts)")
            out["max_inflight"] = max_inflight
        return out

    def register_job(self, job: str, uri: str, num_parts: int,
                     parser: Optional[dict] = None,
                     plan: Optional[dict] = None,
                     snapshot: Optional[dict] = None,
                     priority: Optional[int] = None,
                     weight: Optional[int] = None,
                     slo_wait_frac: Optional[float] = None,
                     max_inflight: Optional[int] = None) -> dict:
        """In-process job registration (the RPC's twin — LocalFleet and
        tests use it directly). Returns the registered spec reply;
        raises :class:`ServiceConfigError` when ``job`` exists with a
        conflicting spec (job identity is immutable). ``priority`` /
        ``weight`` / ``slo_wait_frac`` / ``max_inflight`` are the job's
        QoS class (docs/service.md Production QoS) — part of the
        immutable identity."""
        with self._lock:
            resp = self._register_job_locked({
                "job": job, "uri": uri, "num_parts": num_parts,
                "parser": parser, "plan": plan, "snapshot": snapshot,
                "priority": priority, "weight": weight,
                "slo_wait_frac": slo_wait_frac,
                "max_inflight": max_inflight})
        if "error" in resp:
            raise ServiceConfigError(resp["error"])
        return resp

    def _register_job_locked(self, req: dict) -> dict:
        job = str(req.get("job") or "")
        uri = req.get("uri")
        if not job:
            return {"error": "register_job: empty job name"}
        if not uri:
            return {"error": f"register_job {job!r}: a dataset uri is "
                             f"required"}
        try:
            num_parts = int(req.get("num_parts", 0))
        except (TypeError, ValueError):
            return {"error": f"register_job {job!r}: num_parts "
                             f"{req.get('num_parts')!r} is not an integer"}
        if num_parts < 1:
            return {"error": f"register_job {job!r}: num_parts "
                             f"{num_parts} must be >= 1"}
        qos = self._validate_qos(job, req)
        if isinstance(qos, str):
            return {"error": qos}
        state = self._make_job(job, str(uri), num_parts,
                               dict(req.get("parser") or {}),
                               dict(req.get("plan") or {}),
                               dict(req.get("snapshot") or {}),
                               qos=qos)
        prev = self._jobs.get(job)
        if prev is not None:
            if (prev.uri == state.uri
                    and prev.num_parts == state.num_parts
                    and prev.parser == state.parser
                    and prev.plan == state.plan
                    and prev.snapshot == state.snapshot
                    and prev.qos_dict() == state.qos_dict()):
                # idempotent re-registration (a trainer restarting its
                # client re-binds to the live job state)
                return dict(prev.spec_dict(), job=job, ok=True,
                            existing=True, share_sig=prev.share_sig)
            return {"error":
                    f"register_job {job!r}: job already registered with "
                    f"a different spec (have uri={prev.uri!r} "
                    f"num_parts={prev.num_parts} parser={prev.parser} "
                    f"qos={prev.qos_dict()}; "
                    f"got uri={state.uri!r} num_parts={state.num_parts} "
                    f"parser={state.parser} qos={state.qos_dict()}) — "
                    f"job identity is "
                    f"immutable; register the new dataset under a new "
                    f"job name"}
        self._jobs[job] = state
        self._journal_append(self._job_event(state), sync=True)
        logger.info("dispatcher: registered job %s -> %s (%d parts%s)",
                    job, state.uri, state.num_parts,
                    f", shared sig {state.share_sig}"
                    if state.share_sig else "")
        return dict(state.spec_dict(), job=job, ok=True, existing=False,
                    share_sig=state.share_sig)

    @staticmethod
    def _job_event(state: _JobState) -> dict:
        """The journal record of one job registration. The default job
        keeps the exact PR 12 `dataset` shape (uri + num_parts only —
        its full spec re-arrives with the constructor at restart);
        non-default jobs journal the whole spec, because nothing else
        re-supplies it across a restart."""
        if state.job == DEFAULT_JOB:
            return {"op": "dataset", "uri": state.uri,
                    "num_parts": state.num_parts}
        return {"op": "dataset", "job": state.job, "uri": state.uri,
                "num_parts": state.num_parts, "parser": state.parser,
                "plan": state.plan, "snapshot": state.snapshot,
                "share_sig": state.share_sig, "qos": state.qos_dict()}

    # ---------------- journal + replay ----------------

    def _journal_append(self, event: dict, sync: bool = True) -> None:
        """Journal one state transition (no-op without a journal). All
        assignment events fsync: the journal IS the recovery contract,
        and its volume is O(jobs × parts + workers + failovers) per
        run."""
        if self._journal is not None:
            self._journal.append(event, sync=sync)

    def _job_tag(self, job: _JobState) -> dict:
        """The job qualifier assignment events carry: empty for the
        default job (byte-compatible with PR 12 journals), ``{"job": j}``
        otherwise."""
        return {} if job.job == DEFAULT_JOB else {"job": job.job}

    def _replay_dataset_locked(self, ev: dict) -> None:
        """Replay one job-registration event. A default-job record that
        disagrees with the constructor — or a per-job record that
        disagrees with an already-restored spec — is a fatal
        configuration error, never an assertion and never retryable:
        recovery must not silently serve the wrong corpus."""
        name = str(ev.get("job") or DEFAULT_JOB)
        if name == DEFAULT_JOB:
            current = self._jobs.get(DEFAULT_JOB)
            if current is None:
                raise ServiceConfigError(
                    f"dispatcher journal {self._journal.path} records "
                    f"dataset {ev.get('uri')!r} ({ev.get('num_parts')} "
                    f"parts) but this dispatcher was constructed with no "
                    f"default dataset — recover with "
                    f"Dispatcher(uri={ev.get('uri')!r}, "
                    f"num_parts={ev.get('num_parts')}, ...) or point "
                    f"journal_path at a fresh journal")
            want_parts = int(ev.get("num_parts", current.num_parts))
            want_uri = ev.get("uri", current.uri)
            if want_parts != current.num_parts or want_uri != current.uri:
                raise ServiceConfigError(
                    f"dispatcher journal {self._journal.path}: journaled "
                    f"dataset is {want_uri!r} with {want_parts} parts, "
                    f"constructor says {current.uri!r} with "
                    f"{current.num_parts} — a restart must recover the "
                    f"SAME dataset. Restart the dispatcher with the "
                    f"journaled dataset, or point journal_path at a "
                    f"fresh journal to start over")
            return
        prev = self._jobs.get(name)
        qos = dict(ev.get("qos") or {})
        restored = _JobState(
            name, ev.get("uri"), int(ev.get("num_parts", 0) or 0),
            dict(ev.get("parser") or {}), dict(ev.get("plan") or {}),
            dict(ev.get("snapshot") or {}),
            share_sig=ev.get("share_sig"),
            priority=qos.get("priority", 0), weight=qos.get("weight", 1),
            slo_wait_frac=qos.get("slo_wait_frac"),
            max_inflight=qos.get("max_inflight"))
        if prev is None:
            self._jobs[name] = restored
            return
        if (prev.uri != restored.uri
                or prev.num_parts != restored.num_parts
                or prev.parser != restored.parser):
            raise ServiceConfigError(
                f"dispatcher journal {self._journal.path}: job {name!r} "
                f"recorded twice with conflicting specs "
                f"({prev.uri!r}/{prev.num_parts} vs "
                f"{restored.uri!r}/{restored.num_parts}) — the journal "
                f"is corrupt or two dispatchers shared one journal_path; "
                f"point this dispatcher at a fresh journal")

    def _recover(self, compact_lines: int) -> None:
        """Replay the journal into the exact per-job assignment state:
        completed parts stay done with their owner, in-flight parts
        re-queue at the FRONT (lowest first — clients consume
        part-major), replayed workers get a fresh liveness window to
        re-attach, registered jobs are restored with their full spec,
        and the generation token bumps past every `start` ever
        journaled."""
        with self._journal.locked():
            lines = self._journal.read_lines()
            events = _journal_mod.decode_events(lines)
            last_gen = 0
            journaled_jobs: Set[str] = set()
            in_todo: Dict[str, Set[int]] = {}
            workers: Dict[str, tuple] = {}
            draining: Set[str] = set()

            def books(name: str) -> Optional[Tuple[_JobState, Set[int]]]:
                state = self._jobs.get(name)
                if state is None:
                    return None  # event for a job the journal lost
                if name not in in_todo:
                    in_todo[name] = set(state.todo)
                return state, in_todo[name]

            for ev in events:
                op = ev.get("op")
                name = str(ev.get("job") or DEFAULT_JOB)
                if op == "dataset":
                    self._replay_dataset_locked(ev)
                    journaled_jobs.add(name)
                    continue
                if op == "start":
                    last_gen = max(last_gen, int(ev.get("gen", 0) or 0))
                elif op == "register":
                    workers[str(ev.get("worker"))] = (
                        str(ev.get("host", "")), int(ev.get("port", 0)))
                    draining.discard(str(ev.get("worker")))
                elif op == "dead":
                    workers.pop(str(ev.get("worker")), None)
                    draining.discard(str(ev.get("worker")))
                elif op == "drain":
                    # a drain in flight at the crash: the worker stays out
                    # of the grant rotation after replay (its completed
                    # parts keep serving; the drain deadline re-arms)
                    if str(ev.get("worker")) in workers:
                        draining.add(str(ev.get("worker")))
                elif op == "join":
                    pass  # membership rides `register`; join is the record
                elif op == "grant":
                    got = books(name)
                    if got is None:
                        continue
                    state, todo_set = got
                    part = int(ev.get("part", -1))
                    if part in todo_set:
                        todo_set.discard(part)
                        state.todo.remove(part)
                    state.assigned[part] = str(ev.get("worker"))
                elif op == "spec_grant":
                    # the speculative twin of a grant: the part is already
                    # out of todo; whoever journals `complete` first owns
                    # it (the dedupe below), so replay needs no side state
                    pass
                elif op == "complete":
                    got = books(name)
                    if got is None:
                        continue
                    state, todo_set = got
                    part = int(ev.get("part", -1))
                    if 0 <= part < state.num_parts:
                        if part in todo_set:
                            todo_set.discard(part)
                            state.todo.remove(part)
                        # the completing worker wins the part — for a
                        # hedged part this is the first-complete owner,
                        # which may be the speculative worker
                        state.assigned[part] = str(ev.get("worker"))
                        state.completed.add(part)
                elif op == "reissue":
                    got = books(name)
                    if got is None:
                        continue
                    state, todo_set = got
                    part = int(ev.get("part", -1))
                    state.assigned.pop(part, None)
                    state.completed.discard(part)
                    if 0 <= part < state.num_parts \
                            and part not in todo_set:
                        todo_set.add(part)
                        state.todo.appendleft(part)
                elif op == "reclaim":
                    got = books(name)
                    if got is None:
                        continue
                    state, todo_set = got
                    part = int(ev.get("part", -1))
                    if part in todo_set:
                        todo_set.discard(part)
                        state.todo.remove(part)
                    state.assigned[part] = str(ev.get("worker"))
                    state.completed.add(part)
            requeued = 0
            for name, state in self._jobs.items():
                todo_set = in_todo.setdefault(name, set(state.todo))
                # in-flight at the crash (granted, never completed): the
                # owner's frames may be partial — re-queue at the front,
                # lowest part first; reclaim re-adopts what survived
                inflight = sorted(p for p in state.assigned
                                  if p not in state.completed)
                for part in inflight:
                    state.assigned.pop(part)
                # parts completed by a worker the journal no longer knows
                # (dead without a reissue line — a torn tail can lose
                # one): nothing serves them, so they re-queue behind the
                # in-flight
                orphaned = sorted(p for p, w in state.assigned.items()
                                  if w not in workers)
                for part in orphaned:
                    state.assigned.pop(part)
                    state.completed.discard(part)
                for part in reversed(inflight + orphaned):
                    if part not in todo_set:
                        todo_set.add(part)
                        state.todo.appendleft(part)
                requeued += len(inflight) + len(orphaned)
            now = get_time()
            # replayed workers start a fresh liveness window in the
            # JOINING state: a worker that survived the dispatcher
            # re-attaches within it (its next poll sees the generation
            # bump), one that died with the dispatcher goes stale and
            # its parts re-issue normally. A worker that was DRAINING at
            # the crash replays as draining — still out of the grant
            # rotation, still serving, deadline re-armed fresh.
            self._workers = {}
            for w, (h, p) in workers.items():
                info = _WorkerInfo(w, h, p, now)
                if w in draining:
                    info.state = DRAINING
                    info.drain_deadline = now + self._drain_deadline_s
                self._workers[w] = info
            self.generation = last_gen + 1
            if len(lines) > compact_lines:
                self._journal.rewrite(self._live_events())
            else:
                for name, state in self._jobs.items():
                    if name not in journaled_jobs:
                        self._journal.append(self._job_event(state),
                                             sync=True)
            self._journal.append(
                {"op": "start", "gen": self.generation}, sync=True)
            if events:
                logger.info(
                    "dispatcher: recovered from %s — gen %d, %d job(s), "
                    "%d parts done, %d re-queued, %d workers awaiting "
                    "re-attach", self._journal.path, self.generation,
                    len(self._jobs),
                    sum(len(j.completed) for j in self._jobs.values()),
                    requeued, len(self._workers))

    def _live_events(self) -> List[dict]:
        """The current state as a canonical journal (compaction): the
        jobs, the last start, live workers, and grant+complete pairs
        for done parts. Unassigned parts are implicit (replay seeds each
        queue from ``range(num_parts)``); the queues' front-ordering
        normalizes to ascending across a compaction."""
        events: List[dict] = [self._job_event(state)
                              for state in self._jobs.values()]
        events.append({"op": "start", "gen": self.generation - 1})
        for info in self._workers.values():
            if info.alive:
                events.append({"op": "register", "worker": info.worker,
                               "host": info.host, "port": info.port})
        for info in self._workers.values():
            # a drain in progress must survive compaction, or a restart
            # would put the draining worker back in the grant rotation
            if info.state == DRAINING:
                events.append({"op": "drain", "worker": info.worker})
        for state in self._jobs.values():
            tag = self._job_tag(state)
            for part in sorted(state.completed):
                worker = state.assigned.get(part)
                if worker is None:
                    continue
                events.append(dict({"op": "grant", "part": part,
                                    "worker": worker}, **tag))
                events.append(dict({"op": "complete", "part": part,
                                    "worker": worker}, **tag))
        return events

    # ---------------- assignment core (lock held) ----------------

    def _requeue_locked(self, job: _JobState, parts, worker: str,
                        why: str) -> None:
        """Re-issue ``parts`` of ``job`` at the FRONT, lowest part first
        (clients consume part-major, so the earliest lost part is the
        one blocking them), journaling each re-queue."""
        parts = sorted(parts)
        tag = self._job_tag(job)
        for part in parts:
            job.assigned.pop(part, None)
            job.completed.discard(part)
            self._drop_spec_locked(job, part)
            job.grant_times.pop(part, None)
            try:
                job.hedge_todo.remove(part)
            except ValueError:
                pass
        for part in reversed(parts):
            job.todo.appendleft(part)
            self._journal_append(dict({"op": "reissue", "part": part,
                                       "worker": worker}, **tag))
        if parts:
            logger.warning("dispatcher: worker %s %s; re-issuing "
                           "job %s parts %s", worker, why, job.job, parts)

    def _drop_spec_locked(self, job: _JobState,
                          part: int) -> Optional[str]:
        """Forget a part's speculative grant (and its grant stamp);
        returns the speculative worker, if any."""
        job.spec_times.pop(part, None)
        return job.spec.pop(part, None)

    def _drop_worker_specs_locked(self, worker: str) -> None:
        """Forget every speculative grant ``worker`` holds, every job —
        its speculative parses die with it (death, drain, departure)."""
        for job in self._jobs.values():
            for part in [p for p, w in job.spec.items() if w == worker]:
                self._drop_spec_locked(job, part)

    def _inherit_or_requeue_locked(self, job: _JobState, worker: str,
                                   parts, why: str) -> List[int]:
        """``worker`` is giving up ``parts`` of ``job``: promote each
        hedged part's speculative twin to primary (the hedge already has
        a live parse going — re-queuing would waste it) and re-queue the
        rest at the front. Returns the re-queued parts."""
        requeue = []
        tag = self._job_tag(job)
        for part in parts:
            spec_stamp = job.spec_times.get(part)
            spec = self._drop_spec_locked(job, part)
            if spec is not None and part not in job.completed:
                # the hedge worker inherits the part outright; its clock
                # restarts at ITS spec grant — keeping the stuck
                # primary's stamp would re-flag the part for hedging at
                # the very next tick and poison the latency median
                job.assigned[part] = spec
                job.grant_times[part] = (spec_stamp if spec_stamp
                                         is not None else get_time())
                self._journal_append(dict({"op": "grant", "part": part,
                                           "worker": spec}, **tag))
                logger.info("dispatcher: job %s part %d inherited by "
                            "hedge worker %s (%s %s)", job.job, part,
                            spec, worker, why)
            else:
                requeue.append(part)
        self._requeue_locked(job, requeue, worker, why)
        return requeue

    def _release_worker_parts_locked(self, worker: str, why: str) -> None:
        """A worker left (death or completed drain): drop speculative
        grants it held itself, then inherit-or-requeue everything it
        owned across every job (completed parts re-queue too — its frame
        store is gone)."""
        self._drop_worker_specs_locked(worker)
        for job in self._jobs.values():
            parts = sorted(p for p, o in job.assigned.items()
                           if o == worker)
            self._inherit_or_requeue_locked(job, worker, parts, why)

    def _mark_dead_locked(self, worker: str) -> None:
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return
        info.state = DEAD
        self._journal_append({"op": "dead", "worker": worker})
        self._decision_locked(
            "mark_dead",
            {"last_seen_s": round(get_time() - info.last_seen, 3)},
            "worker declared dead; its parts re-issue", worker=worker)
        self._release_worker_parts_locked(worker, "lost")

    def _reap_stale_locked(self, now: float) -> None:
        if self.liveness_timeout <= 0:
            return
        for info in list(self._workers.values()):
            if info.alive and now - info.last_seen > self.liveness_timeout:
                logger.warning("dispatcher: worker %s missed heartbeats "
                               "(last seen %.1fs ago)", info.worker,
                               now - info.last_seen)
                self._mark_dead_locked(info.worker)

    def _clients_active_locked(self) -> bool:
        return any(j.clients_active for j in self._jobs.values())

    # ---------------- drain + hedging (lock held) ----------------

    def _finish_drain_locked(self, info: _WorkerInfo, why: str) -> None:
        """Complete a drain: the worker leaves the fleet for good — its
        next poll reads ``drained`` and exits instead of re-attaching.
        Handoff-confirmed completed parts stay ASSIGNED to the departed
        worker and re-queue lazily at the next ``locate``: every client
        that confirmed already streamed them, so an eager re-issue here
        would make the always-polling fleet re-parse frames nobody asked
        for. Everything else (unconfirmed completed parts included —
        their frames die with the worker) releases through the normal
        death path (re-queue / hedge inheritance) right now."""
        if info.state != DRAINING:
            return
        info.drained = True
        logger.info("dispatcher: drain of worker %s complete (%s)",
                    info.worker, why)
        info.state = DEAD
        self._journal_append({"op": "dead", "worker": info.worker})
        self._decision_locked(
            "drain_complete",
            {"handed_off": len(info.handed_off)}, why,
            worker=info.worker)
        self._drop_worker_specs_locked(info.worker)
        for job in self._jobs.values():
            keep = {p for (j, p) in info.handed_off
                    if j == job.job
                    and job.assigned.get(p) == info.worker
                    and p in job.completed}
            self._inherit_or_requeue_locked(
                job, info.worker,
                sorted(p for p, o in job.assigned.items()
                       if o == info.worker and p not in keep),
                why)

    def _serving_locked(self, worker: str) -> Set[Tuple[str, int]]:
        """The frame-store-complete (job, part) pairs ``worker`` still
        owns — what a drain must hand off before completing early."""
        return {(job.job, p) for job in self._jobs.values()
                for p, w in job.assigned.items()
                if w == worker and p in job.completed}

    def _maybe_finish_drain_locked(self, info: _WorkerInfo) -> None:
        """Complete the drain as soon as every still-assigned
        frame-store-complete part is handoff-confirmed — vacuously so
        for a worker with nothing to serve out (preempted before any
        part completed), which must exit within its notice window, not
        idle out the full deadline."""
        if info.state != DRAINING:
            return
        serving = self._serving_locked(info.worker)
        if serving <= info.handed_off:
            self._finish_drain_locked(
                info, "all served parts handed off"
                if serving else "nothing left to serve")

    def _expire_drains_locked(self, now: float) -> None:
        for info in list(self._workers.values()):
            if info.state != DRAINING:
                continue
            # the serving set can shrink without a handoff RPC (e.g. a
            # report_lost re-queued a part): re-check completion on the
            # wall-clock tick too, then the deadline backstop
            self._maybe_finish_drain_locked(info)
            if (info.state == DRAINING and info.drain_deadline is not None
                    and now >= info.drain_deadline):
                self._finish_drain_locked(info, "drain deadline expired")

    def _hedge_check_locked(self, now: float) -> None:
        """Flag in-flight parts stuck past ``hedge_factor`` times their
        JOB's median grant->complete latency for speculative re-issue.
        Guarded by a minimum per-job sample count and an absolute age
        floor so ordinary jitter on fast parts can never trigger a
        duplicate parse; the flagged part is granted to the next polling
        worker that is not the stuck primary."""
        for job in self._jobs.values():
            if len(job.latencies) < HEDGE_MIN_SAMPLES:
                continue
            threshold = max(self._hedge_factor
                            * statistics.median(job.latencies),
                            HEDGE_MIN_AGE_S)
            for part, granted_at in list(job.grant_times.items()):
                if (part in job.completed or part in job.spec
                        or part in job.hedge_todo):
                    continue
                owner = job.assigned.get(part)
                info = (self._workers.get(owner)
                        if owner is not None else None)
                if info is None or info.state != ACTIVE:
                    continue  # death/drain paths own those parts
                age = now - granted_at
                if age <= threshold:
                    continue
                if not any(w.state == ACTIVE and w.worker != owner
                           and w.registered_gen == self.generation
                           for w in self._workers.values()):
                    continue  # nobody to hedge onto
                job.hedge_todo.append(part)
                self._decision_locked(
                    "hedge",
                    {"part": part, "age_s": round(age, 3),
                     "threshold_s": round(threshold, 3),
                     "median_s": round(
                         statistics.median(job.latencies), 3)},
                    f"part {part} on {owner} flagged for "
                    f"speculative re-issue", job=job.job)
                logger.warning(
                    "dispatcher: job %s part %d on worker %s stuck "
                    "%.2fs (> %.2fs = %dx job median); flagging for "
                    "speculative re-issue", job.job, part, owner, age,
                    threshold, self._hedge_factor)

    def _tick_loop(self) -> None:
        """The wall-clock driver behind liveness, drain deadlines, and
        hedging — RPC traffic is no longer required for any of them."""
        while not self._tick_stop.wait(self._tick_interval):
            now = get_time()
            with self._lock:
                self._reap_stale_locked(now)
                self._expire_drains_locked(now)
                self._hedge_check_locked(now)

    # ---------------- request handlers ----------------

    def _handle(self, req: dict) -> dict:
        t0 = get_time()
        # adopt the caller's trace context (optional `trace` wire key,
        # docs/service.md) for the duration of this command, so the
        # service_rpc span — and anything the handler records — links
        # into the caller's trace
        ctx = _telemetry.trace_context_from_wire(req.get("trace"))
        with _telemetry.trace(ctx[0] if ctx else None,
                              ctx[1] if ctx else ""):
            resp = self._dispatch_cmd(req)
            _telemetry.record_span("service_rpc", t0, get_time() - t0,
                                   cmd=str(req.get("cmd") or ""))
        # the monotonic generation token: peers detect a restart at
        # their next control exchange and re-register/revalidate
        resp["gen"] = self.generation
        # monotonic clock stamp: `request()` pairs it with its own
        # send/receive midpoint to estimate this process's clock offset
        # (merged pod timelines, docs/observability.md)
        resp["now"] = round(get_time(), 6)
        return resp

    def _job_for(self, req: dict) -> Optional[_JobState]:
        """The job a request addresses (absent field = default job)."""
        return self._jobs.get(str(req.get("job") or DEFAULT_JOB))

    def _bands_locked(self) -> List[List[_JobState]]:
        """Jobs grouped into priority bands, highest band first, each
        band rotated from the round-robin cursor (docs/service.md
        Production QoS): a higher band fully preempts lower ones in the
        grant order; rotation within a band is what DRR credits shape."""
        bands: Dict[int, List[_JobState]] = {}
        for j in self._jobs.values():
            bands.setdefault(j.priority, []).append(j)
        out = []
        for prio in sorted(bands, reverse=True):
            band = bands[prio]
            k = self._rr % len(band)
            out.append(band[k:] + band[:k])
        return out

    def _grant_rotation_locked(self) -> List[_JobState]:
        """The flat job visitation order (priority bands descending,
        round-robin within each band) — the hedge scan and fairness
        probes walk this, so a latency-critical job's straggler re-issues
        ahead of a batch job's fresh work."""
        return [j for band in self._bands_locked() for j in band]

    def _fleet_inflight_locked(self) -> int:
        """Granted-not-completed parts across every job — what the
        fleet-wide admission ceiling bounds."""
        return sum(j.inflight() for j in self._jobs.values())

    def _admission_locked(self, job: _JobState) -> bool:
        """True when `job` may be granted one more part: under its own
        max_inflight budget AND the fleet under the
        DMLC_TPU_QOS_MAX_INFLIGHT ceiling. Hedge re-issues bypass this —
        they duplicate work already admitted."""
        if (job.max_inflight is not None
                and job.inflight() >= job.max_inflight):
            return False
        ceiling = _knobs.qos_max_inflight()
        if ceiling is not None and self._fleet_inflight_locked() >= ceiling:
            return False
        return True

    def _dispatch_cmd(self, req: dict) -> dict:
        cmd = req.get("cmd")
        now = get_time()
        with self._lock:
            if cmd == "config":
                job = self._job_for(req)
                if job is None:
                    if "job" in req:
                        return {"error": f"unknown job {req.get('job')!r}"
                                         f" (register_job first; "
                                         f"registered: "
                                         f"{sorted(self._jobs)})"}
                    # a dispatcher born empty: workers boot against this
                    # and fetch real job specs lazily per grant
                    return {"uri": None, "num_parts": 0, "parser": {},
                            "plan": {}, "snapshot": {}}
                resp = job.spec_dict()
                if "job" in req:
                    resp["job"] = job.job
                return resp
            if cmd == "register_job":
                return self._register_job_locked(req)
            if cmd == "register":
                worker = str(req["worker"])
                prev = self._workers.get(worker)
                if (prev is not None and prev.alive
                        and prev.registered_gen == self.generation):
                    # a worker id already seen alive THIS generation is
                    # re-registering: the process crash-restarted fast
                    # (before the liveness reaper fired) and its frame
                    # store is presumed gone — re-queue everything it
                    # owned; the reclaim that follows adopts back what
                    # actually survived (docs/service.md)
                    self._release_worker_parts_locked(
                        worker, "re-registered (crash-restart)")
                self._workers[worker] = _WorkerInfo(
                    worker, str(req["host"]), int(req["port"]), now,
                    registered_gen=self.generation)
                self._journal_append({"op": "register", "worker": worker,
                                      "host": str(req["host"]),
                                      "port": int(req["port"])})
                if prev is None and self._clients_active_locked():
                    # a brand-new worker id arriving while clients are
                    # consuming: a mid-epoch LIVE JOIN — it is in the
                    # grant rotation and the re-issue serving set from
                    # this very reply
                    self._journal_append({"op": "join", "worker": worker})
                    _resilience.record_event("worker_joins")
                    self._decision_locked(
                        "live_join", None,
                        f"worker {worker} joined mid-epoch",
                        worker=worker)
                    logger.info("dispatcher: worker %s joined the live "
                                "fleet", worker)
                return {"ok": True}
            if cmd == "heartbeat":
                info = self._workers.get(str(req.get("worker")))
                if info is not None and info.alive:
                    info.last_seen = now
                return {"ok": True}
            if cmd == "next_split":
                return self._next_split_locked(req, now)
            if cmd == "part_done":
                return self._part_done_locked(req, now)
            if cmd == "drain":
                return self._drain_locked(req, now)
            if cmd == "handoff":
                worker = str(req["worker"])
                part = int(req["part"])
                jname = str(req.get("job") or DEFAULT_JOB)
                info = self._workers.get(worker)
                if info is not None and info.state == DRAINING:
                    info.handed_off.add((jname, part))
                    self._maybe_finish_drain_locked(info)
                return {"ok": True}
            if cmd == "reclaim":
                return self._reclaim_locked(req)
            if cmd == "locate":
                return self._locate_locked(req, now)
            if cmd == "report_lost":
                self._mark_dead_locked(str(req["worker"]))
                return {"ok": True}
            if cmd == "trace_dump":
                # this process's span rings + decisions, with a clock
                # stamp — LocalFleet.dump_trace merges these into ONE
                # pod timeline (docs/observability.md)
                return {"snapshot":
                        _telemetry.component_snapshot("dispatcher")}
            if cmd == "metrics_text":
                return {"text": _telemetry.render_prometheus(),
                        "content_type":
                            "text/plain; version=0.0.4; charset=utf-8"}
            if cmd == "decisions":
                comp = req.get("component")
                return {"decisions": _telemetry.decisions_snapshot(
                            str(comp) if comp else None),
                        "total": _telemetry.decisions_total()}
            if cmd == "status":
                default = self._default()
                jobs = {
                    name: {
                        "uri": j.uri,
                        "num_parts": j.num_parts,
                        "share_sig": j.share_sig,
                        "assigned": {str(p): w
                                     for p, w in j.assigned.items()},
                        "todo": list(j.todo),
                        "completed": sorted(j.completed),
                        "hedged": {str(p): w for p, w in j.spec.items()},
                        "qos": j.qos_dict(),
                        "inflight": j.inflight(),
                    } for name, j in self._jobs.items()}
                return {
                    "workers": {w: {"host": i.host, "port": i.port,
                                    "alive": i.alive, "state": i.state}
                                for w, i in self._workers.items()},
                    # legacy one-dataset view: the default job's books
                    "assigned": ({str(p): w for p, w
                                  in default.assigned.items()}
                                 if default else {}),
                    "todo": list(default.todo) if default else [],
                    "completed": (sorted(default.completed)
                                  if default else []),
                    "hedged": ({str(p): w for p, w in default.spec.items()}
                               if default else {}),
                    "jobs": jobs,
                    "generation": self.generation,
                }
        return {"error": f"unknown command {cmd!r}"}

    def _decision_locked(self, action: str, trigger: Optional[dict],
                         outcome: Optional[str], **extra) -> None:
        """Record one dispatcher control decision: audit-ledger event
        (+ ``decision_events`` counter) and a ``decision`` journal line
        so post-mortems survive the process. Replay skips unknown ops,
        so old dispatchers reading a new journal are unaffected; journal
        compaction drops decision lines (they are observability, not
        assignment state). Never fsync'd — a lost tail decision must not
        cost the control plane a disk flush."""
        event = _telemetry.record_decision("dispatcher", action,
                                           trigger=trigger,
                                           outcome=outcome, **extra)
        self._journal_append(dict({"op": "decision"}, **event),
                             sync=False)

    def _grant_trace_locked(self, job: _JobState, part: int,
                            worker: str, now: float,
                            name: str) -> Optional[dict]:
        """Open (or re-join) the part's trace at grant time and return
        its wire context for the reply. The grant is the trace ROOT: one
        (job, part) = one trace id, and the root span id is what worker
        and client spans parent under. A hedge re-grant re-joins the
        primary grant's trace so both attempts render as one causal
        timeline."""
        if not _telemetry.trace_propagation_enabled():
            return None
        ctx = job.traces.get(part)
        if ctx is None:
            ctx = (_telemetry.new_trace_id(), _telemetry.new_span_id())
            job.traces[part] = ctx
        tid, sid = ctx
        _telemetry.record_span(name, now, get_time() - now,
                               trace_id=tid, span_id=sid,
                               job=job.job, part=part, worker=worker)
        return {"tid": tid, "sid": sid}

    def _next_split_locked(self, req: dict, now: float) -> dict:
        worker = str(req["worker"])
        info = self._workers.get(worker)
        if info is None or not info.alive:
            if info is not None and info.drained:
                # drain complete: tell the worker to exit instead of
                # re-attaching as a zombie
                return {"part": None, "drained": True}
            # unregistered/declared-dead workers get no splits — a
            # zombie must re-register before it can own parts
            return {"part": None, "register": True}
        if info.state == DRAINING:
            # draining workers get NO new work; the poll doubles as
            # liveness while they serve out their parts
            info.last_seen = now
            return {"part": None, "draining": True}
        if info.registered_gen != self.generation:
            # journal-restored worker that has not re-attached this
            # generation: its frame-store contents are unknown until the
            # register+reclaim handshake, and a grant riding the SAME
            # reply as the generation bump would race the reclaim into a
            # duplicate parse
            info.last_seen = now
            return {"part": None, "register": True}
        info.last_seen = now
        self._reap_stale_locked(now)
        rotation = self._grant_rotation_locked()
        # speculative re-issues first, any job: a flagged straggler part
        # goes to the first polling worker that is NOT the stuck primary
        # (journaled spec_grant; first part_done wins)
        for job in rotation:
            for _ in range(len(job.hedge_todo)):
                part = job.hedge_todo.popleft()
                if (part in job.completed or part in job.spec
                        or part not in job.assigned):
                    continue  # stale flag
                if job.assigned.get(part) == worker:
                    job.hedge_todo.append(part)
                    continue
                job.spec[part] = worker
                job.spec_times[part] = now
                self._journal_append(dict(
                    {"op": "spec_grant", "part": part, "worker": worker},
                    **self._job_tag(job)))
                _resilience.record_event("speculative_reissues")
                age = now - job.grant_times.get(part, now)
                self._decision_locked(
                    "spec_grant",
                    {"part": part, "age_s": round(age, 3),
                     "samples": len(job.latencies)},
                    f"re-issued to {worker} (primary "
                    f"{job.assigned.get(part)})", job=job.job)
                logger.warning(
                    "dispatcher: job %s part %d speculatively re-issued "
                    "to worker %s (primary %s)", job.job, part, worker,
                    job.assigned.get(part))
                resp = {"part": part, "job": job.job}
                wire = self._grant_trace_locked(job, part, worker, now,
                                                "service_spec_grant")
                if wire is not None:
                    resp["trace"] = wire
                return resp
        # fresh grants: deficit round-robin within the highest priority
        # band that has admissible work (docs/service.md Production QoS).
        # Higher bands fully preempt lower ones; within a band each job
        # spends one credit per grant and the band replenishes by weight
        # when every eligible credit runs dry — so weight 2 jobs draw
        # twice the grants of weight 1 siblings, and equal-weight jobs
        # keep the historical strict alternation. Over-budget jobs
        # (admission control) are simply not eligible this poll.
        for band in self._bands_locked():
            eligible = [j for j in band
                        if j.todo and self._admission_locked(j)]
            if not eligible:
                continue
            if all(j.deficit < 1.0 for j in eligible):
                for j in eligible:
                    j.deficit = min(j.deficit + j.weight, float(j.weight))
            for i, job in enumerate(eligible):
                if job.deficit < 1.0:
                    continue
                job.deficit -= 1.0
                part = job.todo.popleft()
                job.assigned[part] = worker
                job.grant_times[part] = now
                self._journal_append(dict({"op": "grant", "part": part,
                                           "worker": worker},
                                          **self._job_tag(job)))
                # advance the cursor PAST the granted job: the next
                # grant's band rotation starts at the following job
                self._rr = (self._rr + band.index(job) + 1) % (1 << 30)
                logger.info("dispatcher: job %s part %d -> worker %s",
                            job.job, part, worker)
                resp = {"part": part, "job": job.job}
                wire = self._grant_trace_locked(job, part, worker, now,
                                                "service_grant")
                if wire is not None:
                    resp["trace"] = wire
                return resp
        return {"part": None}

    def _part_done_locked(self, req: dict, now: float) -> dict:
        worker = str(req["worker"])
        part = int(req["part"])
        job = self._job_for(req)
        if job is None:
            return {"ok": True}  # completion for a job nobody knows
        tag = self._job_tag(job)
        primary = job.assigned.get(part)
        spec = job.spec.get(part)
        if part not in job.completed and worker in (primary, spec):
            # journaled completion: a restarted dispatcher keeps the
            # part done instead of re-queuing it as in-flight. For a
            # hedged part the FIRST completion wins; the loser's later
            # part_done is deduped right here.
            job.completed.add(part)
            # the latency sample measures the WINNER's own
            # grant->complete time (the spec grant stamp for a
            # speculative win) — never the stuck primary's age, which
            # exceeds the hedge threshold by construction and would
            # desensitize the median
            granted_at = job.grant_times.pop(part, None)
            if spec is not None and worker == spec:
                job.assigned[part] = worker
                granted_at = job.spec_times.get(part, granted_at)
                _resilience.record_event("speculative_wins")
                self._decision_locked(
                    "spec_win", {"part": part},
                    f"speculative worker {worker} won over {primary}",
                    job=job.job)
                logger.info("dispatcher: speculative worker %s won "
                            "job %s part %d over %s", worker, job.job,
                            part, primary)
            self._drop_spec_locked(job, part)
            self._journal_append(dict({"op": "complete", "part": part,
                                       "worker": worker}, **tag))
            if granted_at is not None:
                job.latencies.append(max(0.0, now - granted_at))
        elif part not in job.completed:
            # a completion for a part we had RE-QUEUED (its grant didn't
            # survive a dispatcher restart, or a report_lost blamed a
            # still-live worker): the frames exist, so adopt it exactly
            # as `reclaim` would instead of letting the queue force a
            # duplicate parse (no latency sample — the grant stamp died
            # with the re-queue)
            info = self._workers.get(worker)
            if (info is not None and info.alive
                    and part in job.todo):
                job.todo.remove(part)
                job.assigned[part] = worker
                job.completed.add(part)
                self._journal_append(dict(
                    {"op": "complete", "part": part, "worker": worker},
                    **tag))
                logger.info("dispatcher: adopted completion of "
                            "re-queued job %s part %d from worker %s",
                            job.job, part, worker)
        return {"ok": True}

    def _locate_locked(self, req: dict, now: float) -> dict:
        job = self._job_for(req)
        if job is None:
            return {"error": f"unknown job {req.get('job')!r} "
                             f"(register_job first)"}
        part = int(req["part"])
        if not 0 <= part < job.num_parts:
            return {"error": f"job {job.job}: part {part} out of range"}
        job.clients_active = True  # a consumer is attached
        self._reap_stale_locked(now)
        owner = job.assigned.get(part)
        info = self._workers.get(owner) if owner is not None else None
        if info is None or not info.alive:
            if owner is not None:
                # the part stayed assigned to a departed drained worker
                # (handoff-confirmed — see _finish_drain_locked) for
                # exactly this moment: a client still wants it, so NOW
                # it re-queues
                self._requeue_locked(
                    job, [part], owner, "located after its drained "
                    "owner left")
            if not self._admission_locked(job):
                # the part is ungranted BECAUSE admission control is
                # shedding this job's grants (its own budget or the
                # fleet ceiling): tell the client to back off with a
                # retryable throttle instead of a hot wait-poll —
                # overload degrades to bounded queueing, never a
                # give-up (docs/service.md Production QoS)
                _resilience.record_event("service_throttles")
                self._decision_locked(
                    "throttle",
                    {"part": part, "inflight": job.inflight(),
                     "fleet_inflight": self._fleet_inflight_locked(),
                     "max_inflight": job.max_inflight},
                    "client told to back off", job=job.job)
                return {"throttled": True}
            return {"wait": True}
        resp = {"worker": info.worker, "host": info.host,
                "port": info.port}
        ctx = job.traces.get(part)
        wire = (_telemetry.trace_context_wire(ctx)
                if ctx is not None else None)
        if wire is not None:
            # the part's grant trace: the client's recv/decode/dispatch
            # spans join the same causal chain the grant opened
            resp["trace"] = wire
        if info.state == DRAINING:
            # the owner is leaving: clients should finish this stream
            # promptly and confirm with `handoff`
            resp["draining"] = True
        have = req.get("have")
        if have is not None and str(have) != info.worker:
            # the part moved off the worker the client last used: the
            # client takes this hint as confirmation that a drain
            # re-issue landed (drain_handoffs) — no dead-socket timeout
            # involved (docs/service.md)
            resp["moved"] = True
        return resp

    def _drain_locked(self, req: dict, now: float) -> dict:
        """Begin (or report) a graceful drain: the worker leaves the
        grant rotation immediately, its unstarted/in-flight parts (every
        job) proactively re-issue at the front (hedged parts are
        inherited by their speculative worker), and its frame-store-
        complete parts keep serving until every one is ``handoff``-
        confirmed or the drain deadline expires. Idempotent — repeats
        report state."""
        worker = str(req["worker"])
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return {"ok": False, "unknown": True}
        # an EXPLICIT deadline of 0 means "leave now" — only an absent
        # field falls back to the knob default (0 is falsy, so `or`
        # would silently re-arm the 30s window the caller opted out of)
        raw_deadline = req.get("deadline")
        deadline_s = (float(raw_deadline) if raw_deadline is not None
                      else self._drain_deadline_s)
        if info.state == DRAINING:
            # a repeat drain may TIGHTEN the window (eviction imminent:
            # drain(deadline=0) means leave now), never loosen it
            if raw_deadline is not None:
                new_at = now + deadline_s
                if (info.drain_deadline is None
                        or new_at < info.drain_deadline):
                    info.drain_deadline = new_at
        else:
            info.state = DRAINING
            info.drain_deadline = now + deadline_s
            info.handed_off = set()
            self._journal_append({"op": "drain", "worker": worker})
            _resilience.record_event("worker_drains")
            self._decision_locked(
                "drain", {"deadline_s": round(deadline_s, 3)},
                f"worker {worker} leaving the grant rotation",
                worker=worker)
            # speculative grants the drainer held die with the drain
            self._drop_worker_specs_locked(worker)
            # proactive re-issue of everything NOT frame-store-complete
            # (those keep serving out): failover starts now, not when
            # the worker's sockets die. A hedged part is inherited by
            # its speculative worker instead of re-queued.
            pending = 0
            for job in self._jobs.values():
                pending += len(self._inherit_or_requeue_locked(
                    job, worker,
                    sorted(p for p, w in job.assigned.items()
                           if w == worker and p not in job.completed),
                    "draining"))
            logger.warning(
                "dispatcher: draining worker %s (deadline %.1fs, "
                "%d unstarted parts re-issued, %d complete parts "
                "serving out)", worker, deadline_s, pending,
                len(self._serving_locked(worker)))
            # nothing to serve out (preempted before any part
            # completed)? the drain is already done — exit within the
            # notice window instead of idling out the deadline
            self._maybe_finish_drain_locked(info)
        serving_jobs: Dict[str, List[int]] = {}
        for jname, part in sorted(self._serving_locked(worker)):
            serving_jobs.setdefault(jname, []).append(part)
        return {"ok": True,
                # legacy shape: the default job's serving parts
                "serving": serving_jobs.get(DEFAULT_JOB, []),
                "serving_jobs": serving_jobs,
                "deadline_s": round(
                    max(0.0, (info.drain_deadline or now) - now), 3)}

    def _reclaim_locked(self, req: dict) -> dict:
        """Adopt the fully-parsed parts a (re-)registered worker's frame
        store still holds — instead of forcing a fleet-wide re-parse —
        and re-queue the journal-complete parts it no longer announces
        (its store lost them, e.g. dispatcher AND worker both died).
        ``parts`` is a flat list (default job, the PR 12 wire shape) or
        ``{job: [parts]}``; the reply's ``adopted`` mirrors the request
        shape. Parts owned by ANOTHER live worker are never stolen;
        parts granted this generation and still mid-parse are left alone
        (the announce lists complete parts only)."""
        worker = str(req["worker"])
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return {"error": f"reclaim from unregistered worker "
                             f"{worker!r} (register first)"}
        raw = req.get("parts")
        flat = not isinstance(raw, dict)
        by_job: Dict[str, Set[int]] = (
            {DEFAULT_JOB: {int(p) for p in (raw or [])}} if flat
            else {str(j): {int(p) for p in (ps or [])}
                  for j, ps in raw.items()})
        adopted: Dict[str, List[int]] = {}
        for jname, held in by_job.items():
            job = self._jobs.get(jname)
            if job is None:
                continue
            tag = self._job_tag(job)
            held = {p for p in held if 0 <= p < job.num_parts}
            got: List[int] = []
            for part in sorted(held):
                owner = job.assigned.get(part)
                if owner == worker:
                    if part not in job.completed:
                        job.completed.add(part)
                        self._journal_append(dict(
                            {"op": "complete", "part": part,
                             "worker": worker}, **tag))
                    got.append(part)
                elif owner is None and part in job.todo:
                    job.todo.remove(part)
                    job.assigned[part] = worker
                    job.completed.add(part)
                    self._journal_append(dict(
                        {"op": "reclaim", "part": part,
                         "worker": worker}, **tag))
                    got.append(part)
                # else: owned by another live worker — exactly-once wins
            if got:
                adopted[jname] = got
        # journal-complete parts this incarnation no longer announces —
        # ACROSS every job, so a worker that came back holding only job
        # A's frames re-queues its stale job-B claims too
        for job in self._jobs.values():
            held = by_job.get(job.job, set())
            stale = [p for p, w in job.assigned.items()
                     if w == worker and p in job.completed
                     and p not in held]
            self._requeue_locked(job, stale, worker, "reclaimed without")
        if adopted:
            logger.info("dispatcher: worker %s reclaimed parts %s",
                        worker, adopted)
        if flat:
            return {"ok": True, "adopted": adopted.get(DEFAULT_JOB, [])}
        return {"ok": True, "adopted": {j: ps
                                        for j, ps in adopted.items()}}

    # ---------------- server loop ----------------

    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            try:
                # accepted sockets do NOT inherit the listener's
                # SO_REUSEADDR: without it, one lingering half-closed
                # handler conn blocks a same-address restart's bind
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            except OSError:
                pass
            # one thread per connection — requests are tiny, but a
            # half-open client blocking the ONLY serve thread for its
            # read timeout would queue every worker heartbeat behind it —
            # capped by the handler semaphore: excess connections shed
            # with a retryable busy reply instead of a new thread
            if not self._handler_slots.acquire(blocking=False):
                self._shed(conn)
                continue
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _shed(self, conn) -> None:
        """Refuse one connection with a retryable busy reply (callers
        heal through the shared RetryPolicy — see :func:`request`)."""
        try:
            conn.settimeout(1.0)
            conn.sendall(b'{"busy": true}\n')
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn) -> None:
        try:
            conn.settimeout(10.0)
            with conn.makefile("rwb") as f:
                line = f.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except (ValueError, KeyError, TypeError) as exc:
                    resp = {"error": f"bad request: {exc}",
                            "gen": self.generation}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
        except OSError as exc:
            logger.debug("dispatcher: connection error: %s", exc)
        finally:
            self._handler_slots.release()
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Crash-simulate the dispatcher (``kill -9``): the listener
        drops with no goodbye and the in-memory assignment state is
        abandoned — the fsync'd journal is all a restart recovers from.
        Mechanically identical to :meth:`close` (the journal is
        append-only, so there is nothing graceful to skip); kept
        separate so chaos tests state their intent."""
        self.close()

    def close(self) -> None:
        self._closed = True
        # stop the background reaper tick first (clean shutdown: the
        # tick must never fire against a half-closed dispatcher)
        self._tick_stop.set()
        if threading.current_thread() is not self._tick_thread:
            self._tick_thread.join(timeout=5.0)
        # shutdown BEFORE close: a thread blocked in accept() holds a
        # kernel reference to the fd, so close() alone leaves the old
        # LISTEN socket alive until the syscall returns — and a restart
        # on the same address then cannot bind. shutdown wakes accept
        # immediately; the join guarantees the reference is dropped.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)
        # force-drop in-flight handler connections, exactly like the
        # kernel does for a dead process — otherwise a lingering
        # half-open peer keeps the port and a same-address restart
        # cannot bind
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


def request(address: str, req: dict, timeout: float = 10.0) -> dict:
    """One dispatcher round trip (shared by workers and clients).
    ``address`` is ``host:port``. Transport failures surface as their
    natural ConnectionError/OSError classes; a torn or empty reply (the
    dispatcher died mid-response) and a shed ``busy`` reply are wrapped
    in retryable ``ConnectionError`` HERE, so every caller — workers,
    clients, fleet bootstrap — heals through the shared
    :class:`~dmlc_tpu.io.resilience.RetryPolicy` instead of re-deriving
    the classification at call sites. The ``dispatch_rpc`` fault-plan op
    fires on every round trip (docs/resilience.md grammar)."""
    _faults.maybe_fail("dispatch_rpc", f"{address} {req.get('cmd', '')}")
    if "trace" not in req:
        # propagate the caller's trace context (optional key — old
        # dispatchers ignore it); copy-on-write so retries and callers
        # that reuse request dicts are unaffected
        wire = _telemetry.trace_context_wire()
        if wire is not None:
            req = dict(req, trace=wire)
    host, _, port = address.rpartition(":")
    t0 = get_time()
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        with s.makefile("rwb") as f:
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            line = f.readline()
    t1 = get_time()
    if not line:
        raise ConnectionError(f"dispatcher {address}: empty reply "
                              f"(died mid-response)")
    try:
        resp = json.loads(line)
    except ValueError as exc:
        # a torn reply mid-crash is JSON garbage — the same transient
        # fault as the connection dropping, classified ONCE here
        raise ConnectionError(
            f"dispatcher {address}: torn reply "
            f"{line[:64]!r}") from exc
    if resp.get("busy"):
        raise ConnectionError(
            f"dispatcher {address}: busy (handler slots exhausted; "
            f"retry after backoff)")
    now = resp.get("now")
    if isinstance(now, (int, float)):
        # clock-offset estimate from the round-trip midpoint: the peer
        # stamped `now` roughly halfway between our send and receive,
        # so ADDING (t0+t1)/2 − now to a peer timestamp lands it on
        # this process's clock (docs/observability.md)
        _note_clock_offset(address, (t0 + t1) / 2.0 - float(now))
    if "error" in resp:
        raise DMLCError(f"dispatcher {address}: {resp['error']}")
    return resp


def register_job(address: str, job: str, uri: str, num_parts: int,
                 parser: Optional[dict] = None,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 priority: Optional[int] = None,
                 weight: Optional[int] = None,
                 slo_wait_frac: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 timeout: float = 10.0) -> dict:
    """Register ``job`` at a running dispatcher over the wire (the
    trainer-side entry point of the multi-tenant service; docs/service.md
    job registry). Idempotent for an identical spec; a conflicting spec
    raises (job identity is immutable). Returns the registered spec —
    including the resolved ``parser`` config, whose ``block_cache`` may
    have been assigned by share-by-signature. ``priority`` / ``weight`` /
    ``slo_wait_frac`` / ``max_inflight`` declare the job's QoS class
    (docs/service.md Production QoS); the keys ride the wire only when
    set, so old dispatchers keep accepting default-class registrations."""
    req = {"cmd": "register_job", "job": str(job), "uri": uri,
           "num_parts": int(num_parts), "parser": dict(parser or {}),
           "plan": dict(plan or {}), "snapshot": dict(snapshot or {})}
    for key, value in (("priority", priority), ("weight", weight),
                       ("slo_wait_frac", slo_wait_frac),
                       ("max_inflight", max_inflight)):
        if value is not None:
            req[key] = value
    return request(address, req, timeout=timeout)
