"""Data-service dispatcher: split assignment + worker registry.

The control plane of the disaggregated RowBlock service (tf.data
service's dispatcher role, arXiv:2210.14826 §3): it owns ONE dataset —
a URI, its partition count, and the parser config every worker must use
— and hands the ``num_parts`` :class:`~dmlc_tpu.io.input_split.InputSplit`
partitions to parse workers **first-come-first-served, exactly once per
epoch**. A split is re-issued only when its owner is declared dead (a
client reported a broken stream, or heartbeats went stale), and re-issued
splits jump the queue so a mid-stream failover heals before new work
starts.

Protocol: one JSON object per connection (newline-terminated request,
newline-terminated response — the same short-lived-connection shape the
rabit tracker uses for ``heartbeat``/``metrics``). Commands:

``config``                      -> the dataset spec workers/clients parse
``register worker host port``   -> join the fleet (re-registration of a
                                   worker already seen alive THIS
                                   generation is treated as a crash-
                                   restart: its parts re-queue at the
                                   front until a ``reclaim`` adopts them
                                   back)
``next_split worker``           -> ``{"part": k}`` | ``{"part": null}``
                                   (nothing to do) — doubles as liveness
``heartbeat worker``            -> liveness only
``locate part``                 -> ``{"worker", "host", "port"}`` of the
                                   live owner, or ``{"wait": true}`` while
                                   the part awaits (re)assignment
``report_lost worker``          -> a client observed the worker dead: all
                                   its parts re-queue at the FRONT
``part_done part worker``       -> the owner finished parsing the part
                                   (journaled: a restarted dispatcher
                                   keeps it done instead of re-issuing)
``reclaim worker parts``        -> the worker re-announces the fully-
                                   parsed parts its frame store still
                                   holds: a restarted dispatcher ADOPTS
                                   them (no fleet-wide re-parse), and
                                   journal-complete parts the worker no
                                   longer holds re-queue
``status``                      -> registry snapshot (tests, operators)

Every response is stamped with the dispatcher's monotonic ``gen``
generation token, so workers and clients detect a restart at their next
control exchange (docs/service.md control-plane recovery).

**Crash recovery**: with ``journal_path=`` set, every state transition —
dataset registration, worker register/death, part grant / complete /
re-issue / reclaim — is appended to a flock'd JSONL journal (the shared
:class:`~dmlc_tpu.store.journal.AppendJournal` substrate: torn-tail skip
at replay, atomic compaction). A restarted ``Dispatcher(journal_path=
...)`` replays it into the exact assignment state: **completed parts
stay done** (their owners get a liveness grace window to re-attach),
**in-flight parts re-queue at the front**, and the generation token
bumps so the fleet re-registers and reclaims. The journal records no
epoch state by design: epochs live with clients and worker frame stores
(``before_first`` re-serves without dispatcher involvement), so the
assignment journal is epoch-invariant.

The dispatcher is deliberately dataset-state-free about *blocks*: block
ordering, resume, and exactly-once delivery live with the client (global
order is part-major), so the dispatcher never becomes a data-plane
bottleneck — it serves O(workers + failovers) tiny requests per epoch.
Concurrent connection handlers are capped (``DMLC_TPU_DISPATCH_WORKERS``
via the knob table); excess connections shed with a retryable ``busy``
reply, so a reconnect storm from a recovering fleet cannot exhaust
threads exactly when the dispatcher must stay responsive.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Set

from dmlc_tpu.io import faults as _faults
from dmlc_tpu.store import journal as _journal_mod
from dmlc_tpu.store.journal import AppendJournal
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils.check import check
from dmlc_tpu.utils.timer import get_time

logger = logging.getLogger("dmlc_tpu.service")

# journal compaction threshold: past this many lines at replay the
# journal is rewritten as the live state (dataset + start + registers +
# grant/complete pairs). Assignment journals are naturally small —
# O(parts + workers + failovers), epochs append nothing — so this only
# triggers after many restart cycles.
JOURNAL_COMPACT_LINES = 4096


class _WorkerInfo:
    __slots__ = ("worker", "host", "port", "last_seen", "alive",
                 "registered_gen")

    def __init__(self, worker: str, host: str, port: int, now: float,
                 registered_gen: Optional[int] = None):
        self.worker = worker
        self.host = host
        self.port = port
        self.last_seen = now
        self.alive = True
        # the generation this worker last sent `register` in; None for a
        # worker restored from the journal that has not re-attached yet
        # (its frame-store contents are unknown until it reclaims)
        self.registered_gen = registered_gen


class Dispatcher:
    """Split-assignment server for one dataset.

    ``parser`` is the config dict every worker builds its parser from
    (``format``/``type_``, ``chunk_bytes``, ``threaded``, ... — the
    kwargs of :func:`dmlc_tpu.data.parsers.create_parser`); shipping it
    from one place is what makes N workers' output byte-identical to a
    local parse with the same config. ``liveness_timeout`` (seconds)
    declares a worker dead when its polls/heartbeats go stale; client
    ``report_lost`` reports short-circuit that wait.

    ``journal_path`` arms crash recovery: state transitions journal to
    an append-only JSONL file and a restart on the same address replays
    them (see the module docstring). Without it the dispatcher is the
    historical in-memory-only control plane (generation fixed at 1).
    """

    def __init__(self, uri: str, num_parts: int,
                 parser: Optional[dict] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout: float = 10.0,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 journal_path: Optional[str] = None,
                 journal_compact_lines: int = JOURNAL_COMPACT_LINES):
        self.uri = uri
        self.num_parts = int(num_parts)
        self.parser = dict(parser or {})
        # the epoch-plan identity of the dataset (shuffle_seed /
        # shuffle_window, dmlc_tpu/data/epoch.py): shipped in `config` so
        # every worker arms its block cache with the SAME plan and every
        # client learns the seed its epochs are a function of — the one
        # place the fleet's shuffle is decided (docs/service.md)
        self.plan = dict(plan or {})
        # snapshot-frame geometry ({batch_size, num_col, x_dtype}): when
        # set, workers ALSO pack each part into fixed-geometry device-
        # layout batches (dmlc_tpu/io/snapshot.py encoding) and clients
        # stream those instead of CSR blocks — x_dtype='bfloat16' halves
        # the wire bytes. One dispatcher-owned knob, like the plan: the
        # whole fleet serves one batch geometry or none (docs/service.md)
        self.snapshot = dict(snapshot or {})
        self.liveness_timeout = float(liveness_timeout)
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerInfo] = {}
        # FCFS visitation queue: parts not yet assigned this epoch.
        # Re-issued parts (dead owner) go to the FRONT so failover work
        # heals before fresh parts are handed out.
        self._todo: Deque[int] = deque(range(self.num_parts))
        self._assigned: Dict[int, str] = {}   # part -> worker id
        self._completed: Set[int] = set()     # parts whose parse finished
        self.generation = 1
        self._journal: Optional[AppendJournal] = None
        if journal_path:
            self._journal = AppendJournal(journal_path)
            self._recover(int(journal_compact_lines))
        # connection-handler cap (knob table; docs/service.md): excess
        # connections shed with a retryable `busy` reply instead of
        # spawning an unbounded thread per connection — a reconnect storm
        # from a recovering fleet must not exhaust threads exactly when
        # the control plane needs to stay responsive
        self._handler_slots = threading.Semaphore(
            _knobs.resolve("dispatch_workers"))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        # in-flight handler connections, force-closed at close()/kill():
        # a dead process's sockets drop with it, and a restart must be
        # able to rebind the SAME port immediately (lingering accepted
        # sockets without SO_REUSEADDR would hold it)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="service-dispatcher")
        self._thread.start()
        logger.info("dispatcher for %s (%d parts) on %s:%d gen %d",
                    uri, num_parts, self.host, self.port, self.generation)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ---------------- journal + replay ----------------

    def _journal_append(self, event: dict, sync: bool = True) -> None:
        """Journal one state transition (no-op without a journal). All
        assignment events fsync: the journal IS the recovery contract,
        and its volume is O(parts + workers + failovers) per run."""
        if self._journal is not None:
            self._journal.append(event, sync=sync)

    def _recover(self, compact_lines: int) -> None:
        """Replay the journal into the exact assignment state: completed
        parts stay done with their owner, in-flight parts re-queue at
        the FRONT (lowest first — clients consume part-major), replayed
        workers get a fresh liveness window to re-attach, and the
        generation token bumps past every `start` ever journaled."""
        with self._journal.locked():
            lines = self._journal.read_lines()
            events = _journal_mod.decode_events(lines)
            last_gen = 0
            seen_dataset = False
            todo = self._todo
            in_todo = set(todo)
            assigned, completed = self._assigned, self._completed
            workers: Dict[str, tuple] = {}
            for ev in events:
                op = ev.get("op")
                if op == "dataset":
                    check(int(ev.get("num_parts", self.num_parts))
                          == self.num_parts,
                          f"dispatcher journal {self._journal.path}: "
                          f"journaled dataset has "
                          f"{ev.get('num_parts')} parts, constructor "
                          f"says {self.num_parts} — a restart must "
                          f"recover the SAME dataset")
                    seen_dataset = True
                elif op == "start":
                    last_gen = max(last_gen, int(ev.get("gen", 0) or 0))
                elif op == "register":
                    workers[str(ev.get("worker"))] = (
                        str(ev.get("host", "")), int(ev.get("port", 0)))
                elif op == "dead":
                    workers.pop(str(ev.get("worker")), None)
                elif op == "grant":
                    part = int(ev.get("part", -1))
                    if part in in_todo:
                        in_todo.discard(part)
                        todo.remove(part)
                    assigned[part] = str(ev.get("worker"))
                elif op == "complete":
                    part = int(ev.get("part", -1))
                    if part in assigned:
                        completed.add(part)
                elif op == "reissue":
                    part = int(ev.get("part", -1))
                    assigned.pop(part, None)
                    completed.discard(part)
                    if 0 <= part < self.num_parts and part not in in_todo:
                        in_todo.add(part)
                        todo.appendleft(part)
                elif op == "reclaim":
                    part = int(ev.get("part", -1))
                    if part in in_todo:
                        in_todo.discard(part)
                        todo.remove(part)
                    assigned[part] = str(ev.get("worker"))
                    completed.add(part)
            # in-flight at the crash (granted, never completed): the
            # owner's frames may be partial — re-queue at the front,
            # lowest part first; reclaim re-adopts what survived
            inflight = sorted(p for p in assigned if p not in completed)
            for part in inflight:
                assigned.pop(part)
            # parts completed by a worker the journal no longer knows
            # (dead without a reissue line — a torn tail can lose one):
            # nothing serves them, so they re-queue behind the in-flight
            orphaned = sorted(p for p, w in assigned.items()
                              if w not in workers)
            for part in orphaned:
                assigned.pop(part)
                completed.discard(part)
            for part in reversed(inflight + orphaned):
                if part not in in_todo:
                    in_todo.add(part)
                    todo.appendleft(part)
            now = get_time()
            # replayed workers start a fresh liveness window: a worker
            # that survived the dispatcher re-attaches within it (its
            # next poll sees the generation bump), one that died with
            # the dispatcher goes stale and its parts re-issue normally
            self._workers = {
                w: _WorkerInfo(w, h, p, now) for w, (h, p) in
                workers.items()}
            self.generation = last_gen + 1
            if len(lines) > compact_lines:
                self._journal.rewrite(self._live_events())
            if not seen_dataset:
                self._journal.append(
                    {"op": "dataset", "uri": self.uri,
                     "num_parts": self.num_parts}, sync=True)
            self._journal.append(
                {"op": "start", "gen": self.generation}, sync=True)
            if events:
                logger.info(
                    "dispatcher: recovered from %s — gen %d, %d parts "
                    "done, %d re-queued, %d workers awaiting re-attach",
                    self._journal.path, self.generation,
                    len(self._completed), len(inflight) + len(orphaned),
                    len(self._workers))

    def _live_events(self) -> List[dict]:
        """The current state as a canonical journal (compaction): the
        dataset, the last start, live workers, and grant+complete pairs
        for done parts. Unassigned parts are implicit (replay seeds the
        queue from ``range(num_parts)``); the queue's front-ordering
        normalizes to ascending across a compaction."""
        events: List[dict] = [
            {"op": "dataset", "uri": self.uri,
             "num_parts": self.num_parts},
            {"op": "start", "gen": self.generation - 1},
        ]
        for info in self._workers.values():
            if info.alive:
                events.append({"op": "register", "worker": info.worker,
                               "host": info.host, "port": info.port})
        for part in sorted(self._completed):
            worker = self._assigned.get(part)
            if worker is None:
                continue
            events.append({"op": "grant", "part": part, "worker": worker})
            events.append({"op": "complete", "part": part,
                           "worker": worker})
        return events

    # ---------------- assignment core (lock held) ----------------

    def _requeue_locked(self, parts, worker: str, why: str) -> None:
        """Re-issue ``parts`` at the FRONT, lowest part first (clients
        consume part-major, so the earliest lost part is the one
        blocking them), journaling each re-queue."""
        parts = sorted(parts)
        for part in parts:
            self._assigned.pop(part, None)
            self._completed.discard(part)
        for part in reversed(parts):
            self._todo.appendleft(part)
            self._journal_append({"op": "reissue", "part": part,
                                  "worker": worker})
        if parts:
            logger.warning("dispatcher: worker %s %s; re-issuing parts %s",
                           worker, why, parts)

    def _mark_dead_locked(self, worker: str) -> None:
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return
        info.alive = False
        self._journal_append({"op": "dead", "worker": worker})
        self._requeue_locked(
            [p for p, w in self._assigned.items() if w == worker],
            worker, "lost")

    def _reap_stale_locked(self, now: float) -> None:
        if self.liveness_timeout <= 0:
            return
        for info in list(self._workers.values()):
            if info.alive and now - info.last_seen > self.liveness_timeout:
                logger.warning("dispatcher: worker %s missed heartbeats "
                               "(last seen %.1fs ago)", info.worker,
                               now - info.last_seen)
                self._mark_dead_locked(info.worker)

    # ---------------- request handlers ----------------

    def _handle(self, req: dict) -> dict:
        resp = self._dispatch_cmd(req)
        # the monotonic generation token: peers detect a restart at
        # their next control exchange and re-register/revalidate
        resp["gen"] = self.generation
        return resp

    def _dispatch_cmd(self, req: dict) -> dict:
        cmd = req.get("cmd")
        now = get_time()
        with self._lock:
            if cmd == "config":
                return {"uri": self.uri, "num_parts": self.num_parts,
                        "parser": self.parser, "plan": self.plan,
                        "snapshot": self.snapshot}
            if cmd == "register":
                worker = str(req["worker"])
                prev = self._workers.get(worker)
                if (prev is not None and prev.alive
                        and prev.registered_gen == self.generation):
                    # a worker id already seen alive THIS generation is
                    # re-registering: the process crash-restarted fast
                    # (before the liveness reaper fired) and its frame
                    # store is presumed gone — re-queue everything it
                    # owned; the reclaim that follows adopts back what
                    # actually survived (docs/service.md)
                    self._requeue_locked(
                        [p for p, w in self._assigned.items()
                         if w == worker],
                        worker, "re-registered (crash-restart)")
                self._workers[worker] = _WorkerInfo(
                    worker, str(req["host"]), int(req["port"]), now,
                    registered_gen=self.generation)
                self._journal_append({"op": "register", "worker": worker,
                                      "host": str(req["host"]),
                                      "port": int(req["port"])})
                return {"ok": True}
            if cmd == "heartbeat":
                info = self._workers.get(str(req.get("worker")))
                if info is not None and info.alive:
                    info.last_seen = now
                return {"ok": True}
            if cmd == "next_split":
                worker = str(req["worker"])
                info = self._workers.get(worker)
                if info is None or not info.alive:
                    # unregistered/declared-dead workers get no splits —
                    # a zombie must re-register before it can own parts
                    return {"part": None, "register": True}
                if info.registered_gen != self.generation:
                    # journal-restored worker that has not re-attached
                    # this generation: its frame-store contents are
                    # unknown until the register+reclaim handshake, and
                    # a grant riding the SAME reply as the generation
                    # bump would race the reclaim into a duplicate parse
                    info.last_seen = now
                    return {"part": None, "register": True}
                info.last_seen = now
                self._reap_stale_locked(now)
                if not self._todo:
                    return {"part": None}
                part = self._todo.popleft()
                self._assigned[part] = worker
                self._journal_append({"op": "grant", "part": part,
                                      "worker": worker})
                logger.info("dispatcher: part %d -> worker %s", part, worker)
                return {"part": part}
            if cmd == "part_done":
                worker = str(req["worker"])
                part = int(req["part"])
                if (self._assigned.get(part) == worker
                        and part not in self._completed):
                    # journaled completion: a restarted dispatcher keeps
                    # the part done instead of re-queuing it as in-flight
                    self._completed.add(part)
                    self._journal_append({"op": "complete", "part": part,
                                          "worker": worker})
                return {"ok": True}
            if cmd == "reclaim":
                return self._reclaim_locked(req)
            if cmd == "locate":
                part = int(req["part"])
                if not 0 <= part < self.num_parts:
                    return {"error": f"part {part} out of range"}
                self._reap_stale_locked(now)
                owner = self._assigned.get(part)
                info = self._workers.get(owner) if owner is not None else None
                if info is None or not info.alive:
                    return {"wait": True}
                return {"worker": info.worker, "host": info.host,
                        "port": info.port}
            if cmd == "report_lost":
                self._mark_dead_locked(str(req["worker"]))
                return {"ok": True}
            if cmd == "status":
                return {
                    "workers": {w: {"host": i.host, "port": i.port,
                                    "alive": i.alive}
                                for w, i in self._workers.items()},
                    "assigned": {str(p): w
                                 for p, w in self._assigned.items()},
                    "todo": list(self._todo),
                    "completed": sorted(self._completed),
                    "generation": self.generation,
                }
        return {"error": f"unknown command {cmd!r}"}

    def _reclaim_locked(self, req: dict) -> dict:
        """Adopt the fully-parsed parts a (re-)registered worker's frame
        store still holds — instead of forcing a fleet-wide re-parse —
        and re-queue the journal-complete parts it no longer announces
        (its store lost them, e.g. dispatcher AND worker both died).
        Parts owned by ANOTHER live worker are never stolen; parts
        granted this generation and still mid-parse are left alone (the
        announce lists complete parts only)."""
        worker = str(req["worker"])
        info = self._workers.get(worker)
        if info is None or not info.alive:
            return {"error": f"reclaim from unregistered worker "
                             f"{worker!r} (register first)"}
        held = {int(p) for p in (req.get("parts") or [])
                if 0 <= int(p) < self.num_parts}
        adopted: List[int] = []
        for part in sorted(held):
            owner = self._assigned.get(part)
            if owner == worker:
                if part not in self._completed:
                    self._completed.add(part)
                    self._journal_append({"op": "complete", "part": part,
                                          "worker": worker})
                adopted.append(part)
            elif owner is None and part in self._todo:
                self._todo.remove(part)
                self._assigned[part] = worker
                self._completed.add(part)
                self._journal_append({"op": "reclaim", "part": part,
                                      "worker": worker})
                adopted.append(part)
            # else: owned by another live worker — exactly-once wins
        stale = [p for p, w in self._assigned.items()
                 if w == worker and p in self._completed
                 and p not in held]
        self._requeue_locked(stale, worker, "reclaimed without")
        if adopted:
            logger.info("dispatcher: worker %s reclaimed parts %s",
                        worker, adopted)
        return {"ok": True, "adopted": adopted}

    # ---------------- server loop ----------------

    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            try:
                # accepted sockets do NOT inherit the listener's
                # SO_REUSEADDR: without it, one lingering half-closed
                # handler conn blocks a same-address restart's bind
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            except OSError:
                pass
            # one thread per connection — requests are tiny, but a
            # half-open client blocking the ONLY serve thread for its
            # read timeout would queue every worker heartbeat behind it —
            # capped by the handler semaphore: excess connections shed
            # with a retryable busy reply instead of a new thread
            if not self._handler_slots.acquire(blocking=False):
                self._shed(conn)
                continue
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _shed(self, conn) -> None:
        """Refuse one connection with a retryable busy reply (callers
        heal through the shared RetryPolicy — see :func:`request`)."""
        try:
            conn.settimeout(1.0)
            conn.sendall(b'{"busy": true}\n')
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn) -> None:
        try:
            conn.settimeout(10.0)
            with conn.makefile("rwb") as f:
                line = f.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except (ValueError, KeyError, TypeError) as exc:
                    resp = {"error": f"bad request: {exc}",
                            "gen": self.generation}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
        except OSError as exc:
            logger.debug("dispatcher: connection error: %s", exc)
        finally:
            self._handler_slots.release()
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Crash-simulate the dispatcher (``kill -9``): the listener
        drops with no goodbye and the in-memory assignment state is
        abandoned — the fsync'd journal is all a restart recovers from.
        Mechanically identical to :meth:`close` (the journal is
        append-only, so there is nothing graceful to skip); kept
        separate so chaos tests state their intent."""
        self.close()

    def close(self) -> None:
        self._closed = True
        # shutdown BEFORE close: a thread blocked in accept() holds a
        # kernel reference to the fd, so close() alone leaves the old
        # LISTEN socket alive until the syscall returns — and a restart
        # on the same address then cannot bind. shutdown wakes accept
        # immediately; the join guarantees the reference is dropped.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)
        # force-drop in-flight handler connections, exactly like the
        # kernel does for a dead process — otherwise a lingering
        # half-open peer keeps the port and a same-address restart
        # cannot bind
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


def request(address: str, req: dict, timeout: float = 10.0) -> dict:
    """One dispatcher round trip (shared by workers and clients).
    ``address`` is ``host:port``. Transport failures surface as their
    natural ConnectionError/OSError classes; a torn or empty reply (the
    dispatcher died mid-response) and a shed ``busy`` reply are wrapped
    in retryable ``ConnectionError`` HERE, so every caller — workers,
    clients, fleet bootstrap — heals through the shared
    :class:`~dmlc_tpu.io.resilience.RetryPolicy` instead of re-deriving
    the classification at call sites. The ``dispatch_rpc`` fault-plan op
    fires on every round trip (docs/resilience.md grammar)."""
    _faults.maybe_fail("dispatch_rpc", f"{address} {req.get('cmd', '')}")
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        with s.makefile("rwb") as f:
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            line = f.readline()
    if not line:
        raise ConnectionError(f"dispatcher {address}: empty reply "
                              f"(died mid-response)")
    try:
        resp = json.loads(line)
    except ValueError as exc:
        # a torn reply mid-crash is JSON garbage — the same transient
        # fault as the connection dropping, classified ONCE here
        raise ConnectionError(
            f"dispatcher {address}: torn reply "
            f"{line[:64]!r}") from exc
    if resp.get("busy"):
        raise ConnectionError(
            f"dispatcher {address}: busy (handler slots exhausted; "
            f"retry after backoff)")
    if "error" in resp:
        from dmlc_tpu.utils.check import DMLCError

        raise DMLCError(f"dispatcher {address}: {resp['error']}")
    return resp
